"""Measured-RTT routing (reference ping.py:59-100 + sequence_manager
_build_inference_graph:235-296): client->server edges from EMA pings,
server->server edges from announced next_pings."""

import asyncio


from bloombee_tpu.client.sequence_manager import RemoteSequenceManager
from bloombee_tpu.swarm.data import RemoteSpanInfo, ServerInfo


def _span(peer, start, end, rps=10.0, next_pings=None):
    return RemoteSpanInfo(
        peer, start, end,
        ServerInfo(
            host="127.0.0.1", port=1, throughput=rps, inference_rps=rps,
            start_block=start, end_block=end, next_pings=next_pings,
        ),
    )


def _manager(spans):
    m = RemoteSequenceManager(registry=None, model_uid="m", num_blocks=2)
    m.spans = {s.peer_id: s for s in spans}
    return m


def test_slow_pinged_peer_avoided():
    """Two identical servers for the whole range; the one with a high
    measured RTT loses."""
    m = _manager([_span("fast", 0, 2), _span("slow", 0, 2)])
    m.pinger.record("fast", 0.002)
    m.pinger.record("slow", 0.500)
    for _ in range(5):
        route = m.make_sequence()
        assert [s.peer_id for s in route] == ["fast"]


def test_next_pings_steer_second_hop():
    """First span's announced next_pings decide the second span even though
    the client's own pings say otherwise."""
    first = _span("a", 0, 1, next_pings={"c2": 0.001, "c1": 0.400})
    m = _manager([first, _span("c1", 1, 2), _span("c2", 1, 2)])
    # client's own pings would prefer c1 — the announced server->server RTT
    # must win for the a->X hop
    m.pinger.record("a", 0.002)
    m.pinger.record("c1", 0.001)
    m.pinger.record("c2", 0.300)
    route = m.make_sequence()
    assert [s.peer_id for s in route] == ["a", "c2"]


def test_rtt_vs_compute_tradeoff():
    """A slower-RTT server that covers both blocks beats two fast-RTT hops
    when the hop cost dominates (fewer hops, same compute)."""
    m = _manager([
        _span("whole", 0, 2, rps=10.0),
        _span("h1", 0, 1, rps=10.0),
        _span("h2", 1, 2, rps=10.0),
    ])
    m.pinger.record("whole", 0.050)
    m.pinger.record("h1", 0.030)
    m.pinger.record("h2", 0.030)
    route = m.make_sequence()
    # whole: 0.05 + 0.2 compute; h1+h2: 0.03+0.1 + 0.03+0.1 = 0.26
    assert [s.peer_id for s in route] == ["whole"]


def test_e2e_pings_measured_and_next_pings_announced(tmp_path):
    """Live swarm: the client measures real RTTs on update, and a server
    announces next_pings for its successor block's servers."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    import jax.numpy as jnp

    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=2, vocab_size=128,
        max_position_embeddings=64, tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    LlamaForCausalLM(config).eval().save_pretrained(
        tmp_path, safe_serialization=True
    )

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s1 = BlockServer(model_uid="m", start=0, end=1,
                         model_dir=str(tmp_path), registry=rc(),
                         compute_dtype=jnp.float32, num_pages=16,
                         page_size=4, announce_period=0.2)
        s2 = BlockServer(model_uid="m", start=1, end=2,
                         model_dir=str(tmp_path), registry=rc(),
                         compute_dtype=jnp.float32, num_pages=16,
                         page_size=4, announce_period=0.2)
        await s1.start()
        await s2.start()
        await asyncio.sleep(0.6)  # let announce loops ping + re-announce

        manager = RemoteSequenceManager(rc(), "m", 2)
        await manager.update(force=True)
        # client measured both servers
        assert manager.pinger.get(s1.server_id, -1) > 0
        assert manager.pinger.get(s2.server_id, -1) > 0
        # s1 announced a measured RTT toward s2 (its successor block)
        info1 = manager.spans[s1.server_id].server_info
        assert info1.next_pings and s2.server_id in info1.next_pings
        assert 0 < info1.next_pings[s2.server_id] < 1.0
        route = manager.make_sequence()
        assert [s.peer_id for s in route] == [s1.server_id, s2.server_id]

        await s1.stop()
        await s2.stop()
        await reg.stop()

    asyncio.run(run())


def test_clock_offset_measured(tmp_path):
    """NTP-style clock sync (reference handler.py:498-575): pings record a
    per-peer clock offset near zero on one host."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    import jax.numpy as jnp

    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=2, vocab_size=128,
        max_position_embeddings=64, tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    LlamaForCausalLM(config).eval().save_pretrained(
        tmp_path, safe_serialization=True
    )

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s = BlockServer(model_uid="m", start=0, end=2,
                        model_dir=str(tmp_path),
                        registry=RegistryClient("127.0.0.1", reg.port),
                        compute_dtype=jnp.float32, num_pages=16, page_size=4)
        await s.start()
        m = RemoteSequenceManager(
            RegistryClient("127.0.0.1", reg.port), "m", 2
        )
        await m.update(force=True)
        off = m.pinger.clock_offset(s.server_id)
        assert off is not None and abs(off) < 0.5, off  # same host clock
        await s.stop()
        await reg.stop()

    asyncio.run(run())
