"""Replicated registry: announce everywhere, read anywhere, survive a
replica failure (the availability half of the reference's hivemind DHT
replication, utils/dht.py:28-117, without a gossip protocol)."""

import asyncio

import pytest

from bloombee_tpu.swarm.data import ServerInfo, ServerState
from bloombee_tpu.swarm.registry import (
    RegistryClient,
    RegistryServer,
    ReplicatedRegistry,
    make_registry,
)
from bloombee_tpu.swarm.spans import compute_spans


def make_info(port=1234):
    return ServerInfo(host="127.0.0.1", port=port, throughput=1.0)


def test_make_registry_parsing():
    assert isinstance(make_registry("127.0.0.1:7700"), RegistryClient)
    rep = make_registry("127.0.0.1:7700, 127.0.0.1:7701")
    assert isinstance(rep, ReplicatedRegistry)
    assert len(rep.replicas) == 2
    with pytest.raises(ValueError):
        make_registry("  ,  ")


def test_declare_lands_on_every_replica():
    async def run():
        regs = [RegistryServer(host="127.0.0.1") for _ in range(2)]
        for r in regs:
            await r.start()
        rep = make_registry(
            ",".join(f"127.0.0.1:{r.port}" for r in regs)
        )
        await rep.declare_blocks("m", "srv-a", range(0, 4), make_info())
        # each replica independently knows the full record set
        for r in regs:
            solo = RegistryClient("127.0.0.1", r.port)
            infos = await solo.get_module_infos("m", range(0, 4))
            assert all("srv-a" in m.servers for m in infos)
            await solo.close()
        await rep.close()
        for r in regs:
            await r.stop()

    asyncio.run(run())


def test_survives_replica_failure():
    """One replica dies: declare/get still work through the other, and the
    calls stay time-bounded instead of hanging on the dead peer."""

    async def run():
        regs = [RegistryServer(host="127.0.0.1") for _ in range(2)]
        for r in regs:
            await r.start()
        rep = ReplicatedRegistry(
            [RegistryClient("127.0.0.1", r.port) for r in regs],
            timeout=3.0,
        )
        await rep.declare_blocks("m", "srv-a", range(0, 2), make_info())
        await regs[0].stop()  # kill the first replica

        t0 = asyncio.get_event_loop().time()
        await rep.declare_blocks("m", "srv-b", range(2, 4), make_info(4321))
        infos = await rep.get_module_infos("m", range(0, 4))
        elapsed = asyncio.get_event_loop().time() - t0
        assert elapsed < 10.0
        assert all("srv-a" in m.servers for m in infos[:2])
        assert all("srv-b" in m.servers for m in infos[2:])
        await rep.close()
        await regs[1].stop()

    asyncio.run(run())


def test_get_merges_skewed_replicas():
    """Records present on only one replica (announce skew / replica restart)
    still appear in the merged view."""

    async def run():
        regs = [RegistryServer(host="127.0.0.1") for _ in range(2)]
        for r in regs:
            await r.start()
        solo = [RegistryClient("127.0.0.1", r.port) for r in regs]
        await solo[0].declare_blocks("m", "srv-a", range(0, 2), make_info())
        await solo[1].declare_blocks(
            "m", "srv-b", range(0, 2), make_info(4321)
        )
        rep = ReplicatedRegistry(solo)
        infos = await rep.get_module_infos("m", range(0, 2))
        for m in infos:
            assert set(m.servers) == {"srv-a", "srv-b"}
        await rep.close()
        for r in regs:
            await r.stop()

    asyncio.run(run())


def test_revoke_tombstone_beats_missed_replica():
    """A replica that missed the revoke (it was down) cannot resurrect the
    dead server in the merged view: the surviving replica's tombstone is
    newer than the stale live record (latest-write-wins)."""

    async def run():
        regs = [RegistryServer(host="127.0.0.1") for _ in range(2)]
        for r in regs:
            await r.start()
        solo = [RegistryClient("127.0.0.1", r.port) for r in regs]
        rep = ReplicatedRegistry(list(solo))
        await rep.declare_blocks("m", "srv-a", range(0, 2), make_info())
        await asyncio.sleep(0.02)  # the revoke must be strictly newer
        # revoke lands ONLY on replica 0 (replica 1 "was down")
        await solo[0].revoke_blocks("m", "srv-a", range(0, 2))
        infos = await rep.get_module_infos("m", range(0, 2))
        for m in infos:
            assert "srv-a" not in m.servers, "revoked server resurrected"
        # a RE-announce after the revoke wins again (newer than tombstone)
        await asyncio.sleep(0.02)
        await rep.declare_blocks("m", "srv-a", range(0, 2), make_info())
        infos = await rep.get_module_infos("m", range(0, 2))
        assert all("srv-a" in m.servers for m in infos)
        await rep.close()
        for r in regs:
            await r.stop()

    asyncio.run(run())


def test_read_returns_fast_despite_wedged_replica():
    """A replica that accepts connections but never answers must cost reads
    ~read_grace, not the full timeout."""

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        # a "wedged" replica: accepts TCP, never replies
        async def black_hole(reader, writer):
            await asyncio.sleep(3600)

        wedged = await asyncio.start_server(black_hole, "127.0.0.1", 0)
        wedged_port = wedged.sockets[0].getsockname()[1]

        rep = ReplicatedRegistry(
            [
                RegistryClient("127.0.0.1", reg.port),
                RegistryClient("127.0.0.1", wedged_port),
            ],
            timeout=10.0,
            read_grace=0.25,
        )
        solo = RegistryClient("127.0.0.1", reg.port)
        await solo.declare_blocks("m", "srv-a", range(0, 2), make_info())
        t0 = asyncio.get_event_loop().time()
        infos = await rep.get_module_infos("m", range(0, 2))
        elapsed = asyncio.get_event_loop().time() - t0
        assert all("srv-a" in m.servers for m in infos)
        assert elapsed < 2.0, f"read stalled {elapsed:.2f}s on wedged replica"
        await rep.close()
        await solo.close()
        wedged.close()
        await reg.stop()

    asyncio.run(run())


def test_records_carry_writer_stamps_not_replica_clocks():
    """stored_at comes from the WRITER, so every replica holds the same
    stamp for the same write — replica clock skew cannot flip the
    announce-vs-revoke ordering in a merged read (one writer's clock
    orders its own sequence)."""

    async def run():
        regs = [RegistryServer(host="127.0.0.1") for _ in range(2)]
        for r in regs:
            await r.start()
        solo = [RegistryClient("127.0.0.1", r.port) for r in regs]
        # same declare call, one writer stamp, both replicas
        now_rec = {"key": "m.0", "subkey": "srv-a",
                   "value": make_info().to_wire(),
                   "expiration": 30.0, "stored_at": 1234.5}
        for s in solo:
            conn = await s._connection()
            await conn.call("registry_store", {"records": [now_rec]})
        t0 = regs[0]._store._data["m.0"]["srv-a"][2]
        t1 = regs[1]._store._data["m.0"]["srv-a"][2]
        assert t0 == t1 == 1234.5  # replica receive clocks never used
        await rep_cleanup(regs, solo)

    async def rep_cleanup(regs, solo):
        for s in solo:
            await s.close()
        for r in regs:
            await r.stop()

    asyncio.run(run())


def test_promotion_churn_survives_replica_restart(tmp_path):
    """The standby promote -> demote -> re-promote lifecycle is a rapid
    same-subkey state churn; a persisted replica that restarts mid-cycle
    restores a STALE state record from its snapshot. Latest-write-wins
    must keep the merged view showing exactly one span per server with
    the newest declared state — no duplicate records, no resurrected
    (orphaned) stale state, and a final revoke leaves nothing behind."""

    def _state_info(state):
        return ServerInfo(
            host="127.0.0.1", port=9999, throughput=1.0,
            start_block=0, end_block=2, state=state,
            promoted_standby=(state == ServerState.ONLINE),
        )

    async def assert_single_span(reg, state):
        infos = await reg.get_module_infos("m", range(0, 2))
        for m in infos:
            assert list(m.servers) == ["srv-sb"], (
                f"duplicate/orphan records: {sorted(m.servers)}"
            )
            assert m.servers["srv-sb"].state == state
        spans = compute_spans(infos, min_state=ServerState.JOINING)
        assert set(spans) == {"srv-sb"}
        assert (spans["srv-sb"].start, spans["srv-sb"].end) == (0, 2)

    async def run():
        persist = str(tmp_path / "replica0.json")
        regs = [
            RegistryServer(
                host="127.0.0.1", persist_path=persist, persist_period=0.2
            ),
            RegistryServer(host="127.0.0.1"),
        ]
        for r in regs:
            await r.start()
        port0 = regs[0].port
        rep = ReplicatedRegistry(
            [RegistryClient("127.0.0.1", r.port) for r in regs],
            timeout=3.0,
        )

        # standby appears (JOINING), then promotes (ONLINE)
        await rep.declare_blocks(
            "m", "srv-sb", range(0, 2), _state_info(ServerState.JOINING)
        )
        await assert_single_span(rep, ServerState.JOINING)
        await asyncio.sleep(0.02)
        await rep.declare_blocks(
            "m", "srv-sb", range(0, 2), _state_info(ServerState.ONLINE)
        )
        await assert_single_span(rep, ServerState.ONLINE)

        # replica 0 snapshots the ONLINE record and goes down; the demote
        # (drain-back to JOINING) lands only on replica 1
        await regs[0].stop()
        await asyncio.sleep(0.02)
        await rep.declare_blocks(
            "m", "srv-sb", range(0, 2), _state_info(ServerState.JOINING)
        )

        # replica 0 restarts from its snapshot: it restores the stale
        # ONLINE record, but the merged view must show the newer JOINING
        regs[0] = RegistryServer(
            host="127.0.0.1", port=port0, persist_path=persist,
            persist_period=0.2,
        )
        await regs[0].start()
        solo0 = RegistryClient("127.0.0.1", port0)
        infos0 = await solo0.get_module_infos("m", range(0, 2))
        assert infos0[0].servers["srv-sb"].state == ServerState.ONLINE, (
            "restart precondition: the snapshot should hold stale state"
        )
        await solo0.close()
        await assert_single_span(rep, ServerState.JOINING)

        # re-promotion (lands on both replicas) wins over everything
        await asyncio.sleep(0.02)
        await rep.declare_blocks(
            "m", "srv-sb", range(0, 2), _state_info(ServerState.ONLINE)
        )
        await assert_single_span(rep, ServerState.ONLINE)

        # final drain-away: revoke must leave no orphaned span anywhere
        await asyncio.sleep(0.02)
        await rep.revoke_blocks("m", "srv-sb", range(0, 2))
        infos = await rep.get_module_infos("m", range(0, 2))
        for m in infos:
            assert "srv-sb" not in m.servers, "orphaned span record"
        assert compute_spans(infos, min_state=ServerState.JOINING) == {}

        await rep.close()
        for r in regs:
            await r.stop()

    asyncio.run(run())


def test_all_replicas_down_raises():
    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        port = reg.port
        await reg.stop()
        rep = ReplicatedRegistry(
            [RegistryClient("127.0.0.1", port)], timeout=2.0
        )
        with pytest.raises(RuntimeError, match="all 1 replicas"):
            await rep.get_module_infos("m", range(0, 2))
        await rep.close()

    asyncio.run(run())
