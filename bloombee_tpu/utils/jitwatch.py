"""Runtime compile/transfer witness — the dynamic half of bbtpu-lint's
JIT-boundary story (the static half is BB011/BB012/BB013 in
analysis/rules.py).

Static analysis proves which call sites CAN recompile or sync; this
module records what a run ACTUALLY compiled and transferred. Opt-in via
``BBTPU_JITWATCH=1``: :func:`install` registers one
``jax.monitoring`` event-duration listener that ledgers every XLA
backend compile as ``(function, shape_signature, compile_ms, phase)``.
Attribution rides a thread-local region stack: the executor wraps each
dispatch in :func:`region` naming the jit entry and its bucket
signature, so a compile that fires inside the dispatch is pinned to the
exact (function, bucket) that caused it. Compiles outside any region
(model load, client-side jnp work sharing the process) are ledgered as
``(unattributed)`` — counted, visible, but not gated, because only
region-attributed compiles are provably the serving path's fault.

Phases split the compile budget: every process starts in ``warmup``;
``BlockServer.warmup`` drops the fence (:func:`fence`) when its bucket
pre-compilation finishes, and every region-attributed compile after the
fence is a **steady-state recompile** — the recompile-storm signal this
witness exists to catch. Host syncs are recorded by the explicit d2h
sites (``executor.fetch``) via :func:`host_sync`; ones that fire while
the compute-queue worker is mid-task (:func:`hot_wrap`) count as
``host_syncs_hot_path`` — a device stall inside the serialized step
pipeline, the convoy BB011 flags statically.

At interpreter exit the witness appends one JSON line to
``BBTPU_JITWATCH_REPORT`` (append mode, multi-process merge — same
contract as lockwatch/ledger). ``python -m bloombee_tpu.utils.jitwatch
PATH --require`` merges the lines and FAILS on: zero observed compiles
(vacuous green — a witness that saw no XLA activity validated nothing),
no warmup fence in any line (the steady window never opened, so "zero
steady recompiles" is also vacuous), zero warmup compiles (same), or
ANY steady-state recompile. clock is deliberately NOT imported here
(the ledger/clock/*watch utility layer stays import-cycle-free).

The witness also understands the persistent compile-artifact cache
(server/artifacts.py): a cache hit still fires backend_compile_duration,
but ``/jax/compilation_cache/cache_retrieval_time_sec`` fires first on
the same thread, so hits are ledgered as ``cached`` loads — they spend
no warmup budget and never count as steady recompiles. A server that
pre-installed fetched artifacts calls :func:`mark_preinstalled`; any
non-cached region-attributed warmup compile after that is a
``preinstalled_warmup_miss``, and ``--require --preinstalled`` fails on
any miss (or on zero cache hits — a vacuous pre-install). Swallowed
per-bucket warmup failures are recorded via :func:`note_warmup_failure`
and fail plain ``--require`` (``warmup_degraded``), so a zero-recompile
green can't mask buckets that never warmed.
"""

from __future__ import annotations

import atexit
import json
import threading

from bloombee_tpu.utils import env

env.declare(
    "BBTPU_JITWATCH", bool, False,
    "install the runtime compile/transfer witness: ledgers every XLA "
    "backend compile with (function, shape bucket, ms, phase) via the "
    "jax.monitoring hook, counts host syncs on the compute hot path, "
    "and reports at exit. Off = listener never registered, zero overhead",
)
env.declare(
    "BBTPU_JITWATCH_REPORT", str, "",
    "path to append this process's compile-witness report to at exit "
    "(one JSON line: compile ledger, warmup/steady split, hot-path host "
    "syncs); empty = in-memory only. Set by scripts/chaos.sh so the "
    "gate can require zero steady-state recompiles",
)

_MAX_COMPILES = 200  # keep each report line bounded under a compile storm
_UNATTRIBUTED = "(unattributed)"


class _Witness:
    """Process-wide compile/transfer ledger. Internal mutex is a PLAIN
    threading.Lock — the witness must never watch itself. Phase is
    process-wide (one warmup fence per server process); the attribution
    region and hot-path marks are thread-local because dispatches run
    synchronously on the compute worker thread."""

    def __init__(self):
        self._mu = threading.Lock()
        self._tls = threading.local()
        self.compiles: list[dict] = []
        self.xla_compiles = 0
        self.compile_ms_total = 0.0
        self.warmup_compiles = 0
        self.steady_state_recompiles = 0
        self.compile_cache_hits = 0
        self.preinstalled = False
        self.preinstalled_warmup_misses = 0
        self.warmup_failures = 0
        self.host_syncs: dict[str, int] = {}
        self.host_syncs_hot_path = 0
        self.phase = "warmup"
        self.fenced = False

    # ---------------------------------------------------- thread context
    def _regions(self) -> list[tuple[str, str]]:
        st = getattr(self._tls, "regions", None)
        if st is None:
            st = self._tls.regions = []
        return st

    def _hot_depth(self) -> int:
        return getattr(self._tls, "hot", 0)

    # ------------------------------------------------------------ record
    def note_cache_retrieval(self) -> None:
        # a persistent-cache hit still fires backend_compile_duration
        # immediately after cache_retrieval_time_sec on the same thread;
        # flag the thread so the next record_compile knows the executable
        # was LOADED, not compiled
        self._tls.cache_hit = True

    def record_compile(self, duration_s: float) -> None:
        cached = getattr(self._tls, "cache_hit", False)
        self._tls.cache_hit = False
        regions = self._regions()
        function, shape = regions[-1] if regions else (_UNATTRIBUTED, "")
        ms = float(duration_s) * 1000.0
        with self._mu:
            phase = self.phase
            self.xla_compiles += 1
            self.compile_ms_total += ms
            if cached:
                # loaded from the persistent compile-artifact cache: not a
                # real XLA compile, so it never counts as a steady-state
                # recompile — but a warmup-phase load still populates its
                # dispatch bucket, so it satisfies the warmup fence (the
                # shared chaos-matrix cache can legitimately serve EVERY
                # warmup bucket; only --preinstalled mode cares whether the
                # load was a hit, via preinstalled_warmup_misses)
                self.compile_cache_hits += 1
                if phase == "warmup":
                    self.warmup_compiles += 1
            elif phase == "warmup":
                self.warmup_compiles += 1
                if self.preinstalled and function != _UNATTRIBUTED:
                    # pre-installed artifacts promised this bucket would
                    # load, not compile — a miss is the cold start the
                    # artifact path exists to eliminate
                    self.preinstalled_warmup_misses += 1
            elif function != _UNATTRIBUTED:
                # only region-attributed compiles gate: the serving path
                # owns its dispatch buckets, not the client-side jnp work
                # that may share a test process
                self.steady_state_recompiles += 1
            if len(self.compiles) < _MAX_COMPILES:
                self.compiles.append({
                    "function": function,
                    "shape": shape,
                    "compile_ms": round(ms, 3),
                    "phase": phase,
                    "cached": cached,
                })

    def record_host_sync(self, tag: str) -> None:
        hot = self._hot_depth() > 0
        with self._mu:
            self.host_syncs[tag] = self.host_syncs.get(tag, 0) + 1
            if hot:
                self.host_syncs_hot_path += 1

    def note_warmup_failure(self) -> None:
        with self._mu:
            self.warmup_failures += 1

    def mark_preinstalled(self) -> None:
        with self._mu:
            self.preinstalled = True

    # ------------------------------------------------------------- phase
    def set_phase(self, phase: str) -> None:
        with self._mu:
            self.phase = phase

    def fence(self) -> None:
        with self._mu:
            self.phase = "steady"
            self.fenced = True

    # ------------------------------------------------------------ reading
    def snapshot(self) -> dict:
        with self._mu:
            return {
                "compiles": [dict(c) for c in self.compiles],
                "xla_compiles": self.xla_compiles,
                "compile_ms_total": round(self.compile_ms_total, 3),
                "warmup_compiles": self.warmup_compiles,
                "steady_state_recompiles": self.steady_state_recompiles,
                "compile_cache_hits": self.compile_cache_hits,
                "preinstalled": self.preinstalled,
                "preinstalled_warmup_misses": self.preinstalled_warmup_misses,
                "warmup_failures": self.warmup_failures,
                "warmup_degraded": bool(self.warmup_failures),
                "host_syncs": dict(self.host_syncs),
                "host_syncs_hot_path": self.host_syncs_hot_path,
                "fenced": self.fenced,
            }

    def reset(self) -> None:
        with self._mu:
            self.compiles.clear()
            self.xla_compiles = 0
            self.compile_ms_total = 0.0
            self.warmup_compiles = 0
            self.steady_state_recompiles = 0
            self.compile_cache_hits = 0
            self.preinstalled = False
            self.preinstalled_warmup_misses = 0
            self.warmup_failures = 0
            self.host_syncs.clear()
            self.host_syncs_hot_path = 0
            self.phase = "warmup"
            self.fenced = False
        # the CALLING thread's context only (other threads' region stacks
        # are theirs to unwind) — a harness that leaked a region would
        # otherwise misattribute every later compile
        self._regions().clear()
        self._tls.hot = 0
        self._tls.cache_hit = False


_witness = _Witness()
_installed = False
_atexit_registered = False


def enabled() -> bool:
    return bool(env.get("BBTPU_JITWATCH"))


def install() -> bool:
    """Register the XLA compile listener (idempotent; no-op when the
    switch is off). Called by BlockServer/bench startup — the listener
    is process-global and permanent, so the callback re-checks
    :func:`enabled` per event to honor env flips in tests."""
    global _installed, _atexit_registered
    if not enabled():
        return False
    if not _atexit_registered:
        _atexit_registered = True
        if env.get("BBTPU_JITWATCH_REPORT"):
            atexit.register(flush)
    if _installed:
        return True
    try:
        from jax import monitoring
    except Exception:  # jax-free analysis/CLI contexts: witness stays off
        return False

    def _on_event(event: str, duration_s: float, **kwargs) -> None:
        if not enabled():
            return
        # a persistent-cache hit emits cache_retrieval_time_sec and THEN
        # backend_compile_duration for the same executable on the same
        # thread — note the retrieval first so the compile record can
        # tell a cache load from a true XLA compile
        if "cache_retrieval" in event:
            _witness.note_cache_retrieval()
        # one jit call can emit several backend_compile events (aux
        # computations); each is a real XLA compile, ledger them all
        elif "backend_compile" in event:
            _witness.record_compile(duration_s)

    monitoring.register_event_duration_secs_listener(_on_event)
    _installed = True
    return True


# ------------------------------------------------------------ attribution
class _Region:
    """Thread-local attribution frame for one dispatch: compiles fired
    while entered are pinned to (function, shape_signature)."""

    __slots__ = ("_function", "_shape", "_on")

    def __init__(self, function: str, shape: str):
        self._function = function
        self._shape = shape
        self._on = enabled()

    def __enter__(self):
        if self._on:
            _witness._regions().append((self._function, self._shape))
        return self

    def __exit__(self, *exc) -> None:
        if self._on:
            st = _witness._regions()
            if st:
                st.pop()


def region(function: str, shape: str) -> _Region:
    """Wrap one jit dispatch: ``with jitwatch.region("span_step",
    "b2,t1,p64"): ...``. Cheap no-op frame when the witness is off."""
    return _Region(function, shape)


def hot_wrap(fn):
    """Mark `fn` as compute-queue hot-path work: host syncs recorded
    while it runs count as ``host_syncs_hot_path``. Returns `fn`
    unchanged when the witness is off (zero-overhead contract)."""
    if not enabled():
        return fn

    def _hot(*args, **kwargs):
        _witness._tls.hot = _witness._hot_depth() + 1
        try:
            return fn(*args, **kwargs)
        finally:
            _witness._tls.hot = _witness._hot_depth() - 1

    return _hot


def host_sync(tag: str) -> None:
    """Record one device→host sync at an instrumented site (the BB011
    sites that survive triage call this next to the transfer)."""
    if enabled():
        _witness.record_host_sync(tag)


# ------------------------------------------------------------------ phase
def set_phase(phase: str) -> None:
    """Re-open a phase (BlockServer.warmup sets "warmup" so re-entrant
    warmups — e.g. after elastic rebalance — ledger under warmup)."""
    if enabled():
        _witness.set_phase(phase)


def fence() -> None:
    """Drop the warmup fence: every region-attributed compile after this
    is a steady-state recompile and fails the --require gate."""
    if enabled():
        _witness.fence()


def note_warmup_failure() -> None:
    """Record one swallowed per-bucket warmup failure: the fence still
    drops, but the report carries ``warmup_degraded`` so a zero-recompile
    green can't mask buckets that never warmed."""
    if enabled():
        _witness.note_warmup_failure()


def mark_preinstalled() -> None:
    """Declare that compile artifacts were pre-installed before warmup:
    from here on, any non-cached region-attributed warmup compile is a
    ``preinstalled_warmup_miss`` and fails ``--require --preinstalled``."""
    if enabled():
        _witness.mark_preinstalled()


# -------------------------------------------------------------- reporting
def counters() -> dict:
    """Live counter group for rpc_info / health --probe."""
    snap = _witness.snapshot()
    return {
        "xla_compiles": snap["xla_compiles"],
        "compile_ms_total": snap["compile_ms_total"],
        "warmup_compiles": snap["warmup_compiles"],
        "steady_state_recompiles": snap["steady_state_recompiles"],
        "compile_cache_hits": snap["compile_cache_hits"],
        "preinstalled_warmup_misses": snap["preinstalled_warmup_misses"],
        "host_syncs_hot_path": snap["host_syncs_hot_path"],
    }


def snapshot() -> dict:
    return _witness.snapshot()


def reset() -> None:
    _witness.reset()


def flush(path: str | None = None) -> None:
    """Append this process's witness report as one JSON line (atexit
    hook; callable directly by harnesses)."""
    path = path or env.get("BBTPU_JITWATCH_REPORT")
    if not path:
        return
    snap = _witness.snapshot()
    if not snap["xla_compiles"] and not snap["host_syncs"]:
        return
    try:
        with open(path, "a") as f:
            f.write(json.dumps(snap, sort_keys=True) + "\n")
    except OSError:  # the witness must never take down the run it audits
        pass


def merge_lines(text: str) -> dict:
    """Merge a multi-process report file into one compile/sync ledger."""
    merged = {
        "compiles": [],
        "xla_compiles": 0,
        "compile_ms_total": 0.0,
        "warmup_compiles": 0,
        "steady_state_recompiles": 0,
        "compile_cache_hits": 0,
        "preinstalled": False,
        "preinstalled_warmup_misses": 0,
        "warmup_failures": 0,
        "warmup_degraded": False,
        "host_syncs": {},
        "host_syncs_hot_path": 0,
        "fenced": False,
    }
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            snap = json.loads(line)
        except ValueError:
            continue
        merged["compiles"].extend(snap.get("compiles") or [])
        for key in ("xla_compiles", "warmup_compiles",
                    "steady_state_recompiles", "compile_cache_hits",
                    "preinstalled_warmup_misses", "warmup_failures",
                    "host_syncs_hot_path"):
            merged[key] += int(snap.get(key) or 0)
        merged["compile_ms_total"] += float(snap.get("compile_ms_total") or 0)
        for tag, n in (snap.get("host_syncs") or {}).items():
            merged["host_syncs"][tag] = (
                merged["host_syncs"].get(tag, 0) + int(n)
            )
        merged["fenced"] = merged["fenced"] or bool(snap.get("fenced"))
        merged["preinstalled"] = (
            merged["preinstalled"] or bool(snap.get("preinstalled"))
        )
    merged["compile_ms_total"] = round(merged["compile_ms_total"], 3)
    merged["warmup_degraded"] = bool(merged["warmup_failures"])
    return merged


def _main(argv=None) -> int:
    """``python -m bloombee_tpu.utils.jitwatch PATH [--require]``: merge
    and print a witness report; with --require, exit 1 unless the run
    observed >=1 warmup compile behind a dropped fence (proof the
    witness and the warmup both ran) with ZERO steady-state recompiles."""
    import argparse
    import sys

    ap = argparse.ArgumentParser(description=_main.__doc__)
    ap.add_argument("path")
    ap.add_argument("--require", action="store_true",
                    help="fail (exit 1) on zero compiles, a missing "
                         "warmup fence, any steady-state recompile, or a "
                         "degraded warmup (swallowed per-bucket failures)")
    ap.add_argument("--preinstalled", action="store_true",
                    help="with --require: expect a pre-installed "
                         "compile-artifact run — fail unless some process "
                         "marked itself preinstalled AND loaded >=1 "
                         "executable from the artifact cache AND showed "
                         "zero non-cached warmup compiles for its buckets")
    args = ap.parse_args(argv)
    try:
        with open(args.path) as f:
            text = f.read()
    except OSError:
        text = ""
    merged = merge_lines(text)
    steady = [c for c in merged["compiles"]
              if c.get("phase") == "steady"
              and c.get("function") != _UNATTRIBUTED]
    print(
        f"jitwatch: {merged['xla_compiles']} compile(s) "
        f"({merged['warmup_compiles']} warmup, "
        f"{merged['steady_state_recompiles']} steady-state, "
        f"{merged['compile_cache_hits']} cache hit(s)), "
        f"{merged['compile_ms_total']:.0f}ms total, "
        f"{merged['host_syncs_hot_path']} hot-path host sync(s), "
        f"fenced={merged['fenced']}, "
        f"preinstalled={merged['preinstalled']} "
        f"(misses={merged['preinstalled_warmup_misses']}), "
        f"warmup_failures={merged['warmup_failures']}"
    )
    for tag, n in sorted(merged["host_syncs"].items()):
        print(f"  sync {tag} x{n}")
    for c in steady:
        print(
            f"  STEADY RECOMPILE {c['function']}[{c['shape']}] "
            f"{c['compile_ms']}ms"
        )
    if args.require:
        if not merged["xla_compiles"]:
            print(
                "jitwatch: EMPTY — a witness-enabled run must observe "
                ">=1 XLA compile; a run that compiled nothing validated "
                "nothing", file=sys.stderr,
            )
            return 1
        if args.preinstalled:
            # pre-installed mode: warmup may legitimately compile NOTHING
            # (everything loads from the artifact cache), so the vacuity
            # proof shifts from warmup compiles to cache hits
            if not merged["preinstalled"]:
                print(
                    "jitwatch: NOT PREINSTALLED — no process marked "
                    "compile artifacts as pre-installed, so the "
                    "zero-cold-start claim was never put to the test",
                    file=sys.stderr,
                )
                return 1
            if not merged["compile_cache_hits"]:
                print(
                    "jitwatch: NO CACHE HITS — a pre-installed run loaded "
                    "zero executables from the artifact cache; the "
                    "artifacts installed were never exercised",
                    file=sys.stderr,
                )
                return 1
            if not merged["fenced"]:
                print(
                    "jitwatch: NO WARMUP FENCE — the pre-installed run "
                    "never fenced, so its steady window never opened",
                    file=sys.stderr,
                )
                return 1
            if merged["preinstalled_warmup_misses"]:
                print(
                    "jitwatch: preinstalled warmup miss(es) — a promoted "
                    "replica with pre-installed artifacts still compiled "
                    "during warmup; the artifact for that (function, "
                    "bucket) was missing, stale, or declined",
                    file=sys.stderr,
                )
                return 1
        elif not merged["fenced"] or not merged["warmup_compiles"]:
            print(
                "jitwatch: NO WARMUP FENCE — no process dropped the "
                "warmup fence after >=1 warmup compile, so the "
                "steady-state window never opened and 'zero recompiles' "
                "is vacuous", file=sys.stderr,
            )
            return 1
        if merged["steady_state_recompiles"]:
            print(
                "jitwatch: steady-state recompile(s) observed — a decode "
                "bucket escaped BlockServer.warmup or a shape escaped its "
                "pow2 bucketer (BB012); the ledger above names the "
                "(function, shape) to pre-compile", file=sys.stderr,
            )
            return 1
        if merged["warmup_degraded"]:
            print(
                "jitwatch: DEGRADED WARMUP — per-bucket warmup failures "
                "were swallowed (warmup_failures="
                f"{merged['warmup_failures']}); the fence dropped over "
                "buckets that never warmed, so this green is hollow",
                file=sys.stderr,
            )
            return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
