"""Load-aware routing robustness: the predicted-queue-delay edge term must
keep Dijkstra valid under ARBITRARY advert garbage (NaN, negatives, wrong
types, hostile values), stay monotone in reported load (an advert can only
repel traffic from its own server, never capture traffic for it), and
decay with staleness. Plus the overload penalty class: shorter than fault
bans, retry_after-floored, cleared by success, and respected by standby
selection.

Pure routing-layer tests (registry=None, spans injected) — no servers, no
jax compute.
"""

import math
import random
import time

from bloombee_tpu.client.sequence_manager import (
    LOAD_DELAY_CAP_S,
    LOAD_STALE_S,
    RemoteSequenceManager,
    predicted_queue_delay_s,
)
from bloombee_tpu.swarm.data import RemoteSpanInfo, ServerInfo


def _span(peer_id, start, end, **info_kw):
    info_kw.setdefault("host", "127.0.0.1")
    info_kw.setdefault("port", 7000 + hash(peer_id) % 100)
    info_kw.setdefault("throughput", 10.0)
    info_kw.setdefault("inference_rps", 10.0)
    return RemoteSpanInfo(
        peer_id, start, end,
        ServerInfo(start_block=start, end_block=end, **info_kw),
    )


def _manager(num_blocks=2, **kw):
    kw.setdefault("overload_timeout", 0.2)
    kw.setdefault("overload_max", 1.0)
    kw.setdefault("rng", random.Random(0))
    return RemoteSequenceManager(None, "uid", num_blocks, **kw)


# --------------------------------------------------- cost-term properties
GARBAGE_LOADS = [
    None,
    "not a dict",
    42,
    {},
    {"delay_ms": float("nan")},
    {"delay_ms": float("inf")},
    {"delay_ms": -1e12},
    {"delay_ms": "elephant"},
    {"delay_ms": 1e300, "queue_depth": 1e300},
    {"queue_depth": float("nan"), "wait_ms": "nope"},
    {"wait_ms": {"p95": float("inf"), "p50": None}},
    {"decode_wait_ms": {"p95": -5.0}},
    {"ts": float("nan"), "delay_ms": 500.0},
    {"ts": "yesterday", "delay_ms": 500.0},
    {"ts": -1e18, "delay_ms": 500.0},
    {"ts": 1e18, "delay_ms": 500.0},  # advert from the future
    {"shedding": "maybe", "delay_ms": {}},
    {"delay_ms": [1, 2, 3], "queue_depth": {"a": 1}},
]


def test_predicted_delay_finite_bounded_for_any_garbage():
    """No advert value may produce a negative, NaN, infinite, or
    above-cap cost term — the Dijkstra validity invariant."""
    now = time.time()
    for load in GARBAGE_LOADS:
        info = ServerInfo(load=load)
        d = predicted_queue_delay_s(info, now=now)
        assert math.isfinite(d), load
        assert 0.0 <= d <= LOAD_DELAY_CAP_S, (load, d)


def test_predicted_delay_monotone_in_load():
    """More reported load never lowers the cost term, for each signal the
    term reads — so a server cannot advertise its way into MORE traffic."""
    now = time.time()

    def term(**load):
        load.setdefault("ts", now)
        return predicted_queue_delay_s(ServerInfo(load=load), now=now)

    for key in ("delay_ms", "queue_depth"):
        prev = -1.0
        for v in (0, 1, 10, 100, 1000, 10000, 1e9):
            cur = term(**{key: v})
            assert cur >= prev, (key, v)
            prev = cur
    prev = -1.0
    for p95 in (0, 5, 50, 500, 5000):
        cur = term(wait_ms={"p95": p95})
        assert cur >= prev
        prev = cur
    assert term(delay_ms=100.0, shedding=True) > term(delay_ms=100.0)
    # the floor IS the no-advert baseline: garbage collapses to it
    assert term() == predicted_queue_delay_s(ServerInfo(load=None))


def test_predicted_delay_staleness_decay():
    now = time.time()
    fresh = ServerInfo(load={"ts": now, "delay_ms": 2000.0})
    mid = ServerInfo(load={"ts": now - LOAD_STALE_S / 2, "delay_ms": 2000.0})
    stale = ServerInfo(load={"ts": now - 2 * LOAD_STALE_S, "delay_ms": 2000.0})
    d_fresh = predicted_queue_delay_s(fresh, now=now)
    d_mid = predicted_queue_delay_s(mid, now=now)
    d_stale = predicted_queue_delay_s(stale, now=now)
    assert d_fresh > d_mid > d_stale == 0.0
    # registry fallback stamp is honored when the advert has no usable ts
    info = ServerInfo(load={"delay_ms": 2000.0, "ts": "garbage"})
    info.advert_stored_at = now - 2 * LOAD_STALE_S
    assert predicted_queue_delay_s(info, now=now) == 0.0


def test_hostile_advert_cannot_capture_traffic():
    """A server advertising impossibly-good load (negative delay, NaN) gets
    exactly the no-advert baseline cost — it cannot undercut an honest
    idle server; and its own hostile-HIGH advert only repels itself."""
    m = _manager()
    honest = _span("honest", 0, 2)
    for load in GARBAGE_LOADS:
        liar = _span("liar", 0, 2, load=load)
        assert (
            m._compute_cost(liar, 2, None)
            >= m._compute_cost(honest, 2, None) - 1e-12
        ), load
    # an honestly-hot server loses the route to the idle one
    hot = _span("hot", 0, 2,
                load={"ts": time.time(), "delay_ms": 3000.0})
    m.spans = {"hot": hot, "idle": _span("idle", 0, 2)}
    for _ in range(5):
        assert [s.peer_id for s in m.make_sequence()] == ["idle"]


def test_load_aware_off_ignores_adverts():
    m = _manager(load_aware=False)
    hot = _span("hot", 0, 2, load={"ts": time.time(), "delay_ms": 9e9})
    assert m._compute_cost(hot, 2, None) == m._compute_cost(
        _span("idle", 0, 2), 2, None
    )


# ------------------------------------------------- overload penalty class
def test_overload_penalty_excludes_then_readmits():
    # hand-stepped clock: the backoff expiry is a pure state transition,
    # no real waiting needed
    from bloombee_tpu.utils import clock
    from bloombee_tpu.utils.clock import SteppableClock

    c = SteppableClock()
    prev = clock.install(c)
    try:
        m = _manager(overload_timeout=0.05, overload_max=0.1)
        m.spans = {"a": _span("a", 0, 2), "b": _span("b", 0, 2)}
        m.note_peer_overloaded("a")
        route = m.make_sequence()
        assert [s.peer_id for s in route] == ["b"]
        c.advance(0.15)
        # expired: the peer is routable again (half-open probe)
        assert not m._ban_excludes("a", clock.monotonic())
    finally:
        clock.install(prev)


def test_overload_is_shorter_class_than_fault_ban():
    """Same strike count: the overload backoff must cap far below the
    fault-ban cap, and a shed must never touch the fault-ban map."""
    m = _manager(ban_timeout=15.0, ban_max=120.0,
                 overload_timeout=2.0, overload_max=15.0)
    for _ in range(10):
        m.note_peer_overloaded("a")
    assert "a" not in m._bans
    assert m._hot["a"].banned_until - time.monotonic() <= 15.0 * 1.25 + 0.01
    m2 = _manager(ban_timeout=15.0, ban_max=120.0)
    for _ in range(10):
        m2.ban_peer("a")
    fault_left = m2._bans["a"].banned_until - time.monotonic()
    hot_left = m._hot["a"].banned_until - time.monotonic()
    assert hot_left < fault_left


def test_retry_after_hint_floors_backoff():
    m = _manager(overload_timeout=0.01, overload_max=60.0)
    m.note_peer_overloaded("a", retry_after_s=5.0)
    left = m._hot["a"].banned_until - time.monotonic()
    assert left >= 5.0 * 0.75 - 0.01  # hint floor, with jitter


def test_success_clears_overload_history():
    m = _manager()
    m.note_peer_overloaded("a")
    m.note_peer_ok("a")
    assert "a" not in m._hot
    assert not m._ban_excludes("a", time.monotonic())


def test_pick_standby_avoids_hot_peers():
    m = _manager()
    primary = _span("primary", 0, 2, kv_repl=True, page_size=4)
    cool = _span("cool", 0, 2, kv_repl=True, page_size=4,
                 inference_rps=1.0, throughput=1.0)
    fast_but_hot = _span("hot", 0, 2, kv_repl=True, page_size=4,
                         inference_rps=100.0, throughput=100.0)
    m.spans = {s.peer_id: s for s in (primary, cool, fast_but_hot)}
    # without overload state the faster standby wins
    assert m.pick_standby(primary).peer_id == "hot"
    m.note_peer_overloaded("hot")
    assert m.pick_standby(primary).peer_id == "cool"
    # when EVERY candidate is hot, degrade to the best hot one rather
    # than losing replication entirely
    m.note_peer_overloaded("cool")
    assert m.pick_standby(primary) is not None


def test_pick_standby_discounts_advertised_load():
    m = _manager()
    primary = _span("primary", 0, 2, kv_repl=True, page_size=4)
    busy = _span("busy", 0, 2, kv_repl=True, page_size=4,
                 inference_rps=10.0, throughput=10.0,
                 load={"ts": time.time(), "delay_ms": 5000.0})
    idle = _span("idle", 0, 2, kv_repl=True, page_size=4,
                 inference_rps=9.0, throughput=9.0)
    m.spans = {s.peer_id: s for s in (primary, busy, idle)}
    # near-equal throughput: the advertised 5s queue pushes `busy` below
    assert m.pick_standby(primary).peer_id == "idle"
