"""End-to-end swarm tests: registry + block servers + client generate.

Port of the reference's live-swarm tier (/root/reference/tests/
test_full_model.py — full logits/token parity vs a local HF model — and the
fault-tolerance behavior of inference_session re-routing). Multi-node is
simulated as multiple in-process servers on loopback, like the reference's
multi-process single-host harness.
"""

import asyncio

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from bloombee_tpu.client.model import DistributedModelForCausalLM
from bloombee_tpu.server.block_server import BlockServer
from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer


@pytest.fixture(scope="module")
def tiny_model_dir(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64,
        intermediate_size=128,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_hidden_layers=3,
        vocab_size=128,
        max_position_embeddings=256,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    d = tmp_path_factory.mktemp("tiny_llama")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model, config


def _server(model_dir, registry, start, end, **kw):
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("num_pages", 64)
    kw.setdefault("page_size", 4)
    return BlockServer(
        model_uid="tiny", start=start, end=end, model_dir=model_dir,
        registry=registry, **kw,
    )


def _hf_greedy(model, input_ids, max_new_tokens):
    with torch.no_grad():
        out = model.generate(
            torch.tensor(input_ids), max_new_tokens=max_new_tokens,
            do_sample=False, use_cache=True,
        )
    return out.numpy()


@pytest.mark.parametrize("use_push", [False, True])
def test_two_server_generate_matches_hf(tiny_model_dir, use_push):
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        reg_client = RegistryClient("127.0.0.1", reg.port)
        s1 = _server(model_dir, RegistryClient("127.0.0.1", reg.port), 0, 2)
        s2 = _server(model_dir, RegistryClient("127.0.0.1", reg.port), 2, 3)
        await s1.start()
        await s2.start()

        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, reg_client, model_uid="tiny", use_push=use_push
        )
        rng = np.random.default_rng(0)
        input_ids = rng.integers(0, config.vocab_size, size=(2, 6))
        ids = await model.generate(input_ids, max_new_tokens=8)
        ref = _hf_greedy(hf_model, input_ids, 8)
        np.testing.assert_array_equal(ids, ref)

        await s1.stop()
        await s2.stop()
        await reg_client.close()
        await reg.stop()

    asyncio.run(run())


def test_logits_parity_full_chain(tiny_model_dir):
    """Per-position logits parity vs HF full forward (reference
    test_full_model.py atol 1e-3)."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s1 = _server(model_dir, RegistryClient("127.0.0.1", reg.port), 0, 3)
        await s1.start()

        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, RegistryClient("127.0.0.1", reg.port), model_uid="tiny"
        )
        input_ids = np.arange(10)[None, :] % config.vocab_size
        async with model.inference_session(16, 1) as sess:
            hidden = model.embed(input_ids)
            out = await sess.step(hidden)
        logits = model.logits(out)
        with torch.no_grad():
            ref = hf_model(torch.tensor(input_ids)).logits.numpy()
        np.testing.assert_allclose(logits, ref, atol=1e-3, rtol=1e-3)

        await s1.stop()
        await reg.stop()

    asyncio.run(run())


def test_bf16_wire_logits_close(tiny_model_dir):
    """bf16-compute servers advertise wire_dtype=bf16; hidden states ship
    bf16 both directions (half the decode payload) and logits stay close to
    the fp32 HF reference (ADVICE round-1: fp32-on-the-wire fix)."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s1 = _server(model_dir, rc(), 0, 2, compute_dtype=jnp.bfloat16)
        s2 = _server(model_dir, rc(), 2, 3, compute_dtype=jnp.bfloat16)
        await s1.start()
        await s2.start()

        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny", use_push=False
        )
        input_ids = np.arange(10)[None, :] % config.vocab_size
        async with model.inference_session(16, 1) as sess:
            assert all(
                s.span.server_info.wire_dtype == "bf16" for s in sess._spans
            )
            hidden = model.embed(input_ids)
            out = await sess.step(hidden)
        assert out.dtype == np.float32  # client edge upcasts
        logits = model.logits(out)
        with torch.no_grad():
            ref = hf_model(torch.tensor(input_ids)).logits.numpy()
        # bf16 has an 8-bit mantissa: loose tolerance, but the argmax chain
        # through 3 blocks must still agree for most positions
        np.testing.assert_allclose(logits, ref, atol=0.3, rtol=0.1)

        await s1.stop()
        await s2.stop()
        await reg.stop()

    asyncio.run(run())


def test_overlapping_spans_suffix_entry(tiny_model_dir):
    """Overlapping spans A=[0,2) and B=[1,3): the router enters B mid-span
    (suffix sub-span) and the server must run only the requested layers
    (reference: spans_containing_block partial-span usage)."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s_a = _server(model_dir, rc(), 0, 2)
        s_b = _server(model_dir, rc(), 1, 3)
        await s_a.start()
        await s_b.start()

        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny", use_push=False
        )
        input_ids = np.arange(7)[None, :] % config.vocab_size
        session = model.inference_session(24, 1)
        await session.__aenter__()
        spans = [(s.span.start, s.span.end) for s in session._spans]
        assert spans == [(0, 2), (2, 3)], spans  # B entered at its 2nd layer
        ids = await model.generate(input_ids, max_new_tokens=6, session=session)
        await session.__aexit__(None, None, None)
        ref = _hf_greedy(hf_model, input_ids, 6)
        np.testing.assert_array_equal(ids, ref)

        await s_a.stop()
        await s_b.stop()
        await reg.stop()

    asyncio.run(run())


def test_failover_rereoute_and_replay(tiny_model_dir):
    """Kill the preferred server mid-generation; the session re-routes to the
    backup, replays history, and produces identical tokens
    (reference inference_session._update_sequence semantics)."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s_a = _server(model_dir, rc(), 0, 2, throughput=10.0)
        s_b = _server(model_dir, rc(), 2, 3, throughput=10.0)  # preferred
        s_c = _server(model_dir, rc(), 2, 3, throughput=1.0)  # backup
        for s in (s_a, s_b, s_c):
            await s.start()

        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny", use_push=False
        )
        input_ids = np.arange(5)[None, :] % config.vocab_size
        ref = _hf_greedy(hf_model, input_ids, 6)

        session = model.inference_session(16, 1)
        await session.__aenter__()
        used = {s.span.server_info.port for s in session._spans}
        assert s_b.port in used and s_c.port not in used

        ids = await model.generate(
            input_ids, max_new_tokens=3, session=session
        )
        await s_b.stop()  # preferred server dies mid-session
        more = await model.generate(
            ids[:, -1:], max_new_tokens=2, session=session
        )
        final = np.concatenate([ids, more[:, 1:]], axis=1)
        np.testing.assert_array_equal(final, ref[:, : final.shape[1]])

        await session.__aexit__(None, None, None)
        for s in (s_a, s_c):
            await s.stop()
        await reg.stop()

    asyncio.run(run())


def test_abandoned_client_does_not_leak_pages(tiny_model_dir):
    """Session-leak gate: a client that vanishes mid-generation without
    closing (no FIN — its conns just go silent) must not pin KV pages
    forever. With leases + keepalives on, pages_free returns to the
    pre-session level within roughly one lease period."""
    model_dir, _, config = tiny_model_dir

    async def run():
        from bloombee_tpu.wire.faults import FaultPlan

        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s1 = _server(
            model_dir, rc(), 0, 3, session_lease_s=1.0, keepalive_s=0.2,
        )
        await s1.start()
        free0 = s1.manager.table.free_pages

        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny", use_push=False
        )
        input_ids = np.arange(8)[None, :] % config.vocab_size
        session = model.inference_session(24, 1)
        await session.__aenter__()
        await model.generate(input_ids, max_new_tokens=3, session=session)
        assert s1.manager.table.free_pages < free0
        # the client is abandoned: blackhole its conns (a conn consults
        # the fault plan it captured at creation, so arm them directly)
        # and never call __aexit__
        for sp in session._spans:
            sp.conn.fault_plan = FaultPlan()
            sp.conn._bbtpu_partitioned = True

        deadline = asyncio.get_event_loop().time() + 6.0
        while asyncio.get_event_loop().time() < deadline:
            if (
                s1.manager.table.free_pages >= free0
                and not s1._sessions
            ):
                break
            await asyncio.sleep(0.1)
        assert s1.manager.table.free_pages >= free0, (
            s1.manager.table.free_pages, free0,
        )
        assert not s1._sessions
        assert s1.sessions_reaped == 1

        await s1.stop()
        await reg.stop()

    asyncio.run(run())


def test_feature_combo_int4_microbatch_push(tiny_model_dir):
    """Cross-feature interaction: int4 KV arena + within-stage micro-batching
    + push-mode pipelining in one 2-server chain — generation stays coherent
    and deterministic."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s1 = _server(model_dir, rc(), 0, 2, kv_quant="int4")
        s2 = _server(model_dir, rc(), 2, 3, kv_quant="int4")
        await s1.start()
        await s2.start()

        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny", use_push=True
        )
        rng = np.random.default_rng(4)
        input_ids = rng.integers(0, config.vocab_size, size=(4, 6))
        sess = model.inference_session(24, 4, microbatch=2)
        await sess.__aenter__()
        a = await model.generate(input_ids, max_new_tokens=6, session=sess)
        await sess.__aexit__(None, None, None)
        sess2 = model.inference_session(24, 4, microbatch=2)
        await sess2.__aenter__()
        b = await model.generate(input_ids, max_new_tokens=6, session=sess2)
        await sess2.__aexit__(None, None, None)
        np.testing.assert_array_equal(a, b)  # deterministic under the combo
        assert a.shape == (4, 12)
        # int4 KV drifts logits slightly; GENERATED tokens (prompt columns
        # excluded — they match by construction) still broadly agree with
        # the fp32 HF chain on a short horizon
        ref = _hf_greedy(hf_model, input_ids, 6)
        s = input_ids.shape[1]
        assert (a[:, s:] == ref[:, s:]).mean() > 0.5

        await s1.stop()
        await s2.stop()
        await reg.stop()

    asyncio.run(run())
