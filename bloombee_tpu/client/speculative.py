"""Speculative generation over the swarm (batched).

Port of the reference's DistributedLlamaForSpeculativeGeneration.generate
loop (/root/reference/src/bloombee/models/llama/speculative_model.py:33-117):
draft per-sample trees, verify every row's linearized tree in ONE distributed
step (tree mask + depth positions, KV written speculatively), accept a path
per row, and tell the servers which speculative slots survive per row (they
compact + commit on device). Greedy mode is token-exact with plain greedy
decode.

Batching: all rows share the drafter's static branching, so every row's tree
has identical structure (parents/depths/mask) — only tokens differ. Rows
accept different counts per round; the paged cache tracks per-row lengths
natively and history replay is by ragged token ids.

Round structure: every round's tree has node 0 = the bonus token from the
previous round (certain, always accepted) with the drafter's tree hanging
under it — so the certain token's KV is written in the same step as the
drafts, and the accept metadata rides the NEXT round's step (no extra RTT,
cf. the reference's set_kv_cache piggybacking).
"""

from __future__ import annotations

import numpy as np

from bloombee_tpu.client.model import DistributedModelForCausalLM
from bloombee_tpu.spec.drafter import GreedyTreeDrafter
from bloombee_tpu.spec.tree import DraftTree, tree_attention_mask
from bloombee_tpu.spec.verify import accept_greedy, accept_sampling


def _pick(
    logits: np.ndarray, do_sample: bool, temperature: float,
    rng: np.random.Generator,
) -> np.ndarray:
    """Per-row token choice: delegates to the model's selection logic so
    speculative and plain generate can never drift. [B, V] -> [B]."""
    return DistributedModelForCausalLM._select(
        logits, do_sample, temperature, 1.0, rng
    )


def _per_span_accepts(
    accepts: list, keep: np.ndarray, n_spans: int
) -> list:
    """Translate original-space accepts into each span's KV row space:
    span 0 saw the full tree; downstream spans hold KV in kept-row order
    (every accepted node is verifiable, hence present in keep)."""
    kept_space = []
    for i, acc in enumerate(accepts):
        pos = {int(orig): p for p, orig in enumerate(keep[i]) if orig >= 0}
        kept_space.append(np.asarray([pos[int(a)] for a in acc], np.int64))
    return [accepts] + [kept_space] * (n_spans - 1)


async def generate_speculative(
    model: DistributedModelForCausalLM,
    drafter: GreedyTreeDrafter,
    input_ids: np.ndarray,  # [B, S]
    max_new_tokens: int,
    session=None,
    prune_threshold: float | None = None,  # mid-chain pruning (relay mode)
    prune_max_keep: int | None = None,
    do_sample: bool = False,  # SpecInfer rejection sampling per row
    temperature: float = 1.0,
    seed: int = 0,
) -> np.ndarray:
    input_ids = np.asarray(input_ids)
    b, s = input_ids.shape
    tree_size = 1 + sum(
        int(np.prod(drafter.branching[: i + 1]))
        for i in range(len(drafter.branching))
    )
    max_length = s + max_new_tokens + (tree_size + 1) * 2  # tree spike room
    if do_sample and prune_threshold is not None:
        raise ValueError(
            "sampling accept needs real logits at every node; mid-chain "
            "pruning zeroes pruned rows — use one or the other"
        )
    rng = np.random.default_rng(seed)
    own = session is None
    if own:
        session = model.inference_session(max_length, b)
        await session.__aenter__()
    if session.embed_fn is None:
        raise ValueError(
            "speculative generation records token-id history; the session "
            "must be built with embed_fn (model.inference_session does)"
        )
    try:
        rows = [list(r) for r in input_ids]
        # prefill -> logits at each row's last prompt token
        out = await session.step(model.embed(input_ids), ids=input_ids)
        root_logits = np.array(model.logits(out[:, -1:])[:, 0])  # [B, V]
        bonus = _pick(root_logits, do_sample, temperature, rng)  # [B]
        new_rows = [[int(bonus[i])] for i in range(b)]
        pending_accept = None  # original-space accepts per row
        pending_spans = None  # per-span accepts for pruned chains

        while min(len(r) for r in new_rows) < max_new_tokens:
            # live-row window: finished rows at the batch edges stop
            # burning tree slots — the contiguous [lo, hi) window of
            # unfinished rows ships as a `rows` slice and the servers
            # slice their cache handle to match. Interior done rows
            # (live neighbors on both sides) still occupy a slot but
            # draft from a 1-token context so their drafter cost is nil
            # (their speculative writes roll back via empty accepts).
            # Pruned chains keep the full batch: per-span accept
            # translation is indexed in full-batch space.
            live = [
                i for i in range(b) if len(new_rows[i]) < max_new_tokens
            ]
            if prune_threshold is not None:
                lo, hi = 0, b
            else:
                lo, hi = live[0], live[-1] + 1
            w = hi - lo
            contexts = [
                (rows[i] + new_rows[i])
                if len(new_rows[i]) < max_new_tokens
                else [new_rows[i][-1]]
                for i in range(lo, hi)
            ]
            subs, _probs = drafter.build_batch(contexts)
            # per-row tree: node 0 = that row's last (certain) token, the
            # drafter's tree hanging under it; structure shared across rows
            toks = np.stack(
                [
                    np.concatenate([[new_rows[lo + j][-1]], subs[j].tokens])
                    for j in range(w)
                ]
            )  # [W, T]
            parents = np.concatenate(
                [[-1], np.where(subs[0].parents < 0, 0, subs[0].parents + 1)]
            ).astype(np.int32)
            tree0 = DraftTree(tokens=toks[0], parents=parents)
            t = tree0.size
            mask = np.broadcast_to(
                tree_attention_mask(tree0)[None], (w, t, t)
            )
            depths = np.broadcast_to(tree0.depths()[None], (w, t))

            h_tree = model.embed(toks)
            if prune_threshold is None:
                # recovery owner: the server-side accept/rollback protocol
                # settles speculative rows when the NEXT step's `accept`
                # arrives; a dead session is reaped by its lease
                out = await session.step(  # bbtpu: noqa[BB001]
                    h_tree,
                    commit=False,
                    tree_mask=mask,
                    depths=depths,
                    accept=pending_accept,
                    rows=None if (lo, hi) == (0, b) else (lo, hi),
                )
                logits = model.logits(out)  # [W, T, V]
                verifiable = None
            else:
                # mid-chain pruning: span 0 keeps only MidLMHead survivors;
                # downstream spans verify the smaller tree; restore maps
                # kept logits back to original node indices
                prune_meta = {
                    "threshold": float(prune_threshold),
                    "max_keep": int(prune_max_keep or t),
                    "tokens": toks.tolist(),
                    "parents": parents.tolist(),
                }
                # recovery owner: same accept/rollback protocol as above
                out_k, keep = await session.step(  # bbtpu: noqa[BB001]
                    h_tree,
                    commit=False,
                    tree_mask=mask,
                    depths=depths,
                    prune=prune_meta,
                    accept_per_span=pending_spans,
                )
                logits_k = model.logits(out_k)  # [B, K, V]
                if keep is None:  # pruning span had no pruner weight
                    logits = logits_k
                    verifiable = None
                    keep = np.broadcast_to(np.arange(t), (b, t))
                else:
                    logits = np.zeros((b, t, logits_k.shape[-1]), np.float32)
                    verifiable = np.zeros((b, t), dtype=bool)
                    for i in range(b):
                        valid = keep[i] >= 0
                        logits[i][keep[i][valid]] = logits_k[i][valid]
                        verifiable[i][keep[i][valid]] = True

            pending_accept = []
            committed_rows = []
            drafted_accepts = []  # acceptance feedback for tree shaping
            for i in range(b):
                room = max_new_tokens - len(new_rows[i])
                if room <= 0:
                    # row done: accept nothing (its speculative rows roll
                    # back) so its cache stays "all committed but the final
                    # bonus" while slow rows continue. Rows outside the
                    # shipped window had nothing drafted this round — the
                    # empty accept is a no-op on their (empty) spec region.
                    pending_accept.append(np.asarray([], dtype=np.int64))
                    committed_rows.append([])
                    continue
                j = i - lo  # this row's index in the shipped window
                if do_sample:
                    # SpecInfer rejection sampling over the drafter's
                    # sub-tree (node 0 is the committed bonus; targets at
                    # its children come from logits[0])
                    accepted_sub, nxt = accept_sampling(
                        subs[j], logits[j][0], logits[j][1:], _probs[j],
                        rng, temperature,
                    )
                    accepted = [0] + [a + 1 for a in accepted_sub]
                else:
                    tree_i = DraftTree(tokens=toks[j], parents=parents)
                    accepted, _ = accept_greedy(
                        tree_i, root_logits[i], logits[j],
                        verifiable=(
                            None if verifiable is None else verifiable[j]
                        ),
                    )
                assert accepted and accepted[0] == 0
                drafted_accepts.append(len(accepted) - 1)  # excl. node 0
                # cap so the row lands on EXACTLY max_new_tokens with its
                # last token an uncommitted bonus — the same resume contract
                # as plain generate (last returned token not yet stepped)
                full_len = len(accepted)
                accepted = accepted[: 1 + max(room - 1, 0)]
                if do_sample:
                    if len(accepted) < full_len:
                        # truncated: the discarded children were never
                        # rejected, so the bonus is a plain sample from the
                        # last kept node's target distribution
                        nxt = int(_pick(
                            logits[j][accepted[-1]][None], True,
                            temperature, rng,
                        )[0])
                else:
                    nxt = int(np.argmax(logits[j][accepted[-1]]))
                pending_accept.append(np.asarray(accepted))
                committed_rows.append([int(toks[j][a]) for a in accepted])
                root_logits[i] = logits[j][accepted[-1]]
                new_rows[i].extend(int(toks[j][a]) for a in accepted[1:])
                new_rows[i].append(nxt)
            # accepted nodes' token ids ARE the committed history
            session.record_history_ids(committed_rows)
            if (
                drafted_accepts
                and prune_threshold is None  # pruner-induced stops would
                # read as draft misses and bias shaping toward shallow trees
                and hasattr(drafter, "observe")
            ):
                drafter.observe(drafted_accepts)  # adaptive tree shaping
            if prune_threshold is not None:
                pending_spans = _per_span_accepts(
                    pending_accept, keep, len(session._spans)
                )

        if pending_accept is not None:
            await session.send_accept(pending_accept, per_span=pending_spans)
        # rows converged to exactly max_new_tokens; every returned token
        # except each row's final bonus is committed server-side
        return np.asarray([rows[i] + new_rows[i] for i in range(b)])
    finally:
        if own:
            await session.__aexit__(None, None, None)
