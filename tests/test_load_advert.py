"""Live load adverts: ServerInfo wire compatibility across mixed-version
swarms, the compute queue's live delay signal, and the end-to-end advert
path (BlockServer -> registry -> client manager).

The mixed-version tests pin the from_wire unknown-field-filtering contract
in BOTH directions: an old peer's advert (no `load`) must parse on a new
client, and a new peer's advert (with `load` and future fields) must parse
on an old client — otherwise rolling a swarm upgrade would partition it.
"""

import asyncio
import dataclasses
import time

import pytest
import torch

import jax.numpy as jnp

from bloombee_tpu.server.compute_queue import ComputeQueue
from bloombee_tpu.swarm.data import ServerInfo, ServerState


# ---------------------------------------------------------- wire compat
def _old_server_info_cls():
    """A replica of ServerInfo as it looked BEFORE the `load` field (and
    before any future field), with the same from_wire filtering — stands
    in for an old peer's parser in the new->old direction."""

    @dataclasses.dataclass
    class OldServerInfo:
        state: ServerState = ServerState.ONLINE
        host: str = ""
        port: int = 0
        version: str = "0.1.0"
        throughput: float = 1.0
        start_block: int | None = None
        end_block: int | None = None

        @classmethod
        def from_wire(cls, d):
            d = dict(d)
            d["state"] = ServerState(d.get("state", 2))
            known = {f.name for f in dataclasses.fields(cls)}
            return cls(**{k: v for k, v in d.items() if k in known})

    return OldServerInfo


def test_old_advert_parses_on_new_client():
    """old -> new: an advert with no `load` key (and with an unknown field
    from some OTHER future version) constructs cleanly; load stays None so
    routing adds no load term."""
    wire = {
        "state": 2, "host": "10.0.0.9", "port": 7801, "throughput": 3.0,
        "start_block": 0, "end_block": 4,
        "some_future_field": {"x": 1},  # must be dropped, not crash
    }
    info = ServerInfo.from_wire(wire)
    assert info.state is ServerState.ONLINE
    assert info.host == "10.0.0.9" and info.port == 7801
    assert info.load is None


def test_new_advert_parses_on_old_peer():
    """new -> old: a fully-populated new advert (load dict included) is
    filtered down to the old peer's known fields without error."""
    new = ServerInfo(
        host="10.0.0.2", port=7802, throughput=5.0,
        start_block=0, end_block=8,
        load={"ts": time.time(), "delay_ms": 120.0, "queue_depth": 3,
              "shedding": True},
    )
    old_cls = _old_server_info_cls()
    old = old_cls.from_wire(new.to_wire())
    assert old.host == "10.0.0.2" and old.port == 7802
    assert not hasattr(old, "load")


def test_load_round_trips_between_new_peers():
    load = {
        "ts": 123.0, "delay_ms": 42.5, "queue_depth": 2,
        "wait_ms": {"p50": 1.0, "p95": 9.0}, "mean_batch_width": 1.5,
        "chunk_streams": 0, "pages_free": 17, "active_sessions": 3,
        "shedding": False,
    }
    info = ServerInfo(host="h", port=1, load=load)
    back = ServerInfo.from_wire(info.to_wire())
    assert back.load == load


# ------------------------------------------------- live queue-delay signal
def test_current_delay_ms_idle_queue_is_zero():
    async def run():
        q = ComputeQueue()
        q.start()
        try:
            assert q.depth() == 0
            assert q.current_delay_ms() == 0.0
        finally:
            await q.stop()

    asyncio.run(run())


def test_current_delay_ms_sees_live_jam_and_recent_waits():
    async def run():
        import threading

        from bloombee_tpu.server.compute_queue import PRIORITY_INFERENCE

        q = ComputeQueue()
        q.start()
        try:
            gate = threading.Event()
            jam = asyncio.create_task(
                q.submit(PRIORITY_INFERENCE, gate.wait, 5.0)
            )
            await asyncio.sleep(0.05)  # the jam is on the worker thread
            waiter = asyncio.create_task(
                q.submit(PRIORITY_INFERENCE, lambda: None)
            )
            await asyncio.sleep(0.15)
            # the queued task has recorded NO wait sample yet — the live
            # signal must still see the jam via the stall term, and depth
            # must count the waiter
            assert q.depth() >= 1
            assert q.current_delay_ms() >= 100.0
            gate.set()
            await asyncio.gather(jam, waiter)
            # after the pop, the recorded wait sample keeps the signal warm
            assert q.current_delay_ms() >= 100.0
            # ...but only within the window: old samples age out
            assert q.current_delay_ms(window_s=1e-9) == 0.0
        finally:
            await q.stop()

    asyncio.run(run())


# -------------------------------------------------------- end-to-end advert
@pytest.fixture(scope="module")
def tiny_model_dir(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=3, vocab_size=128,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    d = tmp_path_factory.mktemp("tiny_llama_load")
    model.save_pretrained(d, safe_serialization=True)
    return str(d)


def test_load_advert_reaches_client_manager(tiny_model_dir):
    """A running server's announce publishes the load snapshot; the client
    manager's swarm view exposes it (plus the registry's writer-stamped
    staleness fallback) for the routing cost term."""
    from bloombee_tpu.client.sequence_manager import (
        RemoteSequenceManager,
        predicted_queue_delay_s,
    )
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        server = BlockServer(
            model_uid="tiny", start=0, end=3, model_dir=tiny_model_dir,
            registry=rc(), compute_dtype=jnp.float32, num_pages=16,
            page_size=4, announce_period=0.2, load_advert_s=0.1,
        )
        await server.start()
        try:
            snap = server.load_snapshot()
            for key in ("ts", "delay_ms", "queue_depth", "wait_ms",
                        "mean_batch_width", "chunk_streams", "pages_free",
                        "active_sessions", "shedding"):
                assert key in snap, key
            assert snap["pages_free"] == 16
            assert snap["active_sessions"] == 0

            await asyncio.sleep(0.5)
            manager = RemoteSequenceManager(rc(), "tiny", 3)
            await manager.update(force=True)
            info = manager.spans[server.server_id].server_info
            assert isinstance(info.load, dict)
            assert info.load["pages_free"] == 16
            # registry stamped its own receive time as staleness fallback
            assert getattr(info, "advert_stored_at", None) is not None
            # idle server: the predicted delay term is (near) zero, so the
            # advert does not repel traffic from a cold swarm
            assert predicted_queue_delay_s(info) < 0.1
        finally:
            await server.stop()
            await reg.stop()

    asyncio.run(run())


def test_load_advert_cadence_overrides_announce_period(tiny_model_dir):
    """load_advert_s faster than announce_period re-publishes the snapshot
    at the faster cadence (staleness window stays announce-based, so the
    extra announces only refresh the load view)."""
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        server = BlockServer(
            model_uid="tiny", start=0, end=3, model_dir=tiny_model_dir,
            registry=rc(), compute_dtype=jnp.float32, num_pages=16,
            page_size=4, announce_period=30.0, load_advert_s=0.1,
        )
        await server.start()
        try:
            infos = await rc().get_module_infos("tiny", range(3))
            ts0 = infos[0].servers[server.server_id].load["ts"]
            await asyncio.sleep(0.5)
            infos = await rc().get_module_infos("tiny", range(3))
            ts1 = infos[0].servers[server.server_id].load["ts"]
            # with announce_period=30 alone the snapshot could not have
            # refreshed inside half a second
            assert ts1 > ts0
        finally:
            await server.stop()
            await reg.stop()

    asyncio.run(run())
