"""ALiBi attention biases (Bloom family).

Matches HF Bloom's slope construction (powers of 2^(-8/n) with the
odd-head extension). The bias added to logits is slopes[h] * key_position —
equivalent to the distance form up to a per-row constant, which softmax
ignores.
"""

from __future__ import annotations

import math

import numpy as np


def alibi_slopes(n_heads: int) -> np.ndarray:
    closest = 2 ** math.floor(math.log2(n_heads))
    base = 2.0 ** (-(2.0 ** -(math.log2(closest) - 3)))
    slopes = [base ** (i + 1) for i in range(closest)]
    if closest != n_heads:
        extra_base = 2.0 ** (-(2.0 ** -(math.log2(2 * closest) - 3)))
        num_extra = min(closest, n_heads - closest)
        slopes.extend(extra_base ** (1 + 2 * i) for i in range(num_extra))
    return np.asarray(slopes, dtype=np.float32)
