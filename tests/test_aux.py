"""Aux subsystems: block selection, native codec parity, CLI smoke,
timing tables (ports of reference test coverage for block_selection,
lossless_transport internals, and cli/health-style checks)."""

import subprocess
import sys

import numpy as np

from bloombee_tpu.server.block_selection import (
    block_throughputs,
    choose_best_blocks,
    should_choose_other_blocks,
)
from bloombee_tpu.swarm.data import ModuleInfo, ServerInfo
from bloombee_tpu.swarm.spans import compute_spans


def _infos(num_blocks, spans):
    """spans: list of (server_id, start, end, throughput)."""
    infos = [ModuleInfo(uid=f"m.{i}", servers={}) for i in range(num_blocks)]
    for sid, start, end, tput in spans:
        info = ServerInfo(throughput=tput, start_block=start, end_block=end)
        for i in range(start, end):
            infos[i].servers[sid] = info
    return infos


def test_choose_best_blocks_picks_least_served():
    infos = _infos(8, [("A", 0, 4, 2.0), ("B", 2, 6, 1.0)])
    assert block_throughputs(infos).tolist() == [2, 2, 3, 3, 1, 1, 0, 0]
    start, end = choose_best_blocks(infos, compute_spans(infos), 3)
    assert (start, end) == (5, 8)


def test_should_choose_other_blocks_hysteresis():
    # A sits on a well-served region while blocks 4..8 are empty -> move
    infos = _infos(8, [("A", 0, 4, 1.0), ("B", 0, 4, 5.0)])
    spans = compute_spans(infos)
    assert should_choose_other_blocks("A", infos, spans)
    # balanced swarm -> stay (hysteresis)
    infos = _infos(4, [("A", 0, 2, 1.0), ("B", 2, 4, 1.0)])
    spans = compute_spans(infos)
    assert not should_choose_other_blocks("A", infos, spans)


def test_native_byte_split_parity():
    from bloombee_tpu.native import byte_split_lib
    from bloombee_tpu.wire.tensor_codec import _merge_planes, _split_planes

    rng = np.random.default_rng(0)
    raw = rng.integers(0, 255, size=(1 << 16,), dtype=np.uint8).tobytes()
    split = _split_planes(raw)
    # plane layout: low bytes then high bytes
    ref = np.frombuffer(raw, np.uint8).reshape(-1, 2).T.tobytes()
    assert split == ref
    assert _merge_planes(split) == raw
    # record which path ran so CI logs show it (both are correct)
    print("native lib:", "yes" if byte_split_lib() else "numpy fallback")


def test_cli_help_smoke():
    for mod in ("bloombee_tpu.cli.run_server", "bloombee_tpu.cli.run_registry"):
        out = subprocess.run(
            [sys.executable, "-m", mod, "--help"],
            capture_output=True, text=True, timeout=120,
        )
        assert out.returncode == 0, out.stderr
        assert "usage" in out.stdout.lower()


def test_chunked_head_matches_full():
    """Vocab-chunked LM head (low-RAM client path) is numerically identical
    to the one-shot head, including ragged last chunks and soft-capping."""
    import jax.numpy as jnp
    import numpy as np

    from bloombee_tpu.client.model import _norm_head, _norm_head_chunked

    rng = np.random.default_rng(0)
    d, v = 32, 1000  # v deliberately not a multiple of step
    params = {
        "norm": jnp.asarray(rng.normal(size=(d,)).astype(np.float32)),
        "lm_head": jnp.asarray(rng.normal(size=(d, v)).astype(np.float32)),
    }
    hidden = jnp.asarray(rng.normal(size=(2, 3, d)).astype(np.float32))
    for soft_cap in (0.0, 30.0):
        full = _norm_head(params, hidden, eps=1e-5, soft_cap=soft_cap)
        chunked = _norm_head_chunked(
            params, hidden, eps=1e-5, soft_cap=soft_cap, step=256
        )
        np.testing.assert_allclose(
            np.asarray(chunked), np.asarray(full), rtol=1e-6, atol=1e-6
        )


def test_memory_report_accounts_server_arrays(tmp_path):
    """The memory surface (reference utils/memory_usage.py role) must
    report exact framework-side byte counts and ride rpc_info."""
    import asyncio

    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    import jax.numpy as jnp

    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer
    from bloombee_tpu.utils.memory import (
        format_report,
        server_memory_report,
        tree_nbytes,
    )
    from bloombee_tpu.wire.rpc import connect

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=2, vocab_size=64,
        max_position_embeddings=128, tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    LlamaForCausalLM(config).eval().to(torch.float32).save_pretrained(
        tmp_path, safe_serialization=True
    )

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s = BlockServer(
            model_uid="t", start=0, end=2, model_dir=str(tmp_path),
            registry=RegistryClient("127.0.0.1", reg.port),
            compute_dtype=jnp.float32, num_pages=16, page_size=4,
        )
        await s.start()
        report = server_memory_report(s)
        # exact accounting: arena = L * (pages*page_size) * Hkv * hd
        # * 2 slabs * 4 bytes (fp32); hd = 64 hidden / 4 heads = 16
        assert report["kv_arena_bytes"] == 2 * (16 * 4) * 2 * 16 * 2 * 4
        assert report["span_params_bytes"] == tree_nbytes(s.executor.params)
        assert report["kv_tokens_capacity"] == 64
        assert "params=" in format_report(report)

        conn = await connect("127.0.0.1", s.port)
        info, _ = await conn.call("rpc_info", {}, [])
        await conn.close()
        assert info["memory"]["kv_arena_bytes"] == report["kv_arena_bytes"]

        await s.stop()
        await reg.stop()

    asyncio.run(run())
