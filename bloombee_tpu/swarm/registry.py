"""Registry service: the swarm's discovery plane.

Role of the reference's hivemind DHT + declare_active_modules /
get_remote_module_infos (/root/reference/src/bloombee/utils/dht.py:28-117):
servers periodically store `{uid}.{block}` -> {server_id: (info, expiry)};
records expire, and expiry IS the failure detector (a dead server's records
vanish after `expiration` seconds — reference server.py:957-992). Clients
fetch many uids at once to build the routing table.

Deployment: one `RegistryServer` runs as the bootstrap node (the reference's
`run_dht` role, cli/run_dht.py). `InProcessRegistry` backs single-process
tests. The registry speaks the normal wire RPC so any peer can also proxy it.
"""

from __future__ import annotations

import asyncio
import json
import os
import time

from bloombee_tpu.swarm.data import ModuleInfo, ServerInfo
from bloombee_tpu.wire.rpc import Connection, RpcServer, connect


class _Store:
    def __init__(self):
        # key -> subkey -> (value dict, expiration unix time)
        self._data: dict[str, dict[str, tuple[dict, float]]] = {}

    def store(self, key: str, subkey: str, value: dict, expiration: float):
        self._data.setdefault(key, {})[subkey] = (value, expiration)

    # --------------------------------------------------------- persistence
    def snapshot(self) -> list:
        """Live records as a JSON-serializable list."""
        now = time.time()
        return [
            {"key": k, "subkey": sk, "value": v, "expiration": exp}
            for k, sub in self._data.items()
            for sk, (v, exp) in sub.items()
            if exp > now
        ]

    def load_snapshot(self, records: list) -> None:
        now = time.time()
        for r in records:
            if r["expiration"] > now:
                self.store(r["key"], r["subkey"], r["value"], r["expiration"])

    def get(self, key: str) -> dict[str, dict]:
        now = time.time()
        out = {}
        sub = self._data.get(key)
        if not sub:
            return out
        dead = []
        for sk, (v, exp) in sub.items():
            if exp < now:
                dead.append(sk)
            else:
                out[sk] = v
        for sk in dead:
            del sub[sk]
        return out

    def delete(self, key: str, subkey: str):
        sub = self._data.get(key)
        if sub:
            sub.pop(subkey, None)


class RegistryServer:
    """Standalone registry node (bootstrap peer).

    `persist_path` makes the record store survive restarts: records are
    snapshotted to disk every `persist_period` seconds (and on stop) and
    reloaded at start — a restarted registry immediately knows the swarm
    instead of waiting an announce period for every server (the reference's
    DHT survives via peer replication; a single-node registry needs a disk
    snapshot instead).
    """

    def __init__(
        self,
        host: str = "0.0.0.0",
        port: int = 0,
        persist_path: str | None = None,
        persist_period: float = 5.0,
    ):
        self._store = _Store()
        self.persist_path = persist_path
        self.persist_period = persist_period
        self._persist_task: asyncio.Task | None = None
        self.rpc = RpcServer(
            unary_handlers={
                "registry_store": self._rpc_store,
                "registry_get": self._rpc_get,
                "registry_delete": self._rpc_delete,
            },
            host=host,
            port=port,
        )

    @property
    def port(self) -> int:
        return self.rpc.port

    async def start(self):
        if self.persist_path and os.path.exists(self.persist_path):
            try:
                with open(self.persist_path) as f:
                    self._store.load_snapshot(json.load(f))
            except Exception:
                pass  # a corrupt snapshot must not block bootstrap
        await self.rpc.start()
        if self.persist_path:
            self._persist_task = asyncio.create_task(self._persist_loop())

    async def stop(self):
        if self._persist_task is not None:
            self._persist_task.cancel()
            try:
                # an in-flight to_thread write keeps running through
                # cancel(); await it so the final write can't race it on
                # the same .tmp file
                await self._persist_task
            except (asyncio.CancelledError, Exception):
                pass
            self._write_snapshot()
        await self.rpc.stop()

    def _write_snapshot(self) -> None:
        tmp = f"{self.persist_path}.tmp"
        with open(tmp, "w") as f:
            json.dump(self._store.snapshot(), f)
        os.replace(tmp, self.persist_path)

    async def _persist_loop(self) -> None:
        while True:
            await asyncio.sleep(self.persist_period)
            try:
                await asyncio.to_thread(self._write_snapshot)
            except Exception:
                pass

    async def _rpc_store(self, meta: dict, tensors):
        now = time.time()
        for rec in meta["records"]:
            self._store.store(
                rec["key"], rec["subkey"], rec["value"],
                now + rec["expiration"],
            )
        return {"ok": True}, []

    async def _rpc_get(self, meta: dict, tensors):
        return {"results": {k: self._store.get(k) for k in meta["keys"]}}, []

    async def _rpc_delete(self, meta: dict, tensors):
        for rec in meta["records"]:
            self._store.delete(rec["key"], rec["subkey"])
        return {"ok": True}, []


class RegistryClient:
    """Client handle to the registry (used by servers and model clients)."""

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._conn: Connection | None = None
        self._lock = asyncio.Lock()

    async def _connection(self) -> Connection:
        async with self._lock:
            if self._conn is None or self._conn.is_closing():
                self._conn = await connect(self.host, self.port)
            return self._conn

    async def close(self):
        if self._conn is not None:
            await self._conn.close()
            self._conn = None

    async def declare_blocks(
        self,
        model_uid: str,
        server_id: str,
        blocks: range,
        info: ServerInfo,
        expiration: float = 30.0,
    ) -> None:
        """reference: declare_active_modules (utils/dht.py:28-73)."""
        conn = await self._connection()
        records = [
            {
                "key": f"{model_uid}.{i}",
                "subkey": server_id,
                "value": info.to_wire(),
                "expiration": expiration,
            }
            for i in blocks
        ]
        await conn.call("registry_store", {"records": records})

    async def revoke_blocks(
        self, model_uid: str, server_id: str, blocks: range
    ) -> None:
        conn = await self._connection()
        records = [
            {"key": f"{model_uid}.{i}", "subkey": server_id} for i in blocks
        ]
        await conn.call("registry_delete", {"records": records})

    async def get_module_infos(
        self, model_uid: str, blocks: range
    ) -> list[ModuleInfo]:
        """reference: get_remote_module_infos (utils/dht.py:74-117)."""
        conn = await self._connection()
        keys = [f"{model_uid}.{i}" for i in blocks]
        meta, _ = await conn.call("registry_get", {"keys": keys})
        out = []
        for i, key in zip(blocks, keys):
            servers = {
                sid: ServerInfo.from_wire(v)
                for sid, v in meta["results"].get(key, {}).items()
            }
            out.append(ModuleInfo(uid=key, servers=servers))
        return out


class InProcessRegistry:
    """Registry + client fused for single-process tests."""

    def __init__(self):
        self._store = _Store()

    async def declare_blocks(self, model_uid, server_id, blocks, info,
                             expiration: float = 30.0):
        now = time.time()
        for i in blocks:
            self._store.store(
                f"{model_uid}.{i}", server_id, info.to_wire(), now + expiration
            )

    async def revoke_blocks(self, model_uid, server_id, blocks):
        for i in blocks:
            self._store.delete(f"{model_uid}.{i}", server_id)

    async def get_module_infos(self, model_uid, blocks):
        out = []
        for i in blocks:
            key = f"{model_uid}.{i}"
            servers = {
                sid: ServerInfo.from_wire(v)
                for sid, v in self._store.get(key).items()
            }
            out.append(ModuleInfo(uid=key, servers=servers))
        return out

    async def close(self):
        pass
