"""Runtime compile/transfer witness (utils/jitwatch.py): region-based
compile attribution, the warmup-fence phase contract, hot-path host-sync
counting, the zero-overhead-when-off contract, the multi-process
report/--require gate (vacuous-green, missing-fence, and steady-
recompile failure modes), and one live e2e swarm run proving a
multi-session steady-state decode incurs ZERO post-warmup recompiles
while observing >=1 warmup compile.
"""

import asyncio
import json

import jax
import numpy as np
import pytest

from bloombee_tpu.utils import jitwatch


@pytest.fixture(autouse=True)
def fresh_witness():
    jitwatch.reset()
    yield
    jitwatch.reset()


@pytest.fixture
def watch_on(monkeypatch):
    monkeypatch.setenv("BBTPU_JITWATCH", "1")
    monkeypatch.delenv("BBTPU_JITWATCH_REPORT", raising=False)


# --------------------------------------------------------- off = zero cost
def test_off_is_zero_overhead(monkeypatch):
    """With the switch off: hot_wrap returns the function object itself
    (no wrapper in the compute queue's dispatch path), regions and
    syncs record nothing, and install() declines."""
    monkeypatch.delenv("BBTPU_JITWATCH", raising=False)

    def fn():
        return 7

    assert jitwatch.hot_wrap(fn) is fn
    with jitwatch.region("span_step", "b1,t1,p4"):
        jitwatch.host_sync("executor.fetch")
    jitwatch._witness.record_compile(0.0)  # listener never fires when off;
    # a stray direct record still lands unattributed-warmup, but the
    # public paths above must have recorded nothing
    snap = jitwatch.snapshot()
    assert snap["host_syncs"] == {}
    assert jitwatch.install() is False


# ----------------------------------------------------- attribution + phases
def test_region_attribution_and_warmup_phase(watch_on):
    with jitwatch.region("span_step", "b2,t8,p4"):
        jitwatch._witness.record_compile(0.25)
    jitwatch._witness.record_compile(0.05)  # outside any region
    snap = jitwatch.snapshot()
    assert snap["xla_compiles"] == 2
    assert snap["warmup_compiles"] == 2
    assert snap["steady_state_recompiles"] == 0
    assert snap["compile_ms_total"] == pytest.approx(300.0)
    funcs = [(c["function"], c["shape"], c["phase"]) for c in snap["compiles"]]
    assert funcs == [
        ("span_step", "b2,t8,p4", "warmup"),
        ("(unattributed)", "", "warmup"),
    ]


def test_nested_regions_attribute_to_innermost(watch_on):
    with jitwatch.region("decode_loop", "b1,n8,p4"):
        with jitwatch.region("layer_step", "b1,t1,p4"):
            jitwatch._witness.record_compile(0.01)
        jitwatch._witness.record_compile(0.01)
    snap = jitwatch.snapshot()
    assert [c["function"] for c in snap["compiles"]] == [
        "layer_step", "decode_loop",
    ]


def test_fence_splits_steady_from_warmup(watch_on):
    with jitwatch.region("span_step", "b1,t8,p4"):
        jitwatch._witness.record_compile(0.1)
    jitwatch.fence()
    with jitwatch.region("span_step", "b1,t16,p8"):  # bucket escaped warmup
        jitwatch._witness.record_compile(0.2)
    snap = jitwatch.snapshot()
    assert snap["fenced"] is True
    assert snap["warmup_compiles"] == 1
    assert snap["steady_state_recompiles"] == 1
    assert snap["compiles"][1]["phase"] == "steady"


def test_unattributed_steady_compiles_are_counted_not_gated(watch_on):
    """Client-side jnp work can share a test process with the server:
    its compiles are ledgered (visible in the report) but do not count
    as steady-state recompiles — only region-attributed ones are
    provably the serving path's fault."""
    jitwatch.fence()
    jitwatch._witness.record_compile(0.1)  # no region
    snap = jitwatch.snapshot()
    assert snap["xla_compiles"] == 1
    assert snap["steady_state_recompiles"] == 0


def test_reentrant_warmup_reopens_phase(watch_on):
    jitwatch.fence()
    jitwatch.set_phase("warmup")  # elastic rebalance re-warmup
    with jitwatch.region("span_step", "b4,t8,p4"):
        jitwatch._witness.record_compile(0.1)
    snap = jitwatch.snapshot()
    assert snap["warmup_compiles"] == 1
    assert snap["steady_state_recompiles"] == 0


# ------------------------------------------------------- hot-path host syncs
def test_hot_wrap_marks_syncs_hot(watch_on):
    def task():
        jitwatch.host_sync("executor.fetch")
        return 1

    jitwatch.host_sync("executor.fetch")  # off-queue: not hot
    assert jitwatch.hot_wrap(task)() == 1
    snap = jitwatch.snapshot()
    assert snap["host_syncs"] == {"executor.fetch": 2}
    assert snap["host_syncs_hot_path"] == 1
    assert jitwatch.counters()["host_syncs_hot_path"] == 1


def test_hot_wrap_depth_survives_exceptions(watch_on):
    def boom():
        raise RuntimeError("x")

    with pytest.raises(RuntimeError):
        jitwatch.hot_wrap(boom)()
    jitwatch.host_sync("executor.fetch")  # must be cold again
    assert jitwatch.snapshot()["host_syncs_hot_path"] == 0


def test_compile_ledger_is_bounded(watch_on):
    for _ in range(jitwatch._MAX_COMPILES + 50):
        jitwatch._witness.record_compile(0.001)
    snap = jitwatch.snapshot()
    assert len(snap["compiles"]) == jitwatch._MAX_COMPILES
    # counters keep the true totals past the ledger cap
    assert snap["xla_compiles"] == jitwatch._MAX_COMPILES + 50


# ------------------------------------------------------- report + gate CLI
def _warm_then_fence():
    with jitwatch.region("span_step", "b1,t8,p4"):
        jitwatch._witness.record_compile(0.1)
    jitwatch.fence()


def test_flush_merge_and_require_gate(tmp_path, watch_on, capsys):
    report = tmp_path / "jitwatch.jsonl"
    _warm_then_fence()
    jitwatch.host_sync("executor.fetch")
    jitwatch.flush(str(report))
    # second "process": appended as its own line
    jitwatch.flush(str(report))
    assert len(report.read_text().splitlines()) == 2

    merged = jitwatch.merge_lines(report.read_text())
    assert merged["xla_compiles"] == 2
    assert merged["warmup_compiles"] == 2
    assert merged["steady_state_recompiles"] == 0
    assert merged["host_syncs"] == {"executor.fetch": 2}
    assert merged["fenced"] is True

    assert jitwatch._main([str(report), "--require"]) == 0
    out = capsys.readouterr().out
    assert "2 compile(s)" in out and "fenced=True" in out


def test_require_gate_fails_on_empty_report(tmp_path, capsys):
    report = tmp_path / "empty.jsonl"
    report.write_text("")
    assert jitwatch._main([str(report), "--require"]) == 1
    assert "EMPTY" in capsys.readouterr().err
    # without --require an empty report only informs
    assert jitwatch._main([str(report)]) == 0


def test_require_gate_fails_without_fence(tmp_path, watch_on, capsys):
    """A run that compiled but never dropped the warmup fence proves
    nothing about steady state: 'zero recompiles' would be vacuous."""
    report = tmp_path / "nofence.jsonl"
    with jitwatch.region("span_step", "b1,t8,p4"):
        jitwatch._witness.record_compile(0.1)
    jitwatch.flush(str(report))
    assert jitwatch._main([str(report), "--require"]) == 1
    assert "NO WARMUP FENCE" in capsys.readouterr().err


def test_require_gate_fails_on_steady_recompile(tmp_path, watch_on, capsys):
    report = tmp_path / "steady.jsonl"
    _warm_then_fence()
    with jitwatch.region("span_step_ragged", "r4,s2,p8"):
        jitwatch._witness.record_compile(0.3)
    jitwatch.flush(str(report))
    assert jitwatch._main([str(report), "--require"]) == 1
    out = capsys.readouterr()
    assert "steady-state recompile" in out.err
    # the ledger names the exact (function, shape) to pre-compile
    assert "STEADY RECOMPILE span_step_ragged[r4,s2,p8]" in out.out


def test_flush_skips_empty_witness(tmp_path, watch_on):
    report = tmp_path / "noop.jsonl"
    jitwatch.flush(str(report))
    assert not report.exists() or report.read_text() == ""


def test_merge_skips_garbage_lines(watch_on):
    merged = jitwatch.merge_lines(
        "not json\n" + json.dumps({"xla_compiles": 3, "fenced": True}) + "\n"
    )
    assert merged["xla_compiles"] == 3
    assert merged["fenced"] is True


# ------------------------------------------------------------- live e2e run
@pytest.fixture(scope="module")
def tiny_model_dir(tmp_path_factory):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64,
        intermediate_size=128,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_hidden_layers=3,
        vocab_size=128,
        max_position_embeddings=256,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    d = tmp_path_factory.mktemp("tiny_llama_jitwatch")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), config


@pytest.mark.chaos
def test_e2e_steady_state_decode_has_zero_recompiles(
    tiny_model_dir, monkeypatch, tmp_path
):
    """The acceptance run: a live server, warmed at the session's
    buckets, then TWO sessions prefilling and decoding in steady state
    under BBTPU_JITWATCH=1 — the witness must show >=1 warmup compile
    behind a dropped fence, ZERO steady-state recompiles, and hot-path
    host syncs only at the deliberate executor.fetch chokepoint; the
    flushed report must pass the --require gate."""
    import jax.numpy as jnp

    from bloombee_tpu.client.config import ClientConfig
    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    monkeypatch.setenv("BBTPU_JITWATCH", "1")
    model_dir, config = tiny_model_dir
    report = tmp_path / "jitwatch.jsonl"

    # earlier tests in a full-suite run may have compiled these very
    # shapes on the executor's module-level jitted functions; drop the
    # in-process executable cache so warmup's compiles actually happen
    # (standalone / chaos.sh runs are fresh processes and unaffected)
    jax.clear_caches()

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        server = BlockServer(
            model_uid="tiny", start=0, end=3, model_dir=model_dir,
            registry=rc(), compute_dtype=jnp.float32, num_pages=64,
            page_size=4,
        )
        await server.start()
        # warm the buckets the sessions below will hit: batch 1 and 2
        # (two concurrent decodes fuse into one b=2 group dispatch),
        # prompt bucket t=8, and the pb bucket of a <=16-token session
        await server.warmup(batch_sizes=(1, 2), prefill_tokens=8)
        snap = jitwatch.snapshot()
        assert snap["fenced"] is True
        assert snap["warmup_compiles"] >= 1, snap

        cfg = ClientConfig(use_push=False)
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny", config=cfg
        )
        ids_a = (np.arange(8)[None, :] * 5 + 3) % config.vocab_size
        ids_b = (np.arange(8)[None, :] * 7 + 1) % config.vocab_size

        async def session(input_ids):
            # max length 16 keeps the session inside the warmed page
            # bucket (ceil(17/4) pages -> pb 8 would be a fresh compile)
            async with model.inference_session(16, 1) as sess:
                out = await sess.step(
                    model.embed(input_ids), ids=input_ids
                )
                for _ in range(4):
                    logits = model.logits(out[:, -1:])[:, 0]
                    nxt = np.argmax(logits, axis=-1).astype(
                        input_ids.dtype
                    )[:, None]
                    out = await sess.step(model.embed(nxt), ids=nxt)

        await asyncio.gather(session(ids_a), session(ids_b))

        # the counters also ride rpc_info (BB006 surfacing)
        from bloombee_tpu.wire.rpc import connect

        conn = await connect("127.0.0.1", server.port)
        info, _ = await conn.call("rpc_info", {})
        assert info["xla_compiles"] >= 1
        assert info["steady_state_recompiles"] == 0, info
        await conn.close()

        await server.stop()
        await reg.stop()

    asyncio.run(run())

    snap = jitwatch.snapshot()
    assert snap["warmup_compiles"] >= 1
    assert snap["steady_state_recompiles"] == 0, [
        c for c in snap["compiles"] if c["phase"] == "steady"
    ]
    # every hot-path sync went through the one deliberate chokepoint
    assert set(snap["host_syncs"]) <= {"executor.fetch"}, snap["host_syncs"]

    # the flushed report passes the zero-steady-state-recompile gate
    jitwatch.flush(str(report))
    assert jitwatch._main([str(report), "--require"]) == 0
    # under scripts/chaos.sh the same line feeds the entry's gate (the
    # autouse reset leaves nothing for the atexit flush to double-write)
    jitwatch.flush()
