"""``python -m bloombee_tpu.sim [--require]``: run the swarm simulator.

Runs each requested scenario (default: all three) with ≥1000 virtual
sessions on the virtual clock, prints the per-scenario JSON report, and
with ``--require`` exits 1 when any metastability gate fails — shedding
that never reconverges, retry amplification past bound, promotion
flapping, a session starved while capacity existed — the same gate idiom
as ``python -m bloombee_tpu.utils.ledger --require``.
"""

from __future__ import annotations

import argparse
import json
import sys

from bloombee_tpu.sim.scenarios import SCENARIOS, run_scenario
from bloombee_tpu.utils import clock, env


def _main() -> None:
    ap = argparse.ArgumentParser(
        prog="python -m bloombee_tpu.sim", description=__doc__
    )
    ap.add_argument(
        "--require", action="store_true",
        help="exit 1 when any scenario's metastability gate fails",
    )
    ap.add_argument(
        "--scenarios", default=",".join(SCENARIOS),
        help=f"comma-separated subset of: {', '.join(SCENARIOS)}",
    )
    ap.add_argument(
        "--sessions", type=int, default=None,
        help="virtual sessions per scenario (default BBTPU_SIM_SESSIONS)",
    )
    ap.add_argument(
        "--seed", type=int, default=None,
        help="workload seed (default BBTPU_SIM_SEED)",
    )
    ap.add_argument(
        "--smoke", action="store_true",
        help="~200-session quick profile (bench phase / chaos matrix)",
    )
    ap.add_argument(
        "--json", dest="json_path", default=None,
        help="also write the full report to this file",
    )
    args = ap.parse_args()

    names = [n.strip() for n in args.scenarios.split(",") if n.strip()]
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        ap.error(f"unknown scenario(s): {', '.join(unknown)}")
    sessions = args.sessions
    if sessions is None:
        sessions = 200 if args.smoke else int(env.get("BBTPU_SIM_SESSIONS"))

    wall0 = clock.perf_counter()
    report = {"scenarios": {}, "sessions_per_scenario": sessions}
    failures: list[str] = []
    for name in names:
        result = run_scenario(name, sessions=sessions, seed=args.seed)
        report["scenarios"][name] = result
        failures.extend(result["failures"])
        m = result["metrics"]
        print(
            f"[sim] {name}: {m['completed']}/{m['sessions']} completed, "
            f"ttft p95 {m['ttft_p95_s']:.2f}s, tbt p95 "
            f"{m['tbt_p95_s'] * 1000:.0f}ms, shed {m['shed_total']}, "
            f"retry amp {m['retry_amplification']:.2f}, "
            f"promotions {m['promotions']}, rebalances "
            f"{m['rebalances_moved']} ({result['wall_s']:.1f}s wall, "
            f"{result['advances']} advances)"
        )
    report["ok"] = not failures
    report["failures"] = failures
    report["wall_s"] = round(clock.perf_counter() - wall0, 3)
    if args.json_path:
        with open(args.json_path, "w") as f:
            json.dump(report, f, indent=2, default=str)
    else:
        print(json.dumps(report, indent=2, default=str))

    if failures:
        for f in failures:
            print(f"[sim] GATE FAILED: {f}", file=sys.stderr)
        if args.require:
            sys.exit(1)
    elif args.require:
        print(f"[sim] all gates passed ({report['wall_s']:.1f}s wall)")


if __name__ == "__main__":
    _main()
