"""Standby promotion/demotion loop, factored as a mixin.

The elastic self-healing state machine — standby watches the span's
serving replicas and promotes on sustained overload or span death,
promoted replicas resolve storms and drain back once the span cools — is
pure control-plane logic over a handful of host attributes (`registry`,
`model_uid`, `server_id`, span bounds, the standby/promoted/draining
flags, watermarks, and the promotion counters). Factoring it out of
BlockServer lets the swarm simulator (`bloombee_tpu/sim/`) run the REAL
promotion code against simulated servers: the sim host provides the same
attribute surface and inherits this mixin, so every watermark, dwell
window, jitter guard, and storm-resolution rule measured in simulation
is byte-for-byte the one production runs.

Host contract (attributes the mixin reads; see BlockServer.__init__):
  registry, model_uid, server_id, start_block, end_block,
  _standby, _promoted, _draining, _sessions,
  promote_high_ms, promote_low_ms, promote_sustain_s, promote_jitter_s,
  announce_period, drain_timeout, _promote_rng,
  promotions, demotions, promotions_yielded, demotions_aborted,
  manager.prefix_stats(), _announce(state) coroutine.
"""

from __future__ import annotations

import asyncio
import logging

from bloombee_tpu.swarm.data import ServerState
from bloombee_tpu.utils import clock, ledger

logger = logging.getLogger(__name__)


class PromotionLoopMixin:
    # --------------------------------------------- standby promotion loop
    async def _promotion_loop(self) -> None:
        """The standby side of elastic self-healing. While standby: watch
        the span's serving replicas and promote on sustained overload
        (best server past promote_high_ms for promote_sustain_s) or span
        loss (a block with no live ONLINE server — advert silence past the
        registry lease). While promoted: resolve promotion storms (all but
        the lexicographically-smallest promoted replica yield) and drain
        back to standby once the span's OTHER servers stay cool below
        promote_low_ms for the sustain window — the high/low gap plus the
        dwell time is the hysteresis that stops replica flapping."""

        tick = max(
            0.1,
            min(self.announce_period, max(self.promote_sustain_s, 0.2) / 2),
        )
        hot_since: float | None = None
        cool_since: float | None = None
        while True:
            await clock.async_sleep(tick)
            if self._draining:
                return
            try:
                if self._standby:
                    cool_since = None
                    reason = await self._span_needs_me()
                    if reason is None:
                        hot_since = None
                        continue
                    now = clock.monotonic()
                    if reason == "hot":
                        # sustained-overload dwell; a dead span promotes
                        # without one (there is nobody left to flap with)
                        if hot_since is None:
                            hot_since = now
                        if now - hot_since < self.promote_sustain_s:
                            continue
                    # storm guard: jittered delay, then RE-CHECK — a peer
                    # standby that promoted during our sleep clears the
                    # trigger (span covered again / best server cool)
                    await clock.async_sleep(
                        self._promote_rng.uniform(0, self.promote_jitter_s)
                    )
                    if await self._span_needs_me() is None:
                        hot_since = None
                        continue
                    await self._promote(reason)
                    hot_since = None
                elif self._promoted:
                    hot_since = None
                    # post-declare re-check: concurrent promotions that
                    # slipped past the jitter window resolve here
                    if await self._resolve_promotion_storm():
                        cool_since = None
                        continue
                    if await self._span_cooled():
                        now = clock.monotonic()
                        if cool_since is None:
                            cool_since = now
                        if now - cool_since >= self.promote_sustain_s:
                            await self._demote()
                            cool_since = None
                    else:
                        cool_since = None
                else:
                    return  # demoted back to plain standby duty is handled
                    # by the _standby branch; a primary never runs this loop
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # registry flap: keep watching — a standby that gives up
                # on a transient error is a standby that never fails over
                logger.warning("promotion check failed: %s", e)

    async def _span_pressure(self) -> float | None:
        """Worst-case best-server queue delay (ms) across this span's
        blocks, counting only OTHER ONLINE servers: for each block, the
        minimum predicted queue delay over its live serving replicas (a
        cool replica anywhere absorbs that block's traffic), maximized
        over blocks (the hottest uncovered-by-cool-capacity block gates
        the span). None = some block has no other live server at all.
        Adverts are untrusted: the delay term is the shared bounded /
        staleness-discounted swarm/load.py reading."""
        from bloombee_tpu.swarm.load import predicted_queue_delay_s

        infos = await self.registry.get_module_infos(
            self.model_uid, range(self.start_block, self.end_block)
        )
        worst = 0.0
        for info in infos:
            servers = [
                s for sid, s in (info.servers if info else {}).items()
                if sid != self.server_id and s.state == ServerState.ONLINE
            ]
            if not servers:
                return None
            best = min(
                predicted_queue_delay_s(s) * 1000.0 for s in servers
            )
            worst = max(worst, best)
        return worst

    async def _span_needs_me(self) -> str | None:
        """Why this standby should promote right now: 'dead' (a span block
        has no live server) / 'hot' (best coverage past the high
        watermark) / None (span is fine)."""
        pressure = await self._span_pressure()
        if pressure is None:
            return "dead"
        if pressure >= self.promote_high_ms:
            return "hot"
        return None

    async def _span_cooled(self) -> bool:
        """Demotion trigger: every span block is covered by OTHER live
        servers AND the worst best-server delay sits below the low
        watermark — never drain back the span's sole coverage."""
        pressure = await self._span_pressure()
        return pressure is not None and pressure <= self.promote_low_ms

    async def _promote(self, reason: str) -> None:
        """Standby -> serving replica: flip the flags and declare the span
        ONLINE. The replicated KV shipped to us via kv_put already sits in
        the prefix pool as cached entries, so recovering sessions resume
        off it (prefix probe) the moment routing can see us; nothing needs
        re-installing."""
        stats = self.manager.prefix_stats()
        self._standby = False
        self._promoted = True
        self.promotions += 1
        ledger.recovery("server.promotion")
        logger.warning(
            "standby %s PROMOTING to serve %s[%d:%d) (%s; %d replicated "
            "pages warm)", self.server_id, self.model_uid,
            self.start_block, self.end_block, reason,
            stats.get("repl_pages_installed", 0),
        )
        # declare immediately — the periodic announce loop may be most of
        # a period away, and a dead span bleeds sessions every second. A
        # registry flap here is non-fatal: we stay promoted and the
        # announce loop's next pass re-declares.
        try:
            await self._announce(ServerState.ONLINE)
        except Exception as e:
            logger.warning("promotion announce failed (will retry): %s", e)

    async def _resolve_promotion_storm(self) -> bool:
        """After declaring, check for sibling promoted replicas of this
        exact span: if any has a lexicographically smaller server_id, WE
        yield (demote back) so N racing standbys converge on exactly one
        promoted replica. Returns True when this server yielded."""
        infos = await self.registry.get_module_infos(
            self.model_uid, range(self.start_block, self.end_block)
        )
        siblings: set[str] = set()
        for info in infos:
            for sid, s in (info.servers if info else {}).items():
                if (
                    sid != self.server_id
                    and s.state == ServerState.ONLINE
                    and s.promoted_standby
                    and s.start_block == self.start_block
                    and s.end_block == self.end_block
                ):
                    siblings.add(sid)
        if not siblings or min(siblings) > self.server_id:
            return False
        logger.warning(
            "promotion storm: %s yields %s[%d:%d) to promoted sibling %s",
            self.server_id, self.model_uid, self.start_block,
            self.end_block, min(siblings),
        )
        await self._demote(yielded=True)
        return True

    async def _demote(self, yielded: bool = False) -> bool:
        """Serving replica -> standby, gracefully: refuse NEW sessions at
        once (standby flag + DRAINING advert), wait out open sessions up
        to drain_timeout, then declare JOINING. If sessions outlive the
        window the demotion ABORTS (re-announce ONLINE, retry later) —
        drain-back must never strand live streams on an unroutable
        server."""

        self._standby = True  # session opens now refuse; open streams live
        try:
            await self._announce(ServerState.DRAINING)
        except Exception as e:
            logger.warning("demotion announce failed: %s", e)
        deadline = clock.monotonic() + self.drain_timeout
        while self._sessions and clock.monotonic() < deadline:
            await clock.async_sleep(0.1)
        if self._sessions and not yielded:
            # a yielded storm-duplicate demotes regardless: its sibling
            # serves the span, and any session that raced onto us replays
            # there via the ordinary session_lost path
            self._standby = False
            self.demotions_aborted += 1
            logger.warning(
                "demotion aborted: %d session(s) outlived the %.0fs "
                "drain; staying promoted", len(self._sessions),
                self.drain_timeout,
            )
            try:
                await self._announce(ServerState.ONLINE)
            except Exception as e:
                logger.warning("demotion-abort announce failed: %s", e)
            return False
        self._promoted = False
        if yielded:
            self.promotions_yielded += 1
        else:
            self.demotions += 1
        logger.warning(
            "replica %s demoted back to standby for %s[%d:%d)",
            self.server_id, self.model_uid, self.start_block,
            self.end_block,
        )
        try:
            await self._announce(ServerState.JOINING)
        except Exception as e:
            logger.warning("standby announce failed: %s", e)
        return True
