"""Ring attention: sequence/context parallelism over the "sp" mesh axis.

Each device holds one sequence chunk of Q, K, V. KV chunks rotate around the
ring (lax.ppermute over ICI) while each device accumulates its Q block's
attention with a numerically-stable online softmax (flash-attention style
streaming stats). After sp steps every Q block has seen every KV block and
no device ever materializes full-sequence attention logits.

This fills the reference's explicit long-context gap (SURVEY.md section 5:
"no ring attention / Ulysses / context parallelism" — it only chunks prefill
and offloads the KV slab to host). Compute stays in the input dtype for the
MXU; softmax stats are fp32.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from bloombee_tpu.ops.attention import NEG_INF as NEG, repeat_kv


def ring_attention(
    q: jax.Array,  # [B, C, H, hd] local query chunk
    k: jax.Array,  # [B, C, Hkv, hd] local key chunk
    v: jax.Array,  # [B, C, Hkv, hd]
    axis_name: str = "sp",
    causal: bool = True,
    scale: float | None = None,
) -> jax.Array:
    """Must be called inside shard_map with `axis_name` mapped; returns the
    local output chunk [B, C, H, hd]."""
    n = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    b, c, h, hd = q.shape
    n_rep = h // k.shape[2]
    if scale is None:
        scale = hd**-0.5

    q_pos = rank * c + jnp.arange(c)  # global positions of local queries
    qf = q  # [B, C, H, hd]

    def step(carry, i):
        m, l, acc, k_cur, v_cur = carry
        src = (rank - i) % n  # who produced the block currently held
        kv_pos = src * c + jnp.arange(c)

        def attend(m, l, acc):
            k_r = repeat_kv(k_cur, n_rep)
            v_r = repeat_kv(v_cur, n_rep)
            logits = (
                jnp.einsum("bqhd,bkhd->bhqk", qf, k_r).astype(jnp.float32)
                * scale
            )
            if causal:
                mask = kv_pos[None, :] <= q_pos[:, None]  # [Cq, Ck]
                logits = jnp.where(mask[None, None], logits, NEG)
                pmask = mask[None, None].astype(jnp.float32)
            else:
                pmask = jnp.ones((1, 1, c, c), jnp.float32)

            m_new = jnp.maximum(m, logits.max(axis=-1))
            p = jnp.exp(logits - m_new[..., None]) * pmask
            corr = jnp.exp(m - m_new)
            l_new = l * corr + p.sum(axis=-1)
            acc_new = acc * corr[..., None] + jnp.einsum(
                "bhqk,bkhd->bhqd", p.astype(q.dtype), v_r
            ).astype(jnp.float32)
            return m_new, l_new, acc_new

        if causal:
            # skip blocks entirely in this rank's causal future (half of all
            # (rank, src) pairs): the ppermute still runs every step —
            # collectives must stay uniform across the ring — but the
            # logits/softmax FLOPs are branched away. (Callers wrap with
            # check_vma=False: the identity skip branch is replicated-typed
            # while attend's outputs vary over the ring axis, which strict
            # vma checking would reject despite being correct here.)
            m, l, acc = lax.cond(
                src <= rank, attend, lambda m, l, acc: (m, l, acc), m, l, acc
            )
        else:
            m, l, acc = attend(m, l, acc)

        # rotate KV to the next rank on the ring
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (m, l, acc, k_nxt, v_nxt), None

    m0 = jnp.full((b, h, c), NEG, jnp.float32)
    l0 = jnp.zeros((b, h, c), jnp.float32)
    acc0 = jnp.zeros((b, h, c, hd), jnp.float32)
    # scan (not fori_loop) so the ring is reverse-differentiable for training
    (m, l, acc, _, _), _ = lax.scan(
        step, (m0, l0, acc0, k, v), jnp.arange(n)
    )

    out = acc / jnp.maximum(l, 1e-20)[..., None]  # fully-masked rows -> 0
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # [B, C, H, hd]
