"""Speculative pruner semantics (port of
/root/reference/tests/test_speculative_pruner_manager.py intent)."""

import numpy as np

from bloombee_tpu.spec.pruner import SimpleProbabilityPruner
from bloombee_tpu.spec.tree import DraftTree


def _probs(vocab, rows):
    out = np.full((len(rows), vocab), 1e-6)
    for i, spec in enumerate(rows):
        for tok, p in spec.items():
            out[i, tok] = p
    return out / out.sum(axis=-1, keepdims=True)


def test_prunes_low_probability_children_and_subtrees():
    #  0(tok 1)   1(tok 2)     roots
    #  2(tok 3, child of 0)    3(tok 4, child of 1)
    tree = DraftTree(
        tokens=np.asarray([1, 2, 3, 4]),
        parents=np.asarray([-1, -1, 0, 1]),
    )
    vocab = 8
    # root distribution: token 1 likely, token 2 negligible
    root = _probs(vocab, [{1: 0.9, 2: 0.01}])[0]
    probs = _probs(
        vocab,
        [
            {3: 0.8},  # node 0's dist -> child 2 strong
            {4: 0.9},  # node 1's dist -> child 3 strong, but 1 is pruned
            {},
            {},
        ],
    )
    kept = SimpleProbabilityPruner(threshold=0.1).keep_indices(
        tree, probs, root
    )
    kept_set = set(kept[kept >= 0].tolist())
    assert 0 in kept_set and 2 in kept_set  # strong path survives
    assert 1 not in kept_set  # weak root pruned
    assert 3 not in kept_set  # descendant of pruned node gone too


def test_keep_indices_padding_and_cap():
    tree = DraftTree(
        tokens=np.asarray([1, 2, 3]), parents=np.asarray([-1, 0, 1])
    )
    vocab = 4
    root = _probs(vocab, [{1: 1.0}])[0]
    probs = _probs(vocab, [{2: 1.0}, {3: 1.0}, {}])
    kept = SimpleProbabilityPruner(threshold=0.5, max_keep=2).keep_indices(
        tree, probs, root
    )
    assert kept.tolist() == [0, 1]  # capped at 2
    kept = SimpleProbabilityPruner(threshold=0.99).keep_indices(
        tree, probs, root
    )
    assert kept.tolist() == [0, 1, 2]  # single children renormalize to 1.0