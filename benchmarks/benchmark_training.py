"""Training (fwd+bwd) throughput benchmark — p-tuning steps/sec.

Port of /root/reference/benchmarks/benchmark_training.py.
"""

from __future__ import annotations

import argparse
import asyncio
import time

import numpy as np


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("model_dir")
    parser.add_argument("--model-uid", default=None)
    parser.add_argument("--registry", default="127.0.0.1:7700")
    parser.add_argument("--seq-len", type=int, default=64)
    parser.add_argument("--batch", type=int, default=2)
    parser.add_argument("--steps", type=int, default=8)
    parser.add_argument("--n-prompt", type=int, default=8)
    args = parser.parse_args(argv)
    args.model_uid = args.model_uid or args.model_dir.rstrip("/").split("/")[-1]

    async def run():
        from bloombee_tpu.client.model import DistributedModelForCausalLM
        from bloombee_tpu.client.trainer import PTuneTrainer
        from bloombee_tpu.swarm.registry import RegistryClient

        host, port = args.registry.rsplit(":", 1)
        model = DistributedModelForCausalLM.from_pretrained(
            args.model_dir, RegistryClient(host, int(port)),
            model_uid=args.model_uid,
        )
        trainer = PTuneTrainer(model, n_prompt=args.n_prompt)
        rng = np.random.default_rng(0)
        ids = rng.integers(
            0, model.spec.vocab_size, size=(args.batch, args.seq_len + 1)
        )
        await trainer.train_step(ids[:, :-1], ids[:, 1:])  # warmup
        t0 = time.perf_counter()
        losses = []
        for _ in range(args.steps):
            losses.append(await trainer.train_step(ids[:, :-1], ids[:, 1:]))
        dt = time.perf_counter() - t0
        toks = args.steps * args.batch * args.seq_len
        print(
            f"train throughput={toks / dt:.1f} tok/s  "
            f"steps/s={args.steps / dt:.2f}  loss {losses[0]:.3f}->{losses[-1]:.3f}"
        )

    asyncio.run(run())


if __name__ == "__main__":
    main()
