"""InferenceSession: client-side stateful decode across the span chain.

Port of /root/reference/src/bloombee/client/inference_session.py:438-855:
owns one rpc_inference stream per span, steps hidden states through the chain,
and on a span failure re-routes that suffix of the chain and replays the input
history into the replacement servers to rebuild their KV caches
(`_update_sequence`, :802-831). Supports server-to-server push-only decode:
the client sends only to span 0 and each hop forwards activations directly
(reference ClientConfig.push_only_downstream_decode, config.py:19-42).
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
import uuid

import numpy as np

from bloombee_tpu.client.integrity import (
    IntegrityError,
    SanityGate,
    tensors_close,
)
from bloombee_tpu.client.sequence_manager import (
    MissingBlocksError,
    RemoteSequenceManager,
)
from bloombee_tpu.swarm.data import RemoteSpanInfo
from bloombee_tpu.utils import env, ledger
from bloombee_tpu.wire.rpc import (
    Connection,
    OverloadedError,
    RpcError,
    Stream,
    connect,
)
from bloombee_tpu.wire.tensor_codec import dtype_for_name

logger = logging.getLogger(__name__)

env.declare(
    "BBTPU_MICROBATCH", int, 1,
    "default within-stage micro-batch count for client sessions (>1 splits "
    "each step's batch so stage N+1 computes chunk k while stage N computes "
    "k+1 — the reference's BLOOMBEE_MICRO_BATCH_SIZE overlap)",
)
env.declare(
    "BBTPU_REPL_EVERY", int, 0,
    "session-KV replication interval: every N newly-sealed pages the "
    "client asks each span's server to ship them (kv_put) to a standby "
    "covering the same span, so failover replays at most one interval "
    "plus the unsealed tail (0 = replication off)",
)
env.declare(
    "BBTPU_RESUME", bool, True,
    "reconnect-resume: after a stream failure, try to re-attach each "
    "span's lease-parked session (resume: session_id) and retransmit the "
    "failed step under its original id — at-most-once server-side, zero "
    "prompt replay — before falling back to full-replay recovery. Safe "
    "against servers with leases off: they decline and recovery proceeds "
    "as before",
)

# the first no-embed_fn decode_n session in the process warns loudly; later
# sessions demote to DEBUG (a bench tail spawning many raw sessions would
# otherwise repeat the identical warning once per session)
_warned_no_embed_process = False

# default admission-control identity: one id per client process, so all of
# a process's sessions share one fair-share bucket server-side (a client
# can't dodge fairness accounting by opening more sessions)
_PROCESS_CLIENT_ID = f"cli-{uuid.uuid4().hex[:8]}"


class DecodeNUnsupported(RuntimeError):
    """The server cannot run server-side multi-step decode for this session
    (no client params / sub-span route / sharded span). Not a failure — the
    caller falls back to per-step decoding without banning the peer."""


def _raise_if_session_lost(resp_meta: dict) -> None:
    """Typed `session_lost` reply: the server is healthy but this session's
    KV is gone (arena rebuilt after a kernel failure). Raise a plain wire
    error so the caller's retry loop recovers and replays WITHOUT banning
    the peer (the ban paths only trigger on transport failures)."""
    if resp_meta.get("session_lost"):
        raise RpcError(resp_meta.get("reason", "session KV lost"))


def _sanitize_retry_ms(retry_ms) -> int | None:
    try:
        v = int(retry_ms)
    except (TypeError, ValueError):
        return None
    return v if v > 0 else None


class _SpanSession:
    """One open rpc_inference stream to one server
    (reference _ServerInferenceSession)."""

    def __init__(self, span: RemoteSpanInfo, conn: Connection, stream: Stream,
                 session_id: str):
        self.span = span
        self.conn = conn
        self.stream = stream
        self.session_id = session_id

    async def close(self):
        try:
            await self.stream.close()
        except Exception:
            pass
        try:
            await self.conn.close()
        except Exception:
            pass


class InferenceSession:
    def __init__(
        self,
        manager: RemoteSequenceManager,
        max_length: int,
        batch_size: int = 1,
        use_push: bool = True,
        max_retries: int = 3,
        step_timeout: float = 120.0,
        microbatch: int | str | None = None,  # count or "auto"
        embed_fn=None,  # ids [B, T] -> hidden; enables token-id replay
        adapter: str | None = None,  # per-request LoRA adapter name
        prefix_cache: bool | None = None,  # probe servers' shared-prefix
        # pools before the first prefill and send only the uncached suffix
        # (None -> BBTPU_PREFIX_CACHE env)
        repl_every: int | None = None,  # standby-KV replication interval
        # in sealed pages (None -> BBTPU_REPL_EVERY env; 0 disables)
        client_id: str | None = None,  # admission-control identity sent in
        # every session open (None -> one shared id per client process)
        overload_retries: int = 10,  # how many `overloaded` sheds a step
        # rides out (backoff + reroute) before failing hard — a separate,
        # more generous budget than max_retries because a shed is the
        # server WORKING AS DESIGNED under load, not a fault
        resume: bool | None = None,  # reconnect-resume after a stream
        # failure: re-attach each span's lease-parked session and
        # retransmit the failed step under its original id (at-most-once
        # server-side, zero prompt replay); None -> BBTPU_RESUME env
        resume_timeout: float = 10.0,  # per-span resume handshake budget
        # before giving up on the cheap path (the lease clock is running)
        keepalive_s: float | None = None,  # client-side wire keepalive for
        # span connections (None -> BBTPU_KEEPALIVE_S env; 0 disables)
        integrity: bool | None = None,  # Byzantine-robust mode: inline
        # sanity gate + out_digest verification on every received span
        # output; rejects strike the peer and heal via the existing
        # reroute+replay recovery (None -> BBTPU_INTEGRITY env)
        audit_p: float | None = None,  # per-step probability of
        # re-executing a recorded span step on a different replica and
        # tolerance-comparing the outputs (None -> BBTPU_AUDIT_P env;
        # > 0 implies integrity for this session)
    ):
        self.manager = manager
        self.adapter = adapter
        self.max_length = max_length
        self.batch_size = batch_size
        self.use_push = use_push
        self.max_retries = max_retries
        self.step_timeout = step_timeout
        self.client_id = client_id or _PROCESS_CLIENT_ID
        self.overload_retries = max(0, int(overload_retries))
        self.embed_fn = embed_fn
        self.resume = (
            bool(env.get("BBTPU_RESUME")) if resume is None else bool(resume)
        )
        self.resume_timeout = float(resume_timeout)
        self.keepalive_s = keepalive_s
        # integrity layer (opt-in; off = byte-for-byte legacy behavior)
        self.audit_p = (
            float(env.get("BBTPU_AUDIT_P")) if audit_p is None
            else float(audit_p)
        )
        self.integrity = (
            bool(env.get("BBTPU_INTEGRITY")) if integrity is None
            else bool(integrity)
        ) or self.audit_p > 0
        self._gate = SanityGate() if self.integrity else None
        # integrity observability (bench + tests read these)
        self.sanity_rejects = 0
        self.audits_run = 0
        self.audit_mismatches = 0
        self.integrity_reroutes = 0
        self._audit_rng = random.Random()
        # audit input records: span 0 re-embeds its full input from the id
        # history, spans > 0 accumulate their relay-mode input chunks here;
        # None = invalidated (push-mode multi-span, prefix skip, reroute,
        # decode_n/spec commits) — audits then cover span 0 only
        self._span_in: list[list[np.ndarray]] | None = None
        self._last_span_outs: list = []
        # reconnect-resume observability: streams re-attached without
        # replay, resumes the servers declined (fell back to recovery),
        # and the (step_id, prefix_skip) of the last transmitted step so a
        # post-resume retry retransmits it bit-identical under the SAME id
        self.resumed_streams = 0
        self.resume_declines = 0
        self._last_sent: tuple[int, int | None] | None = None
        self.prefix_cache = (
            env.get("BBTPU_PREFIX_CACHE") if prefix_cache is None
            else bool(prefix_cache)
        )
        # standby replication: every `repl_every` sealed pages the client
        # tells each span's server (kv_repl stream item) to export the new
        # pages and kv_put them into a same-span standby's prefix pool, so
        # `_recover`'s probe adopts them and replays only the unsealed tail
        self.repl_every = (
            env.get("BBTPU_REPL_EVERY") if repl_every is None
            else int(repl_every)
        )
        self._repl: list[dict | None] = []  # per-span replication state
        # incremental full-history hash chains, keyed by page size
        self._chains_by_ps: dict[int, list[list[str]]] = {}
        # client-side failover observability: pages sealed but not yet
        # announced to a standby, and tokens re-prefilled by recoveries
        self.repl_lag_pages = 0
        self.failover_replayed_tokens = 0
        # within-stage micro-batch pipelining (reference
        # microbatch_config.py:84-130 overlap-only mode): split each step's
        # batch into chunks so downstream spans start on chunk k while
        # upstream computes k+1
        self.microbatch = (
            microbatch if microbatch is not None
            else env.get("BBTPU_MICROBATCH")
        )
        if not (
            self.microbatch == "auto"
            or (isinstance(self.microbatch, int) and self.microbatch >= 1)
        ):
            raise ValueError(
                f"microbatch must be >= 1 or 'auto', got {self.microbatch!r}"
            )
        self._spans: list[_SpanSession] = []
        # failure-replay history. Preferred: per-row committed token ids
        # (ragged; replayed by re-embedding — the reference replays ids, not
        # hidden states, inference_session.py:802-831). Fallback when no
        # embed_fn / raw-hidden steps: stored hidden arrays (memory-heavy).
        self._id_rows: list[list[int]] = [[] for _ in range(batch_size)]
        self._history: list[np.ndarray] = []  # legacy hidden replay
        self._step_counter = 0
        self.position = 0
        # set when the server-side KV ran past the committed history (e.g.
        # a decode_n chunk truncated at EOS); the next step rebuilds the
        # chain and replays the true history before proceeding
        self._needs_rebuild = False
        self._warned_no_embed = False
        # per-step timing rows (the client half of the reference's
        # [TIMING_TABLE], handler.py:1276-1605): one entry per step with
        # per-span compute ms and the end-to-end wall ms
        self.timings: list[dict] = []

    # ------------------------------------------------------------- lifecycle
    async def __aenter__(self) -> "InferenceSession":
        await self.manager.update(force=True)
        route = self.manager.make_sequence(
            cache_tokens_needed=self.batch_size * self.max_length,
            relay=not self.use_push,
        )
        self._spans = [await self._open_span(s) for s in route]
        self._init_repl()
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    async def close(self) -> None:
        for s in self._spans:
            await s.close()
        self._spans = []

    async def _open_span(self, span: RemoteSpanInfo) -> _SpanSession:
        session_id = f"sess-{uuid.uuid4().hex[:12]}"
        conn = await connect(
            span.server_info.host, span.server_info.port,
            keepalive_s=self.keepalive_s,
        )
        stream = await conn.open_stream(
            "rpc_inference",
            {
                "session_id": session_id,
                "batch_size": self.batch_size,
                "max_length": self.max_length,
                "start": span.start,
                "end": span.end,
                # fair-share identity for admission control (old servers
                # ignore unknown meta keys)
                "client_id": self.client_id,
                **({"adapter": self.adapter} if self.adapter else {}),
            },
        )
        return _SpanSession(span, conn, stream, session_id)

    def _raise_if_shed(self, resp_meta: dict, peer_id: str) -> None:
        """Typed `overloaded` reply (in-stream shed of this session's new
        work): penalize the peer with the SHORT overload class — never a
        fault ban, the server is healthy — and raise the retriable error so
        step()'s overload handler backs off and reroutes."""
        if not resp_meta.get("overloaded"):
            return
        retry_ms = _sanitize_retry_ms(resp_meta.get("retry_after_ms"))
        self.manager.note_peer_overloaded(
            peer_id,
            retry_after_s=retry_ms / 1000.0 if retry_ms else None,
        )
        raise OverloadedError(
            resp_meta.get("reason", "server overloaded"),
            retry_after_ms=retry_ms,
        )

    def _note_shed_exc(self, e: OverloadedError, peer_id: str) -> None:
        """Wire-level overloaded err frame (session-open shed) seen on a
        span's stream: short overload penalty instead of a fault ban."""
        self.manager.note_peer_overloaded(
            peer_id,
            retry_after_s=(
                e.retry_after_ms / 1000.0 if e.retry_after_ms else None
            ),
        )

    # ----------------------------------------------------------- prefix cache
    async def _probe_prefix(
        self,
        id_rows: list[list[int]] | None = None,
        hidden_rows: list[np.ndarray] | None = None,
    ) -> int:
        """Ask every span how much of each row's history its shared-prefix
        pool already holds; returns the chain-wide skippable token count
        (min over spans AND rows — every span receives the same suffix
        hidden, so the chain can only skip what ALL of them have).

        Probes hash whichever history the caller passes: token-id rows
        (the normal prompt / replay path) or raw [T, D] hidden rows
        (embed-less sessions — their chains use a distinct hash root so
        they can never alias an id chain). Spans that don't advertise a
        page size (cache off / old server) force 0. Wire failures
        propagate as step errors so the caller's retry loop rebuilds the
        chain — a timed-out probe must never leave a stale reply queued
        on a reused stream."""
        from bloombee_tpu.kv.prefix import hidden_hash_chain, page_hash_chain

        rows = id_rows if id_rows is not None else hidden_rows
        builder = page_hash_chain if id_rows is not None else hidden_hash_chain
        lens = [len(r) for r in rows] if rows else []
        ps_list = [s.span.server_info.page_size for s in self._spans]
        if not ps_list or any(ps <= 0 for ps in ps_list) or not any(lens):
            # some span can't share (or nothing to hash): whole-chain miss
            return 0
        sizes = set(ps_list)
        chains_by_ps = {
            ps: [builder(row, ps) for row in rows] for ps in sizes
        }
        step_id = self._step_counter
        self._step_counter += 1
        for s in self._spans:
            chains = chains_by_ps[s.span.server_info.page_size]
            await s.stream.send(
                {"step": step_id, "prefix_probe": chains}, []
            )
        matched = None
        for i, s in enumerate(self._spans):
            try:
                item = await asyncio.wait_for(
                    s.stream.recv(), self.step_timeout
                )
            except OverloadedError as e:
                self._note_shed_exc(e, s.span.peer_id)
                raise
            except (RpcError, OSError, asyncio.TimeoutError):
                self.manager.ban_peer(s.span.peer_id)
                raise
            if item is None:
                self.manager.ban_peer(s.span.peer_id)
                raise RpcError(f"span {i} closed during prefix probe")
            resp_meta, _ = item
            _raise_if_session_lost(resp_meta)
            self._raise_if_shed(resp_meta, s.span.peer_id)
            span_min = min(
                int(x) for x in resp_meta.get("prefix_matched") or [0]
            )
            matched = span_min if matched is None else min(matched, span_min)
        # cap below the shortest row so the final prompt position always
        # computes (the caller consumes its output) — ALSO the genuine
        # divergence point: the uncached tail writes into the last shared
        # page and copy-on-write splits it server-side
        shortest = min(lens)
        return max(0, min(matched or 0, shortest - 1))

    # ------------------------------------------------------- kv replication
    def _history_rows(self):
        """(kind, per-row history) for hashing: ("ids", ragged id lists),
        ("hidden", [T, D] arrays), or (None, None) when nothing committed
        yet (or the history kinds are mixed — recovery refuses those)."""
        if any(self._id_rows):
            if self._history:
                return None, None
            return "ids", self._id_rows
        if self._history:
            full = np.concatenate(self._history, axis=1)
            return "hidden", [full[i] for i in range(full.shape[0])]
        return None, None

    def _full_chains(self, ps: int) -> list[list[str]] | None:
        """Per-row hash chains over the session's FULL committed history
        (prompt + generated) at page size `ps`, extended incrementally —
        sealed pages already hashed are never rehashed."""
        kind, rows = self._history_rows()
        if kind is None:
            return None
        from bloombee_tpu.kv.prefix import hidden_hash_chain, page_hash_chain

        fn = page_hash_chain if kind == "ids" else hidden_hash_chain
        cached = self._chains_by_ps.get(ps)
        chains = [
            fn(row, ps, chain=cached[i] if cached else None)
            for i, row in enumerate(rows)
        ]
        self._chains_by_ps[ps] = chains
        return chains

    def _init_repl(self) -> None:
        """(Re)select one standby per span for KV replication. A None slot
        means that span can't replicate: knob off, no page size advertised,
        the session uses a sub-span of the server (its pages would carry
        layers the session doesn't own), or no capable same-span
        alternative exists — all of which degrade to plain full-replay
        recovery, byte-for-byte today's behavior."""
        self._repl = [None] * len(self._spans)
        if self.repl_every <= 0 or not self.prefix_cache:
            return
        exclude = {s.span.peer_id for s in self._spans}
        for i, s in enumerate(self._spans):
            info = s.span.server_info
            if (
                info.page_size <= 0
                or s.span.start != info.start_block
                or s.span.end != info.end_block
            ):
                continue
            standby = self.manager.pick_standby(s.span, exclude=exclude)
            if standby is None:
                continue
            self._repl[i] = {
                "standby": {
                    "host": standby.server_info.host,
                    "port": standby.server_info.port,
                },
                "peer": standby.peer_id,
                "announced": [0] * self.batch_size,
            }

    def _standby_peers(self) -> set[str]:
        """Peers holding (some of) this session's replicated pages — the
        recovery route hint."""
        return {st["peer"] for st in self._repl or [] if st is not None}

    async def _maybe_replicate(self) -> None:
        """Announce newly-sealed pages to each span's server, which exports
        them off the critical path and kv_puts them into the standby's
        prefix pool. Fire-and-forget: no reply rides the stream (so the
        step recv loop never desyncs) and a failed send just leaves the
        pages for the next interval."""
        live = [st for st in self._repl if st is not None]
        if not live:
            self.repl_lag_pages = 0
            return
        kind, rows = self._history_rows()
        if kind is None:
            return
        lag = 0
        for s, st in zip(self._spans, self._repl):
            if st is None:
                continue
            ps = s.span.server_info.page_size
            sealed = [len(r) // ps for r in rows]
            behind = max(
                sl - a for sl, a in zip(sealed, st["announced"])
            )
            if behind < self.repl_every:
                lag = max(lag, behind)
                continue
            chains = self._full_chains(ps)
            if chains is None:
                return
            try:
                await s.stream.send(
                    {"kv_repl": {"standby": st["standby"], "chains": chains}},
                    [],
                )
            except (RpcError, OSError, asyncio.TimeoutError) as e:
                logger.debug("kv_repl announce failed: %s", e)
                lag = max(lag, behind)
                continue
            st["announced"] = sealed
        self.repl_lag_pages = lag

    # ------------------------------------------------------------------ steps
    async def step(
        self,
        hidden: np.ndarray,  # [B, T, D]
        commit: bool = True,
        tree_mask: np.ndarray | None = None,
        depths: np.ndarray | None = None,
        accept: list | None = None,
        ids: np.ndarray | None = None,  # [B, T]: enables token-id replay
        commit_lens: list | None = None,
        prune: dict | None = None,  # mid-chain tree pruning (tree steps)
        accept_per_span: list | None = None,  # pruned chains: accept per span
        rows: tuple | None = None,  # (lo, hi): hidden covers only this
        # contiguous row window of the session's cache; accept stays
        # full-width (servers apply it before slicing the handle)
    ) -> np.ndarray:
        """Push hidden through the whole chain; returns last span's output
        (or (output, keep) for pruned tree steps)."""
        attempt = 0
        overload_waits = 0
        resume_step = None  # (step_id, skip): retransmit after a resume
        while True:
            try:
                if self._needs_rebuild:
                    await self._recover()
                    self._needs_rebuild = False
                if prune is not None or accept_per_span is not None:
                    return await self._step_pruned(
                        hidden, tree_mask, depths, prune, accept_per_span
                    )
                send_hidden, skip, step_id = hidden, None, None
                if resume_step is not None:
                    # retransmit the exact failed step: same id (servers
                    # that applied it dedup instead of re-applying), same
                    # prefix skip (identical suffix bytes) — and no fresh
                    # probe, which would both waste a round trip and bump
                    # the server's last-applied step past the retransmit
                    step_id, skip = resume_step
                    resume_step = None
                    if skip:
                        send_hidden = hidden[:, skip:]
                elif (
                    # shared-prefix fast path: on the session's FIRST
                    # committed prefill, probe the chain's prefix pools and
                    # ship only the uncached suffix (the servers' KV for the
                    # skipped positions is adopted from pooled pages). The
                    # returned output covers only the suffix — callers
                    # consume the last position, which is always kept (the
                    # probe caps the skip below the prompt).
                    self.prefix_cache
                    and commit
                    and tree_mask is None
                    and ids is not None
                    and self.position == 0
                    and hidden.shape[1] > 1
                ):
                    skip = await self._probe_prefix(
                        [list(map(int, row)) for row in np.asarray(ids)]
                    )
                    if skip:
                        send_hidden = hidden[:, skip:]
                out = await self._step_once(
                    send_hidden, commit, tree_mask, depths, accept,
                    commit_lens, prefix_skip=skip, step_id=step_id,
                    rows=rows,
                )
                if (
                    self._gate is not None
                    and self.audit_p > 0
                    and commit
                    and tree_mask is None
                    and rows is None
                    and self._audit_rng.random() < self.audit_p
                ):
                    # BEFORE the commit: a convicted primary raises here
                    # and the retry loop re-executes the step on an honest
                    # chain, so the lying output never reaches the caller
                    # and the committed history stays clean
                    await self._audit_step(out, ids, skip)
                if commit and tree_mask is None:
                    self._record_span_inputs(skip)
                    if ids is not None and self.embed_fn is not None:
                        for i, row in enumerate(np.asarray(ids)):
                            self._id_rows[i].extend(int(t) for t in row)
                    else:
                        self._history.append(hidden)
                    self.position += hidden.shape[1]
                    await self._maybe_replicate()
                return out
            except OverloadedError as e:
                # retriable shed: the peer told us to go elsewhere, not that
                # it is broken. Separate (more generous) budget than fault
                # retries, honor the server's retry_after hint, then reroute
                # — the overload penalty in the manager steers the rebuilt
                # chain away from the hot peer.
                overload_waits += 1
                if overload_waits > self.overload_retries:
                    raise
                wait_s = min((e.retry_after_ms or 500) / 1000.0, 5.0)
                wait_s *= random.uniform(0.75, 1.25)
                logger.info(
                    "step shed by overloaded server (%s); rerouting in "
                    "%.2fs (shed %d/%d)",
                    e, wait_s, overload_waits, self.overload_retries,
                )
                await asyncio.sleep(wait_s)
                try:
                    await self._recover()
                    accept = None
                    accept_per_span = None
                except (
                    RpcError, OSError, asyncio.TimeoutError,
                    MissingBlocksError,
                ) as e2:
                    logger.warning("recovery after shed failed: %s", e2)
            except (RpcError, OSError, asyncio.TimeoutError) as e:
                attempt += 1
                if attempt > self.max_retries:
                    raise
                if (
                    self.resume
                    and self._last_sent is not None
                    and prune is None
                    and accept_per_span is None
                    # resume would retransmit to the SAME peer whose output
                    # an integrity check just rejected — and a lying
                    # server's at-most-once dedup would replay the recorded
                    # lie verbatim. Integrity rejects always take the full
                    # reroute+replay path.
                    and not isinstance(e, IntegrityError)
                ):
                    # cheap path first: re-attach the lease-parked sessions
                    # on fresh streams and retransmit the failed step under
                    # its original id — spans that already applied it answer
                    # from the recorded reply, so no KV is rebuilt and no
                    # prompt token is replayed
                    last = self._last_sent
                    if await self._try_resume():
                        resume_step = last
                        logger.info(
                            "step failed (%s); resumed session, "
                            "retransmitting step %d", e, last[0],
                        )
                        continue
                logger.warning(
                    "step failed (%s); re-routing (attempt %d)", e, attempt
                )
                try:
                    await self._recover()
                    # history replay already committed every accepted token
                    # on the fresh chain; the rebuilt servers have an empty
                    # speculative window, so a carried accept is stale
                    accept = None
                    accept_per_span = None
                except (
                    RpcError, OSError, asyncio.TimeoutError,
                    MissingBlocksError,
                ) as e2:
                    logger.warning("recovery attempt failed: %s", e2)
                    await asyncio.sleep(min(0.2 * attempt, 2.0))

    def _note_spans_ok(self) -> None:
        """A full step succeeded through every span: clear any ban history
        (half-open probes resolve to healthy; backoff resets to base)."""
        for s in self._spans:
            self.manager.note_peer_ok(s.span.peer_id)

    # ------------------------------------------------------------- integrity
    def _check_span_output(self, span_sess, resp_meta, chunk) -> None:
        """Inline checks on one received span-output chunk, run BEFORE the
        chunk enters the output buffer or gets relayed to the next span.
        Digest first (exact: the server hashed the exact bytes it
        serialized, so any in-flight corruption mismatches — this is a
        same-bytes check, never a cross-replica float compare), then the
        O(B*D) sanity gate (all-finite + activation-RMS envelope)."""
        span = span_sess.span
        digest = resp_meta.get("out_digest")
        verified = False
        if digest is not None:
            from bloombee_tpu.kv.prefix import out_digest

            if out_digest(chunk) != digest:
                # bytes changed BETWEEN serialization and us: that is
                # evidence against the wire, not the peer (a liar's digest
                # matches its lie) — ordinary short ban, no quarantine
                # strike, so ambient chaos corruption never convicts an
                # honest server of lying
                self._integrity_reject(
                    span.peer_id,
                    "out_digest mismatch (in-flight corruption)",
                    strike=False, ban=True,
                )
            verified = True
        reason = self._gate.check((span.start, span.end), chunk)
        if reason is not None:
            # a digest-VERIFIED gate reject is the peer's own computation
            # (the wire is ruled out): count a strike but do NOT ban, so
            # routing re-picks the peer and its next lie convicts it at
            # the strike limit — conviction needs repeat evidence, never
            # a single sample. Without a digest the wire could be at
            # fault, so the reroute also takes the safe short ban.
            self._integrity_reject(
                span.peer_id, reason, strike=True, ban=not verified
            )

    def _integrity_reject(
        self, peer_id: str, reason: str, strike: bool, ban: bool
    ) -> None:
        """An integrity check failed: raise into the session retry loop —
        integrity rejects heal exactly like crash faults (reroute +
        replay), they just never silently propagate a poisoned activation
        downstream. `strike=True` (the digest passed or was absent, yet
        the numbers are wrong: the peer COMPUTED garbage) counts a
        quarantine strike, tipping a repeat offender into quarantine;
        `ban` additionally takes the ordinary short fault ban so the
        rebuilt route avoids the peer right now."""
        self.sanity_rejects += 1
        self.integrity_reroutes += 1
        if strike:
            self.manager.note_integrity_strike(peer_id)
        if ban:
            self.manager.ban_peer(peer_id)
        logger.warning(
            "integrity reject: %s from peer %s; rerouting", reason, peer_id
        )
        raise IntegrityError(f"span output rejected ({reason})")

    def _record_span_inputs(self, skip) -> None:
        """Accumulate per-span input history for cross-replica audits.
        Relay-mode chunks are exactly span i+1's inputs, so recording them
        costs nothing extra; span 0 never records (its input re-embeds
        from the id history on demand). Anything that breaks completeness
        — prefix skip, push-mode multi-span hops the client never sees, a
        rerouted chain — invalidates the record and audits fall back to
        span 0 only."""
        if self._gate is None or self.audit_p <= 0 or len(self._spans) <= 1:
            return
        outs = self._last_span_outs
        if (
            skip
            or self.use_push
            or len(outs) != len(self._spans)
            or any(o is None for o in outs[:-1])
            or (
                self._span_in is not None
                and len(self._span_in) != len(self._spans)
            )
        ):
            self._span_in = None
            return
        if self._span_in is None:
            if self.position != 0:
                return  # history started before recording did: incomplete
            self._span_in = [[] for _ in self._spans]
        for i in range(1, len(self._spans)):
            self._span_in[i].append(outs[i - 1])

    def _find_covering(self, start: int, end: int, exclude: set):
        """Active (non-banned, non-quarantined) spans whose server covers
        [start, end), deterministically ordered."""
        spans = [
            s for s in self.manager._active_spans()
            if s.peer_id not in exclude and s.start <= start and s.end >= end
        ]
        spans.sort(key=lambda s: s.peer_id)
        return spans

    async def _remote_forward(self, span, start, end, hidden):
        """Re-execute blocks [start, end) over the full recorded input on
        `span`'s server via the sessionless rpc_forward plane. Returns the
        f32 output, or None when the server is unreachable or declines
        (hetero/host-offload spans have no training path) — an absent
        auditor is never evidence against anyone."""
        try:
            conn = await connect(
                span.server_info.host, span.server_info.port,
                keepalive_s=self.keepalive_s,
            )
        except (OSError, RpcError, asyncio.TimeoutError):
            return None
        try:
            meta = {"start": int(start), "end": int(end), "audit": True}
            if self.adapter:
                meta["adapter"] = self.adapter
            resp, tensors = await conn.call(
                "rpc_forward", meta,
                [np.ascontiguousarray(hidden, dtype=np.float32)],
                timeout=self.step_timeout,
            )
            if not resp.get("ok") or not tensors:
                return None
            return np.asarray(tensors[0], dtype=np.float32)
        except (OSError, RpcError, asyncio.TimeoutError):
            return None
        finally:
            try:
                await conn.close()
            except Exception:
                pass

    async def _audit_step(self, out, ids, skip) -> None:
        """Probabilistic activation audit: re-execute the step just
        received for one span S on a DIFFERENT server covering S, over
        S's full recorded input history (attention needs every previous
        position — a single-step re-execution would compare garbage), and
        tolerance-compare the last step's positions.

        NEVER exact equality: honest replicas differ in ulps because
        float reductions are batch-width dependent (the primary may have
        batched our rows with another session's). A digest fast-path
        short-circuits the compare when the replicas happen to agree
        bitwise; a mismatch escalates to the dtype-aware tolerance
        compare, never straight to a verdict. Disagreement within
        tolerance triggers a third-replica tiebreak when one exists; the
        outvoted peer is quarantined. No quorum -> suspicion strikes for
        both, conviction for neither."""
        from bloombee_tpu.kv.prefix import out_digest

        # choose an auditable span: 0 when the id history re-embeds
        # cleanly, plus any span with a complete relay input record
        candidates: list[int] = []
        if (
            self.embed_fn is not None
            and not self._history
            and ids is not None
            and len({len(r) for r in self._id_rows}) == 1
        ):
            candidates.append(0)
        if self._span_in is not None and len(self._span_in) == len(self._spans):
            candidates.extend(range(1, len(self._spans)))
        if not candidates:
            return
        i = candidates[self._audit_rng.randrange(len(candidates))]
        span_sess = self._spans[i]
        span = span_sess.span
        outs = self._last_span_outs
        primary_out = outs[i] if i < len(outs) else None
        if primary_out is None:
            return
        peers = self._find_covering(span.start, span.end, {span.peer_id})
        if not peers:
            return  # no alternative replica covers S on this topology
        # reconstruct span S's full input history (this step included —
        # the audit runs before the commit, so the id rows don't hold this
        # step's ids yet)
        if i == 0:
            rows = [
                list(r) + [int(t) for t in step_row]
                for r, step_row in zip(self._id_rows, np.asarray(ids))
            ]
            if len({len(r) for r in rows}) != 1:
                return
            full_in = np.asarray(
                self.embed_fn(np.asarray(rows, dtype=np.int64)),
                dtype=np.float32,
            )
        else:
            prev = outs[i - 1]
            if prev is None:
                return
            full_in = np.concatenate(self._span_in[i] + [prev], axis=1)
        self.audits_run += 1
        aud_out = await self._remote_forward(
            peers[0], span.start, span.end, full_in
        )
        if aud_out is None or aud_out.shape[1] < primary_out.shape[1]:
            return  # auditor unavailable: not evidence against the primary
        t_step = primary_out.shape[1]
        aud_tail = np.ascontiguousarray(aud_out[:, -t_step:])
        wire_dt = span.server_info.wire_dtype
        if out_digest(aud_tail) == out_digest(
            np.ascontiguousarray(primary_out)
        ):
            return  # bitwise agreement: cheap fast-path, nothing to judge
        if tensors_close(aud_tail, primary_out, dtype=wire_dt):
            return  # within tolerance: ulp drift, both honest
        self.audit_mismatches += 1
        third = self._find_covering(
            span.start, span.end, {span.peer_id, peers[0].peer_id}
        )
        third_out = (
            await self._remote_forward(
                third[0], span.start, span.end, full_in
            ) if third else None
        )
        if third_out is None or third_out.shape[1] < t_step:
            # no quorum: suspicion (not conviction) strikes both sides
            logger.warning(
                "audit mismatch on span [%d,%d) with no tiebreak replica: "
                "striking %s and %s", span.start, span.end, span.peer_id,
                peers[0].peer_id,
            )
            self.manager.note_integrity_strike(span.peer_id)
            self.manager.note_integrity_strike(peers[0].peer_id)
            return
        third_tail = np.ascontiguousarray(third_out[:, -t_step:])
        agrees_primary = tensors_close(third_tail, primary_out, dtype=wire_dt)
        agrees_auditor = tensors_close(third_tail, aud_tail, dtype=wire_dt)
        if agrees_primary and not agrees_auditor:
            logger.warning(
                "audit tiebreak: auditor %s outvoted; quarantining it",
                peers[0].peer_id,
            )
            self.manager.quarantine_peer(peers[0].peer_id)
            return
        if agrees_auditor and not agrees_primary:
            # primary convicted: quarantine and re-execute the step on an
            # honest chain (we ran before the commit, so history is clean)
            self.manager.quarantine_peer(span.peer_id)
            self.integrity_reroutes += 1
            raise IntegrityError(
                f"audit convicted span peer {span.peer_id} "
                f"(outvoted 2-to-1 on blocks [{span.start},{span.end}))"
            )
        # three-way disagreement: something is deeply wrong, but there is
        # no majority — strike everyone, convict no one
        for pid in (span.peer_id, peers[0].peer_id, third[0].peer_id):
            self.manager.note_integrity_strike(pid)

    async def _step_pruned(
        self, hidden, tree_mask, depths, prune, accept_per_span
    ):
        """Tree step through the chain with mid-chain pruning: span 0 runs
        the full tree and returns only surviving rows + keep indices; the
        client forwards the pruned tree (restricted mask/depths) downstream
        (relay mode only). Accepts may differ per span — downstream spans
        hold KV in kept-row order (reference backend.py:763-775 +
        block_functions.py restore_hidden_states, inverted client-side).

        Returns (out [B, K, D] fp32, keep [B, K] or None if the pruning
        span has no pruner weight)."""
        if not self._spans:
            raise RpcError("session chain is closed (recovery pending)")
        if self.use_push and len(self._spans) > 1:
            raise ValueError("pruned tree steps need relay mode (use_push=False)")
        assert tree_mask is not None and depths is not None
        step_id = self._step_counter
        self._step_counter += 1
        wire_dt = dtype_for_name(self._spans[0].span.server_info.wire_dtype)
        chunk = hidden.astype(wire_dt)
        mask_u8 = np.asarray(tree_mask).astype(np.uint8)
        depths_list = np.asarray(depths).tolist()
        keep = None

        t_start = time.perf_counter()
        compute_ms = []
        for i, span_sess in enumerate(self._spans):
            meta = {
                "step": step_id,
                "commit": False,
                "tree": True,
                "depths": depths_list,
                "reply": "tensor",
                "deadline_s": self.step_timeout,
            }
            if accept_per_span is not None and accept_per_span[i] is not None:
                meta["accept"] = [
                    np.asarray(a).tolist() for a in accept_per_span[i]
                ]
            if i == 0 and prune is not None:
                meta["prune"] = prune
            try:
                await span_sess.stream.send(meta, [chunk, mask_u8])
                item = await asyncio.wait_for(
                    span_sess.stream.recv(), self.step_timeout
                )
            except OverloadedError as e:
                self._note_shed_exc(e, span_sess.span.peer_id)
                raise
            except (RpcError, OSError, asyncio.TimeoutError):
                self.manager.ban_peer(span_sess.span.peer_id)
                raise
            if item is None:
                self.manager.ban_peer(span_sess.span.peer_id)
                raise RpcError(f"span {i} closed mid-session")
            resp_meta, resp_tensors = item
            _raise_if_session_lost(resp_meta)
            self._raise_if_shed(resp_meta, span_sess.span.peer_id)
            compute_ms.append(resp_meta.get("t_compute_ms"))
            chunk = resp_tensors[0]
            if self._gate is not None:
                self._check_span_output(span_sess, resp_meta, chunk)
            if i == 0 and resp_meta.get("keep") is not None:
                from bloombee_tpu.spec.tree import pruned_step_arrays

                keep = np.asarray(resp_meta["keep"], dtype=np.int32)
                mask_k, depths_k = pruned_step_arrays(
                    np.asarray(tree_mask, dtype=bool),
                    np.asarray(depths),
                    keep,
                )
                mask_u8 = mask_k.astype(np.uint8)
                depths_list = depths_k.tolist()
        self._note_spans_ok()
        self.timings.append(
            {
                "step": step_id,
                "tokens": hidden.shape[1],
                "span_compute_ms": compute_ms,
                "total_ms": (time.perf_counter() - t_start) * 1000.0,
            }
        )
        return np.asarray(chunk, dtype=np.float32), keep

    async def _step_once(
        self, hidden, commit, tree_mask, depths=None, accept=None,
        commit_lens=None, prefix_skip=None, step_id=None, rows=None,
    ):
        if not self._spans:
            # a failed recovery left no open chain; surface as a retryable
            # wire error so the caller's retry loop attempts recovery again
            raise RpcError("session chain is closed (recovery pending)")
        if step_id is None:
            step_id = self._step_counter
            self._step_counter += 1
        # remembered for reconnect-resume: a retransmit after a resumed
        # stream must reuse this exact id (the server's at-most-once dedup
        # keys on it) and the same prefix_skip (same suffix bytes)
        self._last_sent = (step_id, prefix_skip)
        meta_base = {
            "step": step_id,
            "commit": commit,
            "tree": tree_mask is not None,
            # remaining-time budget: the server aborts work this client
            # has already given up on (it shrinks the budget by its own
            # elapsed time before forwarding down a push route)
            "deadline_s": self.step_timeout,
        }
        if depths is not None:
            meta_base["depths"] = np.asarray(depths).tolist()
        if accept is not None:
            meta_base["accept"] = [np.asarray(a).tolist() for a in accept]
        if commit_lens is not None:
            meta_base["commit_lens"] = [int(x) for x in commit_lens]
        if prefix_skip is not None:
            # settle the preceding probe: servers keep exactly this many
            # adopted tokens per row (0 drops the adoption). Present on
            # every mb chunk and relay forward via **meta_base.
            meta_base["prefix_skip"] = int(prefix_skip)
        # ship hidden in the first span's advertised wire dtype (bf16 for
        # bf16-compute servers: half the bytes on the latency-critical hop)
        wire_dt = dtype_for_name(self._spans[0].span.server_info.wire_dtype)
        hidden_w = hidden.astype(wire_dt)
        extra = [tree_mask.astype(np.uint8)] if tree_mask is not None else []

        # within-stage micro-batching: plain committed steps only (tree/
        # accept steps keep whole-batch semantics)
        b = hidden.shape[0]
        mb = self.microbatch
        if mb == "auto":
            # size chunks to the pipeline depth (reference
            # microbatch_config.py:84-130 derives the count from the
            # deployment, not a constant): overlap pays when there is more
            # than one stage, and more chunks than stages adds per-chunk
            # overhead without more overlap
            mb = (
                min(b, max(2, len(self._spans)))
                if len(self._spans) > 1 and b > 1
                else 1
            )
        if (
            tree_mask is not None
            or accept is not None
            or commit_lens is not None
            or mb > b
        ):
            mb = 1
        # live-row window (tree steps): hidden carries only rows
        # [rows[0], rows[1]) of the cache — the servers slice their handle
        # to that window, so finished rows stop burning tree slots. All
        # row labels on the wire stay ABSOLUTE; row_base maps them back
        # onto this window-sized hidden/out.
        row_base = 0
        if rows is not None:
            lo_r, hi_r = int(rows[0]), int(rows[1])
            if hi_r - lo_r != b:
                raise ValueError(
                    f"rows window {rows} does not match hidden batch {b}"
                )
            mb = 1
            row_base = lo_r
            bounds = [(lo_r, hi_r)]
        else:
            bounds = [
                (round(k * b / mb), round((k + 1) * b / mb))
                for k in range(mb)
            ]

        route = []
        if self.use_push and len(self._spans) > 1:
            route = [
                {
                    "host": s.span.server_info.host,
                    "port": s.span.server_info.port,
                    "session_id": s.session_id,
                }
                for s in self._spans[1:]
            ]
        for k, (lo, hi) in enumerate(bounds):
            meta = {
                **meta_base,
                "reply": "tensor",
                "mb": k,
                "mb_of": mb,
                "rows": [lo, hi],
            }
            if route:
                meta["route"] = route
            await self._spans[0].stream.send(
                meta, [hidden_w[lo - row_base:hi - row_base]] + extra
            )

        t_start = time.perf_counter()
        out = np.zeros(hidden.shape, dtype=np.float32)
        got_tensor = False
        compute_ms = []
        # per-span outputs this step (audit records): span i's tensor
        # chunks land on span i's stream in both relay and push mode
        span_outs: list = [None] * len(self._spans)
        for i, span_sess in enumerate(self._spans):
            span_ms = 0.0
            for _ in range(mb):
                try:
                    item = await asyncio.wait_for(
                        span_sess.stream.recv(), self.step_timeout
                    )
                except OverloadedError as e:
                    self._note_shed_exc(e, span_sess.span.peer_id)
                    raise
                except (RpcError, OSError, asyncio.TimeoutError):
                    self.manager.ban_peer(span_sess.span.peer_id)
                    raise
                if item is None:
                    self.manager.ban_peer(span_sess.span.peer_id)
                    raise RpcError(f"span {i} closed mid-session")
                resp_meta, resp_tensors = item
                _raise_if_session_lost(resp_meta)
                self._raise_if_shed(resp_meta, span_sess.span.peer_id)
                if resp_meta.get("t_compute_ms") is not None:
                    span_ms += resp_meta["t_compute_ms"]
                if resp_meta.get("ack"):
                    continue
                lo, hi = resp_meta.get("rows") or (row_base, row_base + b)
                chunk = resp_tensors[0]
                if self._gate is not None:
                    # inline integrity: digest + sanity gate BEFORE this
                    # chunk enters `out` or gets forwarded to the next span
                    self._check_span_output(span_sess, resp_meta, chunk)
                out[lo - row_base:hi - row_base] = np.asarray(
                    chunk, dtype=np.float32
                )
                got_tensor = True
                if self._gate is not None and self.audit_p > 0:
                    buf = span_outs[i]
                    if buf is None:
                        buf = span_outs[i] = np.zeros(
                            hidden.shape, dtype=np.float32
                        )
                    buf[lo - row_base:hi - row_base] = np.asarray(
                        chunk, dtype=np.float32
                    )
                if not self.use_push and i + 1 < len(self._spans):
                    # relay mode: forward each chunk as it lands so the next
                    # span starts while this span computes the next chunk
                    fwd_meta = {
                        **meta_base,
                        "reply": "tensor",
                        "mb": resp_meta.get("mb", 0),
                        "mb_of": mb,
                        "rows": [lo, hi],
                    }
                    await self._spans[i + 1].stream.send(
                        fwd_meta, [chunk] + extra
                    )
            compute_ms.append(span_ms)
        assert got_tensor, "no span returned a tensor"
        self._last_span_outs = span_outs
        self._note_spans_ok()
        total_ms = (time.perf_counter() - t_start) * 1000.0
        self.timings.append(
            {
                "step": step_id,
                "tokens": hidden.shape[1],
                "span_compute_ms": compute_ms,
                "total_ms": total_ms,
            }
        )
        return out

    def timing_summary(self) -> dict:
        """Aggregate decode-step timing: mean per-span compute vs wire+other
        (the client-side view of the reference's paper timing tables)."""
        decode = [t for t in self.timings if t["tokens"] == 1]
        rows = decode or self.timings
        if not rows:
            return {}
        n_spans = max(len(t["span_compute_ms"]) for t in rows)
        per_span = [
            float(
                np.mean(
                    [
                        t["span_compute_ms"][i]
                        for t in rows
                        if len(t["span_compute_ms"]) > i
                        and t["span_compute_ms"][i] is not None
                    ]
                    or [0.0]
                )
            )
            for i in range(n_spans)
        ]
        total = float(np.mean([t["total_ms"] for t in rows]))
        compute = float(np.sum(per_span))
        from bloombee_tpu.wire.tensor_codec import transport_stats

        return {
            "steps": len(rows),
            "mean_total_ms": total,
            "mean_compute_ms_per_span": per_span,
            "mean_wire_and_overhead_ms": total - compute,
            # process-wide codec counters (the reference transport
            # profiling channels' client half)
            "transport": transport_stats(),
            # per-span off-loop pipeline counters (wire/pipeline.py): the
            # client half of the codec scheduling the servers report via
            # rpc_info["wire_pipeline"]
            "wire_pipeline": [
                s.conn.pipeline.stats() for s in self._spans
                if s.conn is not None
            ],
        }

    async def decode_n(
        self,
        ids: np.ndarray,  # [B] int: input token of the first step
        n: int,
        eos_token_id: int | None = None,
        finished: np.ndarray | None = None,  # [B] bool rows already at EOS
        head_dtype: str | None = None,  # client's lm_head dtype; servers
        # decline on mismatch so logits stay identical across both paths
    ) -> np.ndarray:
        """Server-side multi-step greedy decode: one RPC returns [B, n] token
        ids — the round-trip-amortizing fast path. Single-span routes run
        the fused on-device scan (runtime/decode_loop.py) or the server's
        host-driven loop; multi-span routes run CHAINED decode: span 0
        embeds and coordinates, hidden states hop server-to-server via
        rpc_push, the tail span applies norm+head+select and pushes each
        next id back to span 0, which replies all n ids at once. Either
        way the client pays ONE round trip per n tokens. Raises
        DecodeNUnsupported when the server declines, so the caller can
        fall back to per-step decoding.

        The servers write n tokens of KV (the input token plus the first
        n-1 selected tokens), so position advances by n and those ids enter
        the replay history.

        Exactness caveat: a chunk whose context CROSSES the paged-attention
        crossover (BBTPU_PAGED_MIN_CONTEXT) runs one kernel for the whole
        chunk while the per-step path would switch mid-way; the kernels
        agree to ~1e-5, so only an exact argmax tie at the boundary could
        differ (runtime/executor.py decode_n gating)."""
        if self.embed_fn is None and not self._warned_no_embed:
            # ids recorded without an embed_fn cannot be replayed: a later
            # transient transport failure becomes a hard RuntimeError in
            # _recover instead of a transparent re-route (fail-loud is
            # intentional; the warning makes the trade visible up front).
            # WARNING once per process, DEBUG for later sessions — a bench
            # tail spawning many raw sessions repeats the identical line
            global _warned_no_embed_process
            self._warned_no_embed = True
            log = (
                logger.debug if _warned_no_embed_process else logger.warning
            )
            _warned_no_embed_process = True
            log(
                "decode_n on a session without embed_fn: the session loses "
                "failure recovery (id history cannot be re-embedded); use "
                "model.inference_session() for recoverable decode"
            )
        self._check_decode_n_route()
        ids = np.asarray(ids).reshape(-1).astype(np.int32)
        attempt = 0
        overload_waits = 0
        resume_step = None  # step_id to retransmit after a resume
        while True:
            try:
                if self._needs_rebuild:
                    await self._recover()
                    self._needs_rebuild = False
                    self._check_decode_n_route()
                step_id, resume_step = resume_step, None
                toks = await self._decode_n_once(
                    ids, n, eos_token_id, finished, head_dtype,
                    step_id=step_id,
                )
            except OverloadedError as e:
                # retriable shed (see step()): separate budget, honor the
                # retry hint, reroute via the overload-penalized manager
                overload_waits += 1
                if overload_waits > self.overload_retries:
                    raise
                wait_s = min((e.retry_after_ms or 500) / 1000.0, 5.0)
                wait_s *= random.uniform(0.75, 1.25)
                logger.info(
                    "decode_n shed by overloaded server (%s); rerouting in "
                    "%.2fs (shed %d/%d)",
                    e, wait_s, overload_waits, self.overload_retries,
                )
                await asyncio.sleep(wait_s)
                try:
                    await self._recover()
                    self._needs_rebuild = False
                    self._check_decode_n_route()
                except (
                    RpcError, OSError, asyncio.TimeoutError,
                    MissingBlocksError,
                ) as e2:
                    logger.warning("recovery after shed failed: %s", e2)
                continue
            except (RpcError, OSError, asyncio.TimeoutError) as e:
                attempt += 1
                if attempt > self.max_retries:
                    raise
                if self.resume and self._last_sent is not None:
                    # cheap path first (see step()): re-attach the parked
                    # sessions and retransmit the chunk under its original
                    # id; a coordinator that already finished it replies
                    # the recorded [B, n] tokens (at-most-once). A chunk
                    # that died mid-commit leaves the server kv_dirty, so
                    # its park is refused and this decline is immediate.
                    last = self._last_sent
                    if await self._try_resume():
                        resume_step = last[0]
                        logger.info(
                            "decode_n failed (%s); resumed session, "
                            "retransmitting step %d", e, last[0],
                        )
                        continue
                logger.warning(
                    "decode_n failed (%s); re-routing (attempt %d)",
                    e, attempt,
                )
                try:
                    await self._recover()
                    # recovery replayed the full history; a dirty-decline's
                    # pending rebuild is satisfied
                    self._needs_rebuild = False
                    self._check_decode_n_route()
                except (
                    RpcError, OSError, asyncio.TimeoutError,
                    MissingBlocksError,
                ) as e2:
                    logger.warning("recovery attempt failed: %s", e2)
                    await asyncio.sleep(min(0.2 * attempt, 2.0))
                continue
            # KV now holds [input, toks[:, :-1]] per row: record for replay
            written = np.concatenate([ids[:, None], toks[:, :-1]], axis=1)
            for i, row in enumerate(written):
                self._id_rows[i].extend(int(t) for t in row)
            self.position += n
            self._span_in = None  # server-side hops: no relay record
            await self._maybe_replicate()
            return toks

    def _check_decode_n_route(self) -> None:
        """decode_n needs a route whose spans cover the whole model: span 0
        embeds (must enter at block 0) and the tail applies the head (must
        end at the last block). Multi-span routes additionally chain via
        server-to-server push."""
        if not self._spans:
            return  # closed chain surfaces as RpcError in _decode_n_once
        if (
            self._spans[0].span.start != 0
            or self._spans[-1].span.end != self.manager.num_blocks
        ):
            raise DecodeNUnsupported(
                "route does not cover the whole model"
            )
        if len(self._spans) > 1 and not self.use_push:
            raise DecodeNUnsupported(
                "chained decode_n needs push transport (use_push=True)"
            )

    async def _decode_n_once(
        self, ids, n, eos_token_id, finished, head_dtype=None, step_id=None
    ) -> np.ndarray:
        if not self._spans:
            raise RpcError("session chain is closed (recovery pending)")
        if step_id is None:
            step_id = self._step_counter
            self._step_counter += 1
        # remembered for reconnect-resume: a retransmit after a resumed
        # stream must reuse this exact id so a coordinator that already
        # finished the chunk answers from its recorded reply
        self._last_sent = (step_id, None)
        meta = {
            "step": step_id,
            "decode_n": int(n),
            "reply": "tensor",
            # matches the client's own recv budget below: once that expires
            # the client re-routes, so any remaining server work is wasted
            "deadline_s": 2 * self.step_timeout + float(n),
        }
        if eos_token_id is not None:
            meta["eos_token_id"] = int(eos_token_id)
        if finished is not None:
            meta["finished"] = np.asarray(finished, dtype=bool).tolist()
        if head_dtype is not None:
            meta["head_dtype"] = head_dtype
        if len(self._spans) > 1:
            # chained decode: span 0 coordinates; give it the downstream
            # hops (same wire shape as the per-step push route)
            meta["route"] = [
                {
                    "host": s.span.server_info.host,
                    "port": s.span.server_info.port,
                    "session_id": s.session_id,
                }
                for s in self._spans[1:]
            ]
        span_sess = self._spans[0]
        t_start = time.perf_counter()
        try:
            await span_sess.stream.send(meta, [ids])
            # one RPC covers n whole-model steps; chained routes also pay
            # per-token server-to-server hops and may hit cold XLA
            # compiles on MIDDLE/TAIL spans (the coordinator itself allows
            # chain_step_timeout=120s per hop for that) — budget at least
            # two cold compiles so a healthy coordinator is never banned
            # for its downstream spans' first-step compile time
            item = await asyncio.wait_for(
                span_sess.stream.recv(), 2 * self.step_timeout + float(n)
            )
        except OverloadedError as e:
            self._note_shed_exc(e, span_sess.span.peer_id)
            raise
        except (RpcError, OSError, asyncio.TimeoutError):
            self.manager.ban_peer(span_sess.span.peer_id)
            raise
        if item is None:
            self.manager.ban_peer(span_sess.span.peer_id)
            raise RpcError("span closed mid-session")
        resp_meta, resp_tensors = item
        _raise_if_session_lost(resp_meta)
        self._raise_if_shed(resp_meta, span_sess.span.peer_id)
        if resp_meta.get("decode_n_unsupported"):
            if resp_meta.get("dirty"):
                # a chained decode failed mid-way: spans hold ragged extra
                # KV beyond the committed history — rebuild-and-replay on
                # the session's next use restores exact state
                self._needs_rebuild = True
            if resp_meta.get("transient"):
                # a span died mid-chain (not a capability decline): surface
                # as a wire error so the retry loop rebuilds the route,
                # replays, and RETRIES chained decode instead of dropping
                # the fast path for the rest of the generation
                raise RpcError(
                    resp_meta.get("reason") or "chained decode_n failed"
                )
            raise DecodeNUnsupported(
                resp_meta.get("reason")
                or "server declined decode_n for this session"
            )
        self._note_spans_ok()
        self.timings.append(
            {
                "step": step_id,
                "tokens": n,
                "decode_n": True,
                "span_compute_ms": [resp_meta.get("t_compute_ms")],
                "total_ms": (time.perf_counter() - t_start) * 1000.0,
            }
        )
        return np.asarray(resp_tensors[0], dtype=np.int64)

    async def send_accept(
        self, accept: list, per_span: list | None = None
    ) -> None:
        """Apply a speculative accept on every span without running compute
        (the final accept of a generation, or an accept with no next tree).
        `per_span` overrides the accept for each span (pruned chains hold KV
        in kept-row order downstream)."""
        step_id = self._step_counter
        self._step_counter += 1
        for i, span_sess in enumerate(self._spans):
            acc = accept if per_span is None else per_span[i]
            meta = {
                "step": step_id,
                "accept": [np.asarray(a).tolist() for a in acc],
                "accept_only": True,
                "reply": "ack",
            }
            await span_sess.stream.send(meta, [])
        for i, span_sess in enumerate(self._spans):
            item = await asyncio.wait_for(
                span_sess.stream.recv(), self.step_timeout
            )
            if item is None:
                raise RpcError(f"span {i} closed during accept")

    def rewind_decoded_tail(self, n_drop: int) -> None:
        """Drop the last `n_drop` tokens from the committed history (every
        row) after a decode_n chunk over-ran an EOS stop. The server-side KV
        still holds them, so the chain is marked for a rebuild-and-replay on
        the session's next use — which restores exactly the rewound context.
        Requires embed_fn (the replay re-embeds ids)."""
        if self.embed_fn is None:
            raise ValueError(
                "rewind_decoded_tail needs a session with embed_fn to "
                "replay the rewound history"
            )
        if n_drop <= 0:
            return
        for row in self._id_rows:
            del row[len(row) - n_drop:]
        self.position -= n_drop
        # incremental chains cover tokens that no longer exist: rehash
        self._chains_by_ps.clear()
        self._span_in = None  # relay records cover dropped tokens too
        self._needs_rebuild = True

    def record_history_ids(self, rows: list[list[int]]) -> None:
        """Ragged per-row committed token ids (batched speculative rounds:
        each row accepts a different count). Requires embed_fn — id history
        can only be replayed by re-embedding."""
        if self.embed_fn is None:
            raise ValueError(
                "record_history_ids needs a session with embed_fn "
                "(model.inference_session provides it)"
            )
        for i, row in enumerate(rows):
            self._id_rows[i].extend(int(t) for t in row)
        # committed via the speculative window: no relay input record
        self._span_in = None

    # -------------------------------------------------------------- recovery
    async def _try_resume(self) -> bool:
        """Cheap half of recovery: reopen each span with `resume:
        session_id` so the server re-attaches our lease-parked session to
        the fresh stream — KV intact, nothing to replay. All-or-nothing
        across spans: any decline (lease expired, leases off, parked pages
        evicted, old server) abandons the whole attempt and the caller
        falls back to the ordinary standby/full-replay path. On success
        the caller retransmits the failed step under its ORIGINAL id;
        spans that already applied it answer from their recorded reply
        (at-most-once), the rest compute it fresh."""
        if not self.resume or not self._spans:
            return False
        old = self._spans
        fresh: list[_SpanSession] = []
        ok = True
        reason = None
        for s in old:
            try:
                conn = await connect(
                    s.span.server_info.host, s.span.server_info.port,
                    keepalive_s=self.keepalive_s,
                )
                stream = await conn.open_stream(
                    "rpc_inference",
                    {
                        "resume": s.session_id,
                        # session_id rides along so the wire trace stays
                        # self-describing; resume-aware servers key off
                        # "resume" alone
                        "session_id": s.session_id,
                        "client_id": self.client_id,
                    },
                )
                fresh.append(_SpanSession(s.span, conn, stream, s.session_id))
                item = await asyncio.wait_for(
                    stream.recv(), self.resume_timeout
                )
                resp_meta = item[0] if item is not None else {}
                if not resp_meta.get("resumed"):
                    ok = False
                    reason = resp_meta.get("reason", "stream closed")
                    break
            except (RpcError, OSError, asyncio.TimeoutError) as e:
                ok = False
                reason = str(e) or type(e).__name__
                break
        if not ok:
            self.resume_declines += 1
            logger.info("session resume declined (%s); falling back to "
                        "full recovery", reason)
            for sp in fresh:
                await sp.close()
            return False
        # the dead streams' conns linger half-open on our side too: abort
        # them so nothing keeps pinging a connection we just superseded
        for sp in old:
            try:
                sp.conn.abort("superseded by resume")
            except Exception:
                pass
        self._spans = fresh
        self.resumed_streams += len(fresh)
        return True

    async def _recover(self) -> None:
        """Rebuild the entire chain and replay history
        (v1 of reference `_update_sequence`: suffix-only rebuild is an
        optimization; full rebuild is correct because servers key KV caches by
        session, and new sessions start empty).

        Route selection prefers peers holding this session's replicated
        pages (the standby hint), so the probe below usually adopts them
        and the replay shrinks to the unsealed tail. A bounded retry loop
        wraps rebuild + replay: each failed attempt bans the offending
        peer (existing backoff machinery), so the next attempt routes
        around it instead of one flaky standby killing the session."""
        if any(self._id_rows) and self.embed_fn is None:
            # id history can only be replayed by re-embedding; a session
            # that recorded ids without an embed_fn (e.g. decode_n from a
            # raw-hidden harness) must fail loudly, not resume with an
            # empty-KV chain
            await self.close()
            raise RuntimeError(
                "session recorded token-id history but has no embed_fn to "
                "replay it"
            )
        if any(self._id_rows) and self._history:
            # both histories populated -> replay interleaving is unknowable;
            # refuse before touching the chain (sessions must record ids
            # consistently: pass ids= to step / record_history_ids)
            await self.close()
            raise RuntimeError(
                "session mixed token-id and hidden-state history; replay "
                "order is ambiguous"
            )
        await self.close()
        attempts = max(1, int(self.max_retries))
        last_exc: Exception | None = None
        for attempt in range(attempts):
            if attempt:
                await asyncio.sleep(min(0.2 * attempt, 1.0))
            try:
                await self._recover_once()
                ledger.recovery("client.reroute_replay")
                return
            except (
                RpcError, OSError, asyncio.TimeoutError, MissingBlocksError,
            ) as e:
                # MissingBlocksError is retriable here: a span can go dark
                # for a beat while the swarm self-heals (standby promoting
                # after the primary died) — give the heal the same bounded
                # retry budget a flaky peer gets
                last_exc = e
                await self.close()
                logger.warning(
                    "recovery attempt %d/%d failed: %s",
                    attempt + 1, attempts, e,
                )
                if isinstance(e, OverloadedError):
                    # replay prefill shed by the rebuilt chain: honor the
                    # retry hint so back-to-back rebuilds don't hammer a
                    # swarm that is uniformly hot
                    await asyncio.sleep(
                        min((e.retry_after_ms or 500) / 1000.0, 2.0)
                    )
        raise last_exc

    async def _recover_once(self) -> None:
        """One rebuild + replay attempt (see _recover)."""
        await self.manager.update(force=True)
        route = self.manager.make_sequence(
            cache_tokens_needed=self.batch_size * self.max_length,
            relay=not self.use_push,
            prefer=self._standby_peers() or None,
        )
        spans: list[_SpanSession] = []
        try:
            for s in route:
                try:
                    spans.append(await self._open_span(s))
                except OverloadedError as e:
                    # session-open shed: short overload penalty, not a
                    # fault ban — the peer is healthy, just hot
                    self._note_shed_exc(e, s.peer_id)
                    raise
                except (OSError, RpcError, asyncio.TimeoutError):
                    self.manager.ban_peer(s.peer_id)
                    raise
        except Exception:
            for sp in spans:
                await sp.close()
            raise
        self._spans = spans
        # the rebuilt chain may have different span boundaries and replays
        # skip relay recording: spans > 0 lose auditability (span 0 keeps
        # it — its input always re-embeds from the id history)
        self._span_in = None
        self._last_span_outs = []
        try:
            if self.embed_fn is not None and any(self._id_rows):
                # token-id replay (ragged rows): right-pad to a rectangle,
                # write speculatively, then commit each row to its true
                # length — padded garbage lands after a row's real tokens so
                # the causal mask hides it, and commit_lens frees its pages
                lens = [len(r) for r in self._id_rows]
                width = max(lens)
                padded = np.zeros((self.batch_size, width), np.int64)
                for i, r in enumerate(self._id_rows):
                    padded[i, : len(r)] = r
                # a prior session (this one, before it failed) likely left
                # its prompt pages in the servers' prefix pools — and a
                # standby holds whatever was replicated — probe so the
                # replay re-embeds and re-ships only the uncached suffix.
                # Chains come from the RAGGED rows, never the padded
                # rectangle: pad garbage must not hash-alias a pooled page
                # of real zeros. commit_lens are absolute, so they need no
                # adjustment for the adopted offset.
                skip = 0
                if self.prefix_cache:
                    skip = await self._probe_prefix(
                        [list(r) for r in self._id_rows]
                    )
                replay = self.embed_fn(padded)
                # recovery owner: commit_lens commits server-side within
                # this same step; a failed replay just re-runs failover
                await self._step_once(  # bbtpu: noqa[BB001]
                    replay[:, skip:], commit=False, tree_mask=None,
                    commit_lens=lens, prefix_skip=skip,
                )
                self.failover_replayed_tokens += sum(
                    max(0, ln - skip) for ln in lens
                )
            elif self._history:
                # hidden-state history probes too: replicated/pooled pages
                # are keyed by hidden-byte chains for these sessions, so a
                # standby hit trims the replay exactly like the id path
                replay = np.concatenate(self._history, axis=1)
                skip = 0
                if self.prefix_cache:
                    skip = await self._probe_prefix(
                        hidden_rows=[
                            replay[i] for i in range(replay.shape[0])
                        ]
                    )
                await self._step_once(
                    replay[:, skip:], commit=True, tree_mask=None,
                    prefix_skip=skip if skip else None,
                )
                self.failover_replayed_tokens += replay.shape[0] * (
                    replay.shape[1] - skip
                )
        except Exception:
            # a half-replayed chain must not be reused: its KV caches are
            # incomplete and a later "successful" step would be garbage
            await self.close()
            raise
        # replicate to a fresh standby from now on (the old one is likely
        # on the new route — often it IS the new primary)
        self._init_repl()
