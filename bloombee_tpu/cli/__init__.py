"""CLI entrypoints (reference: src/bloombee/cli/)."""
