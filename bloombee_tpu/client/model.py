"""Distributed model facade: embeddings + span chain + norm + LM head.

Equivalent of /root/reference/src/bloombee/models/*/model.py
(Distributed*ForCausalLM) + RemoteGenerationMixin
(client/remote_generation.py:104-402). Client math is pure jax (jitted embed
and head), so it runs on CPU or any accelerator — the reference's
`device='xla'` goal of needing no GPU anywhere.

`generate` is the fast greedy/sampling loop (reference `_fast_generate_greedy`
bypasses HF GenerationMixin, remote_generation.py:286-386); resuming a session
across calls mirrors `session.output_ids` resume (:182-216).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from bloombee_tpu.client.sequence_manager import RemoteSequenceManager
from bloombee_tpu.client.session import DecodeNUnsupported, InferenceSession
from bloombee_tpu.models.head import embed_impl, norm_head_impl
from bloombee_tpu.models.spec import ModelSpec
from bloombee_tpu.ops import rms_norm
from bloombee_tpu.ops.norms import layer_norm

_embed = functools.partial(
    jax.jit, static_argnames=("embedding_multiplier", "has_embed_norm", "eps")
)(embed_impl)

_norm_head = functools.partial(
    jax.jit, static_argnames=("eps", "soft_cap", "norm_type")
)(norm_head_impl)


@functools.partial(
    jax.jit, static_argnames=("eps", "soft_cap", "norm_type", "step")
)
def _norm_head_chunked(
    params, hidden, eps: float, soft_cap: float = 0.0,
    norm_type: str = "rms", step: int = 16384,
):
    """Vocab-chunked head: the matmul runs `step` vocab columns at a time
    (lax.map keeps one chunk's intermediates live), bounding transient
    memory on weak client hosts — the role of the reference's
    LMHead.chunked_forward (client/lm_head.py:50-76, 16384-column steps
    for low-RAM / non-AVX512 CPUs)."""
    if norm_type == "ln":
        h = layer_norm(hidden, params["norm"], params.get("norm_bias"), eps)
    else:
        h = rms_norm(hidden, params["norm"], eps)
    w = params["lm_head"]  # [D, V]
    v = w.shape[1]
    step = min(step, v)
    n = -(-v // step)
    # slice the ORIGINAL weight per iteration (no padded/transposed copy —
    # peak transient memory is one [D, step] slice + one [B, T, step]
    # product on top of the full logits output). dynamic_slice clamps the
    # ragged last start to v - step, so read and write overlap identically
    # and the overlap rows are simply rewritten with equal values.
    out = jnp.zeros((*h.shape[:-1], v), jnp.float32)

    def body(i, out):
        start = jnp.minimum(i * step, v - step)
        wi = jax.lax.dynamic_slice_in_dim(w, start, step, axis=1)
        li = (h @ wi).astype(jnp.float32)
        return jax.lax.dynamic_update_slice_in_dim(
            out, li, start, axis=out.ndim - 1
        )

    logits = jax.lax.fori_loop(0, n, body, out)
    if soft_cap:
        logits = jnp.tanh(logits / soft_cap) * soft_cap
    return logits


class DistributedModelForCausalLM:
    """Client-side model: local embed/norm/head + remote block chain."""

    def __init__(
        self,
        spec: ModelSpec,
        client_params: dict,
        manager: RemoteSequenceManager,
        use_push: bool = True,
        config=None,
    ):
        from bloombee_tpu.client.config import ClientConfig

        self.spec = spec
        self.params = client_params
        self.manager = manager
        if config is not None:
            # a pre-built manager must still honor the config's routing
            # knobs (from_pretrained applies them at construction)
            manager.update_period = config.update_period
            manager.ban_timeout = config.ban_timeout
            manager.ban_max = config.ban_max
            manager.allowed_servers = (
                set(config.allowed_servers)
                if config.allowed_servers else None
            )
            manager.blocked_servers = set(config.blocked_servers or ())
            manager.active_adapter = config.active_adapter
            manager.load_aware = config.load_aware_routing
            manager.overload_timeout = config.overload_timeout
            manager.overload_max = config.overload_max
            manager.quarantine_timeout = config.quarantine_timeout
            manager.quarantine_max = config.quarantine_max
            manager.integrity_strike_limit = config.integrity_strike_limit
        self.config = config or ClientConfig(use_push=use_push)
        self.use_push = self.config.use_push

    @classmethod
    def from_pretrained(
        cls,
        model_dir: str,
        registry,
        model_uid: str | None = None,
        dtype=None,
        use_push: bool = True,
        config=None,
    ) -> "DistributedModelForCausalLM":
        from bloombee_tpu.client.config import ClientConfig
        from bloombee_tpu.models.checkpoint import (
            load_client_params,
            load_spec,
        )

        from bloombee_tpu.models.hub import resolve_model_dir

        config = config or ClientConfig(use_push=use_push)
        model_dir = resolve_model_dir(model_dir)
        spec = load_spec(model_dir)
        params = load_client_params(model_dir, dtype=dtype)
        manager = RemoteSequenceManager(
            registry,
            model_uid or model_dir.rstrip("/").split("/")[-1],
            spec.num_hidden_layers,
            update_period=config.update_period,
            ban_timeout=config.ban_timeout,
            ban_max=config.ban_max,
            allowed_servers=config.allowed_servers,
            blocked_servers=config.blocked_servers,
            active_adapter=config.active_adapter,
            load_aware=config.load_aware_routing,
            overload_timeout=config.overload_timeout,
            overload_max=config.overload_max,
            quarantine_timeout=config.quarantine_timeout,
            quarantine_max=config.quarantine_max,
            integrity_strike_limit=config.integrity_strike_limit,
        )
        return cls(spec, params, manager, config=config)

    # ------------------------------------------------------------- components
    def embed(self, input_ids: np.ndarray) -> np.ndarray:
        h = _embed(
            self.params,
            jnp.asarray(input_ids),
            self.spec.embedding_multiplier,
            "embed_norm" in self.params,
            self.spec.rms_norm_eps,
        )
        return np.asarray(h, dtype=np.float32)

    def logits(self, hidden: np.ndarray) -> np.ndarray:
        if self.config.use_chunked_head:
            return np.asarray(
                _norm_head_chunked(
                    self.params,
                    jnp.asarray(hidden),
                    eps=self.spec.rms_norm_eps,
                    soft_cap=self.spec.logits_soft_cap,
                    norm_type=self.spec.norm_type,
                    step=self.config.chunked_head_step,
                )
            )
        return np.asarray(
            _norm_head(
                self.params,
                jnp.asarray(hidden),
                eps=self.spec.rms_norm_eps,
                soft_cap=self.spec.logits_soft_cap,
                norm_type=self.spec.norm_type,
            )
        )

    def inference_session(
        self, max_length: int, batch_size: int = 1,
        microbatch: int | str | None = None,
    ) -> InferenceSession:
        cfg = self.config
        return InferenceSession(
            self.manager, max_length, batch_size, use_push=cfg.use_push,
            max_retries=cfg.max_retries, step_timeout=cfg.step_timeout,
            microbatch=(
                microbatch if microbatch is not None else cfg.microbatch
            ),
            embed_fn=self.embed,
            adapter=cfg.active_adapter,
            prefix_cache=cfg.prefix_cache,
            repl_every=cfg.kv_repl_every,
            client_id=cfg.client_id,
            overload_retries=cfg.overload_retries,
            resume=cfg.resume,
            resume_timeout=cfg.resume_timeout,
            keepalive_s=cfg.keepalive_s,
            integrity=cfg.integrity,
            audit_p=cfg.audit_p,
        )

    # --------------------------------------------------------------- generate
    async def generate(
        self,
        input_ids: np.ndarray,  # [B, S] int
        max_new_tokens: int = 20,
        do_sample: bool = False,
        temperature: float = 1.0,
        top_p: float = 1.0,
        eos_token_id: int | None = None,
        session: InferenceSession | None = None,
        seed: int = 0,
        server_decode: bool | None = None,  # None -> config.server_decode
    ) -> np.ndarray:
        input_ids = np.asarray(input_ids)
        b, s = input_ids.shape
        max_length = s + max_new_tokens
        own_session = session is None
        if own_session:
            session = self.inference_session(max_length, b)
            await session.__aenter__()
        rng = np.random.default_rng(seed)
        use_sd = (
            server_decode
            if server_decode is not None
            else self.config.server_decode
        )
        try:
            if (
                use_sd
                and not do_sample
                and max_new_tokens > 0
                and session._spans
                and session._spans[0].span.start == 0
                and session._spans[-1].span.end == self.spec.num_hidden_layers
                and (len(session._spans) == 1 or session.use_push)
            ):
                # a declining server is handled INSIDE (per-step continuation
                # on the same session — its KV already holds the prefill)
                return await self._generate_server_decode(
                    session, input_ids, max_length, eos_token_id
                )
            hidden = self.embed(input_ids)
            out = await session.step(hidden, ids=input_ids)
            ids = input_ids
            finished = np.zeros((b,), dtype=bool)
            for _ in range(max_new_tokens):
                logits = self.logits(out[:, -1:])[:, 0]  # [B, V]
                next_ids = self._select(
                    logits, do_sample, temperature, top_p, rng
                )
                next_ids, finished = self._mask_finished(
                    next_ids, finished, eos_token_id
                )
                ids = np.concatenate([ids, next_ids[:, None]], axis=1)
                if eos_token_id is not None and finished.all():
                    break
                if ids.shape[1] >= max_length:
                    break
                out = await session.step(
                    self.embed(next_ids[:, None]), ids=next_ids[:, None]
                )
            return ids
        finally:
            if own_session:
                await session.__aexit__(None, None, None)

    async def _generate_server_decode(
        self, session, input_ids, max_length, eos_token_id
    ) -> np.ndarray:
        """Greedy generation with server-side multi-step decode: prefill +
        first token as usual, then chunks of `server_decode_chunk` tokens per
        RPC via session.decode_n. Token-identical to the per-step loop on
        the same backend (runtime/decode_loop.py exactness contract)."""
        b = input_ids.shape[0]

        def _chunk_now() -> int:
            # the server buckets n to next_pow2 and runs the whole bucket,
            # so a non-pow2 chunk (e.g. 24) would burn discarded full-model
            # scan steps EVERY round — round the configured chunk down.
            # Clamp to the CURRENT route's advertised decode_n_max FIRST
            # (recomputed every round: a mid-generation re-route may land
            # on a server with a smaller bound, and a chunk above it gets
            # declined and silently costs the whole fast path — advisor,
            # round 4).
            c = max(1, int(self.config.server_decode_chunk))
            server_max = min(
                (
                    s.span.server_info.decode_n_max
                    for s in session._spans
                    if s.span.server_info.decode_n_max
                ),
                default=None,
            )
            if server_max is not None:
                c = min(c, int(server_max))
            return 1 << (c.bit_length() - 1)
        head_dtype = str(self.params["lm_head"].dtype)
        hidden = self.embed(input_ids)
        out = await session.step(hidden, ids=input_ids)
        logits = self.logits(out[:, -1:])[:, 0]
        finished = np.zeros((b,), dtype=bool)
        next_ids, finished = self._greedy_next(logits, finished, eos_token_id)
        ids = np.concatenate([input_ids, next_ids[:, None]], axis=1)
        while ids.shape[1] < max_length and not (
            eos_token_id is not None and finished.all()
        ):
            # partial chunks round DOWN to a power of two: the server
            # buckets n to next_pow2 and runs the whole bucket, so a
            # non-pow2 request would burn discarded full-model steps
            remaining = max_length - ids.shape[1]
            n = min(_chunk_now(), 1 << (remaining.bit_length() - 1))
            try:
                toks = await session.decode_n(
                    next_ids, n, eos_token_id=eos_token_id,
                    finished=finished, head_dtype=head_dtype,
                )
            except DecodeNUnsupported as e:
                # the server declined (or a recovery re-routed onto a
                # multi-span chain): continue per-step on the SAME session —
                # its KV already holds everything generated so far
                import logging

                # warning, not debug: losing the fast path silently costs
                # the operator the whole feature (round-3 verdict)
                logging.getLogger(__name__).warning(
                    "server-side decode declined (%s); per-step path", e
                )
                return await self._continue_per_step(
                    session, ids, next_ids, finished, max_length,
                    eos_token_id,
                )
            if eos_token_id is not None:
                # truncate where the per-step loop would have stopped: the
                # first column after which every row is finished (the server
                # clamps later columns to eos; appending them would make the
                # output longer than the per-step path's)
                cut = toks.shape[1]
                fin = finished
                for j in range(toks.shape[1]):
                    fin = fin | (toks[:, j] == eos_token_id)
                    if fin.all():
                        cut = j + 1
                        break
                finished = fin
                if cut < toks.shape[1]:
                    # the server's KV/history ran past the stopping point;
                    # rewind the session's record so a REUSED session sees
                    # exactly the per-step path's context (the rewind marks
                    # the chain for a rebuild-and-replay on next use)
                    session.rewind_decoded_tail(toks.shape[1] - cut)
                toks = toks[:, :cut]
            ids = np.concatenate([ids, toks], axis=1)
            next_ids = toks[:, -1]
        return ids

    async def _continue_per_step(
        self, session, ids, next_ids, finished, max_length, eos_token_id
    ) -> np.ndarray:
        """Per-step continuation from mid-generation state (`ids` holds all
        tokens so far; `next_ids` is selected but not yet stepped). Same
        select semantics as the main per-step loop in generate()."""
        while ids.shape[1] < max_length and not (
            eos_token_id is not None and finished.all()
        ):
            out = await session.step(
                self.embed(next_ids[:, None]), ids=next_ids[:, None]
            )
            logits = self.logits(out[:, -1:])[:, 0]
            next_ids, finished = self._greedy_next(
                logits, finished, eos_token_id
            )
            ids = np.concatenate([ids, next_ids[:, None]], axis=1)
        return ids

    @staticmethod
    def _mask_finished(next_ids, finished, eos_token_id):
        """EOS masking — the one definition every decode path shares so
        their semantics cannot drift."""
        if eos_token_id is not None:
            next_ids = np.where(finished, eos_token_id, next_ids)
            finished = finished | (next_ids == eos_token_id)
        return next_ids, finished

    @classmethod
    def _greedy_next(cls, logits, finished, eos_token_id):
        return cls._mask_finished(
            np.argmax(logits, axis=-1).astype(np.int64), finished,
            eos_token_id,
        )

    @staticmethod
    def _select(logits, do_sample, temperature, top_p, rng):
        if not do_sample:
            return np.argmax(logits, axis=-1).astype(np.int64)
        logits = logits / max(temperature, 1e-6)
        logits = logits - logits.max(axis=-1, keepdims=True)
        probs = np.exp(logits)
        probs /= probs.sum(axis=-1, keepdims=True)
        if top_p < 1.0:
            # nucleus: zero out the tail outside the top-p mass
            order = np.argsort(-probs, axis=-1)
            sorted_p = np.take_along_axis(probs, order, axis=-1)
            csum = np.cumsum(sorted_p, axis=-1)
            keep_sorted = csum - sorted_p < top_p
            keep = np.zeros_like(probs, dtype=bool)
            np.put_along_axis(keep, order, keep_sorted, axis=-1)
            probs = np.where(keep, probs, 0.0)
            probs /= probs.sum(axis=-1, keepdims=True)
        return np.stack(
            [rng.choice(probs.shape[-1], p=p) for p in probs]
        ).astype(np.int64)
