#!/usr/bin/env bash
# Chaos gate: replay the chaos-marked suite under a fixed seed matrix of
# ambient wire faults (the BBTPU_CHAOS_* env plan). Each matrix entry is a
# space-separated list of KEY=VAL tokens; anything unset takes the default
# below, so entries name ONLY what they vary (the old positional
# "SEED:DELAY_P:ADMIT:..." strings needed every column on every entry and
# silently misassigned values when a column was added).
#
# Keys:
#   SEED         chaos RNG seed (replays are bit-for-bit per seed)
#   DELAY_P      per-frame send-delay probability (mild ambient jitter, so
#                the per-test seeded FaultPlans stay the dominant source)
#   ADMIT        1 = server admission control (BBTPU_ADMIT, low watermark)
#                so overload shed-and-reroute runs under the same jitter
#   PARTITION_P  silent both-way blackhole probability (no FIN/RST);
#                keepalive is forced small so half-open detection + lease
#                park/resume are the recovery under test
#   MIXED        1 = mixed-batch dispatch (BBTPU_MIXED_BATCH)
#   SPEC         1 = batched tree-speculative verification (BBTPU_SPEC_BATCH)
#   REBALANCE    1 = elastic control loop (measured-load rebalance + fast
#                promotion watermarks)
#   CORRUPT      per-frame probability of corrupting a span-output reply
#                tensor in-flight (well-formed frame, wrong numbers).
#                Forces BBTPU_INTEGRITY=1: only the client integrity layer
#                (out_digest + sanity gate) can see this fault class, and
#                the suite must stay green + token-identical through it
# Fixed seeds keep every run replayable bit-for-bit (wire/faults.py
# contract).
# Exits 0 when pytest is unavailable (mirrors scripts/lint.sh).
set -euo pipefail
cd "$(dirname "$0")/.."

if ! python -c "import pytest" >/dev/null 2>&1; then
    echo "chaos: pytest not installed; skipping" >&2
    exit 0
fi

# Each entry replays the whole chaos-marked suite (~50s), so the matrix
# is budgeted: independent feature flags share an entry instead of each
# getting their own, keeping the tier-1 gate inside its wall-clock cap
# while every flag still runs under ambient chaos.
MATRIX=(
    "SEED=23 DELAY_P=0.1"
    "SEED=43 DELAY_P=0.02 PARTITION_P=0.02"
    "SEED=57 DELAY_P=0.05 MIXED=1 SPEC=1"
    "SEED=83 DELAY_P=0.05 ADMIT=1 REBALANCE=1"
    "SEED=97 DELAY_P=0.02 CORRUPT=0.05"
)
for entry in "${MATRIX[@]}"; do
    # per-entry defaults; each entry overrides only what it varies
    SEED=0 DELAY_P=0 ADMIT=0 PARTITION_P=0 MIXED=0 SPEC=0 REBALANCE=0
    CORRUPT=0
    for tok in ${entry}; do
        case "${tok%%=*}" in
            SEED|DELAY_P|ADMIT|PARTITION_P|MIXED|SPEC|REBALANCE|CORRUPT)
                declare "${tok}" ;;
            *)
                echo "chaos: unknown matrix token '${tok}'" >&2
                exit 1 ;;
        esac
    done
    # partitioned conns go silent instead of erroring: a small keepalive
    # turns the blackhole into a prompt local abort so lease park/resume
    # (not a step_timeout expiry) is the recovery path under test
    keepalive_s=0
    if [ "${PARTITION_P}" != "0" ]; then
        keepalive_s=0.5
    fi
    # the rebalance entry runs with hair-trigger promotion watermarks so
    # the standby control loop actually fires inside short chaos tests
    promote_high_ms=1500
    promote_sustain_s=10
    if [ "${REBALANCE}" != "0" ]; then
        promote_high_ms=500
        promote_sustain_s=0.3
    fi
    # in-flight corruption is invisible to the transport; the integrity
    # layer (server digest stamps + client gate) must be on to catch it
    integrity=0
    if [ "${CORRUPT}" != "0" ]; then
        integrity=1
    fi
    echo "chaos: ${entry}" >&2
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    BBTPU_CHAOS=1 \
    BBTPU_CHAOS_SEED="${SEED}" \
    BBTPU_CHAOS_DELAY_P="${DELAY_P}" \
    BBTPU_CHAOS_DELAY_S=0.02 \
    BBTPU_CHAOS_PARTITION_P="${PARTITION_P}" \
    BBTPU_CHAOS_CORRUPT_P="${CORRUPT}" \
    BBTPU_INTEGRITY="${integrity}" \
    BBTPU_KEEPALIVE_S="${keepalive_s}" \
    BBTPU_ADMIT="${ADMIT}" \
    BBTPU_ADMIT_HIGH_MS=400 \
    BBTPU_MIXED_BATCH="${MIXED}" \
    BBTPU_SPEC_BATCH="${SPEC}" \
    BBTPU_MEASURED_REBALANCE="${REBALANCE}" \
    BBTPU_PROMOTE_HIGH_MS="${promote_high_ms}" \
    BBTPU_PROMOTE_SUSTAIN_S="${promote_sustain_s}" \
    python -m pytest tests/ -q -m chaos \
        -p no:cacheprovider -p no:xdist -p no:randomly "$@"
done
