"""Client-side decode benchmark against a running swarm.

Port of /root/reference/benchmarks/benchmark_inference.py:90-93: prints
per-sequence decode throughput and effective batch throughput, plus TTFT and
the session timing table.

    python benchmarks/benchmark_inference.py MODEL_DIR --registry host:port \\
        --seq-len 128 --max-new-tokens 64 --batch 1 --n-processes 1
"""

from __future__ import annotations

import argparse
import asyncio
import time

import numpy as np


async def run_one(args, proc_idx: int) -> dict:
    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.swarm.registry import RegistryClient

    host, port = args.registry.rsplit(":", 1)
    model = DistributedModelForCausalLM.from_pretrained(
        args.model_dir, RegistryClient(host, int(port)),
        model_uid=args.model_uid,
    )
    rng = np.random.default_rng(proc_idx)
    input_ids = rng.integers(
        0, model.spec.vocab_size, size=(args.batch, args.seq_len)
    )
    sess = model.inference_session(
        args.seq_len + args.max_new_tokens, args.batch
    )
    await sess.__aenter__()
    try:
        t0 = time.perf_counter()
        hidden = model.embed(input_ids)
        out = await sess.step(hidden)
        ttft = time.perf_counter() - t0

        t0 = time.perf_counter()
        n = 0
        next_ids = np.argmax(model.logits(out[:, -1:])[:, 0], axis=-1)
        while n < args.max_new_tokens:
            out = await sess.step(model.embed(next_ids[:, None]))
            next_ids = np.argmax(model.logits(out[:, -1:])[:, 0], axis=-1)
            n += 1
        elapsed = time.perf_counter() - t0
        return {
            "ttft_s": ttft,
            "tok_per_s_per_seq": n / elapsed,
            "effective_tok_per_s": n * args.batch / elapsed,
            "timing": sess.timing_summary(),
        }
    finally:
        await sess.__aexit__(None, None, None)


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("model_dir")
    parser.add_argument("--model-uid", default=None)
    parser.add_argument("--registry", default="127.0.0.1:7700")
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--max-new-tokens", type=int, default=64)
    parser.add_argument("--batch", type=int, default=1)
    parser.add_argument("--n-sessions", "--n-processes", type=int,
                        default=1, dest="n_sessions",
                        help="concurrent client sessions (one event loop)")
    args = parser.parse_args(argv)
    args.model_uid = args.model_uid or args.model_dir.rstrip("/").split("/")[-1]

    async def run():
        results = await asyncio.gather(
            *(run_one(args, i) for i in range(args.n_sessions))
        )
        tput = float(np.mean([r["tok_per_s_per_seq"] for r in results]))
        eff = float(np.sum([r["effective_tok_per_s"] for r in results]))
        ttft = float(np.mean([r["ttft_s"] for r in results]))
        print(
            f"throughput={tput:.2f} tok/s/seq  effective_throughput={eff:.2f}"
            f" tok/s  mean_ttft={ttft*1000:.0f} ms"
        )
        print("timing:", results[0]["timing"])

    asyncio.run(run())


if __name__ == "__main__":
    main()
