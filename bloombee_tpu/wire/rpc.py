"""Async RPC over length-prefixed msgpack frames on TCP.

Provides the reference's RPC surface (SURVEY.md section 2.7 / 5): unary calls
(`rpc_info`, `rpc_forward`, `rpc_backward`), one-way pushes (`rpc_push`), and
bidirectional streams (`rpc_inference`) — the semantics of hivemind's
libp2p/protobuf transport re-provided natively. One TCP connection multiplexes
any number of concurrent calls and streams by frame id.

Frame layout: [u32 frame_len][u32 header_len][msgpack header][tensor blobs].
The header carries method, metadata (msgpack dict — the reference's MSGPack
sidecar), and per-tensor codec metas (see tensor_codec).

Sync codec on the loop (the BB009 noqas below, owner: wire layer): every
serialize/deserialize_tensors call in this module runs synchronously in a
coroutine by design. This module IS the event loop's serialization
boundary — payloads are bounded by the page/chunk budgets the callers
enforce, codec time is profiled via tensor_codec's transport stats, and a
per-frame asyncio.to_thread hop costs more in latency and ordering
complexity than the sub-ms codec work it would offload. Callers holding an
asyncio lock across these calls do NOT inherit this justification — the
transitive BB009 pass flags them at their own site.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import struct
from typing import Awaitable, Callable

import msgpack
import numpy as np

from bloombee_tpu.utils import clock, env, lockwatch
from bloombee_tpu.wire import faults
from bloombee_tpu.wire.tensor_codec import (
    deserialize_tensors,
    serialize_tensors,
)

logger = logging.getLogger(__name__)

MAX_FRAME = 1 << 31  # 2 GiB

env.declare(
    "BBTPU_KEEPALIVE_S", float, 0.0,
    "keepalive interval: idle connections exchange ping/pong frames so a "
    "half-open TCP peer (partition without FIN/RST) is detected instead of "
    "hanging forever in recv(); a connection silent past ~2.5x the interval "
    "is declared dead. 0 disables keepalives (seed behavior)",
)


class RpcError(RuntimeError):
    pass


class ConnectionClosed(RpcError):
    pass


class OverloadedError(RpcError):
    """Structured retriable shed: the peer is healthy but past its
    admission high-watermark, so it refused NEW work instead of letting it
    rot in the queue until the deadline aborts it. Carries the server's
    suggested retry delay; clients treat this as reroute-then-backoff (a
    short overload penalty, never a fault ban)."""

    def __init__(self, msg: str = "server overloaded",
                 retry_after_ms: int | None = None):
        super().__init__(msg)
        self.retry_after_ms = (
            int(retry_after_ms) if retry_after_ms is not None else None
        )


def error_to_meta(e: Exception) -> dict:
    """Serialize a handler failure into an err-frame meta. Overload sheds
    keep their structure (code + retry hint) across the wire; everything
    else degrades to the legacy message string, which old peers parse
    unchanged."""
    meta = {"error": f"{type(e).__name__}: {e}"}
    if isinstance(e, OverloadedError):
        meta["code"] = "overloaded"
        if e.retry_after_ms is not None:
            meta["retry_after_ms"] = int(e.retry_after_ms)
    return meta


def error_from_meta(meta: dict) -> RpcError:
    """Inverse of error_to_meta; unknown codes fall back to plain RpcError
    so a newer peer's error classes never break an older client."""
    msg = meta.get("error", "remote error")
    if meta.get("code") == "overloaded":
        return OverloadedError(msg, retry_after_ms=meta.get("retry_after_ms"))
    return RpcError(msg)


def _encode_frame(header: dict, blobs: list[bytes]) -> bytes:
    header = dict(header)
    header["bl"] = [len(b) for b in blobs]
    h = msgpack.packb(header, use_bin_type=True)
    total = 4 + len(h) + sum(len(b) for b in blobs)
    out = bytearray()
    out += struct.pack("<II", total, len(h))
    out += h
    for b in blobs:
        out += b
    return bytes(out)


class Stream:
    """One side of a bidirectional stream (the rpc_inference session carrier,
    reference: handler.py:798-1257)."""

    def __init__(self, conn: "Connection", stream_id: int, meta: dict,
                 tensors: list[np.ndarray]):
        self.conn = conn
        self.id = stream_id
        self.open_meta = meta
        self.open_tensors = tensors
        self._inbox: asyncio.Queue = asyncio.Queue()
        self._closed_local = False
        self._closed_remote = False

    async def send(self, meta: dict, tensors: list[np.ndarray] | None = None,
                   compression: bool = True) -> None:
        if self._closed_local:
            raise RpcError("stream closed")
        tm, blobs = serialize_tensors(tensors or [], compression)  # bbtpu: noqa[BB009] (sync codec boundary — module docstring)
        await self.conn._send(
            {"t": "sitem", "id": self.id, "meta": meta, "tm": tm}, blobs
        )

    async def recv(self) -> tuple[dict, list[np.ndarray]] | None:
        """Next item, or None once the peer half-closed."""
        if self._closed_remote and self._inbox.empty():
            return None
        item = await self._inbox.get()
        if item is None:
            self._closed_remote = True
            return None
        if isinstance(item, Exception):
            raise item
        return item

    async def close(self, meta: dict | None = None) -> None:
        """Half-close: tells the peer no more items will be sent."""
        if not self._closed_local:
            self._closed_local = True
            if not self.conn.is_closing():
                await self.conn._send(
                    {"t": "send", "id": self.id, "meta": meta or {}}, []
                )

    def _push_inbound(self, item) -> None:
        self._inbox.put_nowait(item)


UnaryHandler = Callable[[dict, list[np.ndarray]], Awaitable[tuple[dict, list[np.ndarray]]]]
StreamHandler = Callable[[Stream], Awaitable[None]]
PushHandler = Callable[[dict, list[np.ndarray]], Awaitable[None]]


class Connection:
    """A multiplexed RPC connection (either direction)."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        unary_handlers: dict[str, UnaryHandler] | None = None,
        stream_handlers: dict[str, StreamHandler] | None = None,
        push_handlers: dict[str, PushHandler] | None = None,
        peer: tuple[str, int] | None = None,
        keepalive_s: float | None = None,
    ):
        self.reader = reader
        self.writer = writer
        self.unary_handlers = unary_handlers or {}
        self.stream_handlers = stream_handlers or {}
        self.push_handlers = push_handlers or {}
        # remote (host, port) when known — fault rules target peers by port
        self.peer = peer or self._peername(writer)
        self.fault_plan = faults.get_plan()
        self._ids = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._streams: dict[int, Stream] = {}
        self._unary_tasks: dict[int, asyncio.Task] = {}
        self._tasks: set[asyncio.Task] = set()
        self._send_lock = lockwatch.async_lock("rpc.send")
        self._reader_task: asyncio.Task | None = None
        self._closed = asyncio.Event()
        self.on_close: Callable[["Connection"], None] | None = None
        # keepalive state: last_recv only advances on frames that survive
        # fault injection, so an injected partition looks exactly as silent
        # as a real half-open peer
        self.keepalive_s = (
            env.get("BBTPU_KEEPALIVE_S") if keepalive_s is None
            else keepalive_s
        )
        self.last_recv = clock.monotonic()
        self.keepalives_sent = 0
        self._keepalive_task: asyncio.Task | None = None

    @staticmethod
    def _peername(writer: asyncio.StreamWriter) -> tuple[str, int] | None:
        try:
            name = writer.get_extra_info("peername")
            return (name[0], name[1]) if name else None
        except Exception:
            return None

    # ------------------------------------------------------------------ setup
    def start(self) -> None:
        self._reader_task = asyncio.create_task(self._read_loop())
        if self.keepalive_s and self.keepalive_s > 0:
            self._keepalive_task = asyncio.create_task(self._keepalive_loop())

    def is_closing(self) -> bool:
        return self._closed.is_set() or self.writer.is_closing()

    async def close(self) -> None:
        self._closed.set()
        if self._reader_task is not None:
            self._reader_task.cancel()
        if self._keepalive_task is not None:
            self._keepalive_task.cancel()
        for t in list(self._tasks):
            t.cancel()
        try:
            self.writer.close()
            await self.writer.wait_closed()
        except Exception:
            pass
        self._fail_all(ConnectionClosed("connection closed"))

    def _fail_all(self, exc: Exception) -> None:
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(exc)
        self._pending.clear()
        for s in self._streams.values():
            s._push_inbound(exc)

    def abort(self, reason: str = "connection aborted") -> None:
        """Fail every pending call/stream locally and kill the transport
        with no FIN handshake. Used to fence a peer we have decided is gone
        (keepalive timeout, superseded by a session resume, expired lease):
        everyone blocked on this connection unwedges NOW instead of
        whenever TCP notices."""
        self._fail_all(ConnectionClosed(reason))
        self._closed.set()
        try:
            transport = self.writer.transport
            if transport is not None:
                transport.abort()
        except Exception:
            pass
        self._streams.clear()

    # -------------------------------------------------------------- client API
    async def call(
        self,
        method: str,
        meta: dict | None = None,
        tensors: list[np.ndarray] | None = None,
        timeout: float | None = None,
        compression: bool = True,
    ) -> tuple[dict, list[np.ndarray]]:
        rid = next(self._ids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        tm, blobs = serialize_tensors(tensors or [], compression)  # bbtpu: noqa[BB009] (sync codec boundary — module docstring)
        await self._send(
            {"t": "req", "id": rid, "m": method, "meta": meta or {}, "tm": tm},
            blobs,
        )
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            # the caller is abandoning this call: tell the server so it can
            # stop computing for a client that will never read the reply
            if not self.is_closing():
                try:
                    await self._send({"t": "cancel", "id": rid}, [])
                except Exception:
                    pass  # best-effort; the timeout still propagates
            raise
        finally:
            self._pending.pop(rid, None)

    async def push(
        self,
        method: str,
        meta: dict | None = None,
        tensors: list[np.ndarray] | None = None,
        compression: bool = True,
    ) -> None:
        """Fire-and-forget (the reference's rpc_push plane)."""
        tm, blobs = serialize_tensors(tensors or [], compression)  # bbtpu: noqa[BB009] (sync codec boundary — module docstring)
        await self._send(
            {"t": "push", "id": 0, "m": method, "meta": meta or {}, "tm": tm},
            blobs,
        )

    async def open_stream(
        self,
        method: str,
        meta: dict | None = None,
        tensors: list[np.ndarray] | None = None,
        compression: bool = True,
    ) -> Stream:
        rid = next(self._ids)
        stream = Stream(self, rid, meta or {}, tensors or [])
        self._streams[rid] = stream
        tm, blobs = serialize_tensors(tensors or [], compression)  # bbtpu: noqa[BB009] (sync codec boundary — module docstring)
        await self._send(
            {"t": "sopen", "id": rid, "m": method, "meta": meta or {}, "tm": tm},
            blobs,
        )
        return stream

    # --------------------------------------------------------------- internals
    async def _send(self, header: dict, blobs: list[bytes]) -> None:
        if self.fault_plan is not None:
            # may sleep (delayed frame), raise after killing the transport
            # (injected reset / mid-stream close / stalled write), mutate
            # header+blobs in place (injected payload corruption — the
            # frame below is encoded from the mutated pair), or ask for a
            # silent discard (injected partition blackhole)
            if await self.fault_plan.on_send(self, header, blobs) == "drop":
                return
        frame = _encode_frame(header, blobs)
        async with self._send_lock:
            self.writer.write(frame)
            await self.writer.drain()

    async def _keepalive_loop(self) -> None:
        """Ping on idle, declare the peer dead when silent too long.

        A half-open connection (peer partitioned without FIN/RST) never
        errors recv() — this loop is the only thing that unwedges it: after
        ~2.5 intervals with no inbound frame the transport is aborted and
        every pending call/stream fails with ConnectionClosed, exactly like
        a real disconnect (retry paths must not special-case it)."""
        interval = self.keepalive_s
        try:
            while not self._closed.is_set():
                await clock.async_sleep(interval / 2)
                idle = clock.monotonic() - self.last_recv
                if idle >= 2.5 * interval:
                    logger.warning(
                        "keepalive timeout after %.2fs silence from %s",
                        idle, self.peer,
                    )
                    self.abort("keepalive timeout")
                    break
                if idle >= interval / 2:
                    try:
                        await self._send({"t": "ping", "id": 0}, [])
                        self.keepalives_sent += 1
                    except Exception:
                        pass  # the read loop will surface the real error
        except asyncio.CancelledError:
            pass

    async def _read_loop(self) -> None:
        try:
            while True:
                head = await self.reader.readexactly(8)
                total, hlen = struct.unpack("<II", head)
                if total > MAX_FRAME:
                    raise RpcError(f"frame too large: {total}")
                body = await self.reader.readexactly(total - 4)
                header = msgpack.unpackb(body[:hlen], raw=False)
                blobs = []
                off = hlen
                for blen in header.get("bl", []):
                    blobs.append(body[off : off + blen])
                    off += blen
                if self.fault_plan is not None:
                    act = await self.fault_plan.on_read(self, header)
                    if act == "drop":
                        continue  # injected stall/loss: frame never arrives
                self.last_recv = clock.monotonic()
                self._dispatch(header, blobs)
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        except asyncio.CancelledError:
            return
        except Exception as e:  # pragma: no cover
            logger.exception("rpc read loop error: %s", e)
        finally:
            self._closed.set()
            if self._keepalive_task is not None:
                self._keepalive_task.cancel()
            self._fail_all(ConnectionClosed("peer disconnected"))
            # close our side of the transport too: asyncio.Server.wait_closed
            # blocks until every accepted connection's transport is closed
            try:
                self.writer.close()
            except Exception:
                pass
            if self.on_close is not None:
                self.on_close(self)

    def _dispatch(self, header: dict, blobs: list[bytes]) -> None:
        t = header["t"]
        rid = header["id"]
        if t == "req":
            task = asyncio.create_task(self._handle_unary(header, blobs))
            self._tasks.add(task)
            task.add_done_callback(self._tasks.discard)
            # indexed by request id so a later "cancel" frame can stop it
            self._unary_tasks[rid] = task
            task.add_done_callback(
                lambda _t, rid=rid: self._unary_tasks.pop(rid, None)
            )
        elif t == "cancel":
            # peer abandoned a unary call (client-side wait_for timeout):
            # stop the in-flight handler; no reply is expected
            task = self._unary_tasks.pop(rid, None)
            if task is not None and not task.done():
                task.cancel()
        elif t == "push":
            self._spawn(self._handle_push(header, blobs))
        elif t == "sopen":
            tensors = deserialize_tensors(header.get("tm", []), blobs)  # bbtpu: noqa[BB009] (sync codec boundary — module docstring)
            stream = Stream(self, rid, header.get("meta", {}), tensors)
            self._streams[rid] = stream
            self._spawn(self._handle_stream(header["m"], stream))
        elif t == "sitem":
            stream = self._streams.get(rid)
            if stream is not None:
                tensors = deserialize_tensors(header.get("tm", []), blobs)  # bbtpu: noqa[BB009] (sync codec boundary — module docstring)
                stream._push_inbound((header.get("meta", {}), tensors))
        elif t == "send":
            stream = self._streams.get(rid)
            if stream is not None:
                stream._push_inbound(None)
        elif t == "res":
            fut = self._pending.get(rid)
            if fut is not None and not fut.done():
                tensors = deserialize_tensors(header.get("tm", []), blobs)  # bbtpu: noqa[BB009] (sync codec boundary — module docstring)
                fut.set_result((header.get("meta", {}), tensors))
        elif t == "err":
            fut = self._pending.get(rid)
            if fut is not None and not fut.done():
                fut.set_exception(error_from_meta(header.get("meta", {})))
            stream = self._streams.get(rid)
            if stream is not None:
                stream._push_inbound(error_from_meta(header.get("meta", {})))
        elif t == "ping":
            # keepalive probe: answer even when we have no keepalive loop of
            # our own, so a one-sided rollout still detects half-open links
            self._spawn(self._send_pong())
        elif t == "pong":
            pass  # liveness already recorded by the read loop
        else:
            logger.warning("unknown frame type %r", t)

    def _spawn(self, coro) -> None:
        task = asyncio.create_task(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _send_pong(self) -> None:
        try:
            if not self.is_closing():
                await self._send({"t": "pong", "id": 0}, [])
        except Exception:
            pass  # a dying transport surfaces through the read loop

    async def _handle_unary(self, header: dict, blobs: list[bytes]) -> None:
        rid = header["id"]
        method = header["m"]
        try:
            handler = self.unary_handlers.get(method)
            if handler is None:
                raise RpcError(f"no such method: {method}")
            tensors = deserialize_tensors(header.get("tm", []), blobs)  # bbtpu: noqa[BB009] (sync codec boundary — module docstring)
            meta, out = await handler(header.get("meta", {}), tensors)
            tm, oblobs = serialize_tensors(out)  # bbtpu: noqa[BB009] (sync codec boundary — module docstring)
            await self._send({"t": "res", "id": rid, "meta": meta, "tm": tm}, oblobs)
        except asyncio.CancelledError:
            # cancelled by a peer "cancel" frame (abandoned call) or by
            # connection teardown: either way nobody is reading the reply
            logger.debug("unary handler %s cancelled", method)
        except Exception as e:
            logger.debug("unary handler %s failed: %s", method, e)
            if not self.is_closing():
                await self._send(
                    {"t": "err", "id": rid, "meta": error_to_meta(e)},
                    [],
                )

    async def _handle_push(self, header: dict, blobs: list[bytes]) -> None:
        method = header["m"]
        handler = self.push_handlers.get(method)
        if handler is None:
            logger.warning("no push handler for %s", method)
            return
        tensors = deserialize_tensors(header.get("tm", []), blobs)  # bbtpu: noqa[BB009] (sync codec boundary — module docstring)
        try:
            await handler(header.get("meta", {}), tensors)
        except Exception as e:
            logger.exception("push handler %s failed: %s", method, e)

    async def _handle_stream(self, method: str, stream: Stream) -> None:
        handler = self.stream_handlers.get(method)
        if handler is None:
            await self._send(
                {"t": "err", "id": stream.id,
                 "meta": {"error": f"no such stream method: {method}"}},
                [],
            )
            return
        try:
            await handler(stream)
        except OverloadedError as e:
            # expected shed under load, not a server fault: no stack trace
            logger.info("stream handler %s shed: %s", method, e)
            if not self.is_closing():
                await self._send(
                    {"t": "err", "id": stream.id, "meta": error_to_meta(e)},
                    [],
                )
        except Exception as e:
            logger.exception("stream handler %s failed: %s", method, e)
            if not self.is_closing():
                await self._send(
                    {"t": "err", "id": stream.id, "meta": error_to_meta(e)},
                    [],
                )
        finally:
            self._streams.pop(stream.id, None)


class RpcServer:
    """Listening side: accepts connections, one Connection per peer."""

    def __init__(
        self,
        unary_handlers: dict[str, UnaryHandler] | None = None,
        stream_handlers: dict[str, StreamHandler] | None = None,
        push_handlers: dict[str, PushHandler] | None = None,
        host: str = "0.0.0.0",
        port: int = 0,
        keepalive_s: float | None = None,
    ):
        self.unary_handlers = unary_handlers or {}
        self.stream_handlers = stream_handlers or {}
        self.push_handlers = push_handlers or {}
        self.host = host
        self.port = port
        self.keepalive_s = keepalive_s
        self._server: asyncio.AbstractServer | None = None
        self._conns: set[Connection] = set()
        # cumulative pings from already-closed connections; live ones are
        # summed on demand (keepalives_sent property)
        self._keepalives_closed = 0

    @property
    def keepalives_sent(self) -> int:
        return self._keepalives_closed + sum(
            c.keepalives_sent for c in self._conns
        )

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._on_connect, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]

    async def _on_connect(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        conn = Connection(
            reader, writer,
            self.unary_handlers, self.stream_handlers, self.push_handlers,
            keepalive_s=self.keepalive_s,
        )
        conn.on_close = self._on_conn_close
        self._conns.add(conn)
        conn.start()

    def _on_conn_close(self, conn: Connection) -> None:
        if conn in self._conns:
            self._keepalives_closed += conn.keepalives_sent
        self._conns.discard(conn)

    async def stop(self) -> None:
        for c in list(self._conns):
            await c.close()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    def abort(self) -> None:
        """Hard-kill (crash fault injection): abort every live
        connection's transport — no close frame, no FIN handshake, every
        pending call on the peer side fails exactly like a process death
        — and close the listener without waiting for it."""
        for c in list(self._conns):
            c.abort("server crashed")
        if self._server is not None:
            self._server.close()
            self._server = None


async def connect(
    host: str,
    port: int,
    unary_handlers: dict[str, UnaryHandler] | None = None,
    stream_handlers: dict[str, StreamHandler] | None = None,
    push_handlers: dict[str, PushHandler] | None = None,
    keepalive_s: float | None = None,
) -> Connection:
    reader, writer = await asyncio.open_connection(host, port)
    conn = Connection(
        reader, writer, unary_handlers, stream_handlers, push_handlers,
        peer=(host, port), keepalive_s=keepalive_s,
    )
    conn.start()
    return conn
