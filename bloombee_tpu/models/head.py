"""Shared embed / norm+LM-head math for the client and the server.

The client model (client/model.py) and the server-side multi-step decode
loop (runtime/decode_loop.py) must produce bit-identical logits on the same
backend — the server loop replaces N client round trips, so any numerical
drift between the two paths would change greedy outputs. Keeping the math in
one place makes that equivalence structural instead of coincidental.

Reference analogs: client LMHead (/root/reference/src/bloombee/client/
lm_head.py:24-93) and the embedding half of Distributed*Model.forward.
"""

from __future__ import annotations

import jax.numpy as jnp

from bloombee_tpu.ops import rms_norm
from bloombee_tpu.ops.norms import layer_norm


def embed_impl(
    params,
    input_ids,
    embedding_multiplier: float = 1.0,
    has_embed_norm: bool = False,
    eps: float = 1e-5,
):
    """Token ids -> hidden states, in the embed table's dtype."""
    h = params["embed"][input_ids]
    if embedding_multiplier != 1.0:
        h = h * embedding_multiplier
    if has_embed_norm:  # bloom: word_embeddings_layernorm
        h = layer_norm(h, params["embed_norm"], params["embed_norm_bias"], eps)
    return h


def norm_head_impl(
    params, hidden, eps: float, soft_cap: float = 0.0, norm_type: str = "rms"
):
    """Final norm + LM head -> fp32 logits (optionally soft-capped)."""
    if norm_type == "ln":
        h = layer_norm(hidden, params["norm"], params.get("norm_bias"), eps)
    else:
        h = rms_norm(hidden, params["norm"], eps)
    logits = (h @ params["lm_head"]).astype(jnp.float32)
    if soft_cap:
        logits = jnp.tanh(logits / soft_cap) * soft_cap
    return logits
