"""Cross-session shared-prefix KV cache, end to end.

The correctness bar (ISSUE): greedy decode must be TOKEN-IDENTICAL with
the cache on and off (both pinned to HF), servers must report nonzero
prefix hits when sessions share a multi-page prompt, copy-on-write must
fire when a sequence diverges inside a shared page, and no page may leak
— including under seeded chaos mid-prefill and under eviction pressure
when the pool is smaller than the shared prefix.
"""

import asyncio

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from bloombee_tpu.client.config import ClientConfig
from bloombee_tpu.client.model import DistributedModelForCausalLM
from bloombee_tpu.server.block_server import BlockServer
from bloombee_tpu.wire import faults
from bloombee_tpu.wire.faults import FaultPlan, FaultRule
from bloombee_tpu.wire.rpc import connect
from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer


@pytest.fixture(scope="module")
def tiny_model_dir(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64,
        intermediate_size=128,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_hidden_layers=3,
        vocab_size=128,
        max_position_embeddings=256,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    d = tmp_path_factory.mktemp("tiny_llama_prefix")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model, config


@pytest.fixture(autouse=True)
def no_leaked_plan():
    yield
    faults.set_plan(None)


def _server(model_dir, registry, start, end, **kw):
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("num_pages", 64)
    kw.setdefault("page_size", 4)
    kw.setdefault("prefix_cache", True)
    return BlockServer(
        model_uid="tiny", start=start, end=end, model_dir=model_dir,
        registry=registry, **kw,
    )


def _hf_greedy(model, input_ids, max_new_tokens):
    with torch.no_grad():
        out = model.generate(
            torch.tensor(input_ids), max_new_tokens=max_new_tokens,
            do_sample=False, use_cache=True,
        )
    return out.numpy()


def _assert_no_leaks(server):
    """free + referenced + cached == num_pages and nothing referenced
    once every session is closed."""
    table = server.manager.table
    c = table.counts()
    assert c["free"] + c["referenced"] + c["cached"] == table.num_pages, c
    assert c["referenced"] == 0, c


# ------------------------------------------------------------ cache on == off
def test_prefix_cache_token_identical_and_hits(tiny_model_dir):
    """Two-span chain, both servers caching: a cold session computes and
    publishes a 3-page prompt; a warm session sharing it prefills only the
    uncached tail (probed skip = prompt - 1, so the last shared page
    diverges -> copy-on-write), and BOTH match HF greedy exactly. A
    cache-off client against the same warm servers matches too, and
    rpc_info reports the hits."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s_a = _server(model_dir, rc(), 0, 2)
        s_b = _server(model_dir, rc(), 2, 3)
        for s in (s_a, s_b):
            await s.start()

        # 12 tokens = 3 full pages at page_size 4 (>= 2-page shared prompt)
        input_ids = (np.arange(12)[None, :] * 5 + 3) % config.vocab_size
        ref = _hf_greedy(hf_model, input_ids, 6)

        cfg_on = ClientConfig(use_push=False, prefix_cache=True)
        model_on = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny", config=cfg_on
        )
        # cold: miss, full prefill, pages published on close
        ids_cold = await model_on.generate(input_ids, max_new_tokens=6)
        np.testing.assert_array_equal(ids_cold, ref)
        assert s_a.manager.prefix_stats()["prefix_hit_tokens"] == 0

        # warm: the probe matches all 3 pages; the skip cap (prompt - 1)
        # trims to 11 so the suffix write diverges INSIDE the last shared
        # page and copy-on-write fires on the serving path
        ids_warm = await model_on.generate(input_ids, max_new_tokens=6)
        np.testing.assert_array_equal(ids_warm, ref)
        for s in (s_a, s_b):
            stats = s.manager.prefix_stats()
            assert stats["prefix_hits"] >= 1
            assert stats["prefix_hit_tokens"] >= 11
            assert stats["cow_copies"] >= 1

        # cache-off client against the SAME warm servers: identical tokens
        model_off = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny",
            config=ClientConfig(use_push=False, prefix_cache=False),
        )
        ids_off = await model_off.generate(input_ids, max_new_tokens=6)
        np.testing.assert_array_equal(ids_off, ref)

        # the wire surface advertises the cache and reports the counters
        conn = await connect("127.0.0.1", s_a.port)
        info, _ = await conn.call("rpc_info", {})
        assert info["prefix_hit_tokens"] >= 11
        assert info["prefix_hits"] >= 1
        await conn.close()

        await asyncio.sleep(0.2)  # server-side session teardown is async
        for s in (s_a, s_b):
            _assert_no_leaks(s)
            await s.stop()
        await reg.stop()

    asyncio.run(run())


# ----------------------------------------------------------------- chaos e2e
@pytest.mark.chaos
def test_prefix_cache_chaos_mid_prefill(tiny_model_dir):
    """Seeded fault mid-prefill on a warm session: the relay forward to the
    tail span resets right after the probe, forcing a recovery replay (which
    probes again). Tokens stay exact, pages don't leak, and the head span —
    which completed its suffix prefill before the fault — still recorded the
    hit."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s_a = _server(model_dir, rc(), 0, 2, throughput=10.0)
        s_b = _server(model_dir, rc(), 2, 3, throughput=10.0)  # preferred
        s_c = _server(model_dir, rc(), 2, 3, throughput=1.0)  # backup
        for s in (s_a, s_b, s_c):
            await s.start()

        input_ids = (np.arange(12)[None, :] * 7 + 1) % config.vocab_size
        ref = _hf_greedy(hf_model, input_ids, 5)

        cfg = ClientConfig(
            use_push=False, prefix_cache=True, ban_timeout=0.5, ban_max=2.0,
        )
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny", config=cfg
        )
        # warm the pool fault-free
        ids_cold = await model.generate(input_ids, max_new_tokens=5)
        np.testing.assert_array_equal(ids_cold, ref)

        # frame 1 to s_b is the probe, frame 2 the relay-forwarded suffix
        # prefill: reset exactly there (mid-prefill, post-adoption)
        plan = FaultPlan(seed=11)
        plan.add(FaultRule(site="send", action="reset", method="sitem",
                           port=s_b.port, nth=2, count=1))
        faults.set_plan(plan)

        session = model.inference_session(20, 1)
        await session.__aenter__()
        used = {s.span.server_info.port for s in session._spans}
        assert s_b.port in used  # the fault targets the route taken
        ids_warm = await model.generate(
            input_ids, max_new_tokens=5, session=session
        )
        await session.__aexit__(None, None, None)
        np.testing.assert_array_equal(ids_warm, ref)
        assert ("send", "reset") in {(s, a) for s, a, _ in plan.log}
        # the head span completed its suffix prefill before the tail reset
        assert s_a.manager.prefix_stats()["prefix_hit_tokens"] > 0

        faults.set_plan(None)
        await asyncio.sleep(0.2)  # server-side session teardown is async
        for s in (s_a, s_b, s_c):
            _assert_no_leaks(s)
            await s.stop()
        await reg.stop()

    asyncio.run(run())


# --------------------------------------------------------- eviction pressure
def test_prefix_cache_eviction_pressure(tiny_model_dir):
    """Arena barely larger than one session's working set: adoptions,
    copy-on-write, and LRU eviction contend for the same few pages across
    back-to-back sessions. Every generation stays HF-exact and the table
    balances to zero references after each close."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s = _server(model_dir, rc(), 0, 3, num_pages=6)
        await s.start()

        input_ids = (np.arange(12)[None, :] * 3 + 2) % config.vocab_size
        ref = _hf_greedy(hf_model, input_ids, 6)

        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny",
            config=ClientConfig(use_push=False, prefix_cache=True),
        )
        for trial in range(3):
            ids = await model.generate(input_ids, max_new_tokens=6)
            np.testing.assert_array_equal(ids, ref, err_msg=f"trial {trial}")
            await asyncio.sleep(0.2)  # server-side session teardown is async
            _assert_no_leaks(s)
        # later sessions adopted the survivor pages
        assert s.manager.prefix_stats()["prefix_hit_tokens"] > 0

        await s.stop()
        await reg.stop()

    asyncio.run(run())


def test_prefix_max_pages_cap(tiny_model_dir, monkeypatch):
    """BBTPU_PREFIX_MAX_PAGES caps the refcount-0 cached pool. With a cap
    below the shared prefix's page count the chain can never fully pool
    (chained hashes: evicting the head breaks the whole match), so warm
    sessions fall back to full prefills — still HF-exact, pool never over
    the cap."""
    model_dir, hf_model, config = tiny_model_dir
    monkeypatch.setenv("BBTPU_PREFIX_MAX_PAGES", "2")

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s = _server(model_dir, rc(), 0, 3)
        await s.start()
        assert s.manager.table.max_cached_pages == 2

        input_ids = (np.arange(12)[None, :] * 11 + 5) % config.vocab_size
        ref = _hf_greedy(hf_model, input_ids, 4)
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny",
            config=ClientConfig(use_push=False, prefix_cache=True),
        )
        for _ in range(2):
            ids = await model.generate(input_ids, max_new_tokens=4)
            np.testing.assert_array_equal(ids, ref)
            assert s.manager.table.cached_pages <= 2
        await asyncio.sleep(0.2)  # server-side session teardown is async
        _assert_no_leaks(s)

        await s.stop()
        await reg.stop()

    asyncio.run(run())
