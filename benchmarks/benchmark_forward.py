"""Forward-pass (training-style) throughput benchmark.

Port of /root/reference/benchmarks/benchmark_forward.py: tokens/sec through
rpc_forward over the whole chain.
"""

from __future__ import annotations

import argparse
import asyncio
import time

import numpy as np


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("model_dir")
    parser.add_argument("--model-uid", default=None)
    parser.add_argument("--registry", default="127.0.0.1:7700")
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--batch", type=int, default=2)
    parser.add_argument("--steps", type=int, default=10)
    args = parser.parse_args(argv)
    args.model_uid = args.model_uid or args.model_dir.rstrip("/").split("/")[-1]

    async def run():
        from bloombee_tpu.client.model import DistributedModelForCausalLM
        from bloombee_tpu.client.trainer import RemoteSpanChain
        from bloombee_tpu.swarm.registry import RegistryClient

        host, port = args.registry.rsplit(":", 1)
        model = DistributedModelForCausalLM.from_pretrained(
            args.model_dir, RegistryClient(host, int(port)),
            model_uid=args.model_uid,
        )
        chain = RemoteSpanChain(model.manager)
        rng = np.random.default_rng(0)
        h = rng.normal(
            size=(args.batch, args.seq_len, model.spec.hidden_size)
        ).astype(np.float32)
        await chain.forward(h)  # warmup/compile
        t0 = time.perf_counter()
        for _ in range(args.steps):
            await chain.forward(h)
        dt = time.perf_counter() - t0
        toks = args.steps * args.batch * args.seq_len
        print(f"forward throughput={toks / dt:.1f} tok/s")

    asyncio.run(run())


if __name__ == "__main__":
    main()
