"""Pytree helpers."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def stack_params(per_layer: list) -> dict:
    """Stack per-layer param pytrees into one pytree with leading dim L
    (the lax.scan layout used by runtime.step.span_step)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_layer)


def unstack_params(stacked: dict, num_layers: int) -> list:
    return [
        jax.tree.map(lambda x: x[i], stacked) for i in range(num_layers)
    ]
