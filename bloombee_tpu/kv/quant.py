"""Group-wise 4-bit KV quantization (the reference's KV-capacity lever).

Capability port of /root/reference/src/bloombee/flexgen_utils/compression.py
:22-210 (`TorchCompressedDevice`: group-wise asymmetric 4-bit quant of
weights/KV with `general_copy_compressed`), redesigned for the jitted paged
arena: the quantized slab is a pytree (`QuantSlab`) whose leaves ride the
span step's `lax.scan` and donation exactly like the dense slab, writes
quantize on-device as part of the step, and page gathers dequantize into the
attention dtype — so int4 KV needs no separate copy path at all.

Layout per slab: codes pack two 4-bit values per uint8 along head_dim;
scale/zero are per-(slot, head, group) float16. At head_dim 128 and
group_size 32 a token costs 64 B codes + 16 B scale/zero = 80 B vs 256 B
bf16 -> 3.2x more tokens per HBM byte.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

GROUP_SIZE = 32


class QuantSlab(NamedTuple):
    """int4-quantized KV slab; a jax pytree (leaves scan/donate like arrays).

    Leading dims mirror the dense slab ([L, S, H, ...] or [S, H, ...]).
    """

    codes: jax.Array  # [..., hd // 2] uint8, two nibbles per byte
    scale: jax.Array  # [..., hd // GROUP_SIZE] f16, (max - min) / 15
    zero: jax.Array  # [..., hd // GROUP_SIZE] f16, group min

    @property
    def shape(self) -> tuple[int, ...]:
        """Logical (unpacked) leading shape; the slot dim matches dense."""
        return self.codes.shape

    @property
    def head_dim(self) -> int:
        return self.codes.shape[-1] * 2


def make_quant_slab(shape: tuple[int, ...], _dtype=None) -> QuantSlab:
    """Empty quantized slab for a dense-equivalent shape [..., hd]."""
    *lead, hd = shape
    gs = min(GROUP_SIZE, hd)
    assert hd % 2 == 0 and hd % gs == 0, f"head_dim {hd} not int4-packable"
    groups = hd // gs
    return QuantSlab(
        codes=jnp.zeros((*lead, hd // 2), jnp.uint8),
        scale=jnp.zeros((*lead, groups), jnp.float16),
        zero=jnp.zeros((*lead, groups), jnp.float16),
    )


def quantize(x: jax.Array) -> QuantSlab:
    """Group-wise asymmetric int4 quantization along the last dim."""
    *lead, hd = x.shape
    gs = min(GROUP_SIZE, hd)
    g = hd // gs
    xg = x.astype(jnp.float32).reshape(*lead, g, gs)
    mn = xg.min(axis=-1)
    mx = xg.max(axis=-1)
    scale = (mx - mn) / 15.0
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(
        jnp.round((xg - mn[..., None]) / safe[..., None]), 0, 15
    ).astype(jnp.uint8)
    q = q.reshape(*lead, hd)
    codes = q[..., 0::2] | (q[..., 1::2] << 4)
    return QuantSlab(
        codes=codes,
        scale=scale.astype(jnp.float16),
        zero=mn.astype(jnp.float16),
    )


def dequantize(slab: QuantSlab, dtype=jnp.bfloat16) -> jax.Array:
    """Inverse of `quantize`: [..., hd] in the requested dtype."""
    codes, scale, zero = slab.codes, slab.scale, slab.zero
    lo = codes & 0xF
    hi = codes >> 4
    q = jnp.stack([lo, hi], axis=-1).reshape(
        *codes.shape[:-1], codes.shape[-1] * 2
    )
    hd = q.shape[-1]
    gs = min(GROUP_SIZE, hd)
    g = hd // gs
    qg = q.reshape(*q.shape[:-1], g, gs).astype(jnp.float32)
    out = qg * scale[..., None].astype(jnp.float32) + zero[..., None].astype(
        jnp.float32
    )
    return out.reshape(*q.shape[:-1], hd).astype(dtype)


def slab_nbytes(slab) -> int:
    """Total bytes of a slab (dense array or QuantSlab)."""
    from bloombee_tpu.utils.memory import tree_nbytes

    return tree_nbytes(slab)
