"""Chaos gate: scripts/chaos.sh must pass as part of the tier-1 suite.

The script replays every chaos-marked test under a fixed BBTPU_CHAOS_*
seed matrix (ambient wire jitter on top of the tests' own seeded fault
plans), so fault-recovery paths are exercised with injected noise on
every run — not only when an operator remembers to soak them. It exits 0
when pytest is unavailable, mirroring the scripts/lint.sh contract.
"""

import pathlib
import re
import subprocess

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_matrix_entries_are_keyval_tokens():
    """The matrix format is KEY=VAL tokens with per-entry defaults — not
    the old positional colon strings, which silently misassigned every
    column to the right of an insertion. Also pins that the Byzantine
    corruption entry exists and forces the integrity layer on (corruption
    is invisible to the transport; without BBTPU_INTEGRITY=1 the entry
    would test nothing)."""
    src = (REPO / "scripts" / "chaos.sh").read_text()
    entries = re.findall(r'^\s+"([^"]+)"$', src, flags=re.M)
    assert len(entries) >= 5, f"matrix lost entries: {entries}"
    known = {
        "SEED", "DELAY_P", "ADMIT", "PARTITION_P", "MIXED", "SPEC",
        "REBALANCE", "CORRUPT",
    }
    for entry in entries:
        for tok in entry.split():
            key, sep, val = tok.partition("=")
            assert sep == "=" and key in known and val, (
                f"matrix entry {entry!r} has non-KEY=VAL token {tok!r}"
            )
    assert any("CORRUPT=" in e for e in entries), (
        "no Byzantine corruption entry in the chaos matrix"
    )
    assert 'BBTPU_INTEGRITY="${integrity}"' in src
    assert 'BBTPU_CHAOS_CORRUPT_P="${CORRUPT}"' in src


def test_chaos_suite_under_seed_matrix():
    proc = subprocess.run(
        ["bash", str(REPO / "scripts" / "chaos.sh")],
        cwd=REPO, capture_output=True, text=True, timeout=580,
    )
    assert proc.returncode == 0, (
        f"chaos regressions:\n{proc.stdout[-8000:]}\n{proc.stderr[-4000:]}"
    )
