"""Paged KV table: host-side control plane.

Ports the *invariants* of the reference's PagedKVTable
(/root/reference/src/bloombee/server/paged_kv.py:52-317): page-granular
allocation (default page size 16, :35), per-sequence page lists, committed
length `l_acc` vs speculative length `l_seq`, `commit`/`rollback` freeing
orphaned pages (:235-261), and prefix reads clamped to `l_acc` (:265-316).

The design differs from the reference in one deliberate way: this table never
touches tensors. The reference's `write` moves KV bytes page-at-a-time into a
torch slab (:137-204); here the table only *assigns slots* —
`assign_write_slots` returns flat arena slot indices that the jitted device
step scatters into (see bloombee_tpu/kv/arena.py). The reference's
`track_write` state-only mirror (:206-231) is therefore the native operation.
"""

from __future__ import annotations

import dataclasses

import numpy as np

DEFAULT_PAGE_SIZE = 16


class OutOfPages(RuntimeError):
    """Raised when the arena has no free pages for a reservation."""


@dataclasses.dataclass
class SeqState:
    pages: list[int]
    l_acc: int = 0  # committed token count
    l_seq: int = 0  # total written (committed + speculative)

    @property
    def num_pages(self) -> int:
        return len(self.pages)


class PagedKVTable:
    """Page allocator + per-sequence length bookkeeping (host side)."""

    def __init__(self, num_pages: int, page_size: int = DEFAULT_PAGE_SIZE):
        if num_pages <= 0 or page_size <= 0:
            raise ValueError("num_pages and page_size must be positive")
        self.num_pages = num_pages
        self.page_size = page_size
        self._free: list[int] = list(range(num_pages - 1, -1, -1))
        self._seqs: dict[int, SeqState] = {}

    # ------------------------------------------------------------- lifecycle
    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def free_tokens(self) -> int:
        return len(self._free) * self.page_size

    def has_seq(self, seq_id: int) -> bool:
        return seq_id in self._seqs

    def seq(self, seq_id: int) -> SeqState:
        return self._seqs[seq_id]

    def add_seq(self, seq_id: int) -> None:
        if seq_id in self._seqs:
            raise ValueError(f"sequence {seq_id} already exists")
        self._seqs[seq_id] = SeqState(pages=[])

    def drop_seq(self, seq_id: int) -> None:
        state = self._seqs.pop(seq_id)
        self._free.extend(state.pages)

    # ------------------------------------------------------------ allocation
    def _pages_for(self, tokens: int) -> int:
        return -(-tokens // self.page_size)

    def reserve(self, seq_id: int, new_total_len: int) -> None:
        """Grow the sequence's page list to cover `new_total_len` tokens."""
        state = self._seqs[seq_id]
        need = self._pages_for(new_total_len) - len(state.pages)
        if need <= 0:
            return
        if need > len(self._free):
            raise OutOfPages(
                f"need {need} pages, only {len(self._free)} free"
            )
        for _ in range(need):
            state.pages.append(self._free.pop())

    # --------------------------------------------------------------- writing
    def assign_write_slots(
        self, seq_id: int, num_tokens: int, commit: bool = True
    ) -> np.ndarray:
        """Assign flat arena slots for the next `num_tokens` tokens.

        Tokens land at positions [l_seq, l_seq + num_tokens); reserves pages
        as needed. `commit=False` marks them speculative (rollback-able),
        mirroring the reference write(commit=...) flag (paged_kv.py:137-204).
        Returns int32 flat slot ids (page * page_size + offset).
        """
        if num_tokens < 0:
            raise ValueError(f"num_tokens must be >= 0, got {num_tokens}")
        state = self._seqs[seq_id]
        start = state.l_seq
        if commit and state.l_acc != start:
            # validate BEFORE reserving: an invalid write must not mutate
            # the table (pages/lengths) on its way to the exception
            raise ValueError(
                "committed write must follow the committed prefix "
                f"(l_acc={state.l_acc}, write starts at {start})"
            )
        self.reserve(seq_id, start + num_tokens)
        positions = np.arange(start, start + num_tokens)
        pages = np.asarray(state.pages, dtype=np.int64)[
            positions // self.page_size
        ]
        slots = pages * self.page_size + positions % self.page_size
        state.l_seq = start + num_tokens
        if commit:
            state.l_acc = state.l_seq
        return slots.astype(np.int32)

    # ------------------------------------------------------ commit / rollback
    def commit(self, seq_id: int, length: int | None = None) -> None:
        """Promote speculative tokens to committed; free pages past the end.

        `length` defaults to l_seq (commit everything written). Mirrors
        paged_kv.py:235-246.
        """
        state = self._seqs[seq_id]
        if length is None:
            length = state.l_seq
        if not (state.l_acc <= length <= state.l_seq):
            raise ValueError(
                f"commit length {length} outside [{state.l_acc}, {state.l_seq}]"
            )
        state.l_acc = length
        state.l_seq = length
        self._trim(state)

    def accept(self, seq_id: int, num_accepted: int) -> None:
        """Keep the first `num_accepted` speculative tokens (after the caller
        compacted the arena rows onto them) and discard the rest."""
        state = self._seqs[seq_id]
        if not 0 <= num_accepted <= state.l_seq - state.l_acc:
            raise ValueError(
                f"accept {num_accepted} outside speculative window "
                f"[0, {state.l_seq - state.l_acc}]"
            )
        state.l_acc += num_accepted
        state.l_seq = state.l_acc
        self._trim(state)

    def range_slots(self, seq_id: int, start: int, end: int) -> np.ndarray:
        """Flat slot ids for positions [start, end) (must be materialized)."""
        state = self._seqs[seq_id]
        if end > len(state.pages) * self.page_size:
            raise ValueError("range beyond allocated pages")
        positions = np.arange(start, end)
        pages = np.asarray(state.pages, dtype=np.int64)[
            positions // self.page_size
        ]
        return (pages * self.page_size + positions % self.page_size).astype(
            np.int32
        )

    def rollback(self, seq_id: int) -> None:
        """Discard speculative tokens; free orphaned pages
        (paged_kv.py:247-261)."""
        state = self._seqs[seq_id]
        state.l_seq = state.l_acc
        self._trim(state)

    def reset_seq(self, seq_id: int) -> None:
        """Drop ALL tokens (committed included) and free the pages, keeping
        the sequence registered — the parking primitive."""
        state = self._seqs[seq_id]
        state.l_acc = 0
        state.l_seq = 0
        self._trim(state)

    def restore_committed(self, seq_id: int, l_acc: int) -> None:
        """Set the committed watermark without touching l_seq (unparking
        re-materializes tokens speculatively, then restores l_acc)."""
        state = self._seqs[seq_id]
        if not 0 <= l_acc <= state.l_seq:
            raise ValueError(
                f"l_acc {l_acc} outside [0, {state.l_seq}]"
            )
        state.l_acc = l_acc

    def _trim(self, state: SeqState) -> None:
        keep = self._pages_for(max(state.l_seq, state.l_acc))
        while len(state.pages) > keep:
            self._free.append(state.pages.pop())

    # ---------------------------------------------------------- device plans
    def page_table(
        self, seq_ids: list[int], max_pages: int
    ) -> np.ndarray:
        """[B, max_pages] int32 page ids, padded with 0 (masked by length)."""
        out = np.zeros((len(seq_ids), max_pages), dtype=np.int32)
        for i, sid in enumerate(seq_ids):
            pages = self._seqs[sid].pages
            if len(pages) > max_pages:
                raise ValueError(
                    f"sequence {sid} has {len(pages)} pages > bucket {max_pages}"
                )
            out[i, : len(pages)] = pages
        return out

    def context_lens(
        self, seq_ids: list[int], committed_only: bool = False
    ) -> np.ndarray:
        """Per-sequence visible lengths; `committed_only` clamps to l_acc —
        the reference's gather_prefix clamp (paged_kv.py:265-316)."""
        return np.asarray(
            [
                self._seqs[s].l_acc if committed_only else self._seqs[s].l_seq
                for s in seq_ids
            ],
            dtype=np.int32,
        )

    def prefix_slots(self, seq_id: int, committed_only: bool = True) -> np.ndarray:
        """Flat slot ids of the sequence prefix, clamped to l_acc by default."""
        state = self._seqs[seq_id]
        n = state.l_acc if committed_only else state.l_seq
        positions = np.arange(n)
        pages = np.asarray(state.pages, dtype=np.int64)[
            positions // self.page_size
        ]
        return (pages * self.page_size + positions % self.page_size).astype(
            np.int32
        )
