"""Discrete-event conductor over the SteppableClock.

The control plane under test is ordinary asyncio + worker-thread code; it
was never written against an event-queue API. The engine therefore drives
it from the OUTSIDE: let the loop and the compute threads run until the
process *quiesces* (nothing runnable now, every live thread parked in a
virtual ``clock.sleep``), then jump the SteppableClock to the earliest
deadline any sleeper is waiting for. Repeat. Minutes of protocol time
cost milliseconds of wall time and every dwell window, lease expiry, and
backoff fires in exact virtual order.

The one genuinely hard part is knowing when compute is mid-flight: a
``run_in_executor`` callable that has been submitted but has not yet
reached its cost-model ``clock.sleep`` is invisible to the clock, and
advancing past it would deliver its completion at the wrong virtual
instant. ``CountingExecutor`` closes that window with two counters:
``submit`` increments a *queued* count on the loop thread; the runner
moves it to *running* the moment the worker picks it up, and decrements
*running* only AFTER publishing the result to the proxy future —
publishing runs the ``wrap_future`` callback synchronously, which
enqueues the asyncio-side resolution via ``call_soon_threadsafe`` — so
a settled count guarantees every finished compute's wakeup is already in
the loop's ready queue. The conductor treats compute as settled only
when every running thread is parked in a virtual sleep AND no executor
has queued work with an idle worker (a hand-off in flight); queued work
*behind* a sleeping runner is settled — it cannot start until the clock
advances, which is exactly what the conductor is about to do.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import threading

from bloombee_tpu.utils import clock as clock_mod
from bloombee_tpu.utils.clock import SteppableClock


class SimStalled(RuntimeError):
    """The simulation can make no progress: live tasks remain but nothing
    sleeps on the virtual clock (a deadlock in the code under test), or a
    wall/virtual budget was exhausted."""


class CountingExecutor:
    """ThreadPoolExecutor facade whose in-flight submissions are countable
    by the conductor. API-compatible with the slice ComputeQueue uses
    (``submit`` + ``shutdown``)."""

    def __init__(self, engine: "SimEngine"):
        self._engine = engine
        # guarded by the engine's lock: submissions the worker has not
        # picked up yet / runners between pickup and result publication
        self._queued = 0
        self._running = 0
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="simcompute"
        )

    def submit(self, fn, *args, **kwargs):
        eng = self._engine
        with eng._plock:
            self._queued += 1
        proxy: concurrent.futures.Future = concurrent.futures.Future()

        def runner():
            with eng._plock:
                self._queued -= 1
                self._running += 1
            if not proxy.set_running_or_notify_cancel():
                with eng._plock:
                    self._running -= 1
                return
            try:
                result = fn(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 — relayed to awaiter
                proxy.set_exception(e)
            else:
                # set_result synchronously runs wrap_future's callback,
                # which call_soon_threadsafe's the asyncio resolution —
                # decrementing AFTER it means settled implies every
                # wakeup is already enqueued on the loop
                proxy.set_result(result)
            with eng._plock:
                self._running -= 1

        inner = self._pool.submit(runner)

        def _on_inner(f):
            # shutdown(cancel_futures=True) cancels queued runners that
            # never start; without this the queued count would leak and
            # the conductor would wait forever
            if f.cancelled():
                proxy.cancel()
                with eng._plock:
                    self._queued -= 1

        inner.add_done_callback(_on_inner)
        return proxy

    def _settled_locked(self) -> tuple[bool, int]:
        """(no hand-off in flight, running count). Caller holds the
        engine lock. Queued work behind a busy (sleeping) worker is
        settled: it cannot start until virtual time advances."""
        return (not self._queued or self._running >= 1), self._running

    def shutdown(self, wait: bool = True, cancel_futures: bool = False):
        self._pool.shutdown(wait=False, cancel_futures=True)


class SimEngine:
    """Owns the SteppableClock, the counting executors, and the
    quiesce-then-advance conductor loop."""

    def __init__(self, start: float = 1000.0):
        self.clock = SteppableClock(start=start)
        self._plock = threading.Lock()
        self._executors: list[CountingExecutor] = []
        self.advances = 0  # conductor diagnostics (tests / --json output)

    # ------------------------------------------------------------- executors
    def new_executor(self) -> CountingExecutor:
        ex = CountingExecutor(self)
        self._executors.append(ex)
        return ex

    def _compute_settled(self) -> bool:
        """True when no compute thread is between submit and its virtual
        sleep: every running submission is accounted for by a thread
        blocked in clock.sleep (or has already published its result), and
        no executor has queued work its worker is free to start."""
        running = 0
        with self._plock:
            for ex in self._executors:
                ok, n = ex._settled_locked()
                if not ok:
                    return False  # worker hand-off in flight
                running += n
        return running <= self.clock.blocked_sleepers()

    # ------------------------------------------------------------- conductor
    def now(self) -> float:
        return self.clock.monotonic()

    async def _quiesce(self, loop) -> None:
        """Run the loop until nothing is immediately runnable and all
        in-flight compute has parked on the virtual clock."""
        while True:
            await asyncio.sleep(0)
            if getattr(loop, "_ready", None):
                continue  # more callbacks became runnable; keep draining
            if not self._compute_settled():
                # a compute thread is running real code between submit and
                # its cost-model sleep; give it a hair of real time
                await asyncio.sleep(0.0002)
                continue
            if getattr(loop, "_ready", None):
                continue
            return

    async def run_tasks(
        self,
        tasks: list,
        max_virtual_s: float = 3600.0,
        max_wall_s: float = 300.0,
    ) -> None:
        """Drive virtual time until every task in `tasks` is done.
        Background loops (announcers, promotion watchers, samplers) may
        keep sleeping; the caller cancels them afterwards."""
        loop = asyncio.get_running_loop()
        horizon = self.clock.monotonic() + max_virtual_s
        wall_end = clock_mod.perf_counter() + max_wall_s
        idle_rounds = 0
        while True:
            await self._quiesce(loop)
            if all(t.done() for t in tasks):
                return
            if clock_mod.perf_counter() > wall_end:
                raise SimStalled(
                    f"wall budget exhausted ({max_wall_s:.0f}s) with "
                    f"{sum(not t.done() for t in tasks)} task(s) live"
                )
            if self.clock.monotonic() >= horizon:
                raise SimStalled(
                    f"virtual horizon exhausted ({max_virtual_s:.0f}s) "
                    f"with {sum(not t.done() for t in tasks)} task(s) live"
                )
            nd = self.clock.next_deadline()
            if nd is None:
                # live tasks but no virtual sleeper: either a thread is
                # about to park (give it real time) or the code under
                # test deadlocked (fail loudly, don't hang CI)
                idle_rounds += 1
                if idle_rounds > 2000:
                    raise SimStalled(
                        "no virtual sleeper and tasks never complete — "
                        "deadlock in the code under test?"
                    )
                await asyncio.sleep(0.0005)
                continue
            idle_rounds = 0
            dt = nd - self.clock.monotonic()
            if dt <= 0:
                # a just-woken sync sleeper still holds its (expired)
                # deadline entry; let its thread run it off
                await asyncio.sleep(0.0002)
                continue
            self.clock.advance(dt)
            self.advances += 1

    # ------------------------------------------------------------------ run
    def run(self, coro, *args, **kwargs):
        """Install the virtual clock process-wide, run `coro` (a coroutine
        function called with this engine + *args), restore the previous
        clock, and tear the executors down."""
        prev = clock_mod.install(self.clock)
        try:
            return asyncio.run(coro(self, *args, **kwargs))
        finally:
            if prev is None:
                clock_mod.reset()  # back to lazy env-driven default
            else:
                clock_mod.install(prev)
            for ex in self._executors:
                ex.shutdown()
