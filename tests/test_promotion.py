"""Elastic self-healing: standby promotion / demotion control loop.

A warm standby (--standby) announces JOINING — invisible to routing,
visible to kv_put replication — and watches its span's serving replicas.
It promotes itself to ONLINE on sustained overload past the high
watermark or on span loss (advert silence past the registry lease), and
drains back to standby once other coverage stays cool below the low
watermark. Promotion storms (N standbys, one hot span) must converge to
exactly ONE promoted replica via the jitter + re-check-after-declare
guard.
"""

import asyncio
import time

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from bloombee_tpu.client.model import DistributedModelForCausalLM
from bloombee_tpu.server.block_server import BlockServer
from bloombee_tpu.swarm.data import ServerInfo, ServerState
from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer
from bloombee_tpu.utils import clock
from bloombee_tpu.utils.clock import ScaledClock


@pytest.fixture(scope="module")
def tiny_model_dir(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64,
        intermediate_size=128,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_hidden_layers=3,
        vocab_size=128,
        max_position_embeddings=256,
        tie_word_embeddings=False,
    )
    torch.manual_seed(7)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    d = tmp_path_factory.mktemp("tiny_llama_promote")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model, config


def _standby_server(model_dir, rc, **kw):
    kw.setdefault("promote_high_ms", 500.0)
    kw.setdefault("promote_low_ms", 100.0)
    kw.setdefault("promote_sustain_s", 0.3)
    kw.setdefault("promote_jitter_s", 0.4)
    return BlockServer(
        model_uid="tiny", start=0, end=3, model_dir=model_dir,
        registry=rc, compute_dtype=jnp.float32, num_pages=64,
        page_size=4, announce_period=0.3, standby=True,
        drain_timeout=2.0, **kw,
    )


async def _wait_for(cond, timeout, what):
    deadline = asyncio.get_event_loop().time() + timeout
    while not cond():
        if asyncio.get_event_loop().time() > deadline:
            raise AssertionError(f"timed out waiting for {what}")
        await asyncio.sleep(0.1)


def test_three_standbys_exactly_one_promotes(tiny_model_dir):
    """The acceptance scenario: 3 standbys watching one chronically hot
    span must end with EXACTLY one promoted serving replica — the
    jittered pre-declare re-check plus the post-declare storm resolution
    (lexicographically-smallest promoted id wins) de-duplicates the rest."""
    model_dir, _, _ = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        # a fake hot primary: ONLINE record whose load advert pins the
        # predicted queue delay at the cap (10s >> the 500ms watermark);
        # ts fresh so the staleness discount stays above the watermark
        # for the whole test
        hot = ServerInfo(
            state=ServerState.ONLINE, host="127.0.0.1", port=1,
            throughput=1.0, start_block=0, end_block=3,
            load={"ts": time.time(), "delay_ms": 1e9},
        )
        await rc().declare_blocks(
            "tiny", "srv-hotprimary", range(3), hot, expiration=60.0
        )

        standbys = [_standby_server(model_dir, rc()) for _ in range(3)]
        for s in standbys:
            await s.start()
        # every deadline in the promotion path (announce lease, sustain
        # dwell, jitter, storm re-check) reads clock.*, and standbys never
        # serve here, so no compute is in flight: the watch -> promote ->
        # storm-resolve sequence runs 4x compressed with identical state
        # transitions. 4x (not the 20x of the lease tests) keeps the
        # 0.75s announce-lease margin ~10x above scheduler noise. The
        # clock is installed AFTER the starts on purpose: the fake hot
        # advert's staleness budget (LOAD_STALE_S) burns in virtual time,
        # so the slow part (3x weight loading) must not run 4x; the
        # install transition can at worst flap a standby lease for one
        # real announce period, and a promotion storm triggered by that
        # converges via the yield protocol — which is what this test
        # asserts anyway.
        prev = clock.install(ScaledClock(scale=4.0))
        try:
            await _wait_for(
                lambda: sum(s._promoted for s in standbys) >= 1, 25.0,
                "any standby promotion",
            )
            # let the storm (if any) fully resolve, then require
            # convergence to exactly one promoted replica, stable over
            # several ticks
            await clock.async_sleep(3.0)
            for _ in range(5):
                assert sum(s._promoted for s in standbys) == 1
                assert sum(s._standby for s in standbys) == 2
                await clock.async_sleep(0.3)
        finally:
            clock.install(prev)
        # every decision is operator-visible: the winner counted its
        # promotion; any racer that also declared counted a yield
        winner = next(s for s in standbys if s._promoted)
        assert winner.promotions >= 1
        assert winner._advert_state() == ServerState.ONLINE
        assert winner.server_info().promoted_standby
        for s in standbys:
            if s is not winner:
                assert s._advert_state() == ServerState.JOINING
                assert s.promotions == s.promotions_yielded
        for s in standbys:
            await s.stop()
        await reg.stop()

    asyncio.run(run())


def test_standby_promotes_on_dead_span_and_serves(tiny_model_dir):
    """Kill the span's only server: the standby must detect the silent
    span, promote, and actually serve — a fresh client run through the
    promoted replica matches HF greedy decoding exactly."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        # everything up to the generate is control traffic on virtual
        # deadlines (announce lease, watcher tick, sustain dwell), so the
        # servers are BORN on a 4x compressed clock: installing before
        # start() keeps every in-flight sleep and every lease on one
        # timeline. Installing mid-run instead leaves pre-install sleeps
        # holding real deadlines while virtual time jumps ahead — the
        # primary's lease flaps expired for a beat and the standby
        # promotes early. Restored to real before the generate; that
        # backward jump only lengthens leases, never expires them.
        prev = clock.install(ScaledClock(scale=4.0))
        try:
            primary = BlockServer(
                model_uid="tiny", start=0, end=3, model_dir=model_dir,
                registry=rc(), compute_dtype=jnp.float32, num_pages=64,
                page_size=4, announce_period=0.3,
            )
            standby = _standby_server(model_dir, rc())
            await primary.start()
            await standby.start()

            # a standby is not a serving replica: a session opened
            # directly against it must be refused before any KV is
            # allocated
            from bloombee_tpu.wire.rpc import RpcError, connect

            conn = await connect("127.0.0.1", standby.port)
            with pytest.raises(RpcError):
                stream = await conn.open_stream(
                    "rpc_inference",
                    {"session_id": "s-refused", "batch_size": 1,
                     "max_length": 8},
                )
                await stream.recv()
            await conn.close()

            # while the primary lives, the standby must not promote:
            # observed over 2.0 virtual seconds (several watcher ticks)
            await clock.async_sleep(2.0)
            assert standby._standby and not standby._promoted

            await primary.stop()  # tombstones the span: advert silence
            await _wait_for(
                lambda: standby._promoted, 20.0, "promotion after span loss"
            )
        finally:
            clock.install(prev)

        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny"
        )
        rng = np.random.default_rng(11)
        input_ids = rng.integers(0, config.vocab_size, size=(1, 4))
        ids = await model.generate(
            input_ids, max_new_tokens=5, server_decode=False
        )
        with torch.no_grad():
            ref = hf_model.generate(
                torch.tensor(input_ids), max_new_tokens=5, do_sample=False,
                use_cache=True,
            ).numpy()
        np.testing.assert_array_equal(ids, ref)
        assert standby.promotions == 1

        await standby.stop()
        await reg.stop()

    asyncio.run(run())


def test_promoted_replica_demotes_when_span_cools(tiny_model_dir):
    """Hysteretic drain-back: once OTHER live coverage stays below the low
    watermark for the sustain window, a promoted replica returns to
    standby (JOINING) — and re-promotes when that coverage disappears
    again. Never demotes while it is the span's sole coverage."""
    model_dir, _, _ = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        standby = _standby_server(model_dir, rc())
        await standby.start()
        # the whole promote -> drain-back -> re-promote cycle is control
        # traffic only (this standby never serves a session), and every
        # deadline in it (watcher tick, sustain dwell, lease expiry)
        # reads clock.*, so it runs end to end on a 4x compressed clock;
        # keep_cool_alive sleeps on the same clock, so its re-declare
        # cadence keeps the same 4x margin over its 2.0s lease
        prev = clock.install(ScaledClock(scale=4.0))
        try:
            # no serving replica at all: the standby must promote...
            await _wait_for(
                lambda: standby._promoted, 20.0, "promotion of sole standby"
            )
            # ...and must NOT demote while it is the only coverage
            await clock.async_sleep(1.5)
            assert standby._promoted and standby.demotions == 0

            # a healthy primary (re)appears, cool (no load advert =
            # delay 0)
            cool = ServerInfo(
                state=ServerState.ONLINE, host="127.0.0.1", port=1,
                throughput=1.0, start_block=0, end_block=3,
            )

            async def keep_cool_alive():
                while True:
                    await rc().declare_blocks(
                        "tiny", "srv-coolprimary", range(3), cool,
                        expiration=2.0,
                    )
                    await clock.async_sleep(0.5)

            alive = asyncio.create_task(keep_cool_alive())
            await _wait_for(
                lambda: not standby._promoted and standby._standby, 20.0,
                "drain-back after the span cooled",
            )
            assert standby.demotions == 1
            assert standby._advert_state() == ServerState.JOINING

            # the primary dies again: the SAME standby must promote again
            alive.cancel()
            await rc().revoke_blocks(
                "tiny", "srv-coolprimary", range(3), expiration=60.0
            )
            await _wait_for(
                lambda: standby._promoted, 20.0, "re-promotion after re-loss"
            )
            assert standby.promotions == 2
        finally:
            clock.install(prev)

        await standby.stop()
        await reg.stop()

    asyncio.run(run())


def test_client_update_sees_standby_spans(tiny_model_dir):
    """The routing view must keep JOINING standbys OUT of self.spans (no
    route may land on one) while exposing them in standby_spans so
    pick_standby can target them for KV replication."""
    model_dir, _, _ = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        primary = BlockServer(
            model_uid="tiny", start=0, end=3, model_dir=model_dir,
            registry=rc(), compute_dtype=jnp.float32, num_pages=64,
            page_size=4, announce_period=0.3, prefix_cache=True,
        )
        standby = _standby_server(model_dir, rc(), prefix_cache=True)
        await primary.start()
        await standby.start()

        from bloombee_tpu.client.sequence_manager import (
            RemoteSequenceManager,
        )

        mgr = RemoteSequenceManager(rc(), "tiny", 3)
        await mgr.update(force=True)
        assert primary.server_id in mgr.spans
        assert standby.server_id not in mgr.spans
        assert standby.server_id in mgr.standby_spans
        # replication targeting: the standby qualifies for the primary's
        # span even though it is invisible to routing
        pick = mgr.pick_standby(mgr.spans[primary.server_id])
        assert pick is not None and pick.peer_id == standby.server_id

        await primary.stop()
        await standby.stop()
        await reg.stop()

    asyncio.run(run())


@pytest.mark.chaos
def test_promotion_survives_registry_chaos(tiny_model_dir):
    """Chaos-marked: the promotion watcher must keep working through a
    flaky registry (transient get_module_infos failures) — errors log
    and retry, they never kill the control loop."""
    model_dir, _, _ = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        class FlakyRegistry:
            def __init__(self, inner, fail_every=3):
                self._inner = inner
                self._calls = 0
                self._fail_every = fail_every

            async def get_module_infos(self, *a, **kw):
                self._calls += 1
                if self._calls % self._fail_every == 0:
                    raise RuntimeError("injected registry flap")
                return await self._inner.get_module_infos(*a, **kw)

            def __getattr__(self, name):
                return getattr(self._inner, name)

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        standby = _standby_server(model_dir, rc())
        standby.registry = FlakyRegistry(rc())
        await standby.start()
        # same 4x compressed clock as the other promotion tests: the
        # watcher's log-and-retry cadence and every promotion deadline
        # are clock-driven, and nothing computes while we wait
        prev = clock.install(ScaledClock(scale=4.0))
        try:
            await _wait_for(
                lambda: standby._promoted, 25.0,
                "promotion through registry chaos",
            )
        finally:
            clock.install(prev)
        assert not standby._promotion_task.done()
        await standby.stop()
        await reg.stop()

    asyncio.run(run())
