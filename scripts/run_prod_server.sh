#!/usr/bin/env bash
# Production worker launcher (role of the reference's cli/run_prod_server.sh):
# env-driven configuration, restart-on-crash loop, logs to a file.
#
# Required:
#   BBTPU_MODEL       model directory or hub name
#   BBTPU_REGISTRY    host:port of the registry bootstrap node
# Optional:
#   BBTPU_BLOCKS      "start:end" block span (default: auto-select)
#   BBTPU_TP          tensor-parallel degree over local chips (default 1)
#   BBTPU_KV_QUANT    none | int4
#   BBTPU_NUM_PAGES   KV pages (default 256)
#   BBTPU_PUBLIC_HOST address to announce (default: first hostname -I entry)
#   BBTPU_LOG_DIR     log directory (default ./logs)
set -euo pipefail

: "${BBTPU_MODEL:?set BBTPU_MODEL}"
: "${BBTPU_REGISTRY:?set BBTPU_REGISTRY}"
LOG_DIR="${BBTPU_LOG_DIR:-./logs}"
mkdir -p "$LOG_DIR"
PUBLIC_HOST="${BBTPU_PUBLIC_HOST:-$(hostname -I 2>/dev/null | awk '{print $1}' || true)}"

ARGS=(
  "$BBTPU_MODEL"
  --registry "$BBTPU_REGISTRY"
  --public-host "${PUBLIC_HOST:-127.0.0.1}"
  --num-pages "${BBTPU_NUM_PAGES:-256}"
  --tp "${BBTPU_TP:-1}"
)
[ -n "${BBTPU_BLOCKS:-}" ] && ARGS+=(--blocks "$BBTPU_BLOCKS")
[ -n "${BBTPU_KV_QUANT:-}" ] && ARGS+=(--kv-quant "$BBTPU_KV_QUANT")

# restart on crash (the reference Server loop restarts its container;
# process-level restart covers hard crashes too)
while true; do
  echo "[run_prod_server] starting worker: ${ARGS[*]}"
  python -m bloombee_tpu.cli.run_server "${ARGS[@]}" \
    2>&1 | tee -a "$LOG_DIR/server.log" && break
  echo "[run_prod_server] worker exited abnormally; restarting in 5s"
  sleep 5
done
