"""TP-sharded serving: GSPMD-partitioned span step parity vs unsharded.

Matches the role of the reference's TP decode serving
(/root/reference/src/bloombee/server/flexgen_tensor_parallel.py:540-828),
tested the reference's way (tests/test_flexgen_tensor_parallel.py shard math
on CPU): tp=2 serving output must equal tp=1 to tight tolerance, through the
real paged executor (prefill + stepwise decode), for dense Llama and for
Mixtral with expert parallelism. Runs on the virtual 8-device CPU mesh from
conftest.
"""

import asyncio

import numpy as np
import pytest

import jax.numpy as jnp
import jax.random as jr

from bloombee_tpu.kv.cache_manager import CacheManager
from bloombee_tpu.models.llama.block import init_block_params
from bloombee_tpu.models.spec import ModelSpec
from bloombee_tpu.parallel.serving import make_serving_mesh
from bloombee_tpu.runtime.executor import SpanExecutor
from bloombee_tpu.utils.tree import stack_params

LLAMA_SPEC = ModelSpec(
    family="llama", hidden_size=64, intermediate_size=128,
    num_attention_heads=4, num_key_value_heads=2, head_dim=16,
    num_hidden_layers=3, vocab_size=64,
)

MOE_SPEC = ModelSpec(
    family="mixtral", hidden_size=32, intermediate_size=64,
    num_attention_heads=4, num_key_value_heads=2, head_dim=8,
    num_hidden_layers=2, vocab_size=64, num_experts=4,
    num_experts_per_tok=2,
)


def _params_for(spec):
    layers = []
    for i in range(spec.num_hidden_layers):
        p = init_block_params(jr.PRNGKey(i), spec)
        if spec.num_experts:
            d, inter, e = (
                spec.hidden_size, spec.intermediate_size, spec.num_experts
            )
            del p["gate_proj"], p["up_proj"], p["down_proj"]
            p["router"] = jr.normal(jr.PRNGKey(10 + i), (d, e)) * 0.1
            p["experts_gate"] = jr.normal(jr.PRNGKey(20 + i), (e, d, inter)) * 0.1
            p["experts_up"] = jr.normal(jr.PRNGKey(30 + i), (e, d, inter)) * 0.1
            p["experts_down"] = jr.normal(jr.PRNGKey(40 + i), (e, inter, d)) * 0.1
        layers.append(p)
    return stack_params(layers)


def _serve_steps(spec, params, mesh):
    """Prefill 6 tokens then decode 3, through the paged executor."""

    async def run():
        manager = CacheManager(
            num_layers=spec.num_hidden_layers, num_pages=32, page_size=4,
            n_kv_heads=spec.num_key_value_heads, head_dim=spec.head_dim,
            dtype=jnp.float32,
        )
        ex = SpanExecutor(
            params, spec, manager, compute_dtype=jnp.float32, mesh=mesh
        )
        rng = np.random.default_rng(0)
        outs = []
        async with manager.allocate(2, 16) as handle:
            hidden = rng.standard_normal((2, 6, spec.hidden_size)).astype(
                np.float32
            )
            outs.append(ex.prefill(handle, hidden))
            for s in range(3):
                step = rng.standard_normal((2, 1, spec.hidden_size)).astype(
                    np.float32
                )
                outs.append(ex.decode(handle, step))
        return outs

    return asyncio.run(run())


@pytest.mark.parametrize("spec", [LLAMA_SPEC, MOE_SPEC],
                         ids=["llama", "mixtral_ep"])
def test_tp2_matches_tp1(spec):
    params = _params_for(spec)
    ref = _serve_steps(spec, params, mesh=None)
    tp2 = _serve_steps(spec, params, mesh=make_serving_mesh(2))
    for a, b in zip(ref, tp2):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)


def test_tp4_matches_tp1_llama():
    # tp=4 means one attention head per device and kv heads replicated?
    # No: Hkv=2 < tp=4 is rejected; use Hkv=4 here.
    spec = ModelSpec(
        family="llama", hidden_size=64, intermediate_size=128,
        num_attention_heads=4, num_key_value_heads=4, head_dim=16,
        num_hidden_layers=2, vocab_size=64,
    )
    params = _params_for(spec)
    ref = _serve_steps(spec, params, mesh=None)
    tp4 = _serve_steps(spec, params, mesh=make_serving_mesh(4))
    for a, b in zip(ref, tp4):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)


def test_tp_rejects_indivisible_heads():
    with pytest.raises(ValueError):
        _serve_steps(LLAMA_SPEC, _params_for(LLAMA_SPEC),
                     mesh=make_serving_mesh(3))


def test_tp2_block_server_e2e(tmp_path):
    """Full swarm path with a tp=2 server: greedy tokens match HF."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=3, vocab_size=128,
        max_position_embeddings=256, tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    model.save_pretrained(tmp_path, safe_serialization=True)

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        server = BlockServer(
            model_uid="t", start=0, end=3, model_dir=str(tmp_path),
            registry=rc(), compute_dtype=jnp.float32, num_pages=64,
            page_size=4, tp=2,
        )
        await server.start()
        dm = DistributedModelForCausalLM.from_pretrained(
            str(tmp_path), rc(), model_uid="t"
        )
        ids_in = np.arange(6)[None, :] % config.vocab_size
        ids = await dm.generate(ids_in, max_new_tokens=6)
        with torch.no_grad():
            ref = model.generate(
                torch.tensor(ids_in), max_new_tokens=6, do_sample=False,
                use_cache=True,
            ).numpy()
        np.testing.assert_array_equal(ids, ref)
        await server.stop()
        await reg.stop()

    asyncio.run(run())


@pytest.mark.parametrize("bits", [8, 4], ids=["int8", "int4"])
def test_tp2_quantized_matches_tp1_quantized(bits):
    """weight-quant x TP composition: the SAME quantized weights served
    tp=2 must match tp=1 to tight tolerance (codes shard like their dense
    counterparts, scales stay shard-local — the composition the reference
    builds from compression.py + flexgen_tensor_parallel.py)."""
    from bloombee_tpu.models import wquant

    qparams = wquant.quantize_span_params(_params_for(LLAMA_SPEC), bits)
    ref = _serve_steps(LLAMA_SPEC, qparams, mesh=None)
    tp2 = _serve_steps(LLAMA_SPEC, qparams, mesh=make_serving_mesh(2))
    for a, b in zip(ref, tp2):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)


def test_tp2_quantized_moe_expert_parallel():
    """Quantized expert stacks shard over the expert dim (codes AND
    scales), composing int8 weights with expert parallelism."""
    from bloombee_tpu.models import wquant

    qparams = wquant.quantize_span_params(_params_for(MOE_SPEC), 8)
    ref = _serve_steps(MOE_SPEC, qparams, mesh=None)
    tp2 = _serve_steps(MOE_SPEC, qparams, mesh=make_serving_mesh(2))
    for a, b in zip(ref, tp2):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)


def test_tp2_int8_block_server_e2e(tmp_path):
    """Full swarm path with a tp=2 int8-quantized server: greedy tokens
    must match a tp=1 server with the same quantized weights."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=3, vocab_size=128,
        max_position_embeddings=256, tie_word_embeddings=False,
    )
    torch.manual_seed(2)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    model.save_pretrained(tmp_path, safe_serialization=True)

    async def run_swarm(tp):
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        server = BlockServer(
            model_uid="t", start=0, end=3, model_dir=str(tmp_path),
            registry=rc(), compute_dtype=jnp.float32, num_pages=64,
            page_size=4, tp=tp, weight_quant="int8",
        )
        await server.start()
        dm = DistributedModelForCausalLM.from_pretrained(
            str(tmp_path), rc(), model_uid="t"
        )
        ids_in = np.arange(6)[None, :] % config.vocab_size
        ids = await dm.generate(
            ids_in, max_new_tokens=6, server_decode=False
        )
        await server.stop()
        await reg.stop()
        return ids

    async def run():
        ids_tp1 = await run_swarm(1)
        ids_tp2 = await run_swarm(2)
        np.testing.assert_array_equal(ids_tp1, ids_tp2)

    asyncio.run(run())
