"""LoRA adapter merging: served logits must match an HF model whose weights
were merged in torch (port of /root/reference/tests/test_peft.py intent)."""

import asyncio
import json

import numpy as np
import torch

import jax.numpy as jnp


def test_lora_merge_matches_torch(tmp_path):
    """Merge-at-load path (adapter_dirs): served logits must match an HF
    model whose weights were merged in torch."""
    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    hf, base = _tiny_llama(tmp_path)
    adir, merged = _write_adapter(tmp_path, hf, "adapter", ("q_proj", "v_proj"))

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        server = BlockServer(
            model_uid="m", start=0, end=2, model_dir=base,
            registry=RegistryClient("127.0.0.1", reg.port),
            compute_dtype=jnp.float32, num_pages=32, page_size=4,
            adapter_dirs=[adir],
        )
        await server.start()
        model = DistributedModelForCausalLM.from_pretrained(
            base, RegistryClient("127.0.0.1", reg.port), model_uid="m"
        )
        input_ids = np.arange(8)[None, :]
        async with model.inference_session(16, 1) as sess:
            out = await sess.step(model.embed(input_ids))
        logits = model.logits(out)
        with torch.no_grad():
            ref = merged(torch.tensor(input_ids)).logits.numpy()
        np.testing.assert_allclose(logits, ref, atol=2e-3, rtol=2e-3)
        await server.stop()
        await reg.stop()

    asyncio.run(run())


def _tiny_llama(tmp_path, seed=0):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=2, vocab_size=128,
        rms_norm_eps=1e-5, tie_word_embeddings=False,
    )
    torch.manual_seed(seed)
    hf = LlamaForCausalLM(config).eval().to(torch.float32)
    base = str(tmp_path / "base")
    hf.save_pretrained(base, safe_serialization=True)
    return hf, base


def _write_adapter(tmp_path, hf, name, targets, r=4, alpha=8.0, seed=1):
    """Random PEFT adapter over `targets`; returns (dir, merged hf copy)."""
    import copy

    from safetensors.torch import save_file

    adapter = tmp_path / name
    adapter.mkdir()
    merged = copy.deepcopy(hf)
    tensors = {}
    torch.manual_seed(seed)
    for i, layer in enumerate(merged.model.layers):
        for proj in targets:
            mod = (
                getattr(layer.self_attn, proj)
                if hasattr(layer.self_attn, proj)
                else getattr(layer.mlp, proj)
            )
            prefix = "self_attn" if hasattr(layer.self_attn, proj) else "mlp"
            a = torch.randn(r, mod.weight.shape[1]) * 0.1
            b = torch.randn(mod.weight.shape[0], r) * 0.1
            key = f"base_model.model.model.layers.{i}.{prefix}.{proj}"
            tensors[f"{key}.lora_A.weight"] = a
            tensors[f"{key}.lora_B.weight"] = b
            with torch.no_grad():
                mod.weight += (alpha / r) * (b @ a)
    save_file(tensors, str(adapter / "adapter_model.safetensors"))
    (adapter / "adapter_config.json").write_text(
        json.dumps({"r": r, "lora_alpha": alpha, "peft_type": "LORA"})
    )
    return str(adapter), merged


def test_per_request_adapter_switching(tmp_path):
    """One server, UNMERGED base: a session that names the adapter gets the
    tuned logits, a plain session gets the base logits (reference
    utils/peft.py using_adapter + --adapters serving)."""
    from bloombee_tpu.client.config import ClientConfig
    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    hf, base = _tiny_llama(tmp_path)
    adir, merged = _write_adapter(
        tmp_path, hf, "tuned", ("q_proj", "v_proj", "gate_proj", "down_proj")
    )

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        server = BlockServer(
            model_uid="m", start=0, end=2, model_dir=base,
            registry=RegistryClient("127.0.0.1", reg.port),
            compute_dtype=jnp.float32, num_pages=32, page_size=4,
            adapters={"tuned": adir},
        )
        await server.start()
        input_ids = np.arange(8)[None, :]
        results = {}
        for label, cfg in (
            ("tuned", ClientConfig(active_adapter="tuned")),
            ("base", None),
        ):
            model = DistributedModelForCausalLM.from_pretrained(
                base, RegistryClient("127.0.0.1", reg.port), model_uid="m",
                config=cfg,
            )
            async with model.inference_session(16, 1) as sess:
                out = await sess.step(model.embed(input_ids))
            results[label] = model.logits(out)
        await server.stop()
        await reg.stop()
        return results

    results = asyncio.run(run())
    input_ids = np.arange(8)[None, :]
    with torch.no_grad():
        ref_base = hf(torch.tensor(input_ids)).logits.numpy()
        ref_tuned = merged(torch.tensor(input_ids)).logits.numpy()
    np.testing.assert_allclose(
        results["base"], ref_base, atol=2e-3, rtol=2e-3
    )
    np.testing.assert_allclose(
        results["tuned"], ref_tuned, atol=2e-3, rtol=2e-3
    )
    # the adapter must actually change the logits for the switch to mean
    # anything
    assert np.abs(ref_tuned - ref_base).max() > 1e-2


def test_adapter_routing_filter(tmp_path):
    """active_adapter routes only to servers announcing that adapter
    (reference sequence_manager's adapter-aware span filtering)."""
    from bloombee_tpu.client.sequence_manager import RemoteSequenceManager
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    hf, base = _tiny_llama(tmp_path)
    adir, _ = _write_adapter(tmp_path, hf, "tuned", ("q_proj", "v_proj"))

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        plain = BlockServer(
            model_uid="m", start=0, end=2, model_dir=base,
            registry=RegistryClient("127.0.0.1", reg.port),
            compute_dtype=jnp.float32, num_pages=16, page_size=4,
        )
        tuned = BlockServer(
            model_uid="m", start=0, end=2, model_dir=base,
            registry=RegistryClient("127.0.0.1", reg.port),
            compute_dtype=jnp.float32, num_pages=16, page_size=4,
            adapters={"tuned": adir},
        )
        await plain.start()
        await tuned.start()
        manager = RemoteSequenceManager(
            RegistryClient("127.0.0.1", reg.port), "m", 2,
            active_adapter="tuned",
        )
        await manager.update(force=True)
        routes = {
            manager.make_sequence()[0].peer_id for _ in range(8)
        }
        assert routes == {tuned.server_id}
        # without the filter both servers are candidates
        manager.active_adapter = None
        all_peers = {s.peer_id for s in manager._active_spans()}
        assert all_peers == {plain.server_id, tuned.server_id}
        await plain.stop()
        await tuned.stop()
        await reg.stop()

    asyncio.run(run())
