"""Rotary position embeddings (RoPE).

Replaces the reference's rotary helpers + CUDA-graphed rotary
(/root/reference/src/bloombee/flexgen_utils/pytorch_backend.py:59-110,
/root/reference/src/bloombee/models/llama/block.py:76-81). The CUDA-graph capture
role is played by `jax.jit`: the whole step is traced once and compiled.

Position ids are explicit everywhere (no module state) because the paged KV design
and tree speculative decoding both need arbitrary per-token positions
(reference: backend.py:944-1047 tree rotary position ids).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rotary_cos_sin(
    positions: jax.Array,  # [..., T] int32 absolute positions
    head_dim: int,
    theta: float = 10000.0,
    dtype: jnp.dtype = jnp.float32,
) -> tuple[jax.Array, jax.Array]:
    """cos/sin tables for the given absolute positions; fp32 math like HF."""
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    freqs = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., T, hd/2]
    emb = jnp.concatenate([freqs, freqs], axis=-1)  # [..., T, hd]
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def _rotate_half(x: jax.Array) -> jax.Array:
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rotary(
    q: jax.Array,  # [B, T, H, hd]
    k: jax.Array,  # [B, T, Hkv, hd]
    cos: jax.Array,  # [B, T, hd]
    sin: jax.Array,  # [B, T, hd]
) -> tuple[jax.Array, jax.Array]:
    """Apply RoPE to q and k (head axis broadcast)."""
    cos = cos[:, :, None, :].astype(q.dtype)
    sin = sin[:, :, None, :].astype(q.dtype)
    q_out = q * cos + _rotate_half(q) * sin
    k_out = k * cos.astype(k.dtype) + _rotate_half(k) * sin.astype(k.dtype)
    return q_out, k_out
