"""Gemma-4 family: heterogeneous per-layer attention geometry.

Reference: /root/reference/src/bloombee/models/gemma4/ + server/backend.py
:243-306. Key traits beyond the gemma2/3 lineage:
- `layer_types` alternates sliding/full attention; FULL layers use
  `global_head_dim` (e.g. 512 vs 256) and `num_global_key_value_heads`,
  so per-layer KV slabs have per-layer shapes (runtime/hetero.py).
- Full layers alias V to K (`attention_k_eq_v`): one shared K=V projection,
  no v_proj weight.
- Sliding layers rope with `rope_local_base_freq`; full layers with
  `rope_theta`.
- Checkpoints are saved by the multimodal wrapper, so every weight lives
  under `model.language_model.*` (reference gemma4/config.py block_prefix).

Gemma norms store zero-centered weights; converted to (1 + w) at load.
"""

from __future__ import annotations

import math
from typing import Any

from bloombee_tpu.models.auto import Family, register_family
from bloombee_tpu.models.checkpoint import read_tensor as _t
from bloombee_tpu.models.spec import ModelSpec

_PREFIX = "model.language_model"

_NORMS = (
    "input_layernorm",
    "post_attention_layernorm",
    "pre_feedforward_layernorm",
    "post_feedforward_layernorm",
)


def gemma4_spec_from_hf(config: Any) -> ModelSpec:
    # published checkpoints are multimodal bundles: the text tower's
    # geometry nests under text_config (reference gemma4/config.py
    # documents exactly this trap)
    text = getattr(config, "text_config", None)
    if text is not None:
        from types import SimpleNamespace

        config = (
            SimpleNamespace(**text) if isinstance(text, dict) else text
        )
    layer_types = getattr(config, "layer_types", None)
    if layer_types:
        pattern = tuple(
            "sliding" if "sliding" in t else "full" for t in layer_types
        )
    else:
        pattern = ("sliding", "full")
    qpas = getattr(config, "query_pre_attn_scalar", None)
    return ModelSpec(
        family="gemma4",
        hidden_size=config.hidden_size,
        intermediate_size=config.intermediate_size,
        num_attention_heads=config.num_attention_heads,
        num_key_value_heads=config.num_key_value_heads,
        head_dim=config.head_dim,
        num_hidden_layers=config.num_hidden_layers,
        vocab_size=config.vocab_size,
        rms_norm_eps=getattr(config, "rms_norm_eps", 1e-6),
        rope_theta=getattr(config, "rope_theta", 1_000_000.0),
        rope_local_theta=getattr(config, "rope_local_base_freq", 10_000.0),
        tie_word_embeddings=True,
        layer_types=pattern,
        sliding_window=getattr(config, "sliding_window", 1024),
        attention_multiplier=qpas and qpas**-0.5,
        embedding_multiplier=math.sqrt(config.hidden_size),
        mlp_type="gelu_tanh_gated",
        sandwich_norms=True,
        qk_norm=bool(getattr(config, "use_qk_norm", True)),
        global_head_dim=getattr(config, "global_head_dim", 0) or 0,
        num_global_key_value_heads=(
            getattr(config, "num_global_key_value_heads", 0) or 0
        ),
        k_eq_v_full=bool(getattr(config, "attention_k_eq_v", False)),
    )


def _load_block(reader, layer_idx: int, dtype=None, spec=None) -> dict:
    p = f"{_PREFIX}.layers.{layer_idx}"
    params = {}
    for ln in _NORMS:
        params[ln] = 1.0 + _t(reader, f"{p}.{ln}.weight", dtype)
    projs = ["q", "k", "o"]
    # sliding layers have a real v_proj; full layers alias V to K when
    # attention_k_eq_v (no v weight exists in the checkpoint)
    if reader.has(f"{p}.self_attn.v_proj.weight"):
        projs.append("v")
    for proj in projs:
        params[f"{proj}_proj"] = _t(
            reader, f"{p}.self_attn.{proj}_proj.weight", dtype
        ).T
    for name, key in (("q_norm", "q_norm"), ("k_norm", "k_norm")):
        full = f"{p}.self_attn.{key}.weight"
        if reader.has(full):
            params[name] = 1.0 + _t(reader, full, dtype)
    for proj in ("gate", "up", "down"):
        params[f"{proj}_proj"] = _t(
            reader, f"{p}.mlp.{proj}_proj.weight", dtype
        ).T
    return params


def _load_client(reader, dtype=None) -> dict:
    embed = _t(reader, f"{_PREFIX}.embed_tokens.weight", dtype)
    return {
        "embed": embed,
        "norm": 1.0 + _t(reader, f"{_PREFIX}.norm.weight", dtype),
        "lm_head": embed.T,
    }


register_family(
    Family(
        "gemma4", gemma4_spec_from_hf, loader=_load_block,
        client_loader=_load_client,
    )
)
