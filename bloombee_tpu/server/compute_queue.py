"""Prioritized single-worker compute queue with decode-step coalescing.

Role of the reference's PrioritizedTaskPool + hivemind Runtime
(/root/reference/src/bloombee/server/task_pool.py:30-236, task_prioritizer.py):
all device work funnels through one worker so steps execute one at a time
(the TPU is a serial resource), inference outranks forward/backward, and the
asyncio event loop never blocks on device compute.

On top of that, the queue implements the gathering half of Orca-style
continuous batching (Yu et al., OSDI'22): callers may submit *batchable*
tasks (`submit_group`) carrying a compatibility key. When the worker pops
one, it drains every already-queued task with the same key — plus any that
arrive within the `BBTPU_BATCH_WINDOW_MS` gather window — and hands all
their payloads to ONE `run_group` call on the compute thread, scattering
the per-member outcomes back to each caller's future. With N concurrent
decode sessions this turns N serialized span dispatches per round into one.
"""

from __future__ import annotations

import asyncio
import collections
import dataclasses
import functools
import itertools
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Hashable

from bloombee_tpu.utils import clock, env, jitwatch

PRIORITY_INFERENCE = 0.0  # reference DummyTaskPrioritizer: inference=1.0
# resumable prefill chunks re-enter the queue BETWEEN decode steps and
# training work: queued decode(-group) steps preempt the next chunk
# (Sarathi-Serve's stall-free batching), but a chunk still outranks
# forward/backward/warmup
PRIORITY_PREFILL_CHUNK = 0.5
PRIORITY_TRAINING = 1.0  # beats forward/backward=2.0 — same ordering

env.declare(
    "BBTPU_BATCH_WINDOW_MS", float, 0.0,
    "continuous-batching gather window: after popping a batchable decode "
    "step the worker waits this long for more same-key steps before "
    "dispatching (0 = coalesce only steps already queued, no added latency)",
)
env.declare(
    "BBTPU_CHUNK_AGE_S", float, 2.0,
    "chunked-prefill aging horizon: a chunk stream's priority decays "
    "linearly from PRIORITY_PREFILL_CHUNK to decode priority over this "
    "many seconds, so a constant decode load can delay a prefill but "
    "never starve it forever",
)


def aged_chunk_priority(
    stream_started_at: float, now: float | None = None
) -> float:
    """Priority for the next chunk of a prefill stream that began at
    `stream_started_at` (clock.monotonic()). Fresh streams yield to queued
    decode steps; once the stream has aged past BBTPU_CHUNK_AGE_S its
    chunks compete at decode priority (FIFO by submission order), bounding
    worst-case prefill delay under sustained decode pressure."""
    horizon = max(1e-9, float(env.get("BBTPU_CHUNK_AGE_S")))
    if now is None:
        now = clock.monotonic()
    frac = min(1.0, max(0.0, (now - stream_started_at) / horizon))
    return PRIORITY_PREFILL_CHUNK * (1.0 - frac)

# wait-time samples kept for the p50/p95 queue-wait estimate in rpc_info;
# bounded so a long-lived server's stats track recent load, not its lifetime
_WAIT_SAMPLES = 512


class DeadlineExpired(RuntimeError):
    """The task's client-supplied deadline passed while it sat in the
    queue: the client has already given up, so running it would only
    delay work somebody still wants."""


@dataclasses.dataclass
class _Task:
    """A plain (non-batchable) unit of compute: one zero-arg callable."""

    fn: Callable[[], Any]
    fut: asyncio.Future
    deadline: float | None  # clock.monotonic() cutoff, checked at pop time
    enqueued_at: float
    task_class: str | None = None  # "prefill"/"decode" wait-stat bucket


@dataclasses.dataclass
class _GroupTask:
    """One member of a batchable group. Tasks whose `key` compares equal
    may be executed by a single `run_group([payload, ...])` call; the
    callable must return one outcome per payload, in order (a returned
    Exception instance fails just that member's future)."""

    key: Hashable
    payload: Any
    run_group: Callable[[list], list]
    fut: asyncio.Future
    deadline: float | None
    enqueued_at: float
    task_class: str | None = None


class ComputeQueue:
    def __init__(
        self,
        max_group: int = 8,
        compat: Callable[[list, "_GroupTask"], bool] | None = None,
        group_hint: Callable[[list], int] | None = None,
        executor: ThreadPoolExecutor | None = None,
    ) -> None:
        self._queue: asyncio.PriorityQueue = asyncio.PriorityQueue()
        self._seq = itertools.count()
        # injectable for simulation (a counting executor lets a
        # discrete-event driver see exactly when compute is in flight);
        # default is the same single worker thread as always
        self._thread = executor or ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="compute"
        )
        self._worker_task: asyncio.Task | None = None
        self.max_group = max(1, int(max_group))
        # group-membership predicate: compat(members_so_far, candidate).
        # None = exact key equality, the classic same-shape decode
        # coalescing. A custom predicate lets the server admit
        # heterogeneous members into one dispatch (mixed decode+prefill
        # batching) while still refusing cross-adapter/dtype mixes.
        self.compat = compat
        # upper bound on how many members a gather could EVER collect,
        # given the members gathered so far (the server derives it from
        # its open-session count, kind-aware: a gather that can only
        # admit tree rows is bounded by the sessions currently
        # speculating, not every open session). When the group reaches
        # it, the gather window is pure dead time and the dispatch goes
        # out immediately. None = no bound known; the window always runs
        # to its deadline.
        self.group_hint = group_hint
        # samples are (picked_up_at_monotonic, wait_s) so windowed readers
        # (admission control, load adverts) can discard old load regimes
        # instead of averaging over the whole 512-sample tail
        self._waits: collections.deque = collections.deque(
            maxlen=_WAIT_SAMPLES
        )
        # per-class windows ("prefill"/"decode"): chunked prefill is only
        # stall-free if DECODE queue-wait stays bounded while chunks flow —
        # a blended percentile would hide exactly that signal
        self._class_waits: dict[str, collections.deque] = {}
        # last time the worker popped anything: while the queue is non-empty
        # and nothing pops, (now - _last_pop_at) lower-bounds the wait the
        # NEXT pop will report — the only live signal during a jam, when the
        # sample deques go quiet precisely because nothing completes
        self._last_pop_at: float = clock.monotonic()

    def start(self) -> None:
        self._worker_task = asyncio.create_task(self._worker())

    async def stop(self) -> None:
        self.kill()

    def kill(self) -> None:
        """Synchronous stop — also the crash-fault path, which cannot
        await anything graceful."""
        if self._worker_task is not None:
            self._worker_task.cancel()
        # fail everything still queued: a future that never resolves leaves
        # its awaiter (a session handler) hanging forever on server shutdown
        while True:
            try:
                _, _, task = self._queue.get_nowait()
            except asyncio.QueueEmpty:
                break
            if not task.fut.done():
                task.fut.cancel()
        self._thread.shutdown(wait=False, cancel_futures=True)

    @staticmethod
    def _percentiles(samples) -> dict:
        xs = sorted(w for _, w in samples) if samples else []
        if not xs:
            return {"p50": 0.0, "p95": 0.0}

        def pct(p: float) -> float:
            return xs[min(len(xs) - 1, round(p * (len(xs) - 1)))] * 1000.0

        return {"p50": pct(0.50), "p95": pct(0.95)}

    def wait_stats_ms(self) -> dict:
        """p50/p95 of recent queue-wait times (submit -> worker pickup), in
        milliseconds, overall plus per task class ("prefill"/"decode").
        Rough percentile over a bounded sample window — an operator signal
        for "is the compute queue backed up", not a benchmark."""
        out = self._percentiles(self._waits)
        for cls in ("prefill", "decode"):
            out[cls] = self._percentiles(self._class_waits.get(cls))
        return out

    def depth(self) -> int:
        """Tasks currently waiting for the worker (excludes the one on the
        compute thread right now)."""
        return self._queue.qsize()

    def current_delay_ms(
        self, window_s: float = 5.0, cls: str | None = None
    ) -> float:
        """Best live estimate of the queueing delay a task submitted NOW
        would see, in ms: max of the windowed p95 of recent waits and the
        age of the current jam (time since the last pop, if anything is
        queued). The second term is what makes this usable for admission
        control — during a stall no samples arrive, so a percentile alone
        reads zero exactly when the queue is at its worst."""
        now = clock.monotonic()
        src = self._class_waits.get(cls) if cls is not None else self._waits
        recent = [e for e in (src or ()) if now - e[0] <= window_s]
        p95 = self._percentiles(recent)["p95"]
        stall_ms = 0.0
        if self._queue.qsize() > 0:
            stall_ms = (now - self._last_pop_at) * 1000.0
        return max(p95, stall_ms)

    async def submit(
        self,
        priority: float,
        fn: Callable[..., Any],
        *args,
        deadline: float | None = None,  # clock.monotonic() cutoff: the task
        # is abandoned (DeadlineExpired) if the worker reaches it later
        task_class: str | None = None,  # wait-stat bucket, not passed to fn
        **kwargs,
    ) -> Any:
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        task = _Task(
            # bind fn/args NOW: a late-binding closure would capture the
            # worker loop's variables, not this submission's
            fn=functools.partial(fn, *args, **kwargs),
            fut=fut,
            deadline=deadline,
            enqueued_at=clock.monotonic(),
            task_class=task_class,
        )
        self._queue.put_nowait((priority, next(self._seq), task))
        return await fut

    async def submit_group(
        self,
        priority: float,
        key: Hashable,
        payload: Any,
        run_group: Callable[[list], list],
        *,
        deadline: float | None = None,
        task_class: str | None = None,
    ) -> Any:
        """Submit one member of a batchable group. All queued members whose
        `key` equals this one's (arriving before the worker dispatches, or
        within the gather window) execute as ONE `run_group` call; this
        caller gets back its own member's outcome. Each member keeps its
        own deadline — an expired member is dropped from the group with
        DeadlineExpired, the rest still run."""
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        task = _GroupTask(
            key=key,
            payload=payload,
            run_group=run_group,
            fut=fut,
            deadline=deadline,
            enqueued_at=clock.monotonic(),
            task_class=task_class,
        )
        self._queue.put_nowait((priority, next(self._seq), task))
        return await fut

    async def _worker(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            _, _, task = await self._queue.get()
            self._last_pop_at = clock.monotonic()
            try:
                if isinstance(task, _GroupTask):
                    await self._run_group(loop, task)
                else:
                    await self._run_one(loop, task)
            except asyncio.CancelledError:
                # stop() cancelled us mid-task: the popped task is no
                # longer in the queue, so stop()'s drain can't see it —
                # resolve its future(s) here or the awaiter hangs
                if not task.fut.done():
                    task.fut.cancel()
                raise

    async def _run_one(self, loop, task: _Task) -> None:
        if task.fut.cancelled():
            return
        self._note_wait(task)
        if self._expired(task):
            return
        try:
            # hot_wrap: while this runs on the compute thread any host
            # sync counts against jitwatch's hot-path budget (the queue
            # serializes device work, so a sync here convoys every session)
            result = await loop.run_in_executor(
                self._thread, jitwatch.hot_wrap(task.fn)
            )
            if not task.fut.done():
                task.fut.set_result(result)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            if not task.fut.done():
                task.fut.set_exception(e)

    async def _run_group(self, loop, first: _GroupTask) -> None:
        members = [first]
        self._gather(members, self.max_group - len(members))
        window_s = float(env.get("BBTPU_BATCH_WINDOW_MS")) / 1000.0
        if window_s > 0 and len(members) < self.max_group:
            # hold the device for one short window: steps of other sessions
            # in the same decode round are typically in flight right now.
            # Sliced, so a member landing mid-window joins at the next
            # slice and the hold ends the moment the group provably cannot
            # grow — group_hint(members) bounds the possible member
            # count for THIS gather's kinds, so a full house dispatches
            # at once instead of sleeping out the window (a solo session
            # skips the hold entirely).
            deadline = clock.monotonic() + window_s
            while len(members) < self.max_group:
                if (
                    self.group_hint is not None
                    and len(members) >= self.group_hint(members)
                ):
                    break
                remaining = deadline - clock.monotonic()
                if remaining <= 0:
                    break
                await clock.async_sleep(min(0.05, remaining))
                self._gather(members, self.max_group - len(members))
        try:
            live = []
            for m in members:
                if m.fut.cancelled():
                    continue
                self._note_wait(m)
                if self._expired(m):
                    continue
                live.append(m)
            if not live:
                return
            outcomes = await loop.run_in_executor(
                self._thread,
                jitwatch.hot_wrap(functools.partial(
                    first.run_group, [m.payload for m in live]
                )),
            )
            if len(outcomes) != len(live):
                raise RuntimeError(
                    f"run_group returned {len(outcomes)} outcomes for "
                    f"{len(live)} members"
                )
        except asyncio.CancelledError:
            for m in members:
                if not m.fut.done():
                    m.fut.cancel()
            raise
        except Exception as e:
            # a failure of the group call itself (not a per-member outcome)
            # fails every member; callers own their per-session recovery
            for m in live:
                if not m.fut.done():
                    m.fut.set_exception(e)
            return
        for m, out in zip(live, outcomes):
            if m.fut.done():
                continue
            if isinstance(out, BaseException):
                m.fut.set_exception(out)
            else:
                m.fut.set_result(out)

    def _match(self, members: list[_GroupTask], task: _GroupTask) -> bool:
        """Can `task` join the group gathered so far? Default: exact key
        equality with the first member. A server-supplied `compat`
        predicate sees the whole group, so it can enforce structural rules
        (e.g. at most one prefill chunk per mixed dispatch)."""
        if self.compat is not None:
            return bool(self.compat(members, task))
        return task.key == members[0].key

    def _gather(self, members: list[_GroupTask], limit: int) -> None:
        """Pull up to `limit` queued group tasks compatible with the group
        gathered so far, appending them to `members` in place (each
        admission may widen what the next candidate is matched against);
        everything else goes back with its original (priority, seq) so
        ordering is untouched."""
        taken = 0
        keep: list = []
        while True:
            try:
                entry = self._queue.get_nowait()
            except asyncio.QueueEmpty:
                break
            task = entry[2]
            if (
                taken < limit
                and isinstance(task, _GroupTask)
                and not task.fut.cancelled()
                and self._match(members, task)
            ):
                members.append(task)
                taken += 1
            else:
                keep.append(entry)
        for entry in keep:
            self._queue.put_nowait(entry)

    def _note_wait(self, task) -> None:
        now = clock.monotonic()
        wait = now - task.enqueued_at
        self._waits.append((now, wait))
        if task.task_class is not None:
            dq = self._class_waits.get(task.task_class)
            if dq is None:
                dq = self._class_waits[task.task_class] = collections.deque(
                    maxlen=_WAIT_SAMPLES
                )
            dq.append((now, wait))

    def _expired(self, task) -> bool:
        # checked at execution time, not submit time: a deep queue behind
        # a slow step is exactly when expiry happens
        if task.deadline is not None and clock.monotonic() > task.deadline:
            if not task.fut.done():
                task.fut.set_exception(
                    DeadlineExpired(
                        "deadline passed while queued; dropping compute"
                    )
                )
            return True
        return False
