"""RemoteSequenceManager: the client routing brain.

Port of /root/reference/src/bloombee/client/routing/sequence_manager.py:66-599:
keeps a fresh view of which server spans cover which blocks, builds a chain of
spans covering [0, num_blocks) by shortest-path search ("min_latency": Dijkstra
over block boundaries with per-span compute cost + per-hop network cost,
reference `_build_inference_graph` :235-296), or length-weighted random choice
("max_throughput", :320-342), and bans failing peers with backoff (:412-429).
"""

from __future__ import annotations

import heapq
import logging
import random
import time

from bloombee_tpu.swarm.data import RemoteSpanInfo
from bloombee_tpu.swarm.spans import compute_spans

logger = logging.getLogger(__name__)

DEFAULT_HOP_COST_S = 0.01  # client<->server / server->server RTT estimate
CACHE_MISSING_PENALTY_S = 10.0  # reference: +10s if cache won't fit


class MissingBlocksError(RuntimeError):
    def __init__(self, blocks):
        super().__init__(
            f"no online server covers block(s) {blocks}; swarm incomplete"
        )
        self.blocks = blocks


class RemoteSequenceManager:
    def __init__(
        self,
        registry,
        model_uid: str,
        num_blocks: int,
        update_period: float = 5.0,
        ban_timeout: float = 15.0,
        rng: random.Random | None = None,
    ):
        self.registry = registry
        self.model_uid = model_uid
        self.num_blocks = num_blocks
        self.update_period = update_period
        self.ban_timeout = ban_timeout
        self.spans: dict[str, RemoteSpanInfo] = {}
        self._banned_until: dict[str, float] = {}
        self._last_update = 0.0
        self._rng = rng or random.Random()

    # ---------------------------------------------------------------- updates
    async def update(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_update < self.update_period:
            return
        infos = await self.registry.get_module_infos(
            self.model_uid, range(self.num_blocks)
        )
        self.spans = compute_spans(infos)
        self._last_update = now

    def ban_peer(self, peer_id: str) -> None:
        """reference: on_request_failure + ban_timeout backoff."""
        self._banned_until[peer_id] = time.monotonic() + self.ban_timeout
        logger.info("banned peer %s for %.0fs", peer_id, self.ban_timeout)

    def _active_spans(self) -> list[RemoteSpanInfo]:
        now = time.monotonic()
        return [
            s
            for s in self.spans.values()
            if self._banned_until.get(s.peer_id, 0.0) <= now
        ]

    # ---------------------------------------------------------------- routing
    def make_sequence(
        self,
        start: int = 0,
        end: int | None = None,
        mode: str = "min_latency",
        cache_tokens_needed: int | None = None,
    ) -> list[RemoteSpanInfo]:
        end = self.num_blocks if end is None else end
        spans = self._active_spans()
        if mode == "max_throughput":
            return self._random_route(spans, start, end)
        return self._dijkstra_route(spans, start, end, cache_tokens_needed)

    def _span_cost(
        self, span: RemoteSpanInfo, blocks: int, cache_tokens_needed
    ) -> float:
        rps = span.server_info.inference_rps or span.server_info.throughput or 1.0
        cost = DEFAULT_HOP_COST_S + blocks / max(rps, 1e-6)
        left = span.server_info.cache_tokens_left
        if (
            cache_tokens_needed is not None
            and left is not None
            and left < cache_tokens_needed
        ):
            cost += CACHE_MISSING_PENALTY_S
        return cost

    def _dijkstra_route(
        self, spans, start: int, end: int, cache_tokens_needed
    ) -> list[RemoteSpanInfo]:
        # nodes = block boundaries; a span [s, e) contributes edges b -> e for
        # every b in [s, e) (a server can serve a suffix of its span)
        edges: dict[int, list[tuple[int, float, RemoteSpanInfo]]] = {}
        for span in spans:
            s, e = max(span.start, start), min(span.end, end)
            for b in range(s, e):
                edges.setdefault(b, []).append(
                    (e, self._span_cost(span, e - b, cache_tokens_needed), span)
                )
        dist = {start: 0.0}
        prev: dict[int, tuple[int, RemoteSpanInfo]] = {}
        heap = [(0.0, start)]
        while heap:
            d, node = heapq.heappop(heap)
            if node == end:
                break
            if d > dist.get(node, float("inf")):
                continue
            for nxt, cost, span in edges.get(node, []):
                nd = d + cost
                if nd < dist.get(nxt, float("inf")):
                    dist[nxt] = nd
                    prev[nxt] = (node, span)
                    heapq.heappush(heap, (nd, nxt))
        if end not in prev and start != end:
            covered = {b for s in spans for b in range(s.start, s.end)}
            missing = [b for b in range(start, end) if b not in covered]
            raise MissingBlocksError(missing or list(range(start, end)))
        # walk back
        route: list[RemoteSpanInfo] = []
        node = end
        while node != start:
            pnode, span = prev[node]
            route.append(
                RemoteSpanInfo(span.peer_id, pnode, node, span.server_info)
            )
            node = pnode
        return list(reversed(route))

    def _random_route(self, spans, start: int, end: int):
        """Length-weighted random chaining (reference :320-342)."""
        route = []
        cur = start
        while cur < end:
            options = [s for s in spans if s.start <= cur < s.end]
            if not options:
                raise MissingBlocksError([cur])
            weights = [s.end - cur for s in options]
            chosen = self._rng.choices(options, weights=weights)[0]
            stop = min(chosen.end, end)
            route.append(
                RemoteSpanInfo(chosen.peer_id, cur, stop, chosen.server_info)
            )
            cur = stop
        return route
