"""Tensor (de)serialization with optional lossless compression.

Capability port of the reference's lossless transport wrapper
(/root/reference/src/bloombee/utils/lossless_transport.py): every tensor on
the wire may be wrapped in a losslessly-compressed envelope with
- codec choice (zstd default, zlib fallback),
- a byte-split layout for 2-byte dtypes (bf16/fp16): the two byte planes of
  the little-endian pairs are separated before compression, which compresses
  far better because the exponent-byte plane is highly redundant (reference
  `byte_split` layout),
- min-size and min-gain gates so tiny or incompressible payloads ship raw
  (reference: 48 KiB min size, 2 KiB min gain).

bfloat16 is handled via ml_dtypes so client/server never need torch.
"""

from __future__ import annotations

import dataclasses
import zlib

import ml_dtypes
import numpy as np

try:
    import zstandard as _zstd

    _ZSTD_C = _zstd.ZstdCompressor(level=3)
    _ZSTD_D = _zstd.ZstdDecompressor()
except Exception:  # pragma: no cover - zstandard is in the base image
    _zstd = None

from bloombee_tpu.utils import env as _env
from bloombee_tpu.utils import lockwatch as _lockwatch

import time as _time


class _TransportStats:
    """Per-process transport profiling (the role of the reference
    lossless_transport profiling channels): per direction, tensor count,
    raw vs wire bytes, codec time. Snapshot via transport_stats(); the
    `transport` log channel (BBTPU_LOG_CHANNELS=transport) logs one line
    per call site."""

    def __init__(self):
        self._lock = _lockwatch.thread_lock("wire.codec_stats")
        self.reset()

    def reset(self):
        with self._lock:
            self._d = {
                "tx": {"n": 0, "raw_bytes": 0, "wire_bytes": 0, "s": 0.0,
                       "compressed": 0},
                "rx": {"n": 0, "raw_bytes": 0, "wire_bytes": 0, "s": 0.0,
                       "compressed": 0},
            }

    def record(self, direction, raw_len, wire_len, seconds, compressed):
        with self._lock:
            d = self._d[direction]
            d["n"] += 1
            d["raw_bytes"] += raw_len
            d["wire_bytes"] += wire_len
            d["s"] += seconds
            d["compressed"] += bool(compressed)

    def snapshot(self) -> dict:
        with self._lock:
            out = {}
            for k, d in self._d.items():
                out[k] = dict(d)
                out[k]["ratio"] = (
                    d["wire_bytes"] / d["raw_bytes"] if d["raw_bytes"] else 1.0
                )
            return out


_STATS = _TransportStats()


def transport_stats() -> dict:
    """Snapshot of this process's wire-codec counters (tx/rx tensors, raw vs
    wire bytes, compression ratio, codec seconds)."""
    return _STATS.snapshot()


def reset_transport_stats() -> None:
    _STATS.reset()


# defaults; overridable per process via the env switches declared below
MIN_COMPRESS_BYTES = 48 * 1024
MIN_GAIN_BYTES = 2 * 1024

_env.declare(
    "BBTPU_MIN_COMPRESS_BYTES", int, MIN_COMPRESS_BYTES,
    "payloads below this ship raw (reference lossless_transport 48 KiB gate)",
)
_env.declare(
    "BBTPU_MIN_COMPRESS_GAIN", int, MIN_GAIN_BYTES,
    "compression kept only if it saves at least this many bytes",
)
_env.declare(
    "BBTPU_WIRE_COMPRESSION", bool, True,
    "losslessly compress large wire tensors (zstd byte-split)",
)
_env.declare(
    "BBTPU_WIRE_CODECS", str, "",
    "comma-separated allowlist restricting which codecs this process "
    "advertises and uses on the wire (negotiation, wire/rpc.py); empty "
    "means every built-in codec, 'raw' disables compression entirely",
)


# --- codec registry + negotiation support -----------------------------------
# name -> (compress, decompress). "raw" is implicit and always supported.
_CODECS: dict[str, tuple] = {"zlib": (lambda b: zlib.compress(b, 6),
                                      zlib.decompress)}
if _zstd is not None:
    _CODECS["zstd"] = (_ZSTD_C.compress, _ZSTD_D.decompress)

# preference order when several codecs are permitted for a payload
_PREFERENCE: list[str] = ["zstd", "zlib"]

# The pre-negotiation wire contract: every historical peer decodes exactly
# these. A peer that never advertises (older build) is assumed to speak
# them and nothing more, so mixed swarms degrade byte-for-byte to the
# legacy codec choice instead of flag-daying.
LEGACY_WIRE_CODECS = frozenset({"raw", "zstd", "zlib"})


def register_codec(name: str, compress, decompress, *,
                   prefer: bool = False) -> None:
    """Plug in a codec (e.g. a dict-trained zstd for activation planes).
    Registered codecs are only chosen toward peers that advertise them in
    the connection handshake (wire/rpc.py negotiation) — an un-upgraded
    swarm never sees the new name on the wire."""
    _CODECS[name] = (compress, decompress)
    if name not in _PREFERENCE:
        if prefer:
            _PREFERENCE.insert(0, name)
        else:
            _PREFERENCE.append(name)


def unregister_codec(name: str) -> None:
    """Test hook: remove a codec registered by register_codec."""
    _CODECS.pop(name, None)
    if name in _PREFERENCE:
        _PREFERENCE.remove(name)


def supported_codecs() -> frozenset:
    """Codecs this process can encode/decode right now — what a connection
    advertises to its peer. BBTPU_WIRE_CODECS restricts the set ("raw" is
    always kept: it is the identity codec, not an option)."""
    names = {"raw", *_CODECS}
    allow = str(_env.get("BBTPU_WIRE_CODECS")).strip()
    if allow:
        keep = {c.strip() for c in allow.split(",") if c.strip()}
        names &= keep | {"raw"}
    return frozenset(names)

_DTYPES = {
    "f32": np.float32,
    "f16": np.float16,
    "bf16": ml_dtypes.bfloat16,
    "i32": np.int32,
    "i64": np.int64,
    "u8": np.uint8,
    "bool": np.bool_,
    "f64": np.float64,
}
_DTYPE_NAMES = {np.dtype(v): k for k, v in _DTYPES.items()}


def dtype_for_name(name: str, default=np.float32):
    """Resolve a wire dtype name ("bf16", "f32", ...) to a numpy dtype."""
    dt = _DTYPES.get(name)
    return np.dtype(dt) if dt is not None else np.dtype(default)


def name_for_dtype(dtype) -> str:
    """Wire name of a numpy dtype (the inverse of dtype_for_name)."""
    return _DTYPE_NAMES[np.dtype(dtype)]


@dataclasses.dataclass
class TensorMeta:
    dtype: str
    shape: tuple[int, ...]
    codec: str  # "raw" | "zstd" | "zlib"
    byte_split: bool

    def to_wire(self) -> dict:
        return {
            "d": self.dtype,
            "s": list(self.shape),
            "c": self.codec,
            "b": self.byte_split,
        }

    @classmethod
    def from_wire(cls, d: dict) -> "TensorMeta":
        # .get defaults so an older peer's lean meta (dtype+shape only)
        # never KeyErrors a newer server: absent codec means raw bytes
        return cls(d["d"], tuple(d["s"]), d.get("c", "raw"),
                   d.get("b", False))


def _compress(buf, codec: str) -> bytes:
    try:
        return _CODECS[codec][0](buf)
    except KeyError:
        raise ValueError(f"unknown codec {codec}") from None


def _decompress(buf, codec: str) -> bytes:
    try:
        return _CODECS[codec][1](buf)
    except KeyError:
        raise ValueError(f"unknown codec {codec}") from None


def serialize_tensor(
    arr: np.ndarray, compression: bool = True,
    allowed: frozenset | None = None,
) -> tuple[TensorMeta, bytes]:
    """Serialize one array; returns (meta, payload bytes).

    `allowed` is the negotiated codec set for the destination peer (see
    wire/rpc.py). None means the pre-negotiation contract
    (LEGACY_WIRE_CODECS), so un-negotiated callers keep the seed's exact
    codec choice byte-for-byte."""
    t0 = _time.perf_counter()
    arr = np.ascontiguousarray(arr)
    dtype = np.dtype(arr.dtype)
    if dtype not in _DTYPE_NAMES:
        raise TypeError(f"unsupported wire dtype {dtype}")
    raw = arr.tobytes()
    codec = "raw"
    byte_split = False
    payload = raw
    min_bytes = _env.get("BBTPU_MIN_COMPRESS_BYTES")
    min_gain = _env.get("BBTPU_MIN_COMPRESS_GAIN")
    if not _env.get("BBTPU_WIRE_COMPRESSION"):
        compression = False
    if allowed is None:
        allowed = LEGACY_WIRE_CODECS
    usable = [c for c in _PREFERENCE if c in _CODECS and c in allowed]
    if compression and usable and len(raw) >= min_bytes:
        candidate = raw
        if dtype.itemsize == 2:
            # byte-plane split: [b0 b1 b0 b1 ...] -> [b0 b0 ...][b1 b1 ...]
            candidate = _split_planes(raw)
            byte_split = True
        chosen = usable[0]
        compressed = _compress(candidate, chosen)
        if len(compressed) + min_gain <= len(raw):
            payload = compressed
            codec = chosen
        else:
            byte_split = False
    _STATS.record(
        "tx", len(raw), len(payload), _time.perf_counter() - t0,
        codec != "raw",
    )
    return TensorMeta(_DTYPE_NAMES[dtype], arr.shape, codec, byte_split), payload


def deserialize_tensor(meta: TensorMeta, payload, *,
                       writable: bool = False) -> np.ndarray:
    """Decode one payload (bytes or memoryview) into an ndarray.

    Raw-codec payloads come back as a READ-ONLY view over the receive
    buffer — no copy on the wire hot path. Pass writable=True only when
    the caller mutates the array in place; that is the one path that
    still pays the copy."""
    t0 = _time.perf_counter()
    dtype = np.dtype(_DTYPES[meta.dtype])
    if meta.codec == "raw":
        raw = payload
    else:
        raw = _decompress(payload, meta.codec)
        if meta.byte_split:
            raw = _merge_planes(raw)
    out = np.frombuffer(raw, dtype=dtype).reshape(meta.shape)
    if writable and not out.flags.writeable:
        out = out.copy()
    _STATS.record(
        "rx", len(raw), len(payload), _time.perf_counter() - t0,
        meta.codec != "raw",
    )
    return out


def _split_planes(raw: bytes) -> bytes:
    lib = _native_lib()
    n = len(raw) // 2
    if lib is not None:
        src = np.frombuffer(raw, dtype=np.uint8)
        dst = np.empty(2 * n, dtype=np.uint8)
        lib.byte_split_2(
            src.ctypes.data, dst.ctypes.data, n
        )
        return dst.tobytes()
    return np.frombuffer(raw, dtype=np.uint8).reshape(-1, 2).T.tobytes()


def _merge_planes(raw: bytes) -> bytes:
    lib = _native_lib()
    n = len(raw) // 2
    if lib is not None:
        src = np.frombuffer(raw, dtype=np.uint8)
        dst = np.empty(2 * n, dtype=np.uint8)
        lib.byte_merge_2(src.ctypes.data, dst.ctypes.data, n)
        return dst.tobytes()
    return np.frombuffer(raw, dtype=np.uint8).reshape(2, -1).T.tobytes()


def _native_lib():
    from bloombee_tpu.native import byte_split_lib

    return byte_split_lib()


def serialize_tensors(
    arrays: list[np.ndarray], compression: bool = True,
    allowed: frozenset | None = None,
) -> tuple[list[dict], list[bytes]]:
    metas, blobs = [], []
    for a in arrays:
        m, b = serialize_tensor(a, compression, allowed=allowed)
        metas.append(m.to_wire())
        blobs.append(b)
    return metas, blobs


def deserialize_tensors(metas: list[dict], blobs: list,
                        writable: bool = False) -> list[np.ndarray]:
    return [
        deserialize_tensor(TensorMeta.from_wire(m), b, writable=writable)
        for m, b in zip(metas, blobs)
    ]
