"""Test configuration.

Tests run on CPU with 8 virtual devices so multi-chip sharding (tp/dp/sp meshes)
is exercised without TPU hardware — mirrors the reference's tier-1 strategy of
pure-host unit tests (/root/reference: SURVEY.md section 4).

Env vars must be set before the first jax import.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The machine image's sitecustomize registers a TPU PJRT plugin at interpreter
# start and rewrites jax_platforms; override it back to CPU before any backend
# is initialized (config update is honored until first backend use).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    import jax

    return jax.random.PRNGKey(0)
