"""SimServer: a swarm node whose control plane is the production code.

What is REAL here: the ComputeQueue (priority scheduling, group
coalescing, gather windows, wait-percentile gauges), the
AdmissionController (fair-share shedding with retry-after hints), the
standby promotion/demotion state machine (PromotionLoopMixin — the exact
BlockServer code), measured rebalancing (block_selection.
rebalance_if_needed against this server's duck-typed surface), registry
leases (InProcessRegistry expiry is the failure detector), and the load
adverts every peer routes by. What is simulated: the matmul — a
cost-model ``clock.sleep`` on the compute thread — and process death.

Faults arrive via the production ``wire/faults.py`` schedule: every
decode dispatch on this server ticks ``FaultSchedule.due`` with this
server's (host, port) as the peer, so scenario scripts use the same
"crash at decode step N on port P" vocabulary chaos e2e tests use.
"""

from __future__ import annotations

import logging
import random
import types

from bloombee_tpu.server.admission import AdmissionController
from bloombee_tpu.server.block_selection import rebalance_if_needed
from bloombee_tpu.server.compute_queue import ComputeQueue
from bloombee_tpu.server.promotion import PromotionLoopMixin
from bloombee_tpu.swarm.data import ServerInfo, ServerState
from bloombee_tpu.utils import clock, ledger

logger = logging.getLogger(__name__)


class SimUnreachable(RuntimeError):
    """The peer is crashed or partitioned (wire-level failure)."""


class SimOverloaded(RuntimeError):
    """Admission shed: carries the server's retry-after hint."""

    def __init__(self, retry_after_ms: int):
        super().__init__(f"shed; retry after {retry_after_ms}ms")
        self.retry_after_ms = int(retry_after_ms)


class _PrefixStatsStub:
    """Promotion logs warm-page counts from manager.prefix_stats(); the
    sim has no KV arena, so the count is honestly zero."""

    def prefix_stats(self) -> dict:
        return {}


class SimServer(PromotionLoopMixin):
    def __init__(
        self,
        engine,
        registry,
        model_uid: str,
        server_id: str,
        start_block: int,
        end_block: int,
        num_model_blocks: int,
        cost,
        *,
        port: int,
        standby: bool = False,
        throughput: float = 1.0,
        announce_period: float = 2.0,
        lease_s: float = 6.0,
        admission: AdmissionController | None = None,
        promote_high_ms: float = 600.0,
        promote_low_ms: float = 150.0,
        promote_sustain_s: float = 4.0,
        promote_jitter_s: float = 1.0,
        drain_timeout: float = 20.0,
        rebalance_period: float = 0.0,  # 0 = rebalancing off
        chunk_tokens: int = 256,
        max_group: int = 8,
        cost_scale: float = 1.0,  # slow host: actual compute is this many
        # times the model's cost while the ADVERT still claims nominal
        # throughput — the mismatch only measured rebalancing can see
        rng=None,
        faults=None,  # wire/faults.py FaultSchedule, shared per scenario
    ):
        self.engine = engine
        self.registry = registry
        self.model_uid = model_uid
        self.server_id = server_id
        self.start_block = int(start_block)
        self.end_block = int(end_block)
        self.num_model_blocks = int(num_model_blocks)
        self.cost = cost
        self.host, self.port = "sim", int(port)
        self.throughput = float(throughput)
        self.announce_period = float(announce_period)
        self.lease_s = float(lease_s)
        self.chunk_tokens = int(chunk_tokens)
        self.cost_scale = float(cost_scale)
        self.faults = faults
        if faults is not None:
            faults.bind_crash(server_id, self.crash)

        # promotion-mixin host contract (see server/promotion.py docstring)
        self._standby = bool(standby)
        self._promoted = False
        self._draining = False
        self._sessions: dict[str, str] = {}
        self.promote_high_ms = float(promote_high_ms)
        self.promote_low_ms = float(promote_low_ms)
        self.promote_sustain_s = float(promote_sustain_s)
        self.promote_jitter_s = float(promote_jitter_s)
        self.drain_timeout = float(drain_timeout)
        self._promote_rng = rng or random.Random(
            int.from_bytes(server_id.encode(), "little") & 0xFFFF
        )
        self.promotions = 0
        self.demotions = 0
        self.promotions_yielded = 0
        self.demotions_aborted = 0
        self.manager = _PrefixStatsStub()

        # rebalance contract (block_selection.rebalance_if_needed)
        self.rebalance_period = float(rebalance_period)
        self.spec = types.SimpleNamespace(num_hidden_layers=num_model_blocks)
        self.rebalances_moved = 0
        self.rebalances_failed = 0
        self.rebalance_skipped_hysteresis = 0
        self.rebalance_last_move_at: float | None = None

        # real data plane control: queue + admission on env-default knobs
        # (an AdmissionController() here reads BBTPU_ADMIT_* exactly like
        # production — that is what lets a mis-tuned env knob fail gates)
        self.compute = ComputeQueue(
            max_group=max_group, executor=engine.new_executor()
        )
        self.admission = admission or AdmissionController()

        # fault state
        self._crashed = False
        self.crashed_at: float | None = None
        self._unreachable_until = 0.0
        self.extra_delay_s = 0.0  # degradation: added to every dispatch
        self._tasks: list = []

    # ------------------------------------------------------------- lifecycle
    def start(self) -> None:
        import asyncio

        self.compute.start()
        for coro in (self._announce_loop(),) + (
            (self._promotion_loop(),) if self._standby else ()
        ) + (
            (self._rebalance_loop(),) if self.rebalance_period > 0 else ()
        ):
            self._tasks.append(asyncio.create_task(coro))

    def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        self.compute.kill()

    def crash(self) -> None:
        """Hard process death: compute dies mid-flight, adverts stop, the
        registry lease expires and the swarm routes around the corpse."""
        if self._crashed:
            return
        self._crashed = True
        self.crashed_at = clock.monotonic()
        ledger.fault("server.crash")
        logger.warning("sim server %s CRASHED at t=%.1f", self.server_id,
                       clock.monotonic())
        self.stop()

    def reachable(self) -> bool:
        return (
            not self._crashed
            and clock.monotonic() >= self._unreachable_until
        )

    # -------------------------------------------------------------- sessions
    def open_session(self, session_id: str, client_id: str) -> None:
        """Session-open RPC: refused while standby/draining (the real
        session-open asymmetry), shed by the REAL admission controller on
        NEW work only — established steps never re-consult it."""
        if not self.reachable():
            raise SimUnreachable(self.server_id)
        if self._standby or self._draining:
            raise SimUnreachable(f"{self.server_id} not serving")
        retry = self.admission.admit_new(
            client_id,
            self.compute.current_delay_ms(self.admission.window_s),
        )
        if retry is not None:
            self.admission.shed_sessions += 1
            raise SimOverloaded(retry)
        self._sessions[session_id] = client_id

    def close_session(self, session_id: str) -> None:
        self._sessions.pop(session_id, None)

    # --------------------------------------------------------------- compute
    async def prefill(
        self, session_id: str, client_id: str, tokens: int,
        stream_started_at: float,
    ) -> None:
        """Chunked prefill through the real queue: each chunk rides at the
        aged chunk priority so old streams' chunks outrank fresh ones."""
        from bloombee_tpu.server.compute_queue import aged_chunk_priority

        remaining = int(tokens)
        while remaining > 0:
            chunk = min(self.chunk_tokens, remaining)
            remaining -= chunk
            await self._dispatch(
                "prefill", chunk, aged_chunk_priority(stream_started_at),
                client_id,
            )

    async def decode_step(self, session_id: str, client_id: str) -> None:
        from bloombee_tpu.server.compute_queue import PRIORITY_INFERENCE

        await self._dispatch("decode", 1, PRIORITY_INFERENCE, client_id)
        self._tick_faults()

    async def _dispatch(
        self, kind: str, tokens: int, priority: float, client_id: str
    ) -> None:
        if not self.reachable():
            raise SimUnreachable(self.server_id)
        await self.compute.submit_group(
            priority, (kind,), {"tokens": tokens},
            self._make_run_group(kind), task_class=kind,
        )
        if not self.reachable():  # crashed/partitioned while computing:
            raise SimUnreachable(self.server_id)  # the reply never lands
        self.admission.note_tokens(client_id, tokens)

    def _make_run_group(self, kind: str):
        cost, blocks = self.cost, self.end_block - self.start_block

        def run(payloads: list) -> list:
            toks = sum(int(p["tokens"]) for p in payloads)
            clock.sleep(
                cost.group_s(kind, len(payloads), toks, blocks)
                * self.cost_scale
                + self.extra_delay_s
            )
            return [True] * len(payloads)

        return run

    # ---------------------------------------------------------------- faults
    def _tick_faults(self) -> None:
        """One span-output decode reply on this server: advance the
        scenario's scripted-fault counters exactly like the wire plan
        does, and apply whatever came due."""
        if self.faults is None:
            return
        for f in self.faults.due((self.host, self.port)):
            self.faults.log.append((f.at_step, f.action, f.port))
            ledger.fault(f"wire.scheduled.{f.action}")
            if f.action == "crash":
                cb = self.faults._crash_cbs.get(f.target or self.server_id)
                if cb is not None:
                    cb()
            elif f.action == "partition":
                self._unreachable_until = clock.monotonic() + f.delay_s
                logger.warning(
                    "sim server %s partitioned for %.1fs", self.server_id,
                    f.delay_s,
                )
            elif f.action == "delay":
                self.extra_delay_s += f.delay_s  # creeping degradation

    # ------------------------------------------------------------ announcing
    def _advert_state(self) -> ServerState:
        if self._standby and self._promoted:
            return ServerState.DRAINING  # mid-demotion drain
        if self._standby:
            return ServerState.JOINING
        return ServerState.ONLINE

    def _server_info(self, state: ServerState) -> ServerInfo:
        wait = self.compute.wait_stats_ms()
        return ServerInfo(
            state=state,
            host=self.host,
            port=self.port,
            throughput=self.throughput,
            inference_rps=self.throughput,
            start_block=self.start_block,
            end_block=self.end_block,
            promoted_standby=self._promoted,
            load={
                "ts": clock.now(),
                "delay_ms": self.compute.current_delay_ms(
                    self.admission.window_s
                ),
                "queue_depth": float(self.compute.depth()),
                "wait_ms": {"p50": wait["p50"], "p95": wait["p95"]},
                "active_sessions": float(len(self._sessions)),
                "shedding": self.admission.shedding,
            },
        )

    async def _announce(self, state: ServerState) -> None:
        if not self.reachable():  # a partitioned server can't reach the
            return  # registry either: its lease just ages out
        await self.registry.declare_blocks(
            self.model_uid, self.server_id,
            range(self.start_block, self.end_block),
            self._server_info(state), expiration=self.lease_s,
        )

    async def _announce_loop(self) -> None:
        while not self._crashed:
            try:
                await self._announce(self._advert_state())
            except Exception as e:  # registry flap: next period retries
                logger.warning("announce failed: %s", e)
            await clock.async_sleep(self.announce_period)

    # ------------------------------------------------------------- rebalance
    async def _rebalance_loop(self) -> None:
        while not self._crashed:
            await clock.async_sleep(self.rebalance_period)
            if self._standby or self._draining or not self.reachable():
                continue
            try:
                await rebalance_if_needed(self)
            except Exception as e:
                logger.warning("rebalance failed: %s", e)

    async def rebalance_to(self, start: int, end: int) -> None:
        """Move this server's span: revoke the old lease, flip bounds,
        re-announce — the sim analogue of drain + reload + re-announce."""
        old = (self.start_block, self.end_block)
        await self.registry.revoke_blocks(
            self.model_uid, self.server_id, range(*old)
        )
        self.start_block, self.end_block = int(start), int(end)
        self.rebalance_last_move_at = clock.monotonic()
        ledger.recovery("server.rebalance_reannounce")
        logger.warning(
            "sim server %s rebalanced [%d:%d) -> [%d:%d)", self.server_id,
            old[0], old[1], start, end,
        )
        await self._announce(self._advert_state())

    # ----------------------------------------------------------------- stats
    def stats(self) -> dict:
        """rpc_info-shaped counter surface (health --probe house style)."""
        return {
            "server_id": self.server_id,
            "span": [self.start_block, self.end_block],
            "state": self._advert_state().name,
            "crashed": self._crashed,
            "promotions": self.promotions,
            "demotions": self.demotions,
            "promotions_yielded": self.promotions_yielded,
            "demotions_aborted": self.demotions_aborted,
            "rebalances_moved": self.rebalances_moved,
            "rebalances_failed": self.rebalances_failed,
            "rebalance_skipped_hysteresis": self.rebalance_skipped_hysteresis,
            "admission": self.admission.stats(),
            "queue_wait_ms": self.compute.wait_stats_ms(),
        }
