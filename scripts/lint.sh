#!/usr/bin/env bash
# Lint gate: bbtpu-lint (project AST rules BB001-BB006 + README
# env-table drift, scripts/analyze.sh) then ruff over the package,
# tests, bench, and entry scripts. Ruff config lives in pyproject.toml
# ([tool.ruff]); run with --fix to apply safe autofixes (e.g. deleting
# unused imports) in place.
set -euo pipefail
cd "$(dirname "$0")/.."

scripts/analyze.sh

if ! command -v ruff >/dev/null 2>&1 && ! python -m ruff --version >/dev/null 2>&1; then
    echo "lint: ruff not installed; skipping (pip install ruff to enable)" >&2
    exit 0
fi

RUFF=ruff
command -v ruff >/dev/null 2>&1 || RUFF="python -m ruff"

exec $RUFF check "$@" bloombee_tpu tests bench.py __graft_entry__.py
