"""Paged KV-cache substrate.

TPU-native redesign of the reference's KV stack
(/root/reference/src/bloombee/server/paged_kv.py, memory_cache.py,
memory_cache_manager.py and the FlexGen slab devices in
flexgen_utils/pytorch_backend.py). The split is control plane vs data plane:

- `PagedKVTable` (host, numpy): page allocator + per-sequence bookkeeping with
  the reference's commit/rollback/clamped-read invariants. Pure data, no jax.
- `arena` ops (device, jnp): a per-layer-stacked KV arena updated functionally
  inside the jitted span step (donated buffers, scatter writes, page gathers) —
  the in-place slab mutation of the reference becomes XLA donation.
- `CacheManager`: token-budget admission + handle lifecycle (async, single
  process) + host-DRAM page tiering (the FlexGen offload capability).
"""

from bloombee_tpu.kv.paged import PagedKVTable, SeqState
from bloombee_tpu.kv.arena import (
    make_arena,
    arena_write,
    gather_pages,
    arena_reorder,
)
from bloombee_tpu.kv.cache_manager import CacheManager, CacheHandle

__all__ = [
    "PagedKVTable",
    "SeqState",
    "make_arena",
    "arena_write",
    "gather_pages",
    "arena_reorder",
    "CacheManager",
    "CacheHandle",
]
