"""Llama transformer block as a pure function.

TPU-native replacement for the reference's WrappedLlamaBlock + FLEX_LlamaAttention
/ FLEX_LlamaMLP pipeline (/root/reference/src/bloombee/models/llama/block.py:418-718
and flexgen_utils/pytorch_backend.py:665-1081). The FlexGen ValueHolder /
cache_read_buf / weight_read_buf plumbing collapses into function arguments and
return values; KV-cache policy lives entirely in the caller-provided `attend`
closure, so the same block code serves dense prefill, paged decode, and
speculative tree verify.

Weight convention: all projection matrices are stored transposed relative to
torch `nn.Linear` — shape [in_features, out_features] — so application is `x @ w`
(row-major friendly for XLA tiling onto the MXU).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from bloombee_tpu.models.spec import ModelSpec
from bloombee_tpu.ops import apply_rotary, masked_attention, rms_norm, silu_mlp
from bloombee_tpu.ops.attention import causal_mask

# attend(q, k_new, v_new) -> (attn_out, aux); shapes
#   q: [B, T, H, hd], k_new/v_new: [B, T, Hkv, hd], attn_out: [B, T, H, hd]
Attend = Callable[[jax.Array, jax.Array, jax.Array], tuple[jax.Array, Any]]


def init_block_params(rng: jax.Array, spec: ModelSpec, dtype=jnp.float32) -> dict:
    d, i = spec.hidden_size, spec.intermediate_size
    h, kv, hd = spec.num_attention_heads, spec.num_key_value_heads, spec.head_dim
    keys = jax.random.split(rng, 7)
    scale = d**-0.5

    def w(key, shape):
        return (jax.random.normal(key, shape) * scale).astype(dtype)

    return {
        "input_layernorm": jnp.ones((d,), dtype),
        "post_attention_layernorm": jnp.ones((d,), dtype),
        "q_proj": w(keys[0], (d, h * hd)),
        "k_proj": w(keys[1], (d, kv * hd)),
        "v_proj": w(keys[2], (d, kv * hd)),
        "o_proj": w(keys[3], (h * hd, d)),
        "gate_proj": w(keys[4], (d, i)),
        "up_proj": w(keys[5], (d, i)),
        "down_proj": w(keys[6], (i, d)),
    }


def block_forward(
    params: dict,
    spec: ModelSpec,
    hidden: jax.Array,  # [B, T, D]
    cos: jax.Array,  # [B, T, hd]
    sin: jax.Array,  # [B, T, hd]
    attend: Attend,
) -> tuple[jax.Array, Any]:
    b, t, d = hidden.shape
    h, kv, hd = spec.num_attention_heads, spec.num_key_value_heads, spec.head_dim

    x = rms_norm(hidden, params["input_layernorm"], spec.rms_norm_eps)
    q = (x @ params["q_proj"]).reshape(b, t, h, hd)
    k = (x @ params["k_proj"]).reshape(b, t, kv, hd)
    v = (x @ params["v_proj"]).reshape(b, t, kv, hd)
    q, k = apply_rotary(q, k, cos, sin)

    attn_out, aux = attend(q, k, v)

    attn_out = attn_out.reshape(b, t, h * hd) @ params["o_proj"]
    hidden = hidden + attn_out

    x = rms_norm(hidden, params["post_attention_layernorm"], spec.rms_norm_eps)
    mlp_out = silu_mlp(x, params["gate_proj"], params["up_proj"], params["down_proj"])
    hidden = hidden + mlp_out
    return hidden, aux


def dense_attend(
    past_k: jax.Array | None = None,  # [B, S_past, Hkv, hd]
    past_v: jax.Array | None = None,
    offset: int = 0,
) -> Attend:
    """Plain causal attention with optional dense concatenated past (the
    'local block' reference path used by parity tests, cf.
    /root/reference/tests/test_block_exact_match.py)."""

    def attend(q, k, v):
        if past_k is not None:
            k_all = jnp.concatenate([past_k, k], axis=1)
            v_all = jnp.concatenate([past_v, v], axis=1)
        else:
            k_all, v_all = k, v
        t, s = q.shape[1], k_all.shape[1]
        mask = causal_mask(t, offset=s - t, s=s)[None]
        out = masked_attention(q, k_all, v_all, mask)
        return out, (k_all, v_all)

    return attend


# HF checkpoint key mapping: per-layer torch name -> (our name, transpose?)
HF_BLOCK_KEYS = {
    "input_layernorm.weight": ("input_layernorm", False),
    "post_attention_layernorm.weight": ("post_attention_layernorm", False),
    "self_attn.q_proj.weight": ("q_proj", True),
    "self_attn.k_proj.weight": ("k_proj", True),
    "self_attn.v_proj.weight": ("v_proj", True),
    "self_attn.o_proj.weight": ("o_proj", True),
    "mlp.gate_proj.weight": ("gate_proj", True),
    "mlp.up_proj.weight": ("up_proj", True),
    "mlp.down_proj.weight": ("down_proj", True),
}


def convert_hf_block_params(tensors: dict, dtype=None) -> dict:
    """Convert one decoder layer's HF tensors (suffix-keyed) to our pytree.

    `tensors` maps HF suffixes (e.g. 'self_attn.q_proj.weight') to arrays.
    Replaces the reference's .npy weight conversion
    (models/llama/block.py:329-384 convert_local_llama_weights).
    """
    out = {}
    for hf_key, (name, transpose) in HF_BLOCK_KEYS.items():
        w = jnp.asarray(tensors[hf_key])
        if transpose:
            w = w.T
        if dtype is not None:
            w = w.astype(dtype)
        out[name] = w
    return out
