"""Sequence classification over the frozen remote chain.

Port of the reference's DistributedLlamaForSequenceClassification
(/root/reference/src/bloombee/models/llama/model.py:263 +
utils/auto_config.py:98): the remote blocks stay frozen, a LOCAL trainable
score head maps the last non-pad token's hidden state to class logits (HF
LlamaForSequenceClassification semantics), and training reuses the
sequential-autograd machinery — optionally with trainable prompt
embeddings (the PTune composition the reference gets from PTuneMixin).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from bloombee_tpu.client.model import DistributedModelForCausalLM
from bloombee_tpu.client.sequence_manager import RemoteSequenceManager
from bloombee_tpu.client.trainer import (
    RemoteSpanChain,
    init_prompts,
    prepend_prompts,
    prompt_grad,
)
from bloombee_tpu.models.spec import ModelSpec


@functools.partial(jax.jit, static_argnames=("eps", "norm_type"))
def _score_logits(
    norm_w, norm_b, score_w, chain_out, last_idx, eps: float, norm_type: str
):
    from bloombee_tpu.ops import rms_norm
    from bloombee_tpu.ops.norms import layer_norm

    # gather FIRST: both norm types are position-wise, so norming only the
    # selected token does O(B*D) instead of O(B*S*D) work (autodiff through
    # the gather still yields the full-shaped chain gradient)
    b = chain_out.shape[0]
    h_last = chain_out[jnp.arange(b), last_idx]  # [B, D]
    if norm_type == "ln":
        h_last = layer_norm(h_last, norm_w, norm_b, eps)
    else:
        h_last = rms_norm(h_last, norm_w, eps)
    return (h_last @ score_w).astype(jnp.float32)


@functools.partial(jax.jit, static_argnames=("eps", "norm_type"))
def _score_loss_and_grads(
    norm_w, norm_b, score_w, chain_out, last_idx, labels,
    eps: float, norm_type: str,
):
    """Cross-entropy on the last-token class logits; grads w.r.t. the
    score head and the chain output (the latter feeds prompt tuning)."""

    def loss_fn(w, h):
        logits = _score_logits(
            norm_w, norm_b, w, h, last_idx, eps, norm_type
        )
        logp = jax.nn.log_softmax(logits, axis=-1)
        b = logits.shape[0]
        return -logp[jnp.arange(b), labels].mean()

    loss, (g_score, g_out) = jax.value_and_grad(loss_fn, argnums=(0, 1))(
        score_w, chain_out
    )
    return loss, g_score, g_out


class DistributedModelForSequenceClassification:
    """Client-side classifier: local embed -> remote frozen blocks ->
    local norm + trainable score head on the last non-pad token."""

    def __init__(
        self,
        spec: ModelSpec,
        client_params: dict,
        manager: RemoteSequenceManager,
        num_labels: int,
        n_prompt: int = 0,  # >0: prepend trainable prompts (PTune shallow
        # mode) trained jointly with the score head through rpc_backward
        lr: float = 0.05,
        seed: int = 0,
        config=None,
    ):
        self.model = DistributedModelForCausalLM(
            spec, client_params, manager, config=config
        )
        self.spec = spec
        self.manager = manager
        self.num_labels = int(num_labels)
        self.n_prompt = int(n_prompt)
        self.lr = lr
        self.chain = RemoteSpanChain(
            manager,
            adapter=getattr(self.model.config, "active_adapter", None),
        )
        rng = np.random.default_rng(seed)
        d = spec.hidden_size
        self.score_w = jnp.asarray(
            rng.normal(size=(d, self.num_labels)).astype(np.float32) * 0.02
        )
        self.prompts = (
            init_prompts(seed + 1, self.n_prompt, d)
            if self.n_prompt else None
        )

    @classmethod
    def from_pretrained(
        cls,
        model_dir: str,
        registry,
        num_labels: int,
        model_uid: str | None = None,
        dtype=None,
        n_prompt: int = 0,
        lr: float = 0.05,
        seed: int = 0,
        config=None,
    ) -> "DistributedModelForSequenceClassification":
        base = DistributedModelForCausalLM.from_pretrained(
            model_dir, registry, model_uid=model_uid, dtype=dtype,
            config=config,
        )
        return cls(
            base.spec, base.params, base.manager, num_labels,
            n_prompt=n_prompt, lr=lr, seed=seed, config=base.config,
        )

    def _chain_input(self, input_ids: np.ndarray) -> np.ndarray:
        h_tok = self.model.embed(input_ids)
        if self.prompts is None:
            return h_tok.astype(np.float32)
        return prepend_prompts(self.prompts, h_tok)

    def _last_idx(self, input_ids, attention_mask) -> np.ndarray:
        """Index of the last non-pad token per row (HF semantics: the
        sequence's final real token is the classification summary), offset
        past any prepended prompts.

        RIGHT padding only: with a causal chain, trailing pads cannot
        influence the last real token, so the mask never needs to reach
        the remote servers. Left padding would both pick a pad position
        here and contaminate every later token through causal attention —
        reject it loudly instead of returning plausible garbage."""
        if attention_mask is None:
            last = np.full(
                (input_ids.shape[0],), input_ids.shape[1] - 1, np.int32
            )
        else:
            mask = np.asarray(attention_mask).astype(np.int32)
            if np.any(np.diff(mask, axis=1) > 0):
                raise ValueError(
                    "attention_mask must be right-padded (ones then "
                    "zeros); re-tokenize with padding_side='right'"
                )
            last = mask.sum(axis=1) - 1
        return last + self.n_prompt

    async def scores(
        self, input_ids: np.ndarray, attention_mask: np.ndarray | None = None
    ) -> np.ndarray:
        """Class logits [B, num_labels]."""
        h_in = self._chain_input(np.asarray(input_ids))
        chain_out, _ = await self.chain.forward(h_in)
        logits = _score_logits(
            self.model.params["norm"],
            self.model.params.get("norm_bias"),
            self.score_w,
            jnp.asarray(chain_out),
            jnp.asarray(self._last_idx(input_ids, attention_mask)),
            self.spec.rms_norm_eps,
            self.spec.norm_type,
        )
        return np.asarray(logits)

    async def predict(
        self, input_ids: np.ndarray, attention_mask: np.ndarray | None = None
    ) -> np.ndarray:
        return np.argmax(await self.scores(input_ids, attention_mask), -1)

    async def train_step(
        self,
        input_ids: np.ndarray,
        labels: np.ndarray,  # [B] int class ids
        attention_mask: np.ndarray | None = None,
    ) -> float:
        """One SGD step on the score head (and prompts when n_prompt > 0;
        the prompt gradient flows back through the chain via
        rpc_backward — blocks themselves stay frozen)."""
        input_ids = np.asarray(input_ids)
        h_in = self._chain_input(input_ids)
        chain_out, ctx = await self.chain.forward(h_in)
        loss, g_score, g_out = _score_loss_and_grads(
            self.model.params["norm"],
            self.model.params.get("norm_bias"),
            self.score_w,
            jnp.asarray(chain_out),
            jnp.asarray(self._last_idx(input_ids, attention_mask)),
            jnp.asarray(np.asarray(labels, np.int32)),
            self.spec.rms_norm_eps,
            self.spec.norm_type,
        )
        self.score_w = self.score_w - self.lr * g_score
        if self.prompts is not None:
            g_in = await self.chain.backward(ctx, np.asarray(g_out))
            self.prompts = self.prompts - self.lr * prompt_grad(
                g_in, self.n_prompt
            )
        return float(loss)
