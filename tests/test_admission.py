"""Admission control: per-client fair-share accounting units, the
shed/admit decision table, and the end-to-end contract — a heavy client
flooding new sessions is shed with retriable `overloaded` while a light
client's established decode stream keeps its fair share with zero hard
failures; and with NO contention, admission control is invisible
(token-identical greedy output on vs off).
"""

import asyncio

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from bloombee_tpu.server.admission import AdmissionController
from bloombee_tpu.wire.rpc import OverloadedError


# ------------------------------------------------------------------- units
def _ctl(**kw):
    kw.setdefault("high_ms", 100.0)
    kw.setdefault("window_s", 5.0)
    kw.setdefault("retry_ms", 250.0)
    return AdmissionController(**kw)


def test_below_watermark_everything_admits():
    c = _ctl()
    c.note_tokens("heavy", 100_000, now=0.0)
    assert c.admit_new("heavy", queue_delay_ms=50.0, now=1.0) is None
    assert c.admit_new("light", queue_delay_ms=99.0, now=1.0) is None
    assert not c.shedding
    assert c.stats()["shed_requests"] == 0


def test_heavy_client_shed_at_watermark_light_admitted():
    """Two clients, one at 10x the token rate: past the high watermark the
    heavy one is shed (with a retry hint) while the light one keeps being
    admitted — weighted fair shares, not first-come-first-served."""
    c = _ctl()
    c.note_tokens("heavy", 10_000, now=0.0)
    c.note_tokens("light", 1_000, now=0.0)
    retry = c.admit_new("heavy", queue_delay_ms=200.0, now=1.0)
    assert retry is not None and retry > 0
    assert c.admit_new("light", queue_delay_ms=200.0, now=1.0) is None
    assert c.shedding
    # debts at the synthetic clock BEFORE stats(): stats() reads the real
    # clock, pruning these synthetic-timestamp tokens out of the window
    debts = c.debts(now=1.0)
    assert debts["heavy"] > 0 >= debts["light"]
    st = c.stats()
    assert st["shed_requests"] == 1
    assert any(st["retry_after_ms_hist"].values())


def test_unseen_client_admitted_until_hard_watermark():
    """A brand-new client has no history, hence no debt: it is admitted
    past the high watermark (up to hard_factor x high) so a flood by
    OTHERS cannot lock newcomers out."""
    c = _ctl(hard_factor=4.0)
    c.note_tokens("heavy", 10_000, now=0.0)
    assert c.admit_new("newcomer", queue_delay_ms=399.0, now=1.0) is None
    assert c.admit_new("newcomer", queue_delay_ms=401.0, now=1.0) is not None


def test_uncontended_client_never_shed_below_hard_watermark():
    """Alone in the window a client is by construction at zero debt: only
    the hard watermark (a genuinely wedged server) can shed it."""
    c = _ctl(hard_factor=4.0)
    for t in range(5):
        c.note_tokens("solo", 50_000, now=float(t))
        assert c.admit_new("solo", queue_delay_ms=399.0, now=float(t)) is None
    assert c.admit_new("solo", queue_delay_ms=10_000.0, now=5.0) is not None


def test_retry_hint_scales_with_severity_and_debt():
    c = _ctl()
    c.note_tokens("heavy", 10_000, now=0.0)
    c.note_tokens("light", 100, now=0.0)
    mild = c.admit_new("heavy", queue_delay_ms=150.0, now=1.0)
    severe = c.admit_new("heavy", queue_delay_ms=1500.0, now=1.0)
    assert severe > mild
    assert severe <= 30_000  # capped


def test_token_window_slides():
    c = _ctl(window_s=1.0)
    c.note_tokens("a", 1000, now=0.0)
    assert c.token_rate("a", now=0.5) > 0
    assert c.token_rate("a", now=5.0) == 0.0
    # the old flood aged out: no debt, admitted again
    assert c.fair_share_debt("a", now=5.0) == 0.0


def test_nonfinite_delay_never_sheds():
    c = _ctl()
    c.note_tokens("a", 1_000_000, now=0.0)
    assert c.admit_new("a", queue_delay_ms=float("nan"), now=0.5) is None
    assert c.admit_new("a", queue_delay_ms=float("inf"), now=0.5) is None


# ------------------------------------------------------------------ e2e
@pytest.fixture(scope="module")
def tiny_model_dir(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=3, vocab_size=128,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    d = tmp_path_factory.mktemp("tiny_llama_admit")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model, config


def _hf_greedy(model, input_ids, max_new_tokens):
    with torch.no_grad():
        out = model.generate(
            torch.tensor(input_ids), max_new_tokens=max_new_tokens,
            do_sample=False, use_cache=True,
        )
    return out.numpy()


def test_admission_on_uncontended_is_token_identical(tiny_model_dir):
    """With no contention, admission control must be invisible: greedy
    output with --admit on equals HF greedy (and hence equals admit off)."""
    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    model_dir, hf_model, config = tiny_model_dir
    input_ids = (np.arange(11)[None, :] * 7 + 2) % config.vocab_size
    ref = _hf_greedy(hf_model, input_ids, 6)

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        server = BlockServer(
            model_uid="tiny", start=0, end=3, model_dir=model_dir,
            registry=rc(), compute_dtype=jnp.float32, num_pages=64,
            page_size=4, admit=True, admit_high_ms=750.0,
        )
        await server.start()
        try:
            model = DistributedModelForCausalLM.from_pretrained(
                model_dir, rc(), model_uid="tiny"
            )
            ids = await model.generate(input_ids, max_new_tokens=6)
            np.testing.assert_array_equal(ids, ref)
            st = server.admission.stats()
            assert st["shed_requests"] == 0
            assert st["shed_sessions"] == 0
            assert st["admitted_new"] >= 1
        finally:
            await server.stop()
            await reg.stop()

    asyncio.run(run())


def test_open_shed_surfaces_retriable_overloaded(tiny_model_dir):
    """A server past its watermark sheds a NEW session open with the
    structured retriable error (code + retry_after_ms on the wire), and
    the client maps it to OverloadedError — not a fault ban."""
    from bloombee_tpu.client.sequence_manager import RemoteSequenceManager
    from bloombee_tpu.client.session import InferenceSession
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    model_dir, _, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        server = BlockServer(
            model_uid="tiny", start=0, end=3, model_dir=model_dir,
            registry=rc(), compute_dtype=jnp.float32, num_pages=64,
            page_size=4, admit=True, admit_high_ms=50.0,
        )
        await server.start()
        try:
            # force the shed decision: make this client heavily over-share
            # and the measured queue delay read hot
            server.admission.note_tokens("greedy-cli", 1_000_000)
            server.admission.note_tokens("other-cli", 10)
            server.compute.current_delay_ms = lambda *a, **k: 500.0

            manager = RemoteSequenceManager(rc(), "tiny", 3)
            await manager.update(force=True)
            s = InferenceSession(
                manager, max_length=32, batch_size=1,
                client_id="greedy-cli", overload_retries=0,
            )
            hidden = np.zeros((1, 4, config.hidden_size), np.float32)
            with pytest.raises(OverloadedError) as exc_info:
                async with s:
                    await s.step(hidden)
            assert exc_info.value.retry_after_ms > 0
            # overload penalty, NOT a fault ban — and the server counted it
            assert server.server_id in manager._hot
            assert server.server_id not in manager._bans
            assert server.admission.stats()["shed_sessions"] >= 1
        finally:
            await server.stop()
            await reg.stop()

    asyncio.run(run())


def test_established_stream_survives_heavy_flood(tiny_model_dir):
    """Fairness end-to-end: an established light session keeps decoding
    (zero hard failures, >= fair throughput share) while a 10x-heavier
    client floods new prefill sessions into an admitting server; the
    heavy client's floods get shed with retriable `overloaded`."""
    from bloombee_tpu.client.sequence_manager import (
        MissingBlocksError,
        RemoteSequenceManager,
    )
    from bloombee_tpu.client.session import InferenceSession
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    model_dir, _, config = tiny_model_dir
    H = config.hidden_size

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        server = BlockServer(
            model_uid="tiny", start=0, end=3, model_dir=model_dir,
            registry=rc(), compute_dtype=jnp.float32, num_pages=256,
            page_size=4, admit=True, admit_high_ms=40.0,
        )
        await server.start()
        light_mgr = RemoteSequenceManager(rc(), "tiny", 3)
        heavy_mgr = RemoteSequenceManager(rc(), "tiny", 3)
        await light_mgr.update(force=True)
        await heavy_mgr.update(force=True)

        rng = np.random.default_rng(5)
        light_tokens = 0
        heavy_tokens = 0
        sheds = 0  # server-issued overloaded() refusals seen by the client
        backoffs = 0  # client-side overload backoff: nowhere left to route
        hard_failures = 0
        stop = asyncio.Event()

        light = InferenceSession(
            light_mgr, max_length=256, batch_size=1, client_id="light",
        )
        await light.__aenter__()
        # establish the stream BEFORE the flood (prefill = its one piece
        # of new work), then compile the decode bucket
        await light.step(
            rng.standard_normal((1, 8, H)).astype(np.float32) * 0.02
        )
        await light.step(
            rng.standard_normal((1, 1, H)).astype(np.float32) * 0.02
        )

        async def light_loop():
            nonlocal light_tokens, hard_failures
            while not stop.is_set():
                try:
                    await light.step(
                        rng.standard_normal((1, 1, H)).astype(np.float32)
                        * 0.02
                    )
                    light_tokens += 1
                except Exception:  # noqa: BLE001 — any failure of an
                    # established stream violates the shedding contract
                    hard_failures += 1
                    return

        async def heavy_loop():
            nonlocal heavy_tokens, sheds, backoffs, hard_failures
            while not stop.is_set():
                s = InferenceSession(
                    heavy_mgr, max_length=128, batch_size=1,
                    client_id="heavy", overload_retries=0,
                )
                try:
                    async with s:
                        await s.step(
                            rng.standard_normal((1, 64, H)).astype(
                                np.float32
                            ) * 0.02
                        )
                    heavy_tokens += 64
                except OverloadedError as e:
                    sheds += 1
                    retry = min((e.retry_after_ms or 100) / 1000.0, 0.2)
                    await asyncio.sleep(retry)
                except MissingBlocksError:
                    # the one server is inside its overload backoff: the
                    # client has nowhere to route — backpressure, not a
                    # failure
                    backoffs += 1
                    await asyncio.sleep(0.1)
                except Exception:  # noqa: BLE001
                    hard_failures += 1
                    await asyncio.sleep(0.05)

        async def timer():
            await asyncio.sleep(4.0)
            stop.set()

        try:
            await asyncio.gather(
                timer(), light_loop(), heavy_loop(), heavy_loop(),
            )
            st = server.admission.stats()
            assert hard_failures == 0, (
                f"hard failures under flood: {hard_failures}"
            )
            assert light_tokens > 0
            # the flood was actually pushed back (otherwise the test proved
            # nothing): server-issued sheds, then client-side backoff once
            # the peer entered its overload penalty window. The server's
            # ledger must account for every overloaded() the client saw.
            assert sheds + backoffs > 0
            assert st["shed_requests"] + st["shed_sessions"] >= sheds
            # fairness: per-request the light client is entitled to 1/2 of
            # the admitted steps; each light step is one queue slot, so
            # compare step counts — the light stream must not be starved
            # below a loose fair-share floor by heavier queue items
            total_steps = light_tokens + heavy_tokens / 64
            assert light_tokens / total_steps >= 0.25, (
                light_tokens, heavy_tokens
            )
        finally:
            try:
                await light.__aexit__(None, None, None)
            except Exception:  # noqa: BLE001
                pass
            await server.stop()
            await reg.stop()

    asyncio.run(run())
