"""Registry service: the swarm's discovery plane.

Role of the reference's hivemind DHT + declare_active_modules /
get_remote_module_infos (/root/reference/src/bloombee/utils/dht.py:28-117):
servers periodically store `{uid}.{block}` -> {server_id: (info, expiry)};
records expire, and expiry IS the failure detector (a dead server's records
vanish after `expiration` seconds — reference server.py:957-992). Clients
fetch many uids at once to build the routing table.

Deployment: one `RegistryServer` runs as the bootstrap node (the reference's
`run_dht` role, cli/run_dht.py). `InProcessRegistry` backs single-process
tests. The registry speaks the normal wire RPC so any peer can also proxy it.
"""

from __future__ import annotations

import asyncio
import time

from bloombee_tpu.swarm.data import ModuleInfo, ServerInfo
from bloombee_tpu.wire.rpc import Connection, RpcServer, connect


class _Store:
    def __init__(self):
        # key -> subkey -> (value dict, expiration unix time)
        self._data: dict[str, dict[str, tuple[dict, float]]] = {}

    def store(self, key: str, subkey: str, value: dict, expiration: float):
        self._data.setdefault(key, {})[subkey] = (value, expiration)

    def get(self, key: str) -> dict[str, dict]:
        now = time.time()
        out = {}
        sub = self._data.get(key)
        if not sub:
            return out
        dead = []
        for sk, (v, exp) in sub.items():
            if exp < now:
                dead.append(sk)
            else:
                out[sk] = v
        for sk in dead:
            del sub[sk]
        return out

    def delete(self, key: str, subkey: str):
        sub = self._data.get(key)
        if sub:
            sub.pop(subkey, None)


class RegistryServer:
    """Standalone registry node (bootstrap peer)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        self._store = _Store()
        self.rpc = RpcServer(
            unary_handlers={
                "registry_store": self._rpc_store,
                "registry_get": self._rpc_get,
                "registry_delete": self._rpc_delete,
            },
            host=host,
            port=port,
        )

    @property
    def port(self) -> int:
        return self.rpc.port

    async def start(self):
        await self.rpc.start()

    async def stop(self):
        await self.rpc.stop()

    async def _rpc_store(self, meta: dict, tensors):
        now = time.time()
        for rec in meta["records"]:
            self._store.store(
                rec["key"], rec["subkey"], rec["value"],
                now + rec["expiration"],
            )
        return {"ok": True}, []

    async def _rpc_get(self, meta: dict, tensors):
        return {"results": {k: self._store.get(k) for k in meta["keys"]}}, []

    async def _rpc_delete(self, meta: dict, tensors):
        for rec in meta["records"]:
            self._store.delete(rec["key"], rec["subkey"])
        return {"ok": True}, []


class RegistryClient:
    """Client handle to the registry (used by servers and model clients)."""

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._conn: Connection | None = None
        self._lock = asyncio.Lock()

    async def _connection(self) -> Connection:
        async with self._lock:
            if self._conn is None or self._conn.is_closing():
                self._conn = await connect(self.host, self.port)
            return self._conn

    async def close(self):
        if self._conn is not None:
            await self._conn.close()
            self._conn = None

    async def declare_blocks(
        self,
        model_uid: str,
        server_id: str,
        blocks: range,
        info: ServerInfo,
        expiration: float = 30.0,
    ) -> None:
        """reference: declare_active_modules (utils/dht.py:28-73)."""
        conn = await self._connection()
        records = [
            {
                "key": f"{model_uid}.{i}",
                "subkey": server_id,
                "value": info.to_wire(),
                "expiration": expiration,
            }
            for i in blocks
        ]
        await conn.call("registry_store", {"records": records})

    async def revoke_blocks(
        self, model_uid: str, server_id: str, blocks: range
    ) -> None:
        conn = await self._connection()
        records = [
            {"key": f"{model_uid}.{i}", "subkey": server_id} for i in blocks
        ]
        await conn.call("registry_delete", {"records": records})

    async def get_module_infos(
        self, model_uid: str, blocks: range
    ) -> list[ModuleInfo]:
        """reference: get_remote_module_infos (utils/dht.py:74-117)."""
        conn = await self._connection()
        keys = [f"{model_uid}.{i}" for i in blocks]
        meta, _ = await conn.call("registry_get", {"keys": keys})
        out = []
        for i, key in zip(blocks, keys):
            servers = {
                sid: ServerInfo.from_wire(v)
                for sid, v in meta["results"].get(key, {}).items()
            }
            out.append(ModuleInfo(uid=key, servers=servers))
        return out


class InProcessRegistry:
    """Registry + client fused for single-process tests."""

    def __init__(self):
        self._store = _Store()

    async def declare_blocks(self, model_uid, server_id, blocks, info,
                             expiration: float = 30.0):
        now = time.time()
        for i in blocks:
            self._store.store(
                f"{model_uid}.{i}", server_id, info.to_wire(), now + expiration
            )

    async def revoke_blocks(self, model_uid, server_id, blocks):
        for i in blocks:
            self._store.delete(f"{model_uid}.{i}", server_id)

    async def get_module_infos(self, model_uid, blocks):
        out = []
        for i in blocks:
            key = f"{model_uid}.{i}"
            servers = {
                sid: ServerInfo.from_wire(v)
                for sid, v in self._store.get(key).items()
            }
            out.append(ModuleInfo(uid=key, servers=servers))
        return out

    async def close(self):
        pass
