"""Heterogeneous span step: per-layer attention geometry (Gemma-4 style).

The stacked `lax.scan` in runtime/step.py requires every layer's params and
KV slab to share shapes. Gemma-4 breaks that: full-attention layers use
`global_head_dim` (512) and their own KV head count while sliding layers use
the base geometry (reference server/backend.py:243-306 threads a per-block
head_dim into the cache descriptors). Here the span unrolls at trace time —
a Python loop over per-layer params and per-layer slabs inside one jit, each
layer driven by its own static `spec_for_layer` — so XLA still sees one
fused program per bucket, just without the scan's shape uniformity.

The paged control plane is untouched: all layers share ONE PagedKVTable slot
space; each layer simply owns a slab of its own [S_tot, Hkv_l, hd_l] shape
(leading dim of 1 keeps every manager operation — reorder, park, unpark —
uniform with stacked slabs).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from bloombee_tpu.models.spec import ModelSpec
from bloombee_tpu.ops.rotary import rotary_cos_sin
from bloombee_tpu.runtime.layer_body import layer_body
from bloombee_tpu.runtime.step import unpack_plan


def make_hetero_arena(
    spec: ModelSpec,
    num_layers: int,
    start_block: int,
    num_pages: int,
    page_size: int,
    dtype=jnp.bfloat16,
    quant: str | None = None,  # "int4": per-layer QuantSlabs (each layer's
    # head_dim groups independently, so 16- and 32-wide heads coexist)
) -> dict:
    """Per-layer slabs [1, S_tot, Hkv_l, hd_l] as tuples (a jax pytree);
    layer geometry indexed by ABSOLUTE block id (span offset matters)."""
    s_tot = num_pages * page_size
    ks, vs = [], []
    for i in range(num_layers):
        a = start_block + i
        shape = (
            1, s_tot, spec.kv_heads_for_layer(a), spec.head_dim_for_layer(a)
        )
        if quant == "int4":
            from bloombee_tpu.kv.quant import make_quant_slab

            ks.append(make_quant_slab(shape))
            vs.append(make_quant_slab(shape))
        elif quant in (None, "none"):
            ks.append(jnp.zeros(shape, dtype))
            vs.append(jnp.zeros(shape, dtype))
        else:
            # same loud contract as the homogeneous make_arena: a typo'd
            # mode must not silently serve a full-precision arena
            raise ValueError(f"unknown KV quant mode {quant!r}")
    return {"k": tuple(ks), "v": tuple(vs)}


def span_step_hetero_impl(
    layer_params: tuple,  # per-layer param dicts
    arena_k: tuple,  # per-layer [1, S_tot, Hkv_l, hd_l]
    arena_v: tuple,
    payload: jax.Array,  # pack_step_payload buffer
    tree_mask: jax.Array | None = None,
    lora: dict | None = None,  # STACKED [L, ...] LoRA factors (the same
    # pytree the scanned path consumes); sliced per layer at TRACE time —
    # eager per-step slicing would add host dispatch to the decode path
    *,
    spec: ModelSpec,
    b: int,
    t: int,
    page_size: int,
    max_pages: int,
    use_tree_mask: bool = False,
    start_block: int = 0,
    layer_active: tuple | None = None,  # static 0/1 per layer (sub-spans)
    attn_topk: int = 0,  # sparse decode attention (FlexGen
    # Policy.attn_sparsity), same semantics as the scanned path
):
    """Unrolled heterogeneous span step; returns (hidden, arena_k, arena_v).

    `layer_active` is static here (unlike the scanned path's traced gate):
    inactive layers are simply skipped at trace time.
    """
    from bloombee_tpu.runtime.step import unpack_step_payload

    num_layers = len(arena_k)
    hidden, plan = unpack_step_payload(payload, b, t, spec.hidden_size)
    slots, page_table, q_positions, total_lens, _ = unpack_plan(
        plan, b, t, max_pages, num_layers
    )
    tm = tree_mask if use_tree_mask else None

    # one rotary table per distinct (head_dim, theta)
    cos_sin: dict[tuple, tuple] = {}
    new_k, new_v = list(arena_k), list(arena_v)
    for i in range(num_layers):
        if layer_active is not None and not layer_active[i]:
            continue
        abs_idx = start_block + i
        spec_l = spec.spec_for_layer(abs_idx)
        key = (spec_l.head_dim, spec_l.rope_theta)
        if key not in cos_sin:
            cos, sin = rotary_cos_sin(
                q_positions, spec_l.head_dim, spec_l.rope_theta
            )
            cos_sin[key] = (
                cos.astype(hidden.dtype), sin.astype(hidden.dtype)
            )
        cos, sin = cos_sin[key]
        # tree-aware leading-dim squeeze/expand: a quantized slab is a
        # QuantSlab NamedTuple, where plain [0] would be TUPLE indexing
        # (returning the codes leaf), not a slice
        sq = jax.tree.map(lambda x: x[0], (new_k[i], new_v[i]))
        hidden, k_l, v_l = layer_body(
            spec_l, page_size, hidden, layer_params[i],
            sq[0], sq[1], cos, sin, slots, page_table,
            q_positions, total_lens, tm,
            jnp.int32(spec.window_for_layer(abs_idx)),
            lora=(
                jax.tree.map(lambda x, i=i: x[i], lora)
                if lora is not None else None
            ),
            attn_topk=attn_topk,
        )
        new_k[i] = jax.tree.map(lambda x: x[None], k_l)
        new_v[i] = jax.tree.map(lambda x: x[None], v_l)
    return hidden, tuple(new_k), tuple(new_v)


span_step_hetero = functools.partial(
    jax.jit,
    static_argnames=(
        "spec", "b", "t", "page_size", "max_pages", "use_tree_mask",
        "start_block", "layer_active", "attn_topk",
    ),
    donate_argnames=("arena_k", "arena_v"),
)(span_step_hetero_impl)
