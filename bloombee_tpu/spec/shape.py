"""Adaptive tree-shape selection from acceptance statistics.

Role of the reference's Sequoia-style shape optimizer
(/root/reference/src/bloombee/models/llama/spec_decoding_tree_shape.py
:116-250: width optimization driven by an acceptance histogram). The model:
each round the verifier walks one path; depth d is reached iff every level
before it accepted. From observed per-level conditional acceptance rates
p_d (any drafted child at level d matched | level d-1 matched), a candidate
branching (w_1..w_D) yields expected accepted tokens

    E = sum_d prod_{i<=d} a_i(w_i),   a_i(w) = 1 - (1 - q_i)^w

where q_i is the per-child acceptance estimate at level i (p_i observed at
the width that produced it, deflated to a single child). The chooser picks
the candidate with the best E under a node budget (tree size bounds the
verify step's compute and the session's KV spike).
"""

from __future__ import annotations

import dataclasses

import numpy as np


def tree_nodes(branching: tuple[int, ...]) -> int:
    """Node count of the verify tree (incl. the certain root node)."""
    total, width = 1, 1
    for w in branching:
        width *= w
        total += width
    return total


@dataclasses.dataclass
class AcceptanceStats:
    """Per-depth acceptance counters with exponential forgetting."""

    max_depth: int = 8
    decay: float = 0.98
    prior_hits: float = 1.0
    prior_tries: float = 2.0

    def __post_init__(self):
        self.hits = np.zeros(self.max_depth)
        self.tries = np.zeros(self.max_depth)
        self.widths = np.ones(self.max_depth)  # width each level was observed at

    def observe(
        self, accepted_len: int, branching: tuple[int, ...]
    ) -> None:
        """One round for one row: the tree had levels `branching` (per-level
        widths) and `accepted_len` of them matched (0..len(branching)).

        Decay is PER LEVEL, applied only when that level is actually
        reached: an unreached level keeps its last measured rate instead
        of fading back to the optimistic prior. Under acceptance collapse
        level 0's rate falls monotonically while the frozen deeper rates
        stay put, so the chooser's preferred tree shrinks monotonically
        rather than oscillating as stale levels re-inflate."""
        depth = len(branching)
        for d in range(min(depth, self.max_depth)):
            if d > accepted_len:
                break  # level d was never reached
            self.hits[d] *= self.decay
            self.tries[d] *= self.decay
            self.tries[d] += 1
            self.widths[d] = branching[d]  # rate observed at THIS width
            if d < accepted_len:
                self.hits[d] += 1

    def per_level_rate(self, d: int) -> float:
        i = min(d, self.max_depth - 1)
        return float(
            (self.hits[i] + self.prior_hits)
            / (self.tries[i] + self.prior_tries)
        )

    def per_child_rate(self, d: int) -> float:
        """Deflate the level's observed rate to a single child using the
        width it was actually observed at."""
        i = min(d, self.max_depth - 1)
        p = min(self.per_level_rate(d), 0.999)
        w = max(float(self.widths[i]), 1.0)
        return 1.0 - (1.0 - p) ** (1.0 / w)


def expected_accepted(
    branching: tuple[int, ...], stats: AcceptanceStats
) -> float:
    """Expected accepted tokens per round for a candidate branching."""
    e, reach = 0.0, 1.0
    for d, w in enumerate(branching):
        q = stats.per_child_rate(d)
        a = 1.0 - (1.0 - q) ** w
        reach *= a
        e += reach
    return e


DEFAULT_CANDIDATES = (
    (2,), (4,), (2, 1), (2, 2), (4, 2), (2, 2, 1), (2, 2, 2), (4, 2, 1),
)


def choose_branching(
    stats: AcceptanceStats,
    candidates=DEFAULT_CANDIDATES,
    budget_nodes: int = 16,
    cost_per_node: float = 0.0,
    current: tuple[int, ...] | None = None,
    grow_margin: float = 0.0,
) -> tuple[int, ...]:
    """Best candidate under the node budget; ties prefer fewer nodes
    (cheaper verify step).

    `cost_per_node` charges every tree node a fixed expected-token cost:
    E alone is monotone in node count (each extra level or child can only
    add expected accepts), so without a cost the chooser always maxes the
    budget. With one, collapsed acceptance makes every node a net loss and
    the tree shrinks toward the smallest candidate.

    `current`/`grow_margin` add growth hysteresis: a LARGER tree than
    `current` is adopted only when its score beats current's by the
    margin. The per-child rate estimate shifts with the width it was
    observed at, so near-tied small candidates can flap on width changes
    alone — shrinking is always allowed, growing must clear real signal."""
    viable = [c for c in candidates if tree_nodes(c) <= budget_nodes]
    if not viable:
        viable = [min(candidates, key=tree_nodes)]

    def score(c):
        return expected_accepted(c, stats) - cost_per_node * tree_nodes(c)

    best = max(viable, key=lambda c: (score(c), -tree_nodes(c)))
    if (
        current is not None
        and tree_nodes(best) > tree_nodes(current)
        and score(best) < score(tuple(current)) + grow_margin
    ):
        return tuple(current)
    return best
