"""Speculative pruner semantics (port of
/root/reference/tests/test_speculative_pruner_manager.py intent)."""

import numpy as np

from bloombee_tpu.spec.pruner import SimpleProbabilityPruner
from bloombee_tpu.spec.tree import DraftTree


def _probs(vocab, rows):
    out = np.full((len(rows), vocab), 1e-6)
    for i, spec in enumerate(rows):
        for tok, p in spec.items():
            out[i, tok] = p
    return out / out.sum(axis=-1, keepdims=True)


def test_prunes_low_probability_children_and_subtrees():
    #  0(tok 1)   1(tok 2)     roots
    #  2(tok 3, child of 0)    3(tok 4, child of 1)
    tree = DraftTree(
        tokens=np.asarray([1, 2, 3, 4]),
        parents=np.asarray([-1, -1, 0, 1]),
    )
    vocab = 8
    # root distribution: token 1 likely, token 2 negligible
    root = _probs(vocab, [{1: 0.9, 2: 0.01}])[0]
    probs = _probs(
        vocab,
        [
            {3: 0.8},  # node 0's dist -> child 2 strong
            {4: 0.9},  # node 1's dist -> child 3 strong, but 1 is pruned
            {},
            {},
        ],
    )
    kept = SimpleProbabilityPruner(threshold=0.1).keep_indices(
        tree, probs, root
    )
    kept_set = set(kept[kept >= 0].tolist())
    assert 0 in kept_set and 2 in kept_set  # strong path survives
    assert 1 not in kept_set  # weak root pruned
    assert 3 not in kept_set  # descendant of pruned node gone too


def test_keep_indices_padding_and_cap():
    tree = DraftTree(
        tokens=np.asarray([1, 2, 3]), parents=np.asarray([-1, 0, 1])
    )
    vocab = 4
    root = _probs(vocab, [{1: 1.0}])[0]
    probs = _probs(vocab, [{2: 1.0}, {3: 1.0}, {}])
    kept = SimpleProbabilityPruner(threshold=0.5, max_keep=2).keep_indices(
        tree, probs, root
    )
    assert kept.tolist() == [0, 1]  # capped at 2
    kept = SimpleProbabilityPruner(threshold=0.99).keep_indices(
        tree, probs, root
    )
    assert kept.tolist() == [0, 1, 2]  # single children renormalize to 1.0

def test_cap_drops_lowest_scoring_leaves_not_late_indices():
    # two root chains: nodes 0->2 (weak) and 1->3 (strong). Index-order
    # truncation at cap=2 would keep [0, 1]; score-ordered capping must
    # keep the STRONG chain [1, 3] by dropping the weakest leaves first
    # (2 then 0), never orphaning a kept child.
    tree = DraftTree(
        tokens=np.asarray([1, 2, 3, 4]),
        parents=np.asarray([-1, -1, 0, 1]),
    )
    vocab = 8
    root = _probs(vocab, [{1: 0.3, 2: 0.7}])[0]
    probs = _probs(vocab, [{3: 0.9}, {4: 0.95}, {}, {}])
    kept = SimpleProbabilityPruner(threshold=0.05, max_keep=2).keep_indices(
        tree, probs, root
    )
    assert kept.tolist() == [1, 3]


def test_mid_head_trainer_learns_and_checkpoints(tmp_path):
    """Online MidLMHead training (reference lm_head_trainer): CE drops on a
    fixed batch, and save/load round-trips the trained weight."""
    import jax
    import jax.numpy as jnp

    from bloombee_tpu.spec.pruner import MidHeadTrainer, MidLMHead

    rng = np.random.default_rng(0)
    d, v, n = 16, 32, 64
    head = MidLMHead(
        jnp.asarray(rng.normal(size=(d, v)).astype(np.float32) * 0.1),
        jnp.ones((d,), jnp.float32),
    )
    trainer = MidHeadTrainer(head, lr=0.5)
    hidden = rng.normal(size=(n, d)).astype(np.float32)
    targets = rng.integers(0, v, size=(n,))
    losses = [trainer.train_step(hidden, targets) for _ in range(20)]
    assert losses[-1] < losses[0] * 0.8, losses[:3] + losses[-3:]

    path = str(tmp_path / "pruner_head.npz")
    trainer.save(path)
    loaded = MidHeadTrainer.load(path)
    np.testing.assert_array_equal(
        np.asarray(loaded.head.weight), np.asarray(trainer.head.weight)
    )
    assert loaded.steps == trainer.steps


def test_e2e_pruner_online_training(tmp_path, monkeypatch):
    """Pruned speculative decode with BBTPU_PRUNER_TRAIN: the head trains
    on accepted paths while tokens stay exactly greedy."""
    import asyncio

    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    import jax.numpy as jnp

    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.client.speculative import generate_speculative
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.spec.drafter import GreedyTreeDrafter, LocalJaxDraftModel
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    monkeypatch.setenv("BBTPU_PRUNER_TRAIN", "1")
    ckpt = str(tmp_path / "head.npz")
    monkeypatch.setenv("BBTPU_PRUNER_CKPT", ckpt)

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=3, vocab_size=128,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = LlamaForCausalLM(config).eval().to(torch.float32)
    d = str(tmp_path / "model")
    hf.save_pretrained(d, safe_serialization=True)

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s1 = BlockServer(model_uid="m", start=0, end=2, model_dir=d,
                         registry=RegistryClient("127.0.0.1", reg.port),
                         compute_dtype=jnp.float32, num_pages=256,
                         page_size=4)
        s2 = BlockServer(model_uid="m", start=2, end=3, model_dir=d,
                         registry=RegistryClient("127.0.0.1", reg.port),
                         compute_dtype=jnp.float32, num_pages=256,
                         page_size=4)
        await s1.start()
        await s2.start()
        model = DistributedModelForCausalLM.from_pretrained(
            d, RegistryClient("127.0.0.1", reg.port), model_uid="m",
            use_push=False,
        )
        drafter = GreedyTreeDrafter(
            LocalJaxDraftModel.from_dir(d), branching=(2, 2)
        )
        input_ids = np.arange(5)[None, :]
        spec_ids = await generate_speculative(
            model, drafter, input_ids, max_new_tokens=8,
            prune_threshold=0.45,
        )
        plain = await model.generate(input_ids, max_new_tokens=8)
        np.testing.assert_array_equal(spec_ids, plain)
        trainer = s1._pruner_manager.trainer
        assert trainer is not None and trainer.steps > 0
        await s1.stop()
        await s2.stop()
        await reg.stop()

    asyncio.run(run())


def test_neural_pruner_keeps_subtree_contract():
    """Untrained neural pruner (positive output bias) keeps everything;
    forcing the cutoff above 1 keeps exactly the best root child (the
    never-empty guarantee); subtree propagation holds."""
    from bloombee_tpu.spec.pruner import (
        AdaptiveNeuralPruner,
        init_neural_params,
    )

    tree = DraftTree(
        tokens=np.asarray([1, 2, 3, 4]),
        parents=np.asarray([-1, -1, 0, 1]),
    )
    vocab = 8
    root = _probs(vocab, [{1: 0.9, 2: 0.01}])[0]
    probs = _probs(vocab, [{3: 0.8}, {4: 0.9}, {}, {}])

    pruner = AdaptiveNeuralPruner(init_neural_params())
    kept = pruner.keep_indices(tree, probs, root)
    assert set(kept[kept >= 0].tolist()) == {0, 1, 2, 3}  # fresh net keeps

    pruner.threshold = 1.1  # impossible cutoff -> best-root-child fallback
    kept = pruner.keep_indices(tree, probs, root)
    kept_set = set(kept[kept >= 0].tolist())
    assert len(kept_set) == 1 and kept_set <= {0, 1}


def test_neural_pruner_learns_probability_rule(tmp_path):
    """Online BCE training teaches the scorer to keep high-probability
    nodes and drop low ones (labels mimic accepted paths), and the
    checkpoint round-trips."""
    from bloombee_tpu.spec.pruner import (
        AdaptiveNeuralPruner,
        NeuralPrunerTrainer,
        init_neural_params,
        node_features,
    )

    rng = np.random.default_rng(0)
    vocab = 16
    # synthetic nodes: feature = parent dist + own token; label = own
    # conditional prob high
    feats, labels = [], []
    tree1 = DraftTree(tokens=np.asarray([1, 2]), parents=np.asarray([-1, -1]))
    for _ in range(400):
        p_good = rng.uniform(0.6, 0.95)
        p_bad = rng.uniform(0.001, 0.05)
        root = _probs(vocab, [{1: p_good, 2: p_bad}])[0]
        f = node_features(tree1, np.zeros((2, vocab)), root)
        feats.append(f)
        labels.append(np.asarray([1.0, 0.0], np.float32))
    feats = np.concatenate(feats)
    labels = np.concatenate(labels)

    pruner = AdaptiveNeuralPruner(init_neural_params())
    trainer = NeuralPrunerTrainer(pruner, lr=0.05)
    for i in range(0, len(labels), 64):
        trainer.train_step(feats[i : i + 64], labels[i : i + 64])

    # after training: a strong child survives, a weak one is pruned
    root = _probs(vocab, [{1: 0.9, 2: 0.01}])[0]
    kept = pruner.keep_indices(tree1, np.zeros((2, vocab)), root)
    assert set(kept[kept >= 0].tolist()) == {0}

    trainer.save(str(tmp_path / "net"))
    loaded = NeuralPrunerTrainer.load(str(tmp_path / "net"))
    assert loaded.steps == trainer.steps
    kept2 = loaded.pruner.keep_indices(tree1, np.zeros((2, vocab)), root)
    np.testing.assert_array_equal(kept2, kept)


def test_e2e_neural_pruner_online_training(tmp_path, monkeypatch):
    """BBTPU_PRUNER_METHOD=neural: the served pruned-spec path runs the
    learned scorer and trains it online from accepts (greedy output stays
    token-exact — greedy spec decode is exact under any pruner)."""
    import asyncio

    import torch
    import jax.numpy as jnp
    from transformers import LlamaConfig, LlamaForCausalLM

    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.client.speculative import generate_speculative
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.spec.drafter import GreedyTreeDrafter, LocalJaxDraftModel
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=3, vocab_size=128,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = LlamaForCausalLM(config).eval().to(torch.float32)
    d = str(tmp_path / "m")
    hf.save_pretrained(d, safe_serialization=True)

    monkeypatch.setenv("BBTPU_PRUNER_METHOD", "neural")
    monkeypatch.setenv("BBTPU_PRUNER_TRAIN", "1")
    monkeypatch.setenv("BBTPU_PRUNER_CKPT", str(tmp_path / "head"))

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        server = BlockServer(
            model_uid="m", start=0, end=2, model_dir=d,
            registry=RegistryClient("127.0.0.1", reg.port),
            compute_dtype=jnp.float32, num_pages=256, page_size=4,
        )
        s2 = BlockServer(
            model_uid="m", start=2, end=3, model_dir=d,
            registry=RegistryClient("127.0.0.1", reg.port),
            compute_dtype=jnp.float32, num_pages=256, page_size=4,
        )
        await server.start()
        await s2.start()
        model = DistributedModelForCausalLM.from_pretrained(
            d, RegistryClient("127.0.0.1", reg.port), model_uid="m",
            use_push=False,
        )
        drafter = GreedyTreeDrafter(
            LocalJaxDraftModel.from_dir(d), branching=(2, 2)
        )
        input_ids = np.arange(6)[None, :]
        out = await generate_speculative(
            model, drafter, input_ids, max_new_tokens=8,
            prune_threshold=0.45,
        )
        # let background training tasks drain
        await asyncio.sleep(0.5)
        mgr = server._pruner_manager
        trained = (
            mgr is not None
            and getattr(mgr, "neural_trainer", None) is not None
            and mgr.neural_trainer.steps > 0
        )
        await server.stop()
        await s2.stop()
        await reg.stop()
        return out, trained

    out, trained = asyncio.run(run())
    with torch.no_grad():
        ref = hf.generate(
            torch.tensor(np.arange(6)[None, :]), max_new_tokens=8,
            do_sample=False,
        ).numpy()
    np.testing.assert_array_equal(out, ref)
    assert trained, "neural pruner saw no online training steps"


def test_cap_kept_by_score_matches_rescan_reference():
    """The heap-driven cap must pick exactly the set the O(k^2) full
    leaf-rescan reference picks (including score ties), on random trees."""
    import numpy as np

    from bloombee_tpu.spec.pruner import _cap_kept_by_score
    from bloombee_tpu.spec.tree import DraftTree

    def rescan_reference(tree, keep, scores, cap):
        keep = keep.copy()
        t = tree.size
        while int(keep.sum()) > cap:
            kept_now = np.nonzero(keep)[0]
            has_kept_child = np.zeros(t, dtype=bool)
            for c in kept_now:
                parent = int(tree.parents[c])
                if parent >= 0:
                    has_kept_child[parent] = True
            leaves = kept_now[~has_kept_child[kept_now]]
            keep[int(leaves[int(np.argmin(scores[leaves]))])] = False
        return keep

    rng = np.random.default_rng(0)
    for trial in range(50):
        t = int(rng.integers(2, 40))
        parents = np.array(
            [-1] + [int(rng.integers(0, i)) for i in range(1, t)], np.int32
        )
        tree = DraftTree(tokens=np.arange(t), parents=parents)
        keep = rng.random(t) < 0.8
        keep[0] = True
        # quantized scores force plenty of exact ties
        scores = np.round(rng.random(t) * 4) / 4
        cap = int(rng.integers(1, t + 1))
        got = _cap_kept_by_score(tree, keep.copy(), scores, cap)
        want = rescan_reference(tree, keep, scores, cap)
        np.testing.assert_array_equal(got, want, err_msg=f"trial {trial}")
