"""Distributed training step: p-tuning over frozen blocks, full mesh.

The reference's training path optimizes client-held prompt embeddings and
head against frozen remote blocks (SURVEY.md section 3.4: blocks frozen,
gradients w.r.t. inputs and prompts only; client/ptune.py:21-80). Here the
same objective runs as ONE jitted SPMD program over a (dp, pp, tp, sp) mesh:

- dp: batch shards, loss gradients pmean'd across replicas
- pp: layers sharded into GPipe stages (parallel.pipeline)
- tp: head/ffn shards with psum reductions (parallel.spmd)
- sp: ring attention over sequence chunks (parallel.ring_attention)

Trainables: soft-prompt embeddings [n_prompt, D] + LM head. Frozen: all
block params + token embeddings.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bloombee_tpu.models.spec import ModelSpec
from bloombee_tpu.ops import rms_norm
from bloombee_tpu.parallel.pipeline import gpipe_forward
from bloombee_tpu.parallel.spmd import param_specs, shard_span_params


class Trainable(NamedTuple):
    prompts: jax.Array  # [n_prompt, D]
    lm_head: jax.Array  # [D, V]


class Frozen(NamedTuple):
    blocks: dict  # stacked span params [L, ...]
    embed: jax.Array  # [V, D]
    norm: jax.Array  # [D]


def _loss_fn(
    trainable: Trainable,
    frozen: Frozen,
    input_ids: jax.Array,  # [B, S]
    target_ids: jax.Array,  # [B, S] (already shifted; -100 = ignore)
    spec: ModelSpec,
    mesh: Mesh,
    num_micro: int,
):
    b, s = input_ids.shape
    n_prompt = trainable.prompts.shape[0]
    h = frozen.embed[input_ids]  # [B, S, D]
    h = jnp.concatenate(
        [jnp.broadcast_to(trainable.prompts[None], (b, n_prompt, h.shape[-1])), h],
        axis=1,
    )  # [B, P+S, D]

    mb = b // num_micro
    micro = h.reshape(num_micro, mb, n_prompt + s, -1)

    pipeline = jax.shard_map(
        functools.partial(
            gpipe_forward, spec=spec, pp_axis="pp", sp_axis="sp", tp_axis="tp"
        ),
        mesh=mesh,
        in_specs=(param_specs(frozen.blocks), P(None, "dp", "sp", None)),
        out_specs=P(None, "dp", "sp", None),
        check_vma=False,
    )
    out = pipeline(frozen.blocks, micro)  # [M, mb, P+S, D]
    out = out.reshape(b, n_prompt + s, -1)[:, n_prompt:]  # drop prompt outs

    out = rms_norm(out, frozen.norm, spec.rms_norm_eps)
    logits = (out @ trainable.lm_head).astype(jnp.float32)  # [B, S, V]
    logp = jax.nn.log_softmax(logits, axis=-1)
    mask = target_ids >= 0
    tgt = jnp.where(mask, target_ids, 0)
    token_lp = jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    loss = -(token_lp * mask).sum() / jnp.maximum(mask.sum(), 1)
    return loss


def make_train_step(spec: ModelSpec, mesh: Mesh, num_micro: int, lr: float = 0.1):
    """Returns jitted (trainable, frozen, input_ids, target_ids) ->
    (trainable', loss). SGD keeps the example self-contained; optax drops in
    for the optimizer state without changing the sharding story."""

    def step(trainable, frozen, input_ids, target_ids):
        loss, grads = jax.value_and_grad(_loss_fn)(
            trainable, frozen, input_ids, target_ids, spec, mesh, num_micro
        )
        new_t = Trainable(
            prompts=trainable.prompts - lr * grads.prompts,
            lm_head=trainable.lm_head - lr * grads.lm_head,
        )
        return new_t, loss

    # inputs arrive pre-placed (place_frozen / device_put); jit honors the
    # committed shardings and GSPMD propagates the rest
    return jax.jit(step)


def place_frozen(frozen: Frozen, mesh: Mesh) -> Frozen:
    """Shard the frozen pytree onto the mesh (blocks over pp/tp, embeddings
    replicated)."""
    rep = NamedSharding(mesh, P())
    return Frozen(
        blocks=shard_span_params(frozen.blocks, mesh),
        embed=jax.device_put(frozen.embed, rep),
        norm=jax.device_put(frozen.norm, rep),
    )
