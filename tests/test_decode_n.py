"""Server-side multi-step decode (decode_n): token-exactness + fallback.

The decode loop (runtime/decode_loop.py) must be token-identical to the
per-step client path on the same backend — it replaces N client round trips
with one jitted on-device loop, so any drift would silently change greedy
outputs. Reference analog: `_fast_generate_greedy`
(/root/reference/src/bloombee/client/remote_generation.py:286-386), which
this path beats by not round-tripping per token.
"""

import asyncio

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from bloombee_tpu.client.model import DistributedModelForCausalLM
from bloombee_tpu.client.session import DecodeNUnsupported
from bloombee_tpu.server.block_server import BlockServer
from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer


@pytest.fixture(scope="module")
def tiny_model_dir(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64,
        intermediate_size=128,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_hidden_layers=3,
        vocab_size=128,
        max_position_embeddings=256,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(1)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    d = tmp_path_factory.mktemp("tiny_llama_dn")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model, config


def _server(model_dir, registry, start, end, **kw):
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("num_pages", 64)
    kw.setdefault("page_size", 4)
    return BlockServer(
        model_uid="tiny", start=start, end=end, model_dir=model_dir,
        registry=registry, **kw,
    )


def _hf_greedy(model, input_ids, max_new_tokens):
    with torch.no_grad():
        out = model.generate(
            torch.tensor(input_ids), max_new_tokens=max_new_tokens,
            do_sample=False, use_cache=True,
        )
    return out.numpy()


def test_server_decode_matches_per_step_and_hf(tiny_model_dir):
    """Single full-model span: server_decode generate == per-step generate
    == HF greedy, across multiple decode_n chunks (chunk=4, 11 new tokens
    -> prefill token + chunks of 4, 4, 2)."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s1 = _server(model_dir, RegistryClient("127.0.0.1", reg.port), 0, 3)
        await s1.start()

        from bloombee_tpu.client.config import ClientConfig

        cfg = ClientConfig(server_decode=True, server_decode_chunk=4)
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, RegistryClient("127.0.0.1", reg.port),
            model_uid="tiny", config=cfg,
        )
        rng = np.random.default_rng(7)
        input_ids = rng.integers(0, config.vocab_size, size=(2, 5))
        ids_sd = await model.generate(input_ids, max_new_tokens=11)
        ids_ps = await model.generate(
            input_ids, max_new_tokens=11, server_decode=False
        )
        ref = _hf_greedy(hf_model, input_ids, 11)
        np.testing.assert_array_equal(ids_sd, ids_ps)
        np.testing.assert_array_equal(ids_sd, ref)

        await s1.stop()
        await reg.stop()

    asyncio.run(run())


def test_server_decode_chained_two_spans(tiny_model_dir):
    """A 2-server chain runs CHAINED decode_n: span 0 embeds + coordinates,
    the tail selects and pushes ids back — one client RTT per chunk. Must
    be token-exact vs HF greedy AND actually use the decode_n path."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s1 = _server(model_dir, RegistryClient("127.0.0.1", reg.port), 0, 2)
        s2 = _server(model_dir, RegistryClient("127.0.0.1", reg.port), 2, 3)
        await s1.start()
        await s2.start()

        from bloombee_tpu.client.config import ClientConfig

        cfg = ClientConfig(server_decode=True, server_decode_chunk=4)
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, RegistryClient("127.0.0.1", reg.port),
            model_uid="tiny", config=cfg,
        )
        rng = np.random.default_rng(3)
        input_ids = rng.integers(0, config.vocab_size, size=(1, 4))
        sess = model.inference_session(16, 1)
        await sess.__aenter__()
        assert len(sess._spans) == 2, "route must span both servers"
        ids = await model.generate(input_ids, max_new_tokens=6, session=sess)
        dn_steps = [t for t in sess.timings if t.get("decode_n")]
        await sess.__aexit__(None, None, None)
        assert dn_steps, "chained decode_n was not used (fell back?)"
        ref = _hf_greedy(hf_model, input_ids, 6)
        np.testing.assert_array_equal(ids, ref)

        await s1.stop()
        await s2.stop()
        await reg.stop()

    asyncio.run(run())


def test_chained_decode_three_spans_batched_eos(tiny_model_dir):
    """3-server chain (exercises a MIDDLE hop), batch of 2, session-level:
    chunked decode_n == manual per-step reference; EOS-finished rows clamp
    to eos exactly like the per-step loop."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        servers = [
            _server(model_dir, RegistryClient("127.0.0.1", reg.port), a, b)
            for a, b in ((0, 1), (1, 2), (2, 3))
        ]
        for s in servers:
            await s.start()
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, RegistryClient("127.0.0.1", reg.port), model_uid="tiny"
        )
        rng = np.random.default_rng(17)
        input_ids = rng.integers(0, config.vocab_size, size=(2, 4))

        # per-step reference tokens
        async with model.inference_session(16, 2) as sess:
            out = await sess.step(model.embed(input_ids), ids=input_ids)
            cur = np.argmax(model.logits(out[:, -1:])[:, 0], axis=-1)
            ref_toks = []
            for _ in range(5):
                out = await sess.step(
                    model.embed(cur[:, None]), ids=cur[:, None]
                )
                cur = np.argmax(model.logits(out[:, -1:])[:, 0], axis=-1)
                ref_toks.append(cur)
        ref_toks = np.stack(ref_toks, axis=1)  # [B, 5]

        async with model.inference_session(16, 2) as sess:
            assert len(sess._spans) == 3
            out = await sess.step(model.embed(input_ids), ids=input_ids)
            first = np.argmax(model.logits(out[:, -1:])[:, 0], axis=-1)
            t1 = await sess.decode_n(first, 3)
            t2 = await sess.decode_n(t1[:, -1], 2)
            assert sess.position == input_ids.shape[1] + 5
        np.testing.assert_array_equal(
            np.concatenate([t1, t2], axis=1), ref_toks
        )

        # finished rows emit only eos through the chain
        async with model.inference_session(16, 2) as sess:
            await sess.step(model.embed(input_ids), ids=input_ids)
            toks = await sess.decode_n(
                np.array([1, 2]), 4, eos_token_id=5,
                finished=np.array([True, True]),
            )
        np.testing.assert_array_equal(toks, np.full((2, 4), 5))

        for s in servers:
            await s.stop()
        await reg.stop()

    asyncio.run(run())


def test_chained_decode_dirty_fallback_on_tail_without_params(
    tiny_model_dir,
):
    """Tail server has no norm/head params: the chain declines with
    dirty=True after span 0 already committed a token; the client must
    rebuild-and-replay, continue per-step, and still match HF."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        from bloombee_tpu.models.checkpoint import load_span_params

        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s1 = _server(model_dir, RegistryClient("127.0.0.1", reg.port), 0, 2)
        params, spec = load_span_params(model_dir, 2, 3, dtype=jnp.float32)
        s2 = BlockServer(  # raw params: no model_dir => no head for tail
            model_uid="tiny", start=2, end=3, params=params, spec=spec,
            registry=RegistryClient("127.0.0.1", reg.port),
            compute_dtype=jnp.float32, num_pages=64, page_size=4,
        )
        await s1.start()
        await s2.start()

        from bloombee_tpu.client.config import ClientConfig

        cfg = ClientConfig(server_decode=True, server_decode_chunk=4)
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, RegistryClient("127.0.0.1", reg.port),
            model_uid="tiny", config=cfg,
        )
        rng = np.random.default_rng(23)
        input_ids = rng.integers(0, config.vocab_size, size=(1, 4))
        ids = await model.generate(input_ids, max_new_tokens=6)
        ref = _hf_greedy(hf_model, input_ids, 6)
        np.testing.assert_array_equal(ids, ref)

        await s1.stop()
        await s2.stop()
        await reg.stop()

    asyncio.run(run())


def test_chained_decode_mid_span_death_recovers(tiny_model_dir):
    """A middle server dies between decode_n chunks: the transient dirty
    decline must trigger rebuild-and-replay onto a replacement server and
    RETRY chained decode (not drop the fast path), staying token-exact."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s1 = _server(model_dir, rc(), 0, 1)
        s2 = _server(model_dir, rc(), 1, 2)
        s3 = _server(model_dir, rc(), 2, 3)
        for s in (s1, s2, s3):
            await s.start()
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny"
        )
        rng = np.random.default_rng(31)
        input_ids = rng.integers(0, config.vocab_size, size=(2, 4))

        # per-step reference
        async with model.inference_session(40, 2) as sref:
            out = await sref.step(model.embed(input_ids), ids=input_ids)
            cur = np.argmax(model.logits(out[:, -1:])[:, 0], axis=-1)
            ref_toks = []
            for _ in range(8):
                out = await sref.step(
                    model.embed(cur[:, None]), ids=cur[:, None]
                )
                cur = np.argmax(model.logits(out[:, -1:])[:, 0], axis=-1)
                ref_toks.append(cur)
        ref_toks = np.stack(ref_toks, axis=1)

        sess = model.inference_session(40, 2)
        await sess.__aenter__()
        out = await sess.step(model.embed(input_ids), ids=input_ids)
        first = np.argmax(model.logits(out[:, -1:])[:, 0], axis=-1)
        t1 = await sess.decode_n(first, 4)
        await s2.stop()  # kill the middle hop between chunks
        s2b = _server(model_dir, rc(), 1, 2)
        await s2b.start()
        t2 = await sess.decode_n(t1[:, -1], 4)  # must replay + retry chain
        dn = [t for t in sess.timings if t.get("decode_n")]
        await sess.__aexit__(None, None, None)
        assert len(dn) >= 2, "retry did not go back through decode_n"
        np.testing.assert_array_equal(
            np.concatenate([t1, t2], axis=1), ref_toks
        )

        for s in (s1, s2b, s3):
            await s.stop()
        await reg.stop()

    asyncio.run(run())


def test_local_stepped_decode_n_with_int4_kv(tiny_model_dir):
    """Single server with an int4 KV arena: the fused scan is ineligible
    but the host-driven stepped loop must serve decode_n anyway,
    token-exact vs the per-step path on the same (quantized) server."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s1 = _server(
            model_dir, RegistryClient("127.0.0.1", reg.port), 0, 3,
            kv_quant="int4",
        )
        await s1.start()
        assert s1._decode_n_ineligible() is not None  # fused declined

        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, RegistryClient("127.0.0.1", reg.port), model_uid="tiny"
        )
        rng = np.random.default_rng(29)
        input_ids = rng.integers(0, config.vocab_size, size=(2, 4))

        async with model.inference_session(16, 2) as sess:
            out = await sess.step(model.embed(input_ids), ids=input_ids)
            cur = np.argmax(model.logits(out[:, -1:])[:, 0], axis=-1)
            ref_toks = []
            for _ in range(4):
                out = await sess.step(
                    model.embed(cur[:, None]), ids=cur[:, None]
                )
                cur = np.argmax(model.logits(out[:, -1:])[:, 0], axis=-1)
                ref_toks.append(cur)
        ref_toks = np.stack(ref_toks, axis=1)

        async with model.inference_session(16, 2) as sess:
            out = await sess.step(model.embed(input_ids), ids=input_ids)
            first = np.argmax(model.logits(out[:, -1:])[:, 0], axis=-1)
            toks = await sess.decode_n(first, 4)
        np.testing.assert_array_equal(toks, ref_toks)

        await s1.stop()
        await reg.stop()

    asyncio.run(run())


def test_decode_n_session_level_exactness_and_eos(tiny_model_dir):
    """Direct session decode_n vs manual per-step loop: same tokens, same
    position; finished rows are clamped to eos."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s1 = _server(model_dir, RegistryClient("127.0.0.1", reg.port), 0, 3)
        await s1.start()

        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, RegistryClient("127.0.0.1", reg.port), model_uid="tiny"
        )
        rng = np.random.default_rng(11)
        input_ids = rng.integers(0, config.vocab_size, size=(2, 4))

        # per-step reference tokens
        async with model.inference_session(16, 2) as sess:
            out = await sess.step(model.embed(input_ids), ids=input_ids)
            cur = np.argmax(model.logits(out[:, -1:])[:, 0], axis=-1)
            ref_toks = []
            for _ in range(5):
                out = await sess.step(
                    model.embed(cur[:, None]), ids=cur[:, None]
                )
                cur = np.argmax(model.logits(out[:, -1:])[:, 0], axis=-1)
                ref_toks.append(cur)
        ref_toks = np.stack(ref_toks, axis=1)  # [B, 5]

        # decode_n in two chunks
        async with model.inference_session(16, 2) as sess:
            out = await sess.step(model.embed(input_ids), ids=input_ids)
            first = np.argmax(model.logits(out[:, -1:])[:, 0], axis=-1)
            t1 = await sess.decode_n(first, 3)
            t2 = await sess.decode_n(t1[:, -1], 2)
            assert sess.position == input_ids.shape[1] + 5
        np.testing.assert_array_equal(
            np.concatenate([t1, t2], axis=1), ref_toks
        )

        # finished rows emit only eos
        async with model.inference_session(16, 2) as sess:
            await sess.step(model.embed(input_ids), ids=input_ids)
            toks = await sess.decode_n(
                np.array([1, 2]), 4, eos_token_id=5,
                finished=np.array([True, True]),
            )
        np.testing.assert_array_equal(toks, np.full((2, 4), 5))

        await s1.stop()
        await reg.stop()

    asyncio.run(run())


def test_server_decode_eos_mid_chunk_and_session_reuse(tiny_model_dir):
    """EOS landing mid-chunk: output must truncate exactly where the
    per-step loop stops, and a REUSED session must see the same context
    (the over-run KV is rewound via rebuild-and-replay)."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s1 = _server(model_dir, RegistryClient("127.0.0.1", reg.port), 0, 3)
        await s1.start()

        from bloombee_tpu.client.config import ClientConfig

        cfg = ClientConfig(server_decode=True, server_decode_chunk=4)
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, RegistryClient("127.0.0.1", reg.port),
            model_uid="tiny", config=cfg,
        )
        rng = np.random.default_rng(13)
        x = rng.integers(0, config.vocab_size, size=(1, 4))
        # learn the greedy continuation; pick its 3rd new token as "eos" so
        # it lands mid-chunk (prefill token + chunk of 4 -> column 1)
        plain = await model.generate(x, max_new_tokens=8, server_decode=False)
        eos = int(plain[0, x.shape[1] + 2])

        ids_ps = await model.generate(
            x, max_new_tokens=8, eos_token_id=eos, server_decode=False
        )
        ids_sd = await model.generate(
            x, max_new_tokens=8, eos_token_id=eos, server_decode=True
        )
        np.testing.assert_array_equal(ids_sd, ids_ps)

        # two-turn session reuse: turn 1 stops at eos mid-chunk, turn 2
        # continues on the same session — both modes must agree
        y = rng.integers(0, config.vocab_size, size=(1, 3))

        async def two_turns(server_decode: bool):
            sess = model.inference_session(40, 1)
            async with sess:
                a1 = await model.generate(
                    x, max_new_tokens=8, eos_token_id=eos, session=sess,
                    server_decode=server_decode,
                )
                a2 = await model.generate(
                    y, max_new_tokens=5, session=sess,
                    server_decode=server_decode,
                )
            return a1, a2

        sd1, sd2 = await two_turns(True)
        ps1, ps2 = await two_turns(False)
        np.testing.assert_array_equal(sd1, ps1)
        np.testing.assert_array_equal(sd2, ps2)

        await s1.stop()
        await reg.stop()

    asyncio.run(run())


def test_decode_n_declined_without_client_params(tiny_model_dir):
    """A server built from raw params (no model_dir, no client_params) must
    decline decode_n instead of erroring the stream."""
    model_dir, _, config = tiny_model_dir

    async def run():
        from bloombee_tpu.models.checkpoint import load_span_params

        params, spec = load_span_params(model_dir, 0, 3, dtype=jnp.float32)
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s1 = BlockServer(
            model_uid="tiny", start=0, end=3, params=params, spec=spec,
            registry=RegistryClient("127.0.0.1", reg.port),
            compute_dtype=jnp.float32, num_pages=64, page_size=4,
        )
        await s1.start()

        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, RegistryClient("127.0.0.1", reg.port), model_uid="tiny"
        )
        async with model.inference_session(16, 1) as sess:
            ids = np.array([[3, 4, 5]])
            await sess.step(model.embed(ids), ids=ids)
            with pytest.raises(DecodeNUnsupported):
                await sess.decode_n(np.array([1]), 2)

        # generate(server_decode=True) against the declining server must
        # continue per-step on the same session (no double prefill) and
        # still match HF greedy
        from transformers import LlamaForCausalLM

        hf_model = LlamaForCausalLM.from_pretrained(model_dir).eval()
        rng = np.random.default_rng(5)
        input_ids = rng.integers(0, config.vocab_size, size=(2, 4))
        ids = await model.generate(
            input_ids, max_new_tokens=6, server_decode=True
        )
        ref = _hf_greedy(hf_model, input_ids, 6)
        np.testing.assert_array_equal(ids, ref)

        await s1.stop()
        await reg.stop()

    asyncio.run(run())
