"""Parallelism over the device mesh: tp / dp / sp / pp.

The reference's parallelism checklist (SURVEY.md section 2.8) mapped to
TPU-native constructs:

- tensor parallelism: Megatron-style sharded projections with explicit psum
  under shard_map (replaces FlexgenLlamaTensorParallel's per-device CUDA
  streams + NCCL all-reduce, flexgen_tensor_parallel.py:172-828) — rides ICI.
- sequence/context parallelism: ring attention over the "sp" axis (ppermute
  of KV blocks + online softmax) AND Ulysses all-to-all head/sequence
  exchange — the capability the reference LACKS (SURVEY.md section 5
  long-context) and handles only by host offload.
- data parallelism: batch sharding over "dp".
- pipeline parallelism: GPipe micro-batch schedule over the "pp" axis inside
  one jit (the swarm-level span pipeline remains inter-host over the wire).
"""

import jax as _jax

if not hasattr(_jax, "shard_map"):
    # jax < 0.6 API drift shim: package code and tests call
    # jax.shard_map(..., check_vma=False) (the current spelling); older
    # jax only ships jax.experimental.shard_map.shard_map with the
    # equivalent knob named check_rep. Install a top-level alias that
    # translates, so both jax versions run the same call sites.
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def _shard_map_compat(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _legacy_shard_map(*args, **kwargs)

    _jax.shard_map = _shard_map_compat

if not hasattr(_jax.lax, "axis_size"):
    # same drift: lax.axis_size is the current spelling; on older jax
    # psum of the literal 1 constant-folds to the static axis size (a
    # plain int, safe in Python control flow)

    def _axis_size_compat(axis_name):
        return _jax.lax.psum(1, axis_name)

    _jax.lax.axis_size = _axis_size_compat

from bloombee_tpu.parallel.mesh import make_mesh, MeshConfig
from bloombee_tpu.parallel.ring_attention import ring_attention
from bloombee_tpu.parallel.ulysses import ulysses_attention
from bloombee_tpu.parallel.spmd import (
    shard_span_params,
    spmd_block_forward,
    spmd_span_forward,
)

__all__ = [
    "make_mesh",
    "MeshConfig",
    "ring_attention",
    "ulysses_attention",
    "shard_span_params",
    "spmd_block_forward",
    "spmd_span_forward",
]
