"""Qwen3 family: Llama structure + per-head q/k RMSNorm + explicit head_dim.

Reference: /root/reference/src/bloombee/models/qwen3/ (WrappedQwen3Block).
152k vocab -> client-side head is the heavy part (README.md:103 note).
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp

from bloombee_tpu.models.auto import Family, register_family
from bloombee_tpu.models.llama.block import HF_BLOCK_KEYS, convert_hf_block_params
from bloombee_tpu.models.spec import ModelSpec


def qwen3_spec_from_hf(config: Any) -> ModelSpec:
    return ModelSpec(
        family="qwen3",
        hidden_size=config.hidden_size,
        intermediate_size=config.intermediate_size,
        num_attention_heads=config.num_attention_heads,
        num_key_value_heads=config.num_key_value_heads,
        head_dim=getattr(config, "head_dim", None)
        or config.hidden_size // config.num_attention_heads,
        num_hidden_layers=config.num_hidden_layers,
        vocab_size=config.vocab_size,
        rms_norm_eps=config.rms_norm_eps,
        rope_theta=getattr(config, "rope_theta", 1000000.0),
        tie_word_embeddings=getattr(config, "tie_word_embeddings", False),
        qk_norm=True,
    )


def _load_block(reader, layer_idx: int, dtype=None) -> dict:
    prefix = f"model.layers.{layer_idx}"
    tensors = {k: reader.tensor(f"{prefix}.{k}") for k in HF_BLOCK_KEYS}
    params = convert_hf_block_params(tensors, dtype=dtype)
    for name in ("q_norm", "k_norm"):
        w = jnp.asarray(reader.tensor(f"{prefix}.self_attn.{name}.weight"))
        params[name] = w.astype(dtype) if dtype is not None else w
    return params


register_family(
    Family("qwen3", qwen3_spec_from_hf, HF_BLOCK_KEYS, loader=_load_block)
)
