"""CLI surface end-to-end: run_registry + run_server as REAL subprocesses
(the documented deployment flow), then a client generate and the health
probe against them. The reference's equivalent is the manual live-swarm
tier (SURVEY.md §4: run_dht + run_server processes + pytest)."""

import asyncio
import socket
import subprocess
import sys
import time

import numpy as np
import torch


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


_BOOT = (
    "import jax; jax.config.update('jax_platforms', 'cpu'); "
    "from bloombee_tpu.cli.{mod} import main; main({args!r})"
)


def _spawn(mod: str, args: list[str], log_path) -> subprocess.Popen:
    # log to a FILE, not a pipe: an undrained pipe blocks a chatty child
    # after ~64KB and stalls the swarm
    log = open(log_path, "w")
    proc = subprocess.Popen(
        [sys.executable, "-c", _BOOT.format(mod=mod, args=args)],
        stdout=log,
        stderr=subprocess.STDOUT,
        text=True,
    )
    proc._log_path = log_path
    return proc


def _wait_port(port: int, timeout: float = 60.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=1):
                return
        except OSError:
            time.sleep(0.2)
    raise TimeoutError(f"port {port} never came up")


def test_cli_registry_server_client_health(tmp_path):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=2, vocab_size=128,
        max_position_embeddings=128, tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = LlamaForCausalLM(config).eval().to(torch.float32)
    d = str(tmp_path / "model")
    hf.save_pretrained(d, safe_serialization=True)

    reg_port = _free_port()
    procs = [
        _spawn("run_registry",
               ["--host", "127.0.0.1", "--port", str(reg_port)],
               tmp_path / "registry.log"),
    ]
    try:
        _wait_port(reg_port)  # registry must accept before servers announce
        for blocks in ("0:1", "1:2"):
            procs.append(
                _spawn(
                    "run_server",
                    [d, "--model-uid", "tiny", "--registry",
                     f"127.0.0.1:{reg_port}", "--blocks", blocks,
                     "--host", "127.0.0.1", "--public-host", "127.0.0.1",
                     "--num-pages", "32", "--page-size", "4",
                     "--dtype", "float32", "--warmup-batches", ""],
                    tmp_path / f"server{blocks.replace(':', '-')}.log",
                )
            )

        def _logs() -> str:
            return "\n".join(
                f"--- {p._log_path} ---\n"
                + open(p._log_path).read()[-2000:]
                for p in procs
            )

        # wait until the swarm covers both blocks
        from bloombee_tpu.swarm.registry import RegistryClient

        async def wait_complete():
            client = RegistryClient("127.0.0.1", reg_port)
            try:
                for _ in range(120):
                    for p in procs:
                        assert p.poll() is None, _logs()
                    try:
                        infos = await client.get_module_infos(
                            "tiny", range(2)
                        )
                        if all(mi.servers for mi in infos):
                            return
                    except Exception:
                        pass
                    await asyncio.sleep(0.5)
                raise TimeoutError(
                    "swarm never became complete\n" + _logs()
                )
            finally:
                await client.close()

        asyncio.run(wait_complete())

        # client generate through the CLI-launched swarm == HF greedy
        async def client_generate():
            from bloombee_tpu.client.model import DistributedModelForCausalLM

            reg_client = RegistryClient("127.0.0.1", reg_port)
            try:
                model = DistributedModelForCausalLM.from_pretrained(
                    d, reg_client, model_uid="tiny"
                )
                ids_in = np.arange(6)[None, :] % config.vocab_size
                return await model.generate(ids_in, max_new_tokens=5)
            finally:
                await reg_client.close()

        ids = asyncio.run(client_generate())
        with torch.no_grad():
            prompt = torch.tensor(np.arange(6)[None, :] % config.vocab_size)
            ref = hf.generate(
                prompt, attention_mask=torch.ones_like(prompt),
                max_new_tokens=5, do_sample=False,
            ).numpy()
        # HF may stop early at its eos token; the generated prefix must match
        assert ref.shape[1] > prompt.shape[1]
        np.testing.assert_array_equal(ids[:, : ref.shape[1]], ref)

        # ONE health invocation, in probe mode, after real traffic: sees
        # the complete swarm AND must surface the wire-path counters —
        # bytes shipped vs raw (the bytes/token floor) and the off-loop
        # codec pipeline state, the BB006 no-log-access operator surface
        probe = subprocess.run(
            [sys.executable, "-c",
             _BOOT.format(
                 mod="health",
                 args=["tiny", "--num-blocks", "2", "--registry",
                       f"127.0.0.1:{reg_port}", "--probe"],
             )],
            capture_output=True, text=True, timeout=60,
        )
        assert "COMPLETE" in probe.stdout, probe.stdout + probe.stderr
        assert "[reachable]" in probe.stdout, probe.stdout
        assert "tx_wire_bytes=" in probe.stdout, probe.stdout
        assert "pipeline=on" in probe.stdout, probe.stdout
        assert "rx_jobs=" in probe.stdout, probe.stdout
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
