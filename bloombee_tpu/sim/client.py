"""Virtual sessions: the client half of the simulated swarm.

Each session routes with a REAL ``RemoteSequenceManager`` — Dijkstra over
live spans with load-advert edge costs, fault bans, overload backoff with
retry-after floors, and half-open probes are all the production code.
The wire is a virtual RTT; the retry policy around it is the one
``client/session.py`` implements: shed → note_peer_overloaded + sleep the
server's retry-after hint; unreachable → ban_peer + immediate reroute;
no route at all → short fixed backoff and re-resolve.

Retry amplification — session-open attempts that actually REACHED a
server, divided by sessions — is measured HERE, because this loop is
where a mis-tuned retry hint turns one flash crowd into a permanent
stampede (the metastable failure the ``--require`` gate exists to
catch). Naive (gateway) sessions additionally model the classic
metastable amplifier: a client that gives up waiting for its first
token ABANDONS the attempt and retries, while the abandoned prefill
keeps burning on the server's queue — zombie work the next attempt
re-adds. The server's retry-after hint is the only thing pacing that
population.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging

from bloombee_tpu.client.sequence_manager import (
    MissingBlocksError,
    RemoteSequenceManager,
)
from bloombee_tpu.sim.node import SimOverloaded, SimUnreachable
from bloombee_tpu.utils import clock

logger = logging.getLogger(__name__)

NO_ROUTE_BACKOFF_S = 0.5  # re-resolve cadence while the span is dark
RETRY_HINT_CAP_S = 30.0  # mirror of the admission controller's cap
NAIVE_RETRY_FLOOR_S = 0.25  # a naive client's minimum re-try cadence —
# at or below the stock BBTPU_ADMIT_RETRY_MS, so it never masks a sane
# hint, while a mis-tuned 1ms hint still means 4 hammer-attempts/second
NAIVE_TTFT_TIMEOUT_S = 10.0  # gateway first-token timeout: past this a
# naive client abandons the attempt (leaving its queued prefill burning
# as zombie work) and retries


@dataclasses.dataclass
class SessionSpec:
    """One generated session: arrival, shape, and patience."""

    session_id: str
    client_id: str
    arrival_s: float  # virtual seconds from scenario start
    prompt_tokens: int
    decode_tokens: int
    shared_prefix_tokens: int = 0  # agent-loop system prompt: prefill
    # skips this many tokens (the prefix-cache hit the real client gets)
    patience_s: float = 120.0  # gives up past this age
    naive: bool = False  # True: a gateway/HTTP client with no SDK-side
    # penalty machinery — it honors ONLY the server's Retry-After hint.
    # This is the population whose retry storm a mis-tuned
    # BBTPU_ADMIT_RETRY_MS turns metastable (the SDK's overload-backoff
    # class floors the hint at seconds, so defended sessions cannot
    # expose that mis-tuning)


@dataclasses.dataclass
class SessionResult:
    spec: SessionSpec
    ttft_s: float | None = None
    tbts_s: list = dataclasses.field(default_factory=list)
    attempts: int = 0  # open attempts that reached a server (retry amp)
    no_route: int = 0  # route resolutions that found no live span
    abandons: int = 0  # naive first-token timeouts (zombie work left)
    sheds: int = 0
    failures: int = 0  # unreachable / mid-stream errors
    completed: bool = False
    gave_up: bool = False
    starved_with_capacity: bool = False
    finished_at: float | None = None


class SimSwarm:
    """Scenario-scoped world: servers by id, the shared registry, and the
    cost model (for wire RTTs)."""

    def __init__(self, registry, model_uid: str, num_blocks: int, cost):
        self.registry = registry
        self.model_uid = model_uid
        self.num_blocks = int(num_blocks)
        self.cost = cost
        self.servers: dict = {}
        self.zombies: list = []  # abandoned prefill awaiters (BB010:
        # handles kept; the queue work they observe burns on regardless)

    def add(self, server) -> None:
        self.servers[server.server_id] = server

    def adopt_zombie(self, task) -> None:
        task.add_done_callback(
            lambda t: None if t.cancelled() else t.exception()
        )
        self.zombies.append(task)

    def has_capacity_now(self) -> bool:
        """Every block is coverable by a live (possibly standby) server —
        the 'capacity existed' half of the starvation gate."""
        covered = [False] * self.num_blocks
        for s in self.servers.values():
            if s.reachable() and not s._draining:
                for b in range(s.start_block, s.end_block):
                    covered[b] = True
        return all(covered)

    def make_manager(self, rng=None, **kw) -> RemoteSequenceManager:
        """A real sequence manager wired for simulation: RTTs are
        pre-recorded from the cost model and pinned fresh (a virtual
        clock must never trigger the pinger's real-socket re-measure)."""
        sm = RemoteSequenceManager(
            self.registry, self.model_uid, self.num_blocks,
            update_period=2.0, rng=rng, **kw,
        )
        sm.pinger.stale_after = 1e18
        for sid in self.servers:
            sm.pinger.record(sid, self.cost.hop_rtt_ms / 1000.0)
        return sm


async def run_session(
    swarm: SimSwarm, sm: RemoteSequenceManager, spec: SessionSpec,
) -> SessionResult:
    """Drive one session to completion, giving up past its patience."""
    res = SessionResult(spec=spec)
    await clock.async_sleep(spec.arrival_s)
    started = clock.monotonic()
    deadline = started + spec.patience_s
    tokens_out = 0
    last_token_at: float | None = None

    while tokens_out < spec.decode_tokens:
        if clock.monotonic() >= deadline:
            res.gave_up = True
            res.starved_with_capacity = swarm.has_capacity_now()
            break

        # ---------------------------------------------- route + open
        try:
            await sm.update()
            route = sm.make_sequence(0, swarm.num_blocks)
        except MissingBlocksError:
            res.no_route += 1
            await clock.async_sleep(NO_ROUTE_BACKOFF_S)
            continue
        res.attempts += 1
        opened = []
        try:
            for span in route:
                server = swarm.servers[span.peer_id]
                server.open_session(spec.session_id, spec.client_id)
                opened.append(server)
            # ------------------------------------------ prefill + decode
            # replays skip nothing: a failed stream re-prefills its whole
            # prompt on the new route (that replay IS the amplification)
            prefill_tokens = max(
                1, spec.prompt_tokens - spec.shared_prefix_tokens
            )
            if spec.naive:
                if not await _prefill_or_abandon(
                    swarm, opened, spec, prefill_tokens, started, res
                ):
                    continue  # gateway auto-retry; sheds pace the rest
            else:
                for server in opened:
                    await server.prefill(
                        spec.session_id, spec.client_id, prefill_tokens,
                        started,
                    )
            while tokens_out < spec.decode_tokens:
                if clock.monotonic() >= deadline:
                    res.gave_up = True
                    res.starved_with_capacity = swarm.has_capacity_now()
                    break
                await clock.async_sleep(
                    swarm.cost.hop_rtt_ms / 1000.0 * len(route)
                )
                for server in opened:
                    await server.decode_step(
                        spec.session_id, spec.client_id
                    )
                tokens_out += 1
                now = clock.monotonic()
                if res.ttft_s is None:
                    res.ttft_s = now - started
                elif last_token_at is not None:
                    res.tbts_s.append(now - last_token_at)
                last_token_at = now
            else:
                res.completed = True
                for server in opened:
                    sm.note_peer_ok(server.server_id)
            break
        except SimOverloaded as e:
            res.sheds += 1
            retry_s = min(e.retry_after_ms / 1000.0, RETRY_HINT_CAP_S)
            if spec.naive:
                await clock.async_sleep(max(retry_s, NAIVE_RETRY_FLOOR_S))
            else:
                sm.note_peer_overloaded(_culprit(opened, route), retry_s)
                await clock.async_sleep(retry_s)
        except SimUnreachable:
            res.failures += 1
            if spec.naive:
                await clock.async_sleep(NO_ROUTE_BACKOFF_S)
            else:
                dead = [s.server_id for s in opened if not s.reachable()]
                sm.ban_peer(dead[0] if dead else _culprit(opened, route))
                await sm.update(force=True)
        except asyncio.CancelledError:
            # compute died under us (server crash mid-dispatch) — for the
            # session that is an unreachable peer, not a cancellation of
            # the session itself (which the engine never issues mid-run)
            dead = [s.server_id for s in opened if not s.reachable()]
            if not dead:
                raise
            res.failures += 1
            if not spec.naive:
                sm.ban_peer(dead[0])
                await sm.update(force=True)
        finally:
            for server in opened:
                server.close_session(spec.session_id)

    res.finished_at = clock.monotonic()
    return res


async def _prefill_or_abandon(
    swarm: SimSwarm, opened: list, spec: SessionSpec,
    prefill_tokens: int, started: float, res: SessionResult,
) -> bool:
    """Prefill with a naive client's first-token patience: past
    ``NAIVE_TTFT_TIMEOUT_S`` the client walks away and retries, but the
    prefill it queued is NOT cancelled — the server burns that compute
    for nobody (zombie work). That wasted work re-feeding the very queue
    that delays it is the textbook metastable-failure amplifier; the
    admission retry-after hint is what keeps the walked-away population
    from re-entering in sync."""

    async def all_spans() -> None:
        for server in opened:
            await server.prefill(
                spec.session_id, spec.client_id, prefill_tokens, started
            )

    pf = asyncio.ensure_future(all_spans())
    timer = asyncio.ensure_future(
        clock.async_sleep(NAIVE_TTFT_TIMEOUT_S)
    )
    done, _ = await asyncio.wait(
        {pf, timer}, return_when=asyncio.FIRST_COMPLETED
    )
    if pf in done:
        timer.cancel()
        pf.result()  # propagate shed/unreachable to the retry handlers
        return True
    res.abandons += 1
    swarm.adopt_zombie(pf)
    return False


def _culprit(opened: list, route: list) -> str:
    """The peer a failure/shed is charged to: the first hop that had not
    yet finished opening/serving, else the last opened one."""
    if len(opened) < len(route):
        return route[len(opened)].peer_id
    return opened[-1].server_id if opened else route[-1].peer_id
