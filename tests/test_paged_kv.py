"""Paged KV table invariants.

Ports of /root/reference/tests/test_paged_kv.py semantics: page accounting,
commit/rollback freeing orphaned pages, clamped committed reads, and
slab-write/dense-concat byte equivalence (test_phase0_cache_write_parity).
"""

import numpy as np
import pytest

from bloombee_tpu.kv.paged import OutOfPages, PagedKVTable


def test_page_accounting():
    t = PagedKVTable(num_pages=4, page_size=4)
    t.add_seq(0)
    assert t.free_pages == 4
    t.assign_write_slots(0, 5)  # 2 pages
    assert t.free_pages == 2
    t.add_seq(1)
    t.assign_write_slots(1, 8)  # 2 pages
    assert t.free_pages == 0
    with pytest.raises(OutOfPages):
        t.assign_write_slots(0, 4)  # would need a 3rd page
    t.drop_seq(1)
    assert t.free_pages == 2
    t.assign_write_slots(0, 4)
    assert t.seq(0).l_acc == 9


def test_slots_are_page_linear():
    t = PagedKVTable(num_pages=8, page_size=4)
    t.add_seq(0)
    slots = t.assign_write_slots(0, 6)
    pages = t.seq(0).pages
    expect = [pages[0] * 4 + i for i in range(4)] + [
        pages[1] * 4 + i for i in range(2)
    ]
    assert slots.tolist() == expect


def test_speculative_rollback_frees_orphans():
    t = PagedKVTable(num_pages=8, page_size=4)
    t.add_seq(0)
    t.assign_write_slots(0, 4, commit=True)  # 1 page committed
    t.assign_write_slots(0, 6, commit=False)  # spec tokens span 2 more pages
    assert t.seq(0).l_seq == 10 and t.seq(0).l_acc == 4
    assert t.free_pages == 8 - 3
    t.rollback(0)
    assert t.seq(0).l_seq == 4 and t.seq(0).l_acc == 4
    assert t.free_pages == 7  # orphaned spec pages freed


def test_partial_commit_trims():
    t = PagedKVTable(num_pages=8, page_size=4)
    t.add_seq(0)
    t.assign_write_slots(0, 4, commit=True)
    t.assign_write_slots(0, 8, commit=False)
    t.commit(0, length=6)  # accept 2 of 8 speculative tokens
    st = t.seq(0)
    assert st.l_acc == st.l_seq == 6
    assert len(st.pages) == 2 and t.free_pages == 6
    with pytest.raises(ValueError):
        t.commit(0, length=10)  # beyond l_seq


def test_committed_write_must_follow_prefix():
    t = PagedKVTable(num_pages=8, page_size=4)
    t.add_seq(0)
    t.assign_write_slots(0, 2, commit=True)
    t.assign_write_slots(0, 2, commit=False)
    with pytest.raises(ValueError):
        t.assign_write_slots(0, 1, commit=True)  # spec gap in between


def test_page_table_and_clamped_lens():
    t = PagedKVTable(num_pages=8, page_size=4)
    t.add_seq(0)
    t.add_seq(1)
    t.assign_write_slots(0, 7, commit=True)
    t.assign_write_slots(1, 3, commit=True)
    t.assign_write_slots(1, 5, commit=False)
    pt = t.page_table([0, 1], max_pages=3)
    assert pt.shape == (2, 3)
    assert pt[0, :2].tolist() == t.seq(0).pages
    assert np.array_equal(
        t.context_lens([0, 1]), np.asarray([7, 8], dtype=np.int32)
    )
    assert np.array_equal(
        t.context_lens([0, 1], committed_only=True),
        np.asarray([7, 3], dtype=np.int32),
    )
    with pytest.raises(ValueError):
        t.page_table([0], max_pages=1)


def test_prefix_slots_clamped():
    t = PagedKVTable(num_pages=8, page_size=4)
    t.add_seq(0)
    s_committed = t.assign_write_slots(0, 5, commit=True)
    t.assign_write_slots(0, 3, commit=False)
    assert t.prefix_slots(0).tolist() == s_committed.tolist()
    assert len(t.prefix_slots(0, committed_only=False)) == 8


def test_paged_table_fuzz_against_model():
    """Randomized op sequences (write/commit/rollback/accept/drop) against a
    simple list-based model: page accounting, lengths, and prefix slot
    CONTENT mapping must always agree, and no page may be double-owned."""
    import numpy as np

    from bloombee_tpu.kv.paged import OutOfPages, PagedKVTable

    rng = np.random.default_rng(0)
    for trial in range(20):
        num_pages = int(rng.integers(4, 12))
        page_size = int(rng.integers(2, 6))
        table = PagedKVTable(num_pages, page_size)
        # model: sid -> (committed tokens list, speculative tokens list),
        # tokens are (value) with slot tracked via table's own mapping
        model: dict[int, tuple[list, list]] = {}
        slot_of: dict[tuple, int] = {}  # (sid, position) -> slot
        next_sid = 0
        for _ in range(200):
            op = rng.choice(
                ["add", "write", "commit", "rollback", "accept", "drop"]
            )
            if op == "add" or not model:
                table.add_seq(next_sid)
                model[next_sid] = ([], [])
                next_sid += 1
                continue
            sid = int(rng.choice(list(model)))
            acc, spec = model[sid]
            if op == "write":
                n = int(rng.integers(1, 2 * page_size))
                commit = bool(rng.integers(0, 2)) and not spec
                try:
                    slots = table.assign_write_slots(sid, n, commit=commit)
                except (OutOfPages, ValueError):
                    continue
                start = len(acc) + len(spec)
                for j, s in enumerate(slots):
                    slot_of[(sid, start + j)] = int(s)
                (acc if commit else spec).extend(range(start, start + n))
            elif op == "commit":
                table.commit(sid)
                acc.extend(spec)
                spec.clear()
            elif op == "rollback":
                table.rollback(sid)
                for p in spec:
                    slot_of.pop((sid, p), None)
                spec.clear()
            elif op == "accept":
                if not spec:
                    continue
                k = int(rng.integers(0, len(spec) + 1))
                # accept the first k spec tokens in place (no reorder here)
                table.accept(sid, k)
                for p in spec[k:]:
                    slot_of.pop((sid, p), None)
                acc.extend(spec[:k])
                spec.clear()
            elif op == "drop":
                table.drop_seq(sid)
                for p in list(acc) + list(spec):
                    slot_of.pop((sid, p), None)
                del model[sid]
                continue
            # invariants after every op
            st = table.seq(sid)
            assert st.l_acc == len(acc), (trial, op)
            assert st.l_seq == len(acc) + len(spec), (trial, op)
            # committed prefix slots stable: positions map to the SAME
            # slots they were written to
            pref = table.prefix_slots(sid, committed_only=True)
            assert len(pref) == len(acc)
            for j, s in enumerate(pref):
                assert slot_of[(sid, j)] == int(s), (trial, op, j)
        # global invariant: live pages + free pages == num_pages and no
        # page double-owned
        owned = [p for s in model for p in table.seq(s).pages]
        assert len(owned) == len(set(owned))
        assert len(owned) + table.free_pages == num_pages


def test_prefix_cache_fuzz_page_accounting():
    """Randomized op sequences INCLUDING the prefix-cache ops (hash
    publication, adoption, copy-on-write, LRU eviction, pool invalidation):
    after EVERY op, free + referenced + cached == num_pages, the pool and
    its inverse index agree exactly, and every page owned by a live
    sequence holds a positive refcount."""
    from bloombee_tpu.kv.prefix import page_hash_chain

    rng = np.random.default_rng(3)
    for trial in range(15):
        num_pages = int(rng.integers(6, 16))
        page_size = int(rng.integers(2, 5))
        table = PagedKVTable(num_pages, page_size)
        if rng.integers(0, 2):
            table.max_cached_pages = int(rng.integers(1, num_pages))
        # a small prompt set so adoptions genuinely hit pooled pages
        prompts = [
            rng.integers(
                0, 50, size=int(rng.integers(page_size, 6 * page_size))
            ).tolist()
            for _ in range(3)
        ]
        chains = [page_hash_chain(p, page_size) for p in prompts]
        live: list[int] = []
        next_sid = 0

        def check(op, table=table, live=live, num_pages=num_pages,
                  trial=trial):
            c = table.counts()
            assert (
                c["free"] + c["referenced"] + c["cached"] == num_pages
            ), (trial, op, c)
            assert table.free_pages == c["free"] + c["cached"], (trial, op)
            assert (
                {p: h for h, p in table._pool.items()} == table._page_hash
            ), (trial, op)
            owned = [p for s in live for p in table.seq(s).pages]
            for p in owned:
                assert table._ref[p] > 0, (trial, op, p)
            # cached (LRU) pages are refcount-0 and published
            for p in table._lru:
                assert table._ref[p] == 0 and p in table._page_hash, (
                    trial, op, p,
                )

        for _ in range(300):
            op = str(rng.choice(
                ["add", "adopt", "write", "write", "commit", "rollback",
                 "accept", "drop", "trim", "invalidate"]
            ))
            if op == "invalidate" and rng.integers(0, 4):
                op = "write"  # keep invalidation rare
            if op in ("add", "adopt") or not live:
                table.add_seq(next_sid)
                if op == "adopt" or rng.integers(0, 2):
                    ci = int(rng.integers(0, len(chains)))
                    if op == "adopt":
                        table.adopt_prefix(next_sid, chains[ci])
                    else:
                        table.set_seq_hashes(next_sid, chains[ci])
                live.append(next_sid)
                next_sid += 1
                check(op)
                continue
            sid = int(rng.choice(live))
            st = table.seq(sid)
            if op == "write":
                n = int(rng.integers(1, 2 * page_size))
                commit = bool(rng.integers(0, 2))
                try:
                    table.assign_write_slots(sid, n, commit=commit)
                except (OutOfPages, ValueError):
                    pass
            elif op == "commit":
                if rng.integers(0, 2) and st.l_seq > st.l_acc:
                    table.commit(
                        sid, int(rng.integers(st.l_acc, st.l_seq + 1))
                    )
                else:
                    table.commit(sid)
            elif op == "rollback":
                table.rollback(sid)
            elif op == "accept":
                spec = st.l_seq - st.l_acc
                if spec:
                    table.accept(sid, int(rng.integers(0, spec + 1)))
            elif op == "trim":
                if st.l_acc:
                    table.trim_adopted(
                        sid, int(rng.integers(0, st.l_acc + 1))
                    )
            elif op == "invalidate":
                table.invalidate_pool()
            elif op == "drop":
                table.drop_seq(sid)
                live.remove(sid)
            if rng.integers(0, 8) == 0:
                table.take_pending_copies()
            check(op)
        # teardown releases everything back: nothing may leak
        for sid in list(live):
            table.drop_seq(sid)
            live.remove(sid)
        table.invalidate_pool()
        c = table.counts()
        assert c == {
            "free": num_pages, "referenced": 0, "cached": 0,
        }, (trial, c)


def test_lease_park_fuzz_page_accounting():
    """Randomized park/unpark/purge/evict sequences — the session-lease
    cached-park lifecycle. After EVERY op free + referenced + cached ==
    num_pages (a double-free or a leaked page breaks the sum), a failed
    unpark pins nothing, purging twice frees nothing twice, and final
    teardown returns every page exactly once."""
    from bloombee_tpu.kv.prefix import page_hash_chain

    rng = np.random.default_rng(7)
    for trial in range(15):
        num_pages = int(rng.integers(6, 16))
        page_size = int(rng.integers(2, 5))
        table = PagedKVTable(num_pages, page_size)
        live: list[int] = []
        parked: dict[int, tuple[list[str], int]] = {}
        next_sid = 0

        def check(op, table=table, live=live, parked=parked,
                  num_pages=num_pages, trial=trial):
            c = table.counts()
            assert (
                c["free"] + c["referenced"] + c["cached"] == num_pages
            ), (trial, op, c)
            for s in live:
                for p in table.seq(s).pages:
                    assert table._ref[p] > 0, (trial, op, p)
            # a parked sequence pins nothing: its pages are all pool-side
            for s in parked:
                assert not table.seq(s).pages, (trial, op, s)

        for _ in range(300):
            op = str(rng.choice(
                ["add", "write", "write", "park", "unpark", "purge",
                 "pressure", "drop"]
            ))
            if op == "add" or not (live or parked):
                table.add_seq(next_sid)
                if rng.integers(0, 2):
                    prompt = rng.integers(
                        0, 50, size=int(rng.integers(1, 4)) * page_size
                    ).tolist()
                    table.set_seq_hashes(
                        next_sid, page_hash_chain(prompt, page_size)
                    )
                live.append(next_sid)
                next_sid += 1
            elif op == "write" and live:
                sid = int(rng.choice(live))
                n = int(rng.integers(1, 2 * page_size))
                try:
                    table.assign_write_slots(
                        sid, n, commit=bool(rng.integers(0, 2))
                    )
                except (OutOfPages, ValueError):
                    pass
            elif op == "park" and live:
                sid = int(rng.choice(live))
                # the lease layer rolls speculative tokens back first
                table.rollback(sid)
                parked[sid] = table.park_seq_cached(sid)
                live.remove(sid)
            elif op == "unpark" and parked:
                sid = int(rng.choice(list(parked)))
                keys, l_acc = parked[sid]
                before = table.counts()
                if table.unpark_seq_cached(sid, keys, l_acc):
                    del parked[sid]
                    live.append(sid)
                    assert table.seq(sid).l_acc == l_acc, (trial, sid)
                else:
                    # all-or-nothing: a failed resume pinned NOTHING
                    assert table.counts() == before, (trial, sid)
                    table.purge_parked(keys)
                    table.drop_seq(sid)
                    del parked[sid]
            elif op == "purge" and parked:
                # lease reap: synthetic entries free exactly once, and a
                # second purge of the same keys is a no-op
                sid = int(rng.choice(list(parked)))
                keys, _ = parked.pop(sid)
                table.purge_parked(keys)
                assert table.purge_parked(keys) == 0, (trial, sid)
                table.drop_seq(sid)
            elif op == "pressure":
                # allocation pressure: a large write may evict parked
                # (cached, refcount-0) pages — they must never OOM the
                # table while genuinely-free pages could satisfy a write
                table.add_seq(next_sid)
                try:
                    table.assign_write_slots(
                        next_sid,
                        int(rng.integers(1, num_pages + 1)) * page_size,
                        commit=True,
                    )
                except OutOfPages:
                    pass
                table.drop_seq(next_sid)
                next_sid += 1
            elif op == "drop" and live:
                sid = int(rng.choice(live))
                table.drop_seq(sid)
                live.remove(sid)
            check(op)

        # teardown: reap the parked, drop the live — nothing may leak
        for sid in list(live):
            table.drop_seq(sid)
        for sid, (keys, _) in list(parked.items()):
            table.purge_parked(keys)
            table.drop_seq(sid)
        assert table.counts()["referenced"] == 0, (trial, table.counts())
        table.invalidate_pool()
        assert table.counts() == {
            "free": num_pages, "referenced": 0, "cached": 0,
        }, (trial, table.counts())


def test_prefix_adopt_cow_and_eviction():
    """Directed coverage of the sharing lifecycle: publish -> adopt
    (refcount pin) -> copy-on-write on divergence -> LRU eviction under
    pressure."""
    from bloombee_tpu.kv.prefix import page_hash_chain

    t = PagedKVTable(num_pages=8, page_size=4)
    ids = list(range(12))  # 3 full pages
    chain = page_hash_chain(ids, 4)
    t.add_seq(0)
    t.set_seq_hashes(0, chain)
    t.assign_write_slots(0, 12, commit=True)
    assert t.cached_pages == 0  # still referenced by seq 0
    assert t.match_prefix(chain) == 12

    # adoption pins the pages (ref 2) and starts committed at 12 tokens
    t.add_seq(1)
    assert t.adopt_prefix(1, chain) == 12
    assert t.seq(1).pages == t.seq(0).pages
    assert t.seq(1).l_acc == 12

    # trim to 9: still 3 pages (page 2 now partially covered, still shared)
    t.trim_adopted(1, 9)
    assert t.seq(1).l_acc == 9 and len(t.seq(1).pages) == 3
    # writing token 9 lands inside shared page 2 -> copy-on-write
    before = t.seq(1).pages[2]
    t.assign_write_slots(1, 1, commit=True)
    assert t.cow_count == 1
    assert t.seq(1).pages[2] != before
    assert t.take_pending_copies() == [(before, t.seq(1).pages[2])]
    # seq 0's view of the shared page is untouched
    assert t.seq(0).pages[2] == before

    # dropping both: published pages park in the LRU, private pages free
    t.drop_seq(0)
    t.drop_seq(1)
    c = t.counts()
    assert c["referenced"] == 0
    assert c["free"] + c["cached"] == 8
    assert t.cached_pages >= 3  # the 3 published prompt pages survive
    assert t.match_prefix(chain) == 12

    # allocation pressure evicts from the LRU cold end once _free runs dry
    t.add_seq(2)
    t.assign_write_slots(2, 8 * 4, commit=True)  # every page in the arena
    assert t.cached_pages == 0
    assert t.match_prefix(chain) == 0
    t.drop_seq(2)


def test_combine_handles_ragged_write_fuzz():
    """Property-fuzz the mixed-batch KV path: a combined handle over
    several live sessions takes HETEROGENEOUS per-sequence token counts
    (decode members write 1, the chunk member writes many) through
    write_slots_ragged, then randomly commits or truncate_speculative's
    back to the pre-dispatch snapshot. After every round the per-sequence
    lengths match a list-based model, the flat slots are sequence-major
    and agree with the table's own range mapping, no page is double-owned,
    and a failed (OutOfPages) ragged write mutates NOTHING."""
    import asyncio
    import contextlib

    import jax.numpy as jnp

    from bloombee_tpu.kv.cache_manager import CacheManager
    from bloombee_tpu.kv.paged import OutOfPages

    async def run():
        rng = np.random.default_rng(21)
        for trial in range(6):
            page_size = int(rng.integers(2, 6))
            # admission must always fit the 3 handles (up to 6 seqs of 16
            # tokens, page-rounded) or allocate() blocks forever; the writes
            # below still exhaust pages to hit the OutOfPages branch
            num_pages = 6 * (-(-16 // page_size)) + int(rng.integers(0, 8))
            manager = CacheManager(
                num_layers=1, num_pages=num_pages, page_size=page_size,
                n_kv_heads=1, head_dim=8, dtype=jnp.float32,
            )
            async with contextlib.AsyncExitStack() as stack:
                handles = [
                    await stack.enter_async_context(
                        manager.allocate(int(rng.integers(1, 3)), 16,
                                         timeout=10)
                    )
                    for _ in range(3)
                ]
                combined = manager.combine_handles(handles)
                assert combined.seq_ids == [
                    sid for h in handles for sid in h.seq_ids
                ]
                n = len(combined.seq_ids)
                table = manager.table
                model = {sid: [0, 0] for sid in combined.seq_ids}
                for _ in range(30):
                    snap = [model[sid][1] for sid in combined.seq_ids]
                    if rng.integers(0, 2):
                        # mixed-batch shape: all decodes + one fat chunk
                        counts = [1] * n
                        counts[int(rng.integers(0, n))] = int(
                            rng.integers(2, 3 * page_size)
                        )
                    else:
                        counts = [
                            int(c) for c in rng.integers(1, 6, size=n)
                        ]
                    before_free = table.free_pages
                    try:
                        slots = manager.write_slots_ragged(
                            combined, counts, commit=False
                        )
                    except OutOfPages:
                        # atomicity: a failed ragged write claims nothing
                        assert table.free_pages == before_free
                        for sid in combined.seq_ids:
                            st = table.seq(sid)
                            assert [st.l_acc, st.l_seq] == model[sid]
                        manager.truncate_speculative(
                            combined,
                            [model[sid][0] for sid in combined.seq_ids],
                        )
                        for sid in combined.seq_ids:
                            model[sid][1] = model[sid][0]
                        continue
                    assert len(slots) == sum(counts)
                    # sequence-major flat slots match the table's own
                    # per-sequence range mapping
                    off = 0
                    for sid, c in zip(combined.seq_ids, counts):
                        old = model[sid][1]
                        np.testing.assert_array_equal(
                            slots[off:off + c],
                            table.range_slots(sid, old, old + c),
                        )
                        model[sid][1] = old + c
                        off += c
                    action = rng.integers(0, 3)
                    if action == 0:  # dispatch succeeded: commit all
                        manager.commit(combined)
                        for sid in combined.seq_ids:
                            model[sid][0] = model[sid][1]
                    elif action == 1:  # dispatch failed: undo THIS write
                        manager.truncate_speculative(combined, snap)
                        for sid, ln in zip(combined.seq_ids, snap):
                            model[sid][1] = ln
                    # action == 2: leave speculative (mid-stream chunks)
                    np.testing.assert_array_equal(
                        manager.context_lens(combined),
                        [model[sid][1] for sid in combined.seq_ids],
                    )
                    np.testing.assert_array_equal(
                        manager.context_lens(combined, committed_only=True),
                        [model[sid][0] for sid in combined.seq_ids],
                    )
                    owned = [
                        p for sid in combined.seq_ids
                        for p in table.seq(sid).pages
                    ]
                    assert len(owned) == len(set(owned)), (trial, owned)
                    assert len(owned) + table.free_pages == num_pages
            # allocate() exit freed everything
            assert manager.table.free_pages == num_pages, trial

    asyncio.run(run())


def test_native_table_bit_identical_to_python():
    """The C++ table must be BIT-IDENTICAL to the Python table across random
    op sequences (same LIFO free-list order => same slots)."""
    import numpy as np
    import pytest

    from bloombee_tpu.kv.paged import OutOfPages, PagedKVTable
    from bloombee_tpu.kv.paged_native import NativePagedKVTable

    try:
        native = NativePagedKVTable(8, 4)
    except RuntimeError:
        pytest.skip("no C++ toolchain")
    rng = np.random.default_rng(7)
    for trial in range(10):
        py = PagedKVTable(10, 3)
        cc = NativePagedKVTable(10, 3)
        sids: list[int] = []
        next_sid = 0
        for _ in range(300):
            op = rng.choice(
                ["add", "write", "commit", "commit_len", "rollback",
                 "accept", "truncate", "drop"]
            )
            if op == "add" or not sids:
                py.add_seq(next_sid)
                cc.add_seq(next_sid)
                sids.append(next_sid)
                next_sid += 1
                continue
            sid = int(rng.choice(sids))
            if op == "write":
                n = int(rng.integers(1, 7))
                commit = bool(rng.integers(0, 2))
                res = []
                for t in (py, cc):
                    try:
                        res.append(("ok", t.assign_write_slots(
                            sid, n, commit=commit)))
                    except OutOfPages:
                        res.append(("oop", None))
                    except ValueError:
                        res.append(("val", None))
                assert res[0][0] == res[1][0], (trial, op)
                if res[0][0] == "ok":
                    np.testing.assert_array_equal(res[0][1], res[1][1])
            elif op == "commit":
                py.commit(sid)
                cc.commit(sid)
            elif op == "commit_len":
                st = py.seq(sid)
                if st.l_seq > st.l_acc:
                    ln = int(rng.integers(st.l_acc, st.l_seq + 1))
                    py.commit(sid, ln)
                    cc.commit(sid, ln)
            elif op == "rollback":
                py.rollback(sid)
                cc.rollback(sid)
            elif op == "accept":
                st = py.seq(sid)
                spec = st.l_seq - st.l_acc
                if spec:
                    k = int(rng.integers(0, spec + 1))
                    py.accept(sid, k)
                    cc.accept(sid, k)
            elif op == "truncate":
                # partial rollback (mixed-dispatch failure recovery): drop
                # spec tokens past a snapshot length, keep the ones below
                st = py.seq(sid)
                ln = int(rng.integers(st.l_acc, st.l_seq + 1))
                py.truncate_speculative(sid, ln)
                cc.truncate_speculative(sid, ln)
            elif op == "drop":
                py.drop_seq(sid)
                cc.drop_seq(sid)
                sids.remove(sid)
                continue
            # state must match exactly after every op
            assert py.free_pages == cc.free_pages, (trial, op)
            for s in sids:
                ps, cs = py.seq(s), cc.seq(s)
                assert (ps.l_acc, ps.l_seq, ps.pages) == (
                    cs.l_acc, cs.l_seq, cs.pages
                ), (trial, op, s)
                np.testing.assert_array_equal(
                    py.prefix_slots(s, committed_only=False),
                    cc.prefix_slots(s, committed_only=False),
                )
