"""Virtual clock substrate (bloombee_tpu/utils/clock.py).

The whole deterministic-chaos story rests on three promises: the default
RealClock is byte-for-byte stdlib time (production never changes), a
ScaledClock compresses every wait by a constant factor (soak tests), and
a SteppableClock is frozen until advance() — virtual waits complete in
zero wall time, in deadline order, on both the sync and asyncio sides.
"""

import asyncio
import threading
import time

import pytest

from bloombee_tpu.utils import clock
from bloombee_tpu.utils.clock import RealClock, ScaledClock, SteppableClock


@pytest.fixture(autouse=True)
def _restore_clock():
    yield
    clock.reset()


# ---------------------------------------------------------------- RealClock
def test_real_clock_is_stdlib_time():
    c = RealClock()
    assert abs(c.time() - time.time()) < 0.5
    assert abs(c.monotonic() - time.monotonic()) < 0.5
    t0 = time.perf_counter()
    c.sleep(0.01)
    assert time.perf_counter() - t0 >= 0.009


def test_default_install_is_real():
    clock.reset()
    assert isinstance(clock.get(), RealClock)
    assert abs(clock.now() - time.time()) < 0.5


def test_deadline_none_passthrough():
    assert clock.deadline(None) is None
    dl = clock.deadline(5.0)
    assert dl is not None and dl > clock.monotonic()


# -------------------------------------------------------------- ScaledClock
def test_scaled_clock_compresses_virtual_time():
    c = ScaledClock(scale=100.0)
    v0 = c.monotonic()
    time.sleep(0.05)
    advanced = c.monotonic() - v0
    # 0.05 real seconds ≈ 5 virtual seconds at 100x
    assert 2.0 < advanced < 60.0


def test_scaled_clock_divides_sleeps():
    c = ScaledClock(scale=50.0)
    t0 = time.perf_counter()
    c.sleep(1.0)  # 1 virtual second = 20ms real
    real = time.perf_counter() - t0
    assert real < 0.5


def test_scaled_clock_rejects_nonpositive_scale():
    with pytest.raises(ValueError):
        ScaledClock(scale=0.0)


def test_scaled_clock_async_sleep_compressed():
    c = ScaledClock(scale=50.0)

    async def run():
        t0 = time.perf_counter()
        await c.async_sleep(1.0)
        return time.perf_counter() - t0

    assert asyncio.run(run()) < 0.5


# ----------------------------------------------------------- SteppableClock
def test_steppable_clock_frozen_until_advanced():
    c = SteppableClock(start=1000.0)
    assert c.monotonic() == 1000.0
    # wall time is anchored at construction and advances ONLY by advance()
    w0 = c.time()
    time.sleep(0.02)
    assert c.time() == w0
    c.advance(12.5)
    assert c.monotonic() == 1012.5
    assert c.time() == w0 + 12.5


def test_steppable_sync_sleep_wakes_on_advance():
    c = SteppableClock()
    woke = threading.Event()

    def sleeper():
        c.sleep(10.0)
        woke.set()

    t = threading.Thread(target=sleeper, daemon=True)
    t.start()
    time.sleep(0.05)
    assert not woke.is_set(), "sleep returned without the clock moving"
    c.advance(9.0)
    time.sleep(0.05)
    assert not woke.is_set(), "woke before its deadline"
    c.advance(1.0)
    assert woke.wait(2.0), "advance past deadline did not wake the sleeper"
    t.join(2.0)


def test_steppable_async_sleep_wakes_in_deadline_order():
    c = SteppableClock()
    order = []

    async def run():
        async def napper(name, dt):
            await c.async_sleep(dt)
            order.append(name)

        tasks = [
            asyncio.ensure_future(napper("late", 5.0)),
            asyncio.ensure_future(napper("early", 1.0)),
        ]
        await asyncio.sleep(0.05)  # real: let both park on the heap
        assert order == []
        c.advance(10.0)
        await asyncio.wait_for(asyncio.gather(*tasks), 2.0)

    asyncio.run(run())
    assert order == ["early", "late"]


def test_steppable_advance_from_foreign_thread_wakes_async_sleeper():
    c = SteppableClock()

    async def run():
        task = asyncio.ensure_future(c.async_sleep(3.0))
        await asyncio.sleep(0.05)
        threading.Thread(target=lambda: c.advance(4.0), daemon=True).start()
        await asyncio.wait_for(task, 2.0)

    asyncio.run(run())


def test_steppable_perf_counter_stays_real():
    # measurement is NOT a timing decision: even a frozen clock reports
    # real perf_counter durations (throughput numbers must stay honest)
    c = SteppableClock()
    prev = clock.install(c)
    try:
        t0 = clock.perf_counter()
        time.sleep(0.01)
        assert clock.perf_counter() - t0 >= 0.009
        assert clock.monotonic() == c.monotonic()
    finally:
        clock.install(prev)


def test_steppable_cond_wait_times_out_virtually():
    c = SteppableClock()

    async def run():
        cond = asyncio.Condition()

        async def waiter():
            async with cond:
                try:
                    await c.cond_wait(cond, 5.0)
                except asyncio.TimeoutError:
                    return "timed_out"
                return "notified"

        task = asyncio.ensure_future(waiter())
        await asyncio.sleep(0.05)
        assert not task.done(), "cond_wait expired without virtual time"
        c.advance(6.0)
        assert await asyncio.wait_for(task, 2.0) == "timed_out"

    asyncio.run(run())


# -------------------------------------------------------- install machinery
def test_install_returns_previous_and_reset_restores_default():
    stepper = SteppableClock(start=7.0)
    prev = clock.install(stepper)
    try:
        assert clock.monotonic() == 7.0
    finally:
        restored = clock.install(prev)
        assert restored is stepper
    clock.reset()
    assert isinstance(clock.get(), RealClock)


def test_env_scale_builds_scaled_clock(monkeypatch):
    monkeypatch.setenv("BBTPU_CLOCK_SCALE", "25")
    clock.reset()  # pristine: next get() re-reads the env knob
    try:
        assert isinstance(clock.get(), ScaledClock)
        assert clock.get().scale == 25.0
    finally:
        monkeypatch.delenv("BBTPU_CLOCK_SCALE")
        clock.reset()
