"""Pallas flash attention vs dense reference (interpreter mode on CPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bloombee_tpu.ops.attention import causal_mask, masked_attention
from bloombee_tpu.ops.pallas.flash_attention import flash_attention


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("hkv", [4, 2, 1])
def test_flash_matches_dense(causal, hkv):
    b, t, h, hd = 2, 256, 4, 64
    rng = jax.random.PRNGKey(0)
    q = jax.random.normal(rng, (b, t, h, hd), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, t, hkv, hd), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, t, hkv, hd), jnp.float32)

    if causal:
        mask = causal_mask(t)[None]
    else:
        mask = jnp.ones((1, t, t), bool)
    ref = masked_attention(q, k, v, mask)

    out = flash_attention(
        q, k, v, causal=causal, block_q=64, block_k=64, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_flash_prefix_offset_matches_dense():
    """S > T: queries attend to a committed prefix plus themselves, with
    absolute positions offset by s - t (chunked-prefill shape)."""
    b, t, s, h, hkv, hd = 1, 64, 192, 4, 2, 32
    q = jax.random.normal(jax.random.PRNGKey(0), (b, t, h, hd), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, hkv, hd), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, hkv, hd), jnp.float32)
    ref = masked_attention(q, k, v, causal_mask(t, offset=s - t, s=s)[None])
    out = flash_attention(
        q, k, v, causal=True, block_q=32, block_k=32, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_flash_rejects_bad_shapes():
    q = jnp.zeros((1, 100, 2, 16))
    k = v = jnp.zeros((1, 100, 2, 16))
    with pytest.raises(ValueError):
        flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)
    q = jnp.zeros((1, 64, 4, 16))
    k = v = jnp.zeros((1, 64, 3, 16))
    with pytest.raises(ValueError):  # H not a multiple of Hkv
        flash_attention(q, k, v, interpret=True)
    q = jnp.zeros((1, 128, 4, 16))
    k = v = jnp.zeros((1, 64, 2, 16))
    with pytest.raises(ValueError):  # S < T
        flash_attention(q, k, v, interpret=True)
