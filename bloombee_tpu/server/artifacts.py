"""Swarm-shared compile-artifact cache: zero-cold-start recovery.

Elastic self-healing promotes a standby in milliseconds, but the standby
then pays the full warmup-compile bill before it serves at speed — on
real models that bill is minutes, and recovery speed IS availability in
a churning swarm. This module makes compiled executables travel the
swarm the same way KV pages already do: a server's warmed bucket set is
serialized through JAX's persistent compilation cache into a bounded
on-disk **artifact store**, every blob is content-addressed with a
blake2b digest, and a compatibility **fingerprint** (jax/jaxlib version,
backend, device topology, model spec hash, span, dtype, KV page
geometry) guards against installing executables compiled for a different
world. BlockServer exposes the store over ``artifact_get`` (manifest +
named-blob fetch) and pushes it to standbys alongside KV replication via
``artifact_put``; a standby or JOINing server pre-installs the blobs
before warmup, so warmup LOADS executables instead of compiling them
(jitwatch discriminates the two via the cache-retrieval monitoring
event and ``--require --preinstalled`` proves zero true warmup
compiles).

Robustness is the point, not a bolt-on: digest mismatches, fingerprint
mismatches, truncated blobs, and path-escaping names all DECLINE the
install and fall back to local compile (JAX itself treats a corrupt
cache entry as a miss — ``raise_persistent_cache_errors`` stays False —
so a bad blob can never crash the server or serve a wrong executable;
the cache key covers the HLO and compile options). Every fallback is
ledgered as ``server.artifact_fallback_compile`` so the chaos gate can
require the degraded path actually ran. The store is LRU-bounded by
``BBTPU_ARTIFACT_MAX_MB`` so standbys never fill the disk.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os

from bloombee_tpu.utils import env

logger = logging.getLogger(__name__)

env.declare(
    "BBTPU_ARTIFACT_DIR", str, "",
    "directory for the swarm-shared compile-artifact store (doubles as "
    "this process's JAX persistent compilation cache dir). Servers with "
    "a store serve artifact_get, push artifacts to standbys alongside "
    "KV replication, and pre-install fetched artifacts before warmup. "
    "Empty = artifact path off (compile locally, serve/fetch nothing)",
)
env.declare(
    "BBTPU_ARTIFACT_MAX_MB", int, 256,
    "on-disk cap for the artifact store in MiB; least-recently-used "
    "entries are evicted past it so standbys never fill the disk",
)
env.declare(
    "BBTPU_ARTIFACT_FETCH_TIMEOUT_S", float, 10.0,
    "per-peer timeout for one artifact_get call during pre-install; on "
    "timeout/death the fetch retries on the next covering peer, then "
    "falls back to local compile (ledgered)",
)

# only jax persistent-cache files are servable artifacts; anything else
# in the directory (tmp files, stray droppings) is invisible to the store
_SUFFIXES = ("-cache", "-atime")


def blob_digest(blob: bytes) -> str:
    """Content address for one artifact blob (also the wire integrity
    check: recomputed on every install)."""
    return hashlib.blake2b(bytes(blob), digest_size=16).hexdigest()


def fingerprint(spec, start: int, end: int, dtype: str,
                page_size: int) -> dict:
    """Compatibility fingerprint for a span's artifact set.

    Executables are only portable between processes that agree on all of
    this; anything less and a pre-installed blob could silently be a
    miss (harmless but pointless) or — across jaxlib versions — refuse
    to deserialize. The model spec rides as a blake2b hash of its full
    primitive field set, so two servers of different models never trade
    artifacts even over the same span indices.
    """
    import jax

    spec_src = json.dumps(
        dataclasses.asdict(spec), sort_keys=True, default=str
    )
    return {
        "jax": jax.__version__,
        "jaxlib": getattr(
            __import__("jaxlib"), "__version__", jax.__version__
        ),
        "backend": jax.default_backend(),
        "device_count": jax.device_count(),
        "spec_hash": hashlib.blake2b(
            spec_src.encode(), digest_size=16
        ).hexdigest(),
        "span": [int(start), int(end)],
        "dtype": str(dtype),
        "page_size": int(page_size),
    }


def fingerprint_compatible(mine: dict, theirs: dict) -> str | None:
    """None when compatible, else the first mismatching key (the decline
    reason surfaced in counters/logs)."""
    for key in ("jax", "jaxlib", "backend", "device_count", "spec_hash",
                "dtype", "page_size"):
        if mine.get(key) != theirs.get(key):
            return key
    # spans need not be identical — a covering peer's span is a superset
    # of the fetcher's — but they must overlap the fetcher's span, else
    # the artifacts are for someone else's layers entirely
    ms, me = (mine.get("span") or [0, 0])[:2]
    ts, te = (theirs.get("span") or [0, 0])[:2]
    if not (int(ts) <= int(ms) and int(me) <= int(te)):
        return "span"
    return None


def enable_persistent_cache(path: str) -> bool:
    """Point JAX's persistent compilation cache at the artifact store
    (idempotent; safe to call with a new dir mid-process — config is
    re-read per compile). Thresholds drop to zero so every executable
    lands in the store, not just the slow ones."""
    try:
        import jax
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc,
        )

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        # the default XLA-caches integration bakes an autotune-cache PATH
        # (derived from the cache dir) into every compile's options — and
        # the options are hashed into the cache key, so artifacts keyed
        # under one store dir could NEVER hit from another server's
        # store. Swarm portability requires dir-independent keys.
        jax.config.update("jax_persistent_cache_enable_xla_caches", "none")
        # the cache OBJECT latches on first use: a compile that ran before
        # any dir was configured disables it for the process, and a dir
        # change after first use is silently ignored — reset so the next
        # compile re-initializes against the dir just configured
        _cc.reset_cache()
        return True
    except Exception as e:  # cache is an optimization, never a crash
        logger.warning("persistent compile cache unavailable: %s", e)
        return False


def _safe_name(name: str) -> bool:
    """Artifact names are flat jax cache-file names; anything that could
    escape the store directory (separators, drive letters, dot-dirs) is
    rejected before it reaches the filesystem."""
    if not name or len(name) > 512:
        return False
    if name.startswith("."):
        return False
    if "/" in name or "\\" in name or ".." in name or ":" in name:
        return False
    return True


class ArtifactStore:
    """Bounded on-disk artifact store over one directory (the same dir
    the process's JAX persistent cache writes to, so locally-compiled
    executables become servable artifacts with no extra step).

    Not thread-safe by design: all callers run on the server's asyncio
    loop. Crash-safe installs (tmp + rename) mean a concurrent reader
    in another process never sees a torn blob.
    """

    def __init__(self, root: str, max_mb: int | None = None):
        self.root = root
        if max_mb is None:
            max_mb = env.get("BBTPU_ARTIFACT_MAX_MB")
        self.max_bytes = max(1, int(max_mb)) * 2**20
        self.evictions = 0
        self.declined = 0
        os.makedirs(root, exist_ok=True)

    # ------------------------------------------------------------- reads
    def _entries(self) -> list[tuple[str, int, float]]:
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for name in names:
            if not _safe_name(name) or not name.endswith(_SUFFIXES):
                continue
            path = os.path.join(self.root, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            out.append((name, st.st_size, st.st_mtime))
        return out

    def total_bytes(self) -> int:
        return sum(size for _, size, _ in self._entries())

    def stats(self) -> dict:
        """Operator-visible store gauges (surfaced through rpc_info as
        artifact_store_bytes / artifact_evictions /
        artifact_store_declined)."""
        return {
            "bytes": self.total_bytes(),
            "max_bytes": self.max_bytes,
            "entries": len(self._entries()),
            "evictions": self.evictions,
            "declined": self.declined,
        }

    def manifest(self) -> list[dict]:
        """Digest-stamped listing of every servable blob. Unreadable
        entries are skipped (a concurrent eviction is not an error)."""
        out = []
        for name, size, _ in sorted(self._entries()):
            blob = self.read_blob(name)
            if blob is None:
                continue
            out.append({
                "name": name,
                "size": len(blob),
                "digest": blob_digest(blob),
            })
        return out

    def read_blob(self, name: str) -> bytes | None:
        if not _safe_name(name):
            return None
        try:
            with open(os.path.join(self.root, name), "rb") as f:
                return f.read()
        except OSError:
            return None

    # ------------------------------------------------------------ writes
    def install(self, name: str, blob: bytes, digest: str) -> str | None:
        """Install one fetched blob. Returns None on success or a decline
        reason; declines never raise — the caller's fallback is local
        compile, which is always safe."""
        if not _safe_name(name):
            self.declined += 1
            return "bad_name"
        if blob_digest(blob) != digest:
            # truncated or corrupted in flight; installing it would at
            # best be a cache miss and at worst poison the store
            self.declined += 1
            return "digest_mismatch"
        path = os.path.join(self.root, name)
        tmp = path + ".tmp"
        try:
            with open(tmp, "wb") as f:
                f.write(bytes(blob))
            os.replace(tmp, path)
        except OSError as e:
            self.declined += 1
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return f"io_error:{e.__class__.__name__}"
        self.evict()
        return None

    def evict(self) -> int:
        """LRU-evict (by mtime — jax touches -atime files on hits) until
        the store fits the cap. Returns entries removed."""
        entries = self._entries()
        total = sum(size for _, size, _ in entries)
        removed = 0
        for name, size, _ in sorted(entries, key=lambda e: e[2]):
            if total <= self.max_bytes:
                break
            try:
                os.unlink(os.path.join(self.root, name))
            except OSError:
                continue
            total -= size
            removed += 1
            self.evictions += 1
        return removed
