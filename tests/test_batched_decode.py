"""Continuous-batching e2e: concurrent sessions' single-token decode steps
coalesce into one span dispatch per round (ISSUE 2 tentpole).

Correctness bar: greedy decode is token-identical batched vs unbatched for
every member session — including under seeded chaos faults that stagger
step arrivals — and the new rpc_info counters prove the coalescing actually
happened (≈1 device dispatch per decode round with N lockstep sessions)."""

import asyncio

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from bloombee_tpu.client.model import DistributedModelForCausalLM
from bloombee_tpu.server.block_server import BlockServer
from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer
from bloombee_tpu.wire import faults
from bloombee_tpu.wire.faults import FaultPlan, FaultRule
from bloombee_tpu.wire.rpc import connect


@pytest.fixture(scope="module")
def tiny_model_dir(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64,
        intermediate_size=128,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_hidden_layers=3,
        vocab_size=128,
        max_position_embeddings=256,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    d = tmp_path_factory.mktemp("tiny_llama_batched")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model, config


@pytest.fixture(autouse=True)
def no_leaked_plan():
    yield
    faults.set_plan(None)


def _server(model_dir, registry, start, end, **kw):
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("num_pages", 64)
    kw.setdefault("page_size", 4)
    return BlockServer(
        model_uid="tiny", start=start, end=end, model_dir=model_dir,
        registry=registry, **kw,
    )


def _hf_greedy(model, input_ids, max_new_tokens):
    with torch.no_grad():
        out = model.generate(
            torch.tensor(input_ids), max_new_tokens=max_new_tokens,
            do_sample=False, use_cache=True,
        )
    return out.numpy()


def test_lockstep_sessions_share_one_dispatch_per_round(
    tiny_model_dir, monkeypatch
):
    """N=4 sessions stepping in lockstep: each decode round costs ≈1 merged
    device dispatch (counters prove it), and every session's greedy tokens
    equal the HF reference — i.e. batching changes scheduling, not math."""
    model_dir, hf_model, config = tiny_model_dir
    monkeypatch.setenv("BBTPU_BATCH_WINDOW_MS", "50")
    N, ROUNDS = 4, 6

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s = _server(model_dir, rc(), 0, 3, max_batch=8)
        await s.start()
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny"
        )
        rng = np.random.default_rng(7)
        prompts = [
            rng.integers(0, config.vocab_size, size=(1, 5 + i))
            for i in range(N)
        ]
        sessions = [model.inference_session(32, 1) for _ in range(N)]
        for sess in sessions:
            await sess.__aenter__()
        try:
            # prefills (T>1) are not batcher-routed — counters stay zero
            outs = await asyncio.gather(*(
                sess.step(model.embed(p))
                for sess, p in zip(sessions, prompts)
            ))
            assert s.batched_steps == 0 and s.batch_dispatches == 0
            toks = [
                np.argmax(model.logits(o)[:, -1], axis=-1) for o in outs
            ]
            generated = [[t] for t in toks]
            for _ in range(ROUNDS):
                outs = await asyncio.gather(*(
                    sess.step(model.embed(t[:, None]))
                    for sess, t in zip(sessions, toks)
                ))
                toks = [
                    np.argmax(model.logits(o)[:, -1], axis=-1)
                    for o in outs
                ]
                for g, t in zip(generated, toks):
                    g.append(t)

            for p, g in zip(prompts, generated):
                ref = _hf_greedy(hf_model, p, ROUNDS + 1)
                np.testing.assert_array_equal(
                    np.concatenate(g), ref[0, p.shape[1]:]
                )

            # every decode step went through the batcher, and the rounds
            # coalesced to ≈1 device dispatch each (solo steps are full
            # dispatches too, so they count against the budget)
            assert s.batched_steps + s.batch_solo_steps == N * ROUNDS
            assert s.batch_dispatches + s.batch_solo_steps <= ROUNDS + 2
            width = s.batched_steps / max(s.batch_dispatches, 1)
            assert width >= 3.0

            conn = await connect("127.0.0.1", s.port)
            info, _ = await conn.call("rpc_info", {})
            assert info["batched_steps"] == s.batched_steps
            assert info["batch_dispatches"] == s.batch_dispatches
            assert info["mean_batch_width"] == pytest.approx(width)
            assert info["queue_wait_ms"]["p95"] >= 0.0
            await conn.close()
        finally:
            for sess in sessions:
                await sess.__aexit__(None, None, None)
            await s.stop()
            await reg.stop()

    asyncio.run(run())


def test_concurrent_generate_batched_matches_unbatched(
    tiny_model_dir, monkeypatch
):
    """Free-running concurrent generates (no lockstep barrier) on a
    batching server produce exactly the tokens of a max_batch=1 server and
    of HF greedy."""
    model_dir, hf_model, config = tiny_model_dir
    N, NEW = 4, 6
    rng = np.random.default_rng(3)
    prompts = [
        rng.integers(0, config.vocab_size, size=(1, 4 + i % 3))
        for i in range(N)
    ]

    async def run_swarm(max_batch):
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s = _server(
            model_dir, RegistryClient("127.0.0.1", reg.port), 0, 3,
            max_batch=max_batch,
        )
        await s.start()
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, RegistryClient("127.0.0.1", reg.port),
            model_uid="tiny",
        )
        try:
            outs = await asyncio.gather(*(
                model.generate(p, max_new_tokens=NEW) for p in prompts
            ))
        finally:
            await s.stop()
            await reg.stop()
        return [np.asarray(o) for o in outs], s

    monkeypatch.setenv("BBTPU_BATCH_WINDOW_MS", "25")
    batched, s_b = asyncio.run(run_swarm(8))
    monkeypatch.setenv("BBTPU_BATCH_WINDOW_MS", "0")
    unbatched, s_u = asyncio.run(run_swarm(1))

    assert s_u.batched_steps == 0  # max_batch=1 really disables the batcher
    assert s_b.batched_steps > 0  # and the batched run really coalesced
    for p, got_b, got_u in zip(prompts, batched, unbatched):
        ref = _hf_greedy(hf_model, p, NEW)
        np.testing.assert_array_equal(got_b, ref)
        np.testing.assert_array_equal(got_u, ref)


@pytest.mark.chaos
def test_batched_decode_token_identical_under_chaos(
    tiny_model_dir, monkeypatch
):
    """Seeded frame delays stagger the sessions' step arrivals, so rounds
    coalesce into ragged partial groups (plus solo stragglers) — tokens
    must still be exactly HF greedy for every session."""
    model_dir, hf_model, config = tiny_model_dir
    monkeypatch.setenv("BBTPU_BATCH_WINDOW_MS", "10")
    N, NEW = 4, 8

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s = _server(
            model_dir, RegistryClient("127.0.0.1", reg.port), 0, 3,
            max_batch=8,
        )
        await s.start()

        plan = FaultPlan(seed=42)
        plan.add(FaultRule(site="send", action="delay", method="sitem",
                           prob=0.3, delay_s=0.02))
        faults.set_plan(plan)

        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, RegistryClient("127.0.0.1", reg.port),
            model_uid="tiny",
        )
        rng = np.random.default_rng(11)
        prompts = [
            rng.integers(0, config.vocab_size, size=(1, 5))
            for _ in range(N)
        ]
        try:
            outs = await asyncio.gather(*(
                model.generate(p, max_new_tokens=NEW) for p in prompts
            ))
            for p, got in zip(prompts, outs):
                ref = _hf_greedy(hf_model, p, NEW)
                # HF generate stops at EOS; ours runs all NEW tokens —
                # compare the common prefix (the numerics statement)
                np.testing.assert_array_equal(
                    np.asarray(got)[:, :ref.shape[1]], ref
                )
            # the delays actually landed and at least some steps coalesced
            assert any(act == "delay" for _, act, _ in plan.log)
        finally:
            faults.set_plan(None)
            await s.stop()
            await reg.stop()

    asyncio.run(run())
