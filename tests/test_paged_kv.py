"""Paged KV table invariants.

Ports of /root/reference/tests/test_paged_kv.py semantics: page accounting,
commit/rollback freeing orphaned pages, clamped committed reads, and
slab-write/dense-concat byte equivalence (test_phase0_cache_write_parity).
"""

import numpy as np
import pytest

from bloombee_tpu.kv.paged import OutOfPages, PagedKVTable


def test_page_accounting():
    t = PagedKVTable(num_pages=4, page_size=4)
    t.add_seq(0)
    assert t.free_pages == 4
    t.assign_write_slots(0, 5)  # 2 pages
    assert t.free_pages == 2
    t.add_seq(1)
    t.assign_write_slots(1, 8)  # 2 pages
    assert t.free_pages == 0
    with pytest.raises(OutOfPages):
        t.assign_write_slots(0, 4)  # would need a 3rd page
    t.drop_seq(1)
    assert t.free_pages == 2
    t.assign_write_slots(0, 4)
    assert t.seq(0).l_acc == 9


def test_slots_are_page_linear():
    t = PagedKVTable(num_pages=8, page_size=4)
    t.add_seq(0)
    slots = t.assign_write_slots(0, 6)
    pages = t.seq(0).pages
    expect = [pages[0] * 4 + i for i in range(4)] + [
        pages[1] * 4 + i for i in range(2)
    ]
    assert slots.tolist() == expect


def test_speculative_rollback_frees_orphans():
    t = PagedKVTable(num_pages=8, page_size=4)
    t.add_seq(0)
    t.assign_write_slots(0, 4, commit=True)  # 1 page committed
    t.assign_write_slots(0, 6, commit=False)  # spec tokens span 2 more pages
    assert t.seq(0).l_seq == 10 and t.seq(0).l_acc == 4
    assert t.free_pages == 8 - 3
    t.rollback(0)
    assert t.seq(0).l_seq == 4 and t.seq(0).l_acc == 4
    assert t.free_pages == 7  # orphaned spec pages freed


def test_partial_commit_trims():
    t = PagedKVTable(num_pages=8, page_size=4)
    t.add_seq(0)
    t.assign_write_slots(0, 4, commit=True)
    t.assign_write_slots(0, 8, commit=False)
    t.commit(0, length=6)  # accept 2 of 8 speculative tokens
    st = t.seq(0)
    assert st.l_acc == st.l_seq == 6
    assert len(st.pages) == 2 and t.free_pages == 6
    with pytest.raises(ValueError):
        t.commit(0, length=10)  # beyond l_seq


def test_committed_write_must_follow_prefix():
    t = PagedKVTable(num_pages=8, page_size=4)
    t.add_seq(0)
    t.assign_write_slots(0, 2, commit=True)
    t.assign_write_slots(0, 2, commit=False)
    with pytest.raises(ValueError):
        t.assign_write_slots(0, 1, commit=True)  # spec gap in between


def test_page_table_and_clamped_lens():
    t = PagedKVTable(num_pages=8, page_size=4)
    t.add_seq(0)
    t.add_seq(1)
    t.assign_write_slots(0, 7, commit=True)
    t.assign_write_slots(1, 3, commit=True)
    t.assign_write_slots(1, 5, commit=False)
    pt = t.page_table([0, 1], max_pages=3)
    assert pt.shape == (2, 3)
    assert pt[0, :2].tolist() == t.seq(0).pages
    assert np.array_equal(
        t.context_lens([0, 1]), np.asarray([7, 8], dtype=np.int32)
    )
    assert np.array_equal(
        t.context_lens([0, 1], committed_only=True),
        np.asarray([7, 3], dtype=np.int32),
    )
    with pytest.raises(ValueError):
        t.page_table([0], max_pages=1)


def test_prefix_slots_clamped():
    t = PagedKVTable(num_pages=8, page_size=4)
    t.add_seq(0)
    s_committed = t.assign_write_slots(0, 5, commit=True)
    t.assign_write_slots(0, 3, commit=False)
    assert t.prefix_slots(0).tolist() == s_committed.tolist()
    assert len(t.prefix_slots(0, committed_only=False)) == 8
