"""Generative workloads: session populations with realistic structure.

Serving workloads are not Poisson-with-fixed-shapes: prompt lengths are
heavy-tailed (log-normal — a few huge documents dominate prefill work),
agent loops re-send a long shared system prompt (prefix-cache hits in
production, modeled as skipped prefill tokens), arrival rates breathe
diurnally, and the interesting failures start with a flash crowd — a
burst of arrivals compressed into seconds. Every generator is seeded and
deterministic: the same (kind, n, seed) always yields the same sessions,
so gate failures reproduce bit-for-bit.
"""

from __future__ import annotations

import math
import random

from bloombee_tpu.sim.client import SessionSpec


def _shapes(rng: random.Random, i: int, num_clients: int,
            agent_frac: float, patience_s: float) -> dict:
    prompt = int(min(2048, max(16, rng.lognormvariate(math.log(120), 0.8))))
    decode = int(min(64, max(4, rng.expovariate(1.0 / 10.0))))
    shared = 0
    if rng.random() < agent_frac:
        # agent loop: a long shared system prompt dominates the prompt
        # and prefills from cache (only the tail is new work)
        prompt = max(prompt, 256)
        shared = int(prompt * 0.8)
    return dict(
        session_id=f"s{i}",
        client_id=f"c{i % num_clients}",
        prompt_tokens=prompt,
        decode_tokens=decode,
        shared_prefix_tokens=shared,
        patience_s=patience_s,
    )


def poisson_sessions(
    n: int, horizon_s: float, seed: int = 0, num_clients: int = 20,
    agent_frac: float = 0.3, patience_s: float = 120.0,
) -> list[SessionSpec]:
    """Constant-rate Poisson arrivals over `horizon_s`."""
    rng = random.Random(seed)
    rate = n / max(1e-9, horizon_s)
    t, out = 0.0, []
    for i in range(n):
        t += rng.expovariate(rate)
        out.append(SessionSpec(
            arrival_s=min(t, horizon_s),
            **_shapes(rng, i, num_clients, agent_frac, patience_s),
        ))
    return out


def diurnal_sessions(
    n: int, horizon_s: float, seed: int = 0, num_clients: int = 20,
    agent_frac: float = 0.3, patience_s: float = 120.0,
    trough_frac: float = 0.1,
) -> list[SessionSpec]:
    """Inhomogeneous Poisson via thinning: rate ramps from a trough up to
    a peak at horizon/2 and back down (one simulated day)."""
    rng = random.Random(seed)
    # peak rate sized so the thinned total comes out near n
    mean_frac = trough_frac + (1.0 - trough_frac) / 2.0
    peak = n / max(1e-9, horizon_s * mean_frac)
    t, i, out = 0.0, 0, []
    while i < n:
        t += rng.expovariate(peak)
        # sin^2 is periodic: arrivals that spill past horizon_s simply
        # land in the next day's ramp, guaranteeing exactly n sessions
        frac = trough_frac + (1.0 - trough_frac) * (
            math.sin(math.pi * t / horizon_s) ** 2
        )
        if rng.random() > frac:
            continue  # thinned away: off-peak lull
        out.append(SessionSpec(
            arrival_s=t,
            **_shapes(rng, i, num_clients, agent_frac, patience_s),
        ))
        i += 1
    return out


def flash_crowd_sessions(
    n: int, horizon_s: float, seed: int = 0, num_clients: int = 20,
    agent_frac: float = 0.3, patience_s: float = 120.0,
    crowd_n: int = 100, crowd_at_s: float | None = None,
    crowd_width_s: float = 3.0,
) -> list[SessionSpec]:
    """Baseline Poisson traffic plus a flash crowd of ``crowd_n``
    sessions (capped at half of n) landing inside a seconds-wide window.
    The crowd is ABSOLUTE, not a fraction of daily traffic — "the site
    got linked" is the same size event whatever the background rate — so
    the queue backlog it builds, and therefore the overload physics the
    gates score, is identical between a smoke run and the CI-sized one.
    What the gate scores is the AFTERMATH: does shedding converge, or do
    abandon-and-retry clients feed the very queue that sheds them?"""
    rng = random.Random(seed)
    crowd = min(int(crowd_n), n // 2)
    base = poisson_sessions(
        n - crowd, horizon_s, seed=seed + 1, num_clients=num_clients,
        agent_frac=agent_frac, patience_s=patience_s,
    )
    at = horizon_s * 0.4 if crowd_at_s is None else crowd_at_s
    out = list(base)
    for j in range(crowd):
        i = len(base) + j
        shape = _shapes(rng, i, num_clients, 0.0, patience_s)
        # crowd arrivals are NEW users behind a gateway: substantial
        # prompts, nothing in any prefix cache, a separate client pool,
        # and NO SDK penalty machinery — they honor only the server's
        # Retry-After hint (naive), which is what makes a mis-tuned
        # admission retry knob a retry storm instead of a non-event
        # floor sized so the spike clearly crosses even the under-share
        # hard watermark (4x BBTPU_ADMIT_HIGH_MS): fresh crowd clients
        # carry no fair-share debt, so the real admission controller is
        # deliberately lenient with them until the queue is deeply backed
        # up — that leniency is part of what the gate must see through
        shape["prompt_tokens"] = max(800, shape["prompt_tokens"])
        shape["shared_prefix_tokens"] = 0
        shape["client_id"] = f"crowd{j % 10}"
        out.append(SessionSpec(
            arrival_s=at + rng.random() * crowd_width_s, naive=True,
            **shape,
        ))
    out.sort(key=lambda s: s.arrival_s)
    return out
