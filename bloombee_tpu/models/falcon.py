"""Falcon family: rotary + MQA/GQA, LayerNorm, parallel attention/MLP.

Reference: /root/reference/src/bloombee/models/falcon/ (WrappedFalconBlock).
Supports the falcon-7b shape: multi_query fused QKV ([H q-heads | 1 k | 1 v]
rows), parallel residual with a single shared input LayerNorm, bias-free
linears, exact-GELU 4h MLP.
"""

from __future__ import annotations

from typing import Any


from bloombee_tpu.models.auto import Family, register_family
from bloombee_tpu.models.checkpoint import read_tensor as _t
from bloombee_tpu.models.spec import ModelSpec


def falcon_spec_from_hf(config: Any) -> ModelSpec:
    n_head = config.num_attention_heads
    hidden = config.hidden_size
    if getattr(config, "alibi", False) or getattr(config, "bias", False):
        raise NotImplementedError(
            "falcon-rw variants (alibi/bias) are not supported yet"
        )
    new_arch = bool(getattr(config, "new_decoder_architecture", False))
    if new_arch:
        # falcon-40b/180b: grouped GQA fused QKV + (usually) two parallel
        # LayerNorms (ln_attn feeds attention, ln_mlp feeds the MLP)
        n_kv = getattr(config, "num_kv_heads", None) or n_head
        n_ln = getattr(config, "num_ln_in_parallel_attn", None)
        if n_ln is None:
            n_ln = 2
    else:
        n_kv = 1 if getattr(config, "multi_query", True) else n_head
        n_ln = 1
    return ModelSpec(
        family="falcon",
        hidden_size=hidden,
        intermediate_size=4 * hidden,
        num_attention_heads=n_head,
        num_key_value_heads=n_kv,
        head_dim=hidden // n_head,
        num_hidden_layers=config.num_hidden_layers,
        vocab_size=config.vocab_size,
        rms_norm_eps=getattr(config, "layer_norm_epsilon", 1e-5),
        rope_theta=getattr(config, "rope_theta", 10000.0),
        tie_word_embeddings=True,
        norm_type="ln",
        mlp_type="gelu",
        parallel_attn=getattr(config, "parallel_attn", True) or new_arch,
        num_ln_in_parallel_attn=n_ln,
        alibi=getattr(config, "alibi", False),
    )


def _load_block(reader, layer_idx: int, dtype=None) -> dict:
    p = f"transformer.h.{layer_idx}"
    n_head = reader.config["num_attention_heads"]
    d = reader.config["hidden_size"]
    head_dim = d // n_head
    new_arch = bool(reader.config.get("new_decoder_architecture", False))
    params = {}
    if reader.has(f"{p}.ln_attn.weight"):
        # falcon new-arch dual norms: ln_attn feeds attention (our shared
        # "input_layernorm" slot), ln_mlp feeds the MLP
        params["input_layernorm"] = _t(reader, f"{p}.ln_attn.weight", dtype)
        params["input_layernorm_bias"] = _t(
            reader, f"{p}.ln_attn.bias", dtype
        )
        params["mlp_layernorm"] = _t(reader, f"{p}.ln_mlp.weight", dtype)
        params["mlp_layernorm_bias"] = _t(reader, f"{p}.ln_mlp.bias", dtype)
    else:
        params["input_layernorm"] = _t(
            reader, f"{p}.input_layernorm.weight", dtype
        )
        params["input_layernorm_bias"] = _t(
            reader, f"{p}.input_layernorm.bias", dtype
        )
    w = _t(reader, f"{p}.self_attention.query_key_value.weight", dtype)
    if new_arch:
        # grouped layout: per kv group [n_rep q rows | 1 k row | 1 v row]
        # (HF Falcon _split_heads for new_decoder_architecture)
        n_kv = reader.config.get("num_kv_heads") or n_head
        n_rep = n_head // n_kv
        grouped = w.reshape(n_kv, n_rep + 2, head_dim, d)
        params["q_proj"] = (
            grouped[:, :-2].reshape(n_kv * n_rep * head_dim, d).T
        )
        params["k_proj"] = grouped[:, -2].reshape(n_kv * head_dim, d).T
        params["v_proj"] = grouped[:, -1].reshape(n_kv * head_dim, d).T
    else:
        n_kv = 1 if reader.config.get("multi_query", True) else n_head
        # rows: H query heads, then n_kv k heads, then n_kv v heads
        q_rows = n_head * head_dim
        kv_rows = n_kv * head_dim
        params["q_proj"] = w[:q_rows].T
        params["k_proj"] = w[q_rows : q_rows + kv_rows].T
        params["v_proj"] = w[q_rows + kv_rows :].T
    params["o_proj"] = _t(reader, f"{p}.self_attention.dense.weight", dtype).T
    params["up_proj"] = _t(reader, f"{p}.mlp.dense_h_to_4h.weight", dtype).T
    params["down_proj"] = _t(reader, f"{p}.mlp.dense_4h_to_h.weight", dtype).T
    return params


def _load_client(reader, dtype=None) -> dict:
    out = {
        "embed": _t(reader, "transformer.word_embeddings.weight", dtype),
        "norm": _t(reader, "transformer.ln_f.weight", dtype),
        "norm_bias": _t(reader, "transformer.ln_f.bias", dtype),
    }
    if reader.has("lm_head.weight"):
        out["lm_head"] = _t(reader, "lm_head.weight", dtype).T
    else:
        out["lm_head"] = out["embed"].T
    return out


register_family(
    Family(
        "falcon", falcon_spec_from_hf, loader=_load_block,
        client_loader=_load_client,
    )
)
