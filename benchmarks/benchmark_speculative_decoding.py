"""Speculative decoding benchmark: speed vs plain greedy.

Port of /root/reference/benchmarks/benchmark_speculative_decoding.py:55
(prints `Final result: speed=`).
"""

from __future__ import annotations

import argparse
import asyncio
import time

import numpy as np


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("model_dir")
    parser.add_argument("--drafter-dir", default=None,
                        help="small draft model dir (default: target model)")
    parser.add_argument("--model-uid", default=None)
    parser.add_argument("--registry", default="127.0.0.1:7700")
    parser.add_argument("--seq-len", type=int, default=32)
    parser.add_argument("--max-new-tokens", type=int, default=64)
    parser.add_argument("--branching", default="2,2,1")
    args = parser.parse_args(argv)
    args.model_uid = args.model_uid or args.model_dir.rstrip("/").split("/")[-1]

    async def run():
        from bloombee_tpu.client.model import DistributedModelForCausalLM
        from bloombee_tpu.client.speculative import generate_speculative
        from bloombee_tpu.spec.drafter import (
            GreedyTreeDrafter,
            LocalJaxDraftModel,
        )
        from bloombee_tpu.swarm.registry import RegistryClient

        host, port = args.registry.rsplit(":", 1)
        model = DistributedModelForCausalLM.from_pretrained(
            args.model_dir, RegistryClient(host, int(port)),
            model_uid=args.model_uid,
        )
        drafter = GreedyTreeDrafter(
            LocalJaxDraftModel.from_dir(args.drafter_dir or args.model_dir),
            branching=tuple(int(x) for x in args.branching.split(",")),
        )
        rng = np.random.default_rng(0)
        ids = rng.integers(0, model.spec.vocab_size, size=(1, args.seq_len))

        t0 = time.perf_counter()
        plain = await model.generate(ids, max_new_tokens=args.max_new_tokens)
        t_plain = time.perf_counter() - t0
        n_plain = plain.shape[1] - ids.shape[1]

        t0 = time.perf_counter()
        spec = await generate_speculative(
            model, drafter, ids, max_new_tokens=args.max_new_tokens
        )
        t_spec = time.perf_counter() - t0
        n_spec = spec.shape[1] - ids.shape[1]

        assert (spec[:, : plain.shape[1]] == plain).all(), "spec != greedy!"
        print(
            f"Final result: speed={n_spec / t_spec:.2f} tok/s "
            f"(plain {n_plain / t_plain:.2f} tok/s, "
            f"speedup x{(n_spec / t_spec) / (n_plain / t_plain):.2f})"
        )

    asyncio.run(run())


if __name__ == "__main__":
    main()
