"""Training-side span compute: dense forward + VJP backward.

The reference's training path (SURVEY.md section 3.4) runs rpc_forward /
rpc_backward over frozen blocks: gradients flow only w.r.t. inputs and
prompts (p-tuning); the server rebuilds activations then backprops
(block_functions.py:357 run_rpc_backward, backend.py:427-462).

Here the span forward for training reuses the SAME generic family machinery
as serving (span_step_impl over a throwaway zero arena — scatter/gather are
differentiable, so jax.vjp through the paged step gives exact input grads),
and backward is one jitted VJP call. No activation storage between forward
and backward RPCs: like the reference, backward recomputes the forward
(rematerialization is the TPU-native default).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from bloombee_tpu.models.spec import ModelSpec
from bloombee_tpu.runtime.step import pack_plan, span_step_impl


def _train_plan(
    b: int, t: int, num_layers: int,
    layers: tuple[int, int] | None = None,
) -> np.ndarray:
    """Plan for a dense full-sequence pass: one page per sequence of size t.
    `layers` gates a sub-span (router may enter a server's span mid-way)."""
    slots = np.arange(b * t, dtype=np.int32)
    page_table = np.arange(b, dtype=np.int32)[:, None]
    positions = np.broadcast_to(np.arange(t, dtype=np.int32)[None], (b, t))
    total_lens = np.full((b,), t, np.int32)
    layer_active = np.ones((num_layers,), np.int32)
    if layers is not None:
        layer_active[:] = 0
        layer_active[layers[0] : layers[1]] = 1
    return pack_plan(slots, page_table, positions, total_lens, layer_active)


def _dense_forward(stacked_params, hidden, plan, spec, windows, prompts=None,
                   lora=None):
    b, t, _ = hidden.shape
    num_layers = jax.tree.leaves(stacked_params)[0].shape[0]
    arena_shape = (
        num_layers, b * t, spec.num_key_value_heads, spec.head_dim,
    )
    zeros = jnp.zeros(arena_shape, hidden.dtype)
    out, _, _ = span_step_impl(
        stacked_params, zeros, jnp.zeros_like(zeros), hidden, plan, None,
        prompts, lora,
        spec=spec, page_size=t, max_pages=1, windows=windows,
    )
    return out


@functools.partial(jax.jit, static_argnames=("spec", "windows"))
def span_train_forward(
    stacked_params, hidden, plan, prompts=None, lora=None, *,
    spec: ModelSpec, windows=None,
):
    return _dense_forward(
        stacked_params, hidden, plan, spec, windows, prompts, lora
    )


@functools.partial(jax.jit, static_argnames=("spec", "windows"))
def span_train_backward(
    stacked_params, hidden_in, grad_out, plan, prompts=None, lora=None, *,
    spec: ModelSpec, windows=None,
):
    """Returns (forward_output, grad_wrt_input[, grad_wrt_prompts])."""
    if prompts is None:
        out, vjp = jax.vjp(
            lambda h: _dense_forward(
                stacked_params, h, plan, spec, windows, None, lora
            ),
            hidden_in,
        )
        (g_in,) = vjp(grad_out)
        return out, g_in, None
    out, vjp = jax.vjp(
        lambda h, p: _dense_forward(
            stacked_params, h, plan, spec, windows, p, lora
        ),
        hidden_in, prompts,
    )
    g_in, g_prompts = vjp(grad_out)
    return out, g_in, g_prompts


class TrainingExecutor:
    """Host wrapper used by the server's rpc_forward/rpc_backward."""

    def __init__(self, stacked_params, spec: ModelSpec, windows=None,
                 compute_dtype=jnp.float32, adapters=None):
        self.params = stacked_params
        self.spec = spec
        self.windows = windows
        self.compute_dtype = compute_dtype
        self.adapters = adapters or {}
        self.num_layers = jax.tree.leaves(stacked_params)[0].shape[0]

    def _lora(self, adapter):
        from bloombee_tpu.models.checkpoint import resolve_adapter

        return resolve_adapter(self.adapters, adapter)

    def _expand_prompts(self, prompts, layers):
        """Received prompts cover the ACTIVE sub-span only; embed them at
        the right rows of a full [num_layers, P, D] array."""
        if prompts is None:
            return None
        prompts = jnp.asarray(prompts, self.compute_dtype)
        if layers is None or prompts.shape[0] == self.num_layers:
            return prompts
        full = jnp.zeros(
            (self.num_layers, *prompts.shape[1:]), prompts.dtype
        )
        return full.at[layers[0]:layers[1]].set(prompts)

    def forward(
        self, hidden: np.ndarray, layers: tuple[int, int] | None = None,
        prompts: np.ndarray | None = None,
        adapter: str | None = None,
    ) -> np.ndarray:
        b, t, _ = hidden.shape
        plan = jnp.asarray(_train_plan(b, t, self.num_layers, layers))
        out = span_train_forward(
            self.params, jnp.asarray(hidden, self.compute_dtype), plan,
            self._expand_prompts(prompts, layers), self._lora(adapter),
            spec=self.spec, windows=self.windows,
        )
        return np.asarray(out, dtype=np.float32)

    def backward(
        self,
        hidden_in: np.ndarray,
        grad_out: np.ndarray,
        layers: tuple[int, int] | None = None,
        prompts: np.ndarray | None = None,
        adapter: str | None = None,
    ):
        """Returns g_in, or (g_in, g_prompts) when prompts are given
        (g_prompts covers only the active sub-span rows)."""
        b, t, _ = hidden_in.shape
        plan = jnp.asarray(_train_plan(b, t, self.num_layers, layers))
        _, g_in, g_prompts = span_train_backward(
            self.params,
            jnp.asarray(hidden_in, self.compute_dtype),
            jnp.asarray(grad_out, self.compute_dtype),
            plan,
            self._expand_prompts(prompts, layers),
            self._lora(adapter),
            spec=self.spec,
            windows=self.windows,
        )
        g_in = np.asarray(g_in, dtype=np.float32)
        if g_prompts is None:
            return g_in
        g_p = np.asarray(g_prompts, dtype=np.float32)
        if layers is not None and g_p.shape[0] == self.num_layers:
            g_p = g_p[layers[0]:layers[1]]
        return g_in, g_p
