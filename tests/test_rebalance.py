"""Automatic swarm rebalancing + background-task supervision.

Reference: /root/reference/src/bloombee/server/server.py:479-542 (the
module-container restart loop driven by should_choose_other_blocks) and
block_selection.py:40-95 (move simulation with hysteresis). Here the move
happens in-process: drain, reload the new span, swap the serving stack,
re-announce — no container restart.
"""

import asyncio

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from bloombee_tpu.client.model import DistributedModelForCausalLM
from bloombee_tpu.server.block_selection import rebalance_target
from bloombee_tpu.server.block_server import BlockServer
from bloombee_tpu.swarm.data import ModuleInfo, ServerInfo
from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer
from bloombee_tpu.swarm.spans import compute_spans


def _infos(spans, n_blocks):  # spans: {sid: (start, end, throughput)}
    infos = [ModuleInfo(uid=f"b{i}", servers={}) for i in range(n_blocks)]
    for sid, (s, e, tput) in spans.items():
        si = ServerInfo(throughput=tput, start_block=s, end_block=e)
        for i in range(s, e):
            infos[i].servers[sid] = si
    return infos


def test_rebalance_target_moves_off_overlap():
    """Two servers stacked on [0,2) of a 3-block model leave block 2
    unserved; one of them must move to [1,3)."""
    infos = _infos({"a": (0, 2, 1.0), "b": (0, 2, 1.0)}, 3)
    target = rebalance_target("b", infos, compute_spans(infos))
    assert target == (1, 3)


def test_rebalance_target_hysteresis_keeps_balanced_swarm():
    """A balanced split must NOT move (the hysteresis margin prevents
    thrash)."""
    infos = _infos({"a": (0, 2, 1.0), "b": (2, 4, 1.0)}, 4)
    assert rebalance_target("a", infos, compute_spans(infos)) is None
    assert rebalance_target("b", infos, compute_spans(infos)) is None


@pytest.fixture(scope="module")
def tiny_model_dir(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64,
        intermediate_size=128,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_hidden_layers=3,
        vocab_size=128,
        max_position_embeddings=256,
        tie_word_embeddings=False,
    )
    torch.manual_seed(7)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    d = tmp_path_factory.mktemp("tiny_llama_rebal")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model, config


def test_e2e_pathological_split_converges(tiny_model_dir):
    """Two servers both serving [0,2) of a 3-layer model (block 2 dark):
    the rebalancing supervisor must move one to [1,3) WITHOUT operator
    action, after which a client can run the full model and match HF."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        def server(start, end, **kw):
            return BlockServer(
                model_uid="tiny", start=start, end=end, model_dir=model_dir,
                registry=rc(), compute_dtype=jnp.float32, num_pages=64,
                page_size=4, announce_period=0.5, **kw,
            )

        s_a = server(0, 2)  # static
        s_b = server(0, 2, rebalance_period=1.0, drain_timeout=2.0)
        await s_a.start()
        await s_b.start()
        # supervisor tick = announce_period (0.5s); rebalance after 1s
        deadline = asyncio.get_event_loop().time() + 30.0
        while (s_b.start_block, s_b.end_block) == (0, 2):
            if asyncio.get_event_loop().time() > deadline:
                raise AssertionError("rebalance never happened")
            await asyncio.sleep(0.25)
        assert (s_b.start_block, s_b.end_block) == (1, 3)

        # swarm must now serve the whole model, correct vs HF
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny"
        )
        rng = np.random.default_rng(4)
        input_ids = rng.integers(0, config.vocab_size, size=(1, 4))
        ids = await model.generate(
            input_ids, max_new_tokens=5, server_decode=False
        )
        with torch.no_grad():
            ref = hf_model.generate(
                torch.tensor(input_ids), max_new_tokens=5, do_sample=False,
                use_cache=True,
            ).numpy()
        np.testing.assert_array_equal(ids, ref)

        # stability: no further move (hysteresis)
        await asyncio.sleep(2.5)
        assert (s_b.start_block, s_b.end_block) == (1, 3)
        assert (s_a.start_block, s_a.end_block) == (0, 2)

        await s_a.stop()
        await s_b.stop()
        await reg.stop()

    asyncio.run(run())


def test_supervisor_restarts_dead_announce_loop(tiny_model_dir):
    """Kill the announce task; the supervisor must restart it and the
    server must stay visible in the registry past the expiry window."""
    model_dir, _, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s = BlockServer(
            model_uid="tiny", start=0, end=3, model_dir=model_dir,
            registry=rc(), compute_dtype=jnp.float32, num_pages=64,
            page_size=4, announce_period=0.5,
        )
        await s.start()
        s._announce_task.cancel()
        # expiry = announce_period * 2.5 = 1.25s; wait well past it and
        # confirm the record is still alive (supervisor restarted the loop)
        await asyncio.sleep(3.0)
        infos = await rc().get_module_infos("tiny", range(3))
        assert any(s.server_id in i.servers for i in infos), (
            "server expired from the registry after its announce loop died"
        )
        await s.stop()
        await reg.stop()

    asyncio.run(run())
