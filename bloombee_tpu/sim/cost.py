"""Calibrated compute-cost model: what a dispatch costs in device seconds.

The simulator replaces ``SpanExecutor`` with ``clock.sleep(cost)`` on the
compute thread; this module decides the cost. The shape mirrors the
measured bench phases (bench.py): a fixed per-dispatch overhead (jit call
+ host sync) plus per-row work for fused ragged decode and per-token work
for prefill chunks, both scaling with the span's block count.

Defaults are CPU-smoke-bench magnitudes; ``from_bench_json`` refits them
from a real BENCH JSON (``--cost-json`` / ``BBTPU_SIM_COST_JSON``) so a
TPU-calibrated simulation costs one flag. The fitter is tolerant: it
reads whichever of ``chain.steps_per_sec`` / ``decode.tbt_p50_ms`` /
``prefill.ttft_ms``-style keys the bench emitted and keeps defaults for
the rest (bench JSONs evolve; a sim that hard-fails on a missing key
can't consume last month's artifact).
"""

from __future__ import annotations

import dataclasses
import json

from bloombee_tpu.utils import env

env.declare(
    "BBTPU_SIM_COST_JSON", str, "",
    "path to a bench results JSON (bench.py output) to calibrate the "
    "simulator's compute-cost model from; empty = built-in CPU-smoke "
    "magnitudes",
)


@dataclasses.dataclass
class CostModel:
    """Per-dispatch device-seconds model, all knobs in milliseconds."""

    dispatch_ms: float = 2.0  # fixed jit-call + host-sync overhead
    decode_row_ms_per_block: float = 0.25  # one decode row, one block
    prefill_tok_ms_per_block: float = 0.05  # one prefill token, one block
    hop_rtt_ms: float = 10.0  # client<->server wire round trip

    def decode_group_s(self, rows: int, blocks: int) -> float:
        """One fused decode dispatch of `rows` coalesced sessions."""
        return (
            self.dispatch_ms
            + self.decode_row_ms_per_block * blocks * max(1, rows)
        ) / 1000.0

    def prefill_chunk_s(self, tokens: int, blocks: int) -> float:
        """One prefill-chunk dispatch of `tokens` total tokens."""
        return (
            self.dispatch_ms
            + self.prefill_tok_ms_per_block * blocks * max(1, tokens)
        ) / 1000.0

    def group_s(self, kind: str, rows: int, tokens: int,
                blocks: int) -> float:
        if kind == "decode":
            return self.decode_group_s(rows, blocks)
        return self.prefill_chunk_s(tokens, blocks)

    # ------------------------------------------------------------ calibration
    @classmethod
    def from_bench_json(
        cls, source, num_blocks: int = 8
    ) -> "CostModel":
        """Fit from a bench results dict or JSON file path. Bench numbers
        are end-to-end (all spans + wire); the fit attributes the wire
        share to hop_rtt_ms's default and the rest to per-block compute,
        which is the right split for *relative* scenario comparisons (the
        sim's job) even when the absolute split is approximate."""
        if isinstance(source, (str, bytes)):
            with open(source) as f:
                data = json.load(f)
        else:
            data = dict(source or {})
        model = cls()
        step_ms = None
        sps = _dig(data, "chain.steps_per_sec", "steps_per_sec")
        if isinstance(sps, (int, float)) and sps > 0:
            step_ms = 1000.0 / float(sps)
        tbt = _dig(data, "decode.tbt_p50_ms", "tbt_p50_ms", "chain.tbt_p50_ms")
        if isinstance(tbt, (int, float)) and tbt > 0:
            step_ms = float(tbt) if step_ms is None else min(step_ms, tbt)
        if step_ms is not None:
            # one chain step = dispatch + wire + blocks * row cost
            compute_ms = max(0.1, step_ms - model.dispatch_ms
                             - model.hop_rtt_ms)
            model.decode_row_ms_per_block = compute_ms / max(1, num_blocks)
        ttft = _dig(data, "prefill.ttft_ms", "ttft_ms", "chain.ttft_ms")
        toks = _dig(data, "prefill.prompt_tokens", "prompt_tokens")
        if (
            isinstance(ttft, (int, float)) and ttft > 0
            and isinstance(toks, (int, float)) and toks > 0
        ):
            compute_ms = max(0.1, float(ttft) - model.dispatch_ms
                             - model.hop_rtt_ms)
            model.prefill_tok_ms_per_block = compute_ms / (
                float(toks) * max(1, num_blocks)
            )
        return model

    @classmethod
    def from_env(cls, num_blocks: int = 8) -> "CostModel":
        path = env.get("BBTPU_SIM_COST_JSON")
        if path:
            return cls.from_bench_json(path, num_blocks=num_blocks)
        return cls()


def _dig(data: dict, *dotted: str):
    """First present dotted key, tolerant of either nesting or flat keys."""
    for key in dotted:
        node = data
        for part in key.split("."):
            if not isinstance(node, dict) or part not in node:
                node = None
                break
            node = node[part]
        if node is not None:
            return node
    return None
