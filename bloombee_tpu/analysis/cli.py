"""bbtpu-lint CLI: `python -m bloombee_tpu.analysis`.

Exit codes: 0 clean (all findings baselined or suppressed), 1 new
findings or env-docs drift, 2 usage error.

The AST lint itself never imports jax — only `--dump-env-table` /
`--check-env-docs` import the package (to populate the env.declare
registry), which is why scripts/analyze.sh pins JAX_PLATFORMS=cpu.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from bloombee_tpu.analysis.core import (
    load_baseline,
    load_source_files,
    run_rules,
    write_baseline,
)
from bloombee_tpu.analysis.rules import make_rules

DEFAULT_PATHS = ["bloombee_tpu", "bench.py"]
ENV_TABLE_BEGIN = "<!-- bbtpu-env-table:begin -->"
ENV_TABLE_END = "<!-- bbtpu-env-table:end -->"
LOCK_TABLE_BEGIN = "<!-- bbtpu-lock-table:begin -->"
LOCK_TABLE_END = "<!-- bbtpu-lock-table:end -->"


def find_root(start: Path | None = None) -> Path:
    """Repo root = nearest ancestor holding the bloombee_tpu package,
    so the CLI works from any cwd inside the checkout."""
    cur = (start or Path.cwd()).resolve()
    for cand in (cur, *cur.parents):
        if (cand / "bloombee_tpu" / "__init__.py").exists():
            return cand
    return cur


def resolve_root(paths: list[str]) -> Path:
    """find_root from cwd, else from the path arguments — running
    `python -m bloombee_tpu.analysis /abs/checkout/...` from an
    unrelated cwd must still relativize findings against the checkout,
    or their fingerprints can never match the committed baseline."""
    root = find_root()
    if (root / "bloombee_tpu" / "__init__.py").exists():
        return root
    for p in paths:
        cand = find_root(Path(p))
        if (cand / "bloombee_tpu" / "__init__.py").exists():
            return cand
    return root


def default_baseline(root: Path) -> Path:
    return root / "bloombee_tpu" / "analysis" / "baseline.txt"


def build_env_table() -> str:
    """The authoritative BBTPU_* switch table, straight from the
    env.declare registry (imports the declaring modules)."""
    from bloombee_tpu.utils import env

    env.import_declaring_modules()
    return env.describe().strip()


def check_env_docs(root: Path, readme: str) -> int:
    """Fail when README's generated env table drifted from the live
    registry — an undeclared switch can't appear (BB005 catches raw
    reads), and a declared-but-undocumented one fails here."""
    path = root / readme
    if not path.exists():
        print(f"env-docs: {readme} not found", file=sys.stderr)
        return 1
    text = path.read_text(encoding="utf-8")
    try:
        _, rest = text.split(ENV_TABLE_BEGIN, 1)
        documented, _ = rest.split(ENV_TABLE_END, 1)
    except ValueError:
        print(
            f"env-docs: {readme} lacks the generated switch table "
            f"markers ({ENV_TABLE_BEGIN} ... {ENV_TABLE_END}); "
            "insert them and run scripts/analyze.sh --fix-env-docs",
            file=sys.stderr,
        )
        return 1
    live = build_env_table()
    if documented.strip() != live:
        doc_lines = set(documented.strip().splitlines())
        live_lines = set(live.splitlines())
        for line in sorted(live_lines - doc_lines):
            print(f"env-docs: missing from {readme}: {line}",
                  file=sys.stderr)
        for line in sorted(doc_lines - live_lines):
            print(f"env-docs: stale in {readme}: {line}",
                  file=sys.stderr)
        print(
            f"env-docs: {readme} env-switch table drifted from the "
            "env.declare registry; regenerate with "
            "scripts/analyze.sh --fix-env-docs",
            file=sys.stderr,
        )
        return 1
    return 0


def fix_env_docs(root: Path, readme: str) -> int:
    """Rewrite the README's marker-delimited table from the registry."""
    path = root / readme
    text = path.read_text(encoding="utf-8")
    try:
        head, rest = text.split(ENV_TABLE_BEGIN, 1)
        _, tail = rest.split(ENV_TABLE_END, 1)
    except ValueError:
        print(f"env-docs: {readme} lacks the table markers",
              file=sys.stderr)
        return 1
    path.write_text(
        head
        + ENV_TABLE_BEGIN
        + "\n"
        + build_env_table()
        + "\n"
        + ENV_TABLE_END
        + tail,
        encoding="utf-8",
    )
    print(f"env-docs: regenerated table in {readme}")
    return 0


def _replace_marked(
    root: Path, relpath: str, begin: str, end: str, body: str,
    check_only: bool, what: str,
) -> int:
    """Shared engine for the generated README/ARCHITECTURE tables:
    compare (check) or rewrite (fix) the marker-delimited region."""
    path = root / relpath
    if not path.exists():
        print(f"{what}: {relpath} not found", file=sys.stderr)
        return 1
    text = path.read_text(encoding="utf-8")
    try:
        head, rest = text.split(begin, 1)
        current, tail = rest.split(end, 1)
    except ValueError:
        print(
            f"{what}: {relpath} lacks the generated table markers "
            f"({begin} ... {end})", file=sys.stderr,
        )
        return 1
    if check_only:
        if current.strip() != body.strip():
            print(
                f"{what}: {relpath} drifted from "
                "analysis/lock_hierarchy.py; regenerate with "
                "scripts/analyze.sh --fix-lock-docs",
                file=sys.stderr,
            )
            return 1
        return 0
    path.write_text(
        head + begin + "\n" + body.strip() + "\n" + end + tail,
        encoding="utf-8",
    )
    print(f"{what}: regenerated table in {relpath}")
    return 0


def check_lock_docs(root: Path, fix: bool = False) -> int:
    """ARCHITECTURE.md's lock-hierarchy table is generated from the
    declared registry, same contract as the README env table: drift
    fails the gate, --fix-lock-docs rewrites it."""
    from bloombee_tpu.analysis import lock_hierarchy

    return _replace_marked(
        root, "ARCHITECTURE.md", LOCK_TABLE_BEGIN, LOCK_TABLE_END,
        lock_hierarchy.describe(), check_only=not fix, what="lock-docs",
    )


def render_json(findings, files, baselined: int) -> str:
    """Machine-readable finding list for editor/CI integration. The
    human text format stays byte-stable; tooling parses this instead."""
    import json

    return json.dumps(
        {
            "findings": [
                {
                    "rule": f.code,
                    "fingerprint": f.fingerprint(),
                    "path": f.path,
                    "line": f.line,
                    "location": f"{f.path}:{f.line}",
                    "message": f.message,
                    "chain": list(f.chain),
                }
                for f in findings
            ],
            "files": len(files),
            "baselined": baselined,
        },
        indent=1,
        sort_keys=True,
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m bloombee_tpu.analysis", description=__doc__
    )
    parser.add_argument(
        "paths", nargs="*",
        help=f"files/dirs to analyze (default: {' '.join(DEFAULT_PATHS)})",
    )
    parser.add_argument("--baseline", default=None,
                        help="baseline file (default: "
                        "bloombee_tpu/analysis/baseline.txt)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, baselined or not")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline from current findings")
    parser.add_argument("--select", default=None,
                        help="comma-separated BB codes to run (e.g. "
                        "BB001,BB005)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument("--dump-env-table", action="store_true",
                        help="print the BBTPU_* switch table from the "
                        "env.declare registry and exit")
    parser.add_argument("--check-env-docs", action="store_true",
                        help="additionally verify README's generated "
                        "env table matches the registry")
    parser.add_argument("--fix-env-docs", action="store_true",
                        help="regenerate README's env table and exit")
    parser.add_argument("--check-lock-docs", action="store_true",
                        help="additionally verify ARCHITECTURE.md's "
                        "generated lock-hierarchy table matches "
                        "analysis/lock_hierarchy.py")
    parser.add_argument("--fix-lock-docs", action="store_true",
                        help="regenerate ARCHITECTURE.md's lock table "
                        "and exit")
    parser.add_argument("--json", action="store_true",
                        help="emit new findings as JSON on stdout "
                        "(rule, fingerprint, path:line, call chain); "
                        "summary stays on stderr")
    parser.add_argument("--readme", default="README.md")
    args = parser.parse_args(argv)

    root = resolve_root(args.paths)
    if args.list_rules:
        for r in make_rules():
            print(f"{r.code}  {r.name}: {r.summary}")
        return 0
    if args.dump_env_table:
        print(build_env_table())
        return 0
    if args.fix_env_docs:
        return fix_env_docs(root, args.readme)
    if args.fix_lock_docs:
        return check_lock_docs(root, fix=True)

    rules = make_rules()
    if args.select:
        want = {c.strip().upper() for c in args.select.split(",")}
        unknown = want - {r.code for r in rules}
        if unknown:
            parser.error(f"unknown rule code(s): {sorted(unknown)}")
        rules = [r for r in rules if r.code in want]

    files, findings = load_source_files(
        root, args.paths or DEFAULT_PATHS
    )
    findings = findings + run_rules(files, rules)
    findings.sort(key=lambda f: (f.path, f.line, f.code))

    baseline_path = (
        Path(args.baseline) if args.baseline else default_baseline(root)
    )
    if args.update_baseline:
        write_baseline(baseline_path, findings)
        print(
            f"baseline: wrote {len(findings)} finding(s) to "
            f"{baseline_path}"
        )
        return 0

    baseline = (
        set() if args.no_baseline else load_baseline(baseline_path)
    )
    new = [f for f in findings if f.fingerprint() not in baseline]
    old = len(findings) - len(new)
    if args.json:
        print(render_json(new, files, old))
    else:
        for f in new:
            print(f.render())

    rc = 0
    if new:
        print(
            f"bbtpu-lint: {len(new)} new finding(s) "
            f"({old} baselined) across {len(files)} file(s)",
            file=sys.stderr,
        )
        rc = 1
    else:
        print(
            f"bbtpu-lint: clean — {len(files)} file(s), "
            f"{old} baselined finding(s)",
            file=sys.stderr if args.json else sys.stdout,
        )
    stale = baseline - {f.fingerprint() for f in findings}
    if stale and not args.no_baseline:
        # informational: a fixed finding leaves a dead baseline line
        print(
            f"bbtpu-lint: note: {len(stale)} stale baseline entr"
            f"{'y' if len(stale) == 1 else 'ies'} (fixed findings); "
            "run --update-baseline to prune",
            file=sys.stderr,
        )
    if args.check_env_docs:
        rc = max(rc, check_env_docs(root, args.readme))
    if args.check_lock_docs:
        rc = max(rc, check_lock_docs(root))
    return rc
