"""Mixed-batch dispatch e2e: decodes + prefill chunk fused into ONE step.

Correctness bar (ISSUE 8): greedy decode must be TOKEN-IDENTICAL with
mixed batching on and off (both pinned to HF) — including under seeded
chaos delays while concurrent sessions fuse — the fused dispatches must
actually happen (mixed_dispatches > 0, surfaced via rpc_info next to
dispatches_per_token), the gate must default off, and a SETTLED
prefix-adopted session must join merged dispatches instead of soloing
for the rest of its life.
"""

import asyncio

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from bloombee_tpu.client.config import ClientConfig
from bloombee_tpu.client.model import DistributedModelForCausalLM
from bloombee_tpu.server.block_server import (
    BlockServer,
    _BatchMember,
    _Session,
)
from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer
from bloombee_tpu.wire import faults
from bloombee_tpu.wire.faults import FaultPlan, FaultRule
from bloombee_tpu.wire.rpc import connect


@pytest.fixture(scope="module")
def tiny_model_dir(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64,
        intermediate_size=128,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_hidden_layers=3,
        vocab_size=128,
        max_position_embeddings=256,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    d = tmp_path_factory.mktemp("tiny_llama_mixed")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model, config


@pytest.fixture(autouse=True)
def no_leaked_plan():
    yield
    faults.set_plan(None)


def _server(model_dir, registry, start, end, **kw):
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("num_pages", 64)
    kw.setdefault("page_size", 4)
    return BlockServer(
        model_uid="tiny", start=start, end=end, model_dir=model_dir,
        registry=registry, **kw,
    )


def _hf_greedy(model, input_ids, max_new_tokens):
    with torch.no_grad():
        out = model.generate(
            torch.tensor(input_ids), max_new_tokens=max_new_tokens,
            do_sample=False, use_cache=True,
        )
    return out.numpy()


def _assert_no_leaks(server):
    table = server.manager.table
    if hasattr(table, "counts"):
        c = table.counts()
        assert c["free"] + c["referenced"] + c["cached"] == table.num_pages, c
        assert c["referenced"] == 0, c
    else:
        assert table.free_pages == table.num_pages


# ---------------------------------------------- fused dispatch, HF-exact
def test_mixed_batch_token_identical_and_counters(
    tiny_model_dir, monkeypatch
):
    """Two sessions decode continuously while a third prefills a 40-token
    prompt in 4-token chunks on a --mixed-batch server: waiting decode
    steps must FUSE INTO the chunk's device dispatch (mixed_dispatches >
    0 — the one-ragged-dispatch claim), every session stays HF-exact, and
    rpc_info surfaces the fusion counters plus the sub-1.0
    dispatches_per_token amortization."""
    model_dir, hf_model, config = tiny_model_dir
    # a small gather window makes the fusion deterministic: the popped
    # chunk waits a few ms for the decode steps already in flight
    monkeypatch.setenv("BBTPU_BATCH_WINDOW_MS", "8")

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s = _server(
            model_dir, rc(), 0, 3, prefill_chunk=4, max_batch=8,
            mixed_batch=True,
        )
        await s.start()
        assert s.mixed_batch is True
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny"
        )
        rng = np.random.default_rng(5)
        dec_prompts = [
            rng.integers(0, config.vocab_size, size=(1, 5 + i))
            for i in range(2)
        ]
        long_ids = (np.arange(40)[None, :] * 5 + 3) % config.vocab_size
        ref_long = _hf_greedy(hf_model, long_ids, 4)

        dec_sessions = [model.inference_session(40, 1) for _ in range(2)]
        for sess in dec_sessions:
            await sess.__aenter__()
        long_sess = model.inference_session(48, 1)
        await long_sess.__aenter__()
        open_sessions = [*dec_sessions, long_sess]
        try:
            toks = []
            for sess, p in zip(dec_sessions, dec_prompts):
                out = await sess.step(model.embed(p))
                toks.append(np.argmax(model.logits(out)[:, -1], axis=-1))
            generated = [[t] for t in toks]
            prefill_done = asyncio.Event()

            async def decode_loop(i):
                sess = dec_sessions[i]
                while not prefill_done.is_set() and len(generated[i]) < 28:
                    out = await sess.step(
                        model.embed(generated[i][-1][:, None])
                    )
                    generated[i].append(
                        np.argmax(model.logits(out)[:, -1], axis=-1)
                    )

            async def long_prefill():
                try:
                    return await long_sess.step(model.embed(long_ids))
                finally:
                    prefill_done.set()

            out_long, _, _ = await asyncio.gather(
                long_prefill(), decode_loop(0), decode_loop(1)
            )

            # the fusion claim: decode steps rode INSIDE chunk dispatches
            assert s.prefill_chunks >= 10  # the 40-token prompt alone
            assert s.mixed_dispatches > 0
            # every fused dispatch carries >= 1 decode + a multi-token
            # chunk, so it averages well above one token
            assert s.mixed_tokens >= 2 * s.mixed_dispatches

            # numerics: the long prefill continues HF-exact ...
            t = np.argmax(model.logits(out_long)[:, -1], axis=-1)
            got_long = [t]
            for _ in range(3):
                out = await long_sess.step(model.embed(t[:, None]))
                t = np.argmax(model.logits(out)[:, -1], axis=-1)
                got_long.append(t)
            np.testing.assert_array_equal(
                np.concatenate(got_long), ref_long[0, long_ids.shape[1]:]
            )
            # ... and so does every decoder that fused with it
            for p, g in zip(dec_prompts, generated):
                ref = _hf_greedy(hf_model, p, len(g))
                got = np.concatenate(g)[: ref.shape[1] - p.shape[1]]
                np.testing.assert_array_equal(
                    got, ref[0, p.shape[1]:p.shape[1] + got.shape[0]]
                )

            conn = await connect("127.0.0.1", s.port)
            info, _ = await conn.call("rpc_info", {})
            assert info["mixed_batch"] is True
            assert info["mixed_dispatches"] == s.mixed_dispatches
            assert info["mixed_tokens"] == s.mixed_tokens
            # multi-token dispatches amortize: strictly below one
            # dispatch per token
            assert 0.0 < info["dispatches_per_token"] < 1.0
            await conn.close()
            while open_sessions:
                await open_sessions.pop().__aexit__(None, None, None)
            await asyncio.sleep(0.2)  # server-side teardown is async
            _assert_no_leaks(s)
        finally:
            for sess in open_sessions:
                await sess.__aexit__(None, None, None)
            await s.stop()
            await reg.stop()

    asyncio.run(run())


# --------------------------------------------------------- gate defaults
def test_mixed_batch_off_by_default(tiny_model_dir):
    """Without --mixed-batch / BBTPU_MIXED_BATCH a chunking server never
    fuses: generation is HF-exact and the mixed counters stay zero."""
    model_dir, hf_model, config = tiny_model_dir
    input_ids = (np.arange(11)[None, :] * 7 + 2) % config.vocab_size
    ref = _hf_greedy(hf_model, input_ids, 5)

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s = _server(
            model_dir, RegistryClient("127.0.0.1", reg.port), 0, 3,
            prefill_chunk=4,
        )
        await s.start()
        assert s.mixed_batch is False
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, RegistryClient("127.0.0.1", reg.port),
            model_uid="tiny",
        )
        try:
            ids = await model.generate(input_ids, max_new_tokens=5)
            np.testing.assert_array_equal(ids, ref)
            assert s.mixed_dispatches == 0
            assert s.mixed_tokens == 0
            conn = await connect("127.0.0.1", s.port)
            info, _ = await conn.call("rpc_info", {})
            assert info["mixed_batch"] is False
            assert info["mixed_dispatches"] == 0
            await conn.close()
        finally:
            await s.stop()
            await reg.stop()

    asyncio.run(run())


# ------------------------------------------------------------- chaos e2e
@pytest.mark.chaos
def test_mixed_batch_token_identical_under_chaos(
    tiny_model_dir, monkeypatch
):
    """Seeded frame delays reorder arrivals while concurrent prompts
    chunk-prefill and fuse with each other's decode steps: every stream
    stays exactly HF greedy."""
    model_dir, hf_model, config = tiny_model_dir
    monkeypatch.setenv("BBTPU_BATCH_WINDOW_MS", "8")

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s = _server(
            model_dir, RegistryClient("127.0.0.1", reg.port), 0, 3,
            prefill_chunk=4, max_batch=8, mixed_batch=True,
        )
        await s.start()

        plan = FaultPlan(seed=42)
        plan.add(FaultRule(site="send", action="delay", method="sitem",
                           prob=0.3, delay_s=0.02))
        faults.set_plan(plan)

        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, RegistryClient("127.0.0.1", reg.port),
            model_uid="tiny",
        )
        rng = np.random.default_rng(11)
        prompts = [
            rng.integers(0, config.vocab_size, size=(1, 9 + i))
            for i in range(3)
        ]
        try:
            outs = await asyncio.gather(*(
                model.generate(p, max_new_tokens=6) for p in prompts
            ))
            for p, got in zip(prompts, outs):
                ref = _hf_greedy(hf_model, p, 6)
                # HF generate stops at EOS; ours runs all 6 tokens —
                # compare the common prefix (the numerics statement)
                np.testing.assert_array_equal(
                    np.asarray(got)[:, :ref.shape[1]], ref
                )
            assert any(act == "delay" for _, act, _ in plan.log)
        finally:
            faults.set_plan(None)
            await s.stop()
            await reg.stop()

    asyncio.run(run())


# ------------------------------------- settled adoptions rejoin the batch
def test_settled_adoption_batches(tiny_model_dir, monkeypatch):
    """The decode-batcher carve-out for prefix-adopted sessions ends at
    the settle: with the adoption UNSETTLED both members run solo
    (batch_solo_steps), once adoption_settled both join ONE merged
    dispatch (batch_dispatches) despite has_adopted still reporting
    True."""
    model_dir, _, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s = _server(
            model_dir, RegistryClient("127.0.0.1", reg.port), 0, 3,
            max_batch=8,
        )
        await s.start()
        try:
            rng = np.random.default_rng(9)
            async with s.manager.allocate(1, 16, timeout=5.0) as h_a:
                async with s.manager.allocate(1, 16, timeout=5.0) as h_b:
                    for h in (h_a, h_b):
                        s.executor.prefill(
                            h,
                            (rng.standard_normal(
                                (1, 5, config.hidden_size)
                            ) * 0.1).astype(np.float32),
                        )
                    monkeypatch.setattr(
                        s.manager, "has_adopted", lambda handle: True
                    )
                    monkeypatch.setattr(
                        s.manager, "trim_adopted", lambda *a, **k: None
                    )

                    def members():
                        return [
                            _BatchMember(
                                sess, h,
                                (rng.standard_normal(
                                    (1, 1, config.hidden_size)
                                ) * 0.1).astype(np.float32),
                            )
                            for sess, h in zip(sessions, (h_a, h_b))
                        ]

                    sessions = [
                        _Session(f"adopt-{i}", h, 1)
                        for i, h in enumerate((h_a, h_b))
                    ]
                    # unsettled adoption: the members solo (the settle
                    # mutates the table; it cannot run mid-group)
                    assert all(
                        not sess.adoption_settled for sess in sessions
                    )
                    outs = s._compute_step_group(members())
                    assert not any(isinstance(o, Exception) for o in outs)
                    assert s.batch_solo_steps == 2
                    assert s.batch_dispatches == 0
                    # _compute_step settled them; the flag lifts the
                    # carve-out even while has_adopted stays True
                    assert all(sess.adoption_settled for sess in sessions)
                    outs = s._compute_step_group(members())
                    assert not any(isinstance(o, Exception) for o in outs)
                    assert s.batch_solo_steps == 2
                    assert s.batch_dispatches == 1
        finally:
            await s.stop()
            await reg.stop()

    asyncio.run(run())
