"""The jitted span step: all local blocks, one compiled function.

Equivalent of the reference's merged-pool inference step
(/root/reference/src/bloombee/server/backend.py:1368-1399
`_MergedInferenceStep` runs every local block in one pool call, and
backend.py:487-789 `inference_step` does select-cache -> mask -> forward ->
finalize per block). Here the whole span is a single `lax.scan` over stacked
block params; the paged KV arena rides the scan as per-layer xs/ys so XLA can
alias the donated buffers, and the attention mask is computed once from
positions + context lengths.

Shape discipline (SURVEY.md section 7 hard part #1): everything is padded to
static buckets — batch, step tokens T, and cache pages — and validity is
carried by `ctx_lens` / position masks. Out-of-bucket padding rows scatter to
out-of-bounds slots, which jax drops (`mode="drop"`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from bloombee_tpu.kv.arena import arena_write, gather_pages
from bloombee_tpu.models.spec import ModelSpec
from bloombee_tpu.ops import apply_rotary, rms_norm, silu_mlp
from bloombee_tpu.ops.attention import NEG_INF, repeat_kv
from bloombee_tpu.ops.rotary import rotary_cos_sin


def _attend_paged(
    spec: ModelSpec,
    q: jax.Array,  # [B, T, H, hd]
    k_ctx: jax.Array,  # [B, S, Hkv, hd] gathered pages (incl. current tokens)
    v_ctx: jax.Array,
    q_positions: jax.Array,  # [B, T] absolute positions (padding rows: 0)
    total_lens: jax.Array,  # [B] valid cache length incl. current tokens
    tree_mask: jax.Array | None,  # [B, T, T] visibility among current tokens
    window: int = 0,  # sliding-window size; 0 = full attention
) -> jax.Array:
    b, t = q.shape[:2]
    s = k_ctx.shape[1]
    key_pos = jnp.arange(s, dtype=jnp.int32)[None, None, :]  # [1, 1, S]
    q_pos = q_positions[:, :, None]  # [B, T, 1]
    valid = key_pos < total_lens[:, None, None]
    causal = key_pos <= q_pos
    mask = valid & causal
    if window:
        mask &= key_pos > (q_pos - window)
    if tree_mask is not None:
        # Current step's tokens sit at absolute positions total-T .. total-1 in
        # cache order; override causal visibility among them with the tree mask
        # (reference: backend.py:596-652 tree attention mask build).
        step_start = (total_lens - t)[:, None, None]  # [B, 1, 1]
        in_step = (key_pos >= step_start) & (key_pos < total_lens[:, None, None])
        # scatter tree_mask [B, T, T] onto key positions
        rel = key_pos - step_start  # [B, 1, S]
        rel_c = jnp.clip(rel, 0, t - 1)
        tree_on_keys = jnp.take_along_axis(
            tree_mask, jnp.broadcast_to(rel_c, (b, t, s)), axis=2
        )
        mask = jnp.where(in_step, tree_on_keys & valid, mask)

    n_rep = spec.num_attention_heads // spec.num_key_value_heads
    k_r = repeat_kv(k_ctx, n_rep)
    v_r = repeat_kv(v_ctx, n_rep)
    scale = (
        spec.attention_multiplier
        if spec.attention_multiplier is not None
        else spec.head_dim**-0.5
    )
    logits = jnp.einsum("bthd,bshd->bhts", q, k_r).astype(jnp.float32) * scale
    logits = jnp.where(mask[:, None, :, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhts,bshd->bthd", probs, v_r)


def _layer_body(
    spec: ModelSpec,
    page_size: int,
    hidden: jax.Array,  # [B, T, D]
    params: dict,  # one layer's params
    k_slab: jax.Array,  # [S_tot, Hkv, hd]
    v_slab: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
    slots: jax.Array,  # [B*T] flat write slots (OOB => dropped)
    page_table: jax.Array,  # [B, max_pages]
    q_positions: jax.Array,
    total_lens: jax.Array,
    tree_mask: jax.Array | None,
    window: int,
):
    b, t, d = hidden.shape
    h_heads, kv_heads, hd = (
        spec.num_attention_heads,
        spec.num_key_value_heads,
        spec.head_dim,
    )
    x = rms_norm(hidden, params["input_layernorm"], spec.rms_norm_eps)
    q = (x @ params["q_proj"]).reshape(b, t, h_heads, hd)
    k = (x @ params["k_proj"]).reshape(b, t, kv_heads, hd)
    v = (x @ params["v_proj"]).reshape(b, t, kv_heads, hd)
    if spec.qk_norm:
        q = rms_norm(q, params["q_norm"], spec.rms_norm_eps)
        k = rms_norm(k, params["k_norm"], spec.rms_norm_eps)
    q, k = apply_rotary(q, k, cos, sin)

    k_slab, v_slab = arena_write(
        k_slab, v_slab, slots,
        k.reshape(b * t, kv_heads, hd), v.reshape(b * t, kv_heads, hd),
    )
    k_ctx = gather_pages(k_slab, page_table, page_size).astype(hidden.dtype)
    v_ctx = gather_pages(v_slab, page_table, page_size).astype(hidden.dtype)

    attn = _attend_paged(
        spec, q, k_ctx, v_ctx, q_positions, total_lens, tree_mask, window
    )
    hidden = hidden + attn.reshape(b, t, h_heads * hd) @ params["o_proj"]

    x = rms_norm(hidden, params["post_attention_layernorm"], spec.rms_norm_eps)
    hidden = hidden + silu_mlp(
        x, params["gate_proj"], params["up_proj"], params["down_proj"]
    )
    return hidden, k_slab, v_slab


def unpack_plan(plan: jax.Array, b: int, t: int, max_pages: int, num_layers: int):
    """Split the packed int32 plan array back into its parts.

    The plan packs [slots(B*T) | page_table(B*max_pages) | positions(B*T) |
    total_lens(B) | layer_active(L)] into one int32 vector so a step costs ONE
    host->device transfer for all control data (transfer latency dominates on
    DCN-attached hosts; cf. the reference's single metadata sidecar per
    request, handler.py rpc metadata). `layer_active` gates which of the
    server's layers run — a session entering mid-span (suffix sub-span
    routing, reference `spans_containing_block`) skips the leading layers.
    """
    o1 = b * t
    o2 = o1 + b * max_pages
    o3 = o2 + b * t
    o4 = o3 + b
    slots = plan[:o1]
    page_table = plan[o1:o2].reshape(b, max_pages)
    q_positions = plan[o2:o3].reshape(b, t)
    total_lens = plan[o3:o4]
    layer_active = plan[o4 : o4 + num_layers]
    return slots, page_table, q_positions, total_lens, layer_active


def pack_plan(slots, page_table, q_positions, total_lens, layer_active):
    import numpy as np

    return np.concatenate(
        [
            np.ravel(slots).astype(np.int32),
            np.ravel(page_table).astype(np.int32),
            np.ravel(q_positions).astype(np.int32),
            np.ravel(total_lens).astype(np.int32),
            np.ravel(layer_active).astype(np.int32),
        ]
    )


def span_step_impl(
    stacked_params: dict,  # pytree, leading dim L on every leaf
    arena_k: jax.Array,  # [L, S_tot, Hkv, hd] (donated)
    arena_v: jax.Array,  # [L, S_tot, Hkv, hd] (donated)
    hidden: jax.Array,  # [B, T, D]
    plan: jax.Array,  # packed int32 (see unpack_plan)
    tree_mask: jax.Array | None = None,  # [B, T, T] bool
    *,
    spec: ModelSpec,
    page_size: int,
    max_pages: int,
    use_tree_mask: bool = False,
    window: int = 0,
):
    """Run all local blocks over one step; returns (hidden, arena_k, arena_v).

    Rotary cos/sin are computed on-device from the plan's positions (no
    per-step host tables), in fp32 like HF.
    """
    b, t, _ = hidden.shape
    num_layers = arena_k.shape[0]
    slots, page_table, q_positions, total_lens, layer_active = unpack_plan(
        plan, b, t, max_pages, num_layers
    )
    cos, sin = rotary_cos_sin(q_positions, spec.head_dim, spec.rope_theta)
    cos = cos.astype(hidden.dtype)
    sin = sin.astype(hidden.dtype)

    tm = tree_mask if use_tree_mask else None

    def body(h, xs):
        params_l, k_l, v_l, active = xs

        def run(h, k_l, v_l):
            return _layer_body(
                spec, page_size, h, params_l, k_l, v_l, cos, sin, slots,
                page_table, q_positions, total_lens, tm, window,
            )

        def skip(h, k_l, v_l):
            return h, k_l, v_l

        h, k_l, v_l = lax.cond(active > 0, run, skip, h, k_l, v_l)
        return h, (k_l, v_l)

    hidden, (arena_k, arena_v) = lax.scan(
        body, hidden, (stacked_params, arena_k, arena_v, layer_active)
    )
    return hidden, arena_k, arena_v


span_step = functools.partial(
    jax.jit,
    static_argnames=("spec", "page_size", "max_pages", "use_tree_mask", "window"),
    donate_argnames=("arena_k", "arena_v"),
)(span_step_impl)
