"""Swarm load balancing: which blocks should a new server host?

Port of /root/reference/src/bloombee/server/block_selection.py:12-95:
build the per-block aggregate-throughput vector from announced spans, pick
the contiguous window with minimum total throughput (the least-served
region), and decide whether an existing server should move
(`should_choose_other_blocks` with the balance_quality=0.75 hysteresis so
servers don't thrash).
"""

from __future__ import annotations

import numpy as np

from bloombee_tpu.swarm.data import ModuleInfo, RemoteSpanInfo

BALANCE_QUALITY = 0.75


def block_throughputs(module_infos: list[ModuleInfo]) -> np.ndarray:
    """Aggregate announced throughput per block."""
    out = np.zeros(len(module_infos))
    for i, info in enumerate(module_infos):
        for server in info.servers.values():
            out[i] += server.throughput or 0.0
    return out


def choose_best_blocks(
    module_infos: list[ModuleInfo],
    spans: dict[str, RemoteSpanInfo],
    num_blocks: int,
) -> tuple[int, int]:
    """Least-served contiguous window of `num_blocks`."""
    tput = block_throughputs(module_infos)
    num_blocks = min(num_blocks, len(tput))
    best_start, best_sum = 0, float("inf")
    for start in range(len(tput) - num_blocks + 1):
        s = float(tput[start : start + num_blocks].sum())
        if s < best_sum:
            best_start, best_sum = start, s
    return best_start, best_start + num_blocks


def rebalance_target(
    peer_id: str,
    module_infos: list[ModuleInfo],
    spans: dict[str, RemoteSpanInfo],
) -> tuple[int, int] | None:
    """The (start, end) this server should move its span to, or None when
    staying put is within the hysteresis margin. Simulates leaving and
    re-landing at every window, keeping the one that maximizes the swarm's
    bottleneck (minimum per-block) throughput; a move only wins if it
    beats the current bottleneck by more than BALANCE_QUALITY (reference
    should_choose_other_blocks, block_selection.py:40-95)."""
    my_span = spans.get(peer_id)
    if my_span is None:
        return None
    tput = block_throughputs(module_infos)
    current_min = float(tput.min())

    # simulate leaving
    without = tput.copy()
    without[my_span.start : my_span.end] -= my_span.server_info.throughput or 0.0
    # best place to re-land
    n = my_span.length
    best, best_start = None, None
    for start in range(len(tput) - n + 1):
        cand = without.copy()
        cand[start : start + n] += my_span.server_info.throughput or 0.0
        m = float(cand.min())
        if best is None or m > best:
            best, best_start = m, start
    if best is not None and best * BALANCE_QUALITY > current_min:
        return (best_start, best_start + n)
    return None


def should_choose_other_blocks(
    peer_id: str,
    module_infos: list[ModuleInfo],
    spans: dict[str, RemoteSpanInfo],
) -> bool:
    """Would moving this server's span improve the swarm's bottleneck
    throughput by more than the hysteresis margin?"""
    if spans.get(peer_id) is None:
        return True
    return rebalance_target(peer_id, module_infos, spans) is not None


def estimate_block_bytes(spec, dtype) -> int:
    """Parameter bytes of one block (reference block_utils.get_block_size:
    param count x dtype width, meta-device instantiation not needed — the
    spec already knows the shapes)."""
    import numpy as np

    d, i = spec.hidden_size, spec.intermediate_size
    h, kv, hd = (
        spec.num_attention_heads, spec.num_key_value_heads, spec.head_dim,
    )
    attn = d * h * hd + 2 * d * kv * hd + h * hd * d
    if spec.num_experts:
        mlp = spec.num_experts * 3 * d * i + d * spec.num_experts
    elif spec.mlp_type == "silu" or spec.mlp_type == "gelu_tanh_gated":
        mlp = 3 * d * i
    else:
        mlp = 2 * d * i
    norms = 4 * d
    itemsize = np.dtype(dtype).itemsize if dtype is not None else 2
    return (attn + mlp + norms) * itemsize


def choose_num_blocks(
    spec, dtype, num_pages: int, page_size: int, memory_fraction: float = 0.8
) -> int:
    """How many blocks fit in this device's memory, after the KV arena
    (reference Server._choose_num_blocks, server.py:427-477). Falls back to
    the whole model when the backend exposes no memory stats (e.g. CPU)."""
    import numpy as np

    import jax

    try:
        stats = jax.devices()[0].memory_stats()
        limit = stats["bytes_limit"]
    except Exception:
        return spec.num_hidden_layers
    per_block = estimate_block_bytes(spec, dtype)
    arena_bytes = (
        num_pages * page_size * spec.num_key_value_heads * spec.head_dim
        * 2 * np.dtype(dtype).itemsize
    )  # per layer (k+v)
    budget = limit * memory_fraction
    n = int(budget // (per_block + arena_bytes))
    return max(1, min(n, spec.num_hidden_layers))


async def rebalance_if_needed(server) -> bool:
    """Periodic check driven by the server's supervisor loop: fetch swarm
    state, decide, and MOVE (drain, reload the new span, re-announce) via
    server.rebalance_to. Returns True when a move happened (reference
    server.py:479-542 _should_choose_other_blocks + restart loop)."""
    from bloombee_tpu.swarm.spans import compute_spans

    infos = await server.registry.get_module_infos(
        server.model_uid, range(server.spec.num_hidden_layers)
    )
    # a DRAINING server is leaving: its span is not real coverage, so the
    # balance decision must see the post-departure swarm
    target = rebalance_target(
        server.server_id, infos, compute_spans(infos, include_draining=False)
    )
    if target is None or target == (server.start_block, server.end_block):
        return False
    await server.rebalance_to(*target)
    return True
