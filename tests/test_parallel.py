"""Mesh parallelism tests on the 8-device virtual CPU mesh: ring attention
vs dense, tp+sp span forward vs single-device, GPipe pipeline vs sequential,
and the full (dp, pp, tp, sp) training step.
"""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from bloombee_tpu.models.llama.block import init_block_params
from bloombee_tpu.models.spec import ModelSpec
from bloombee_tpu.ops.attention import causal_mask, masked_attention
from bloombee_tpu.parallel.mesh import MeshConfig, make_mesh
from bloombee_tpu.parallel.pipeline import gpipe_forward
from bloombee_tpu.parallel.ring_attention import ring_attention
from bloombee_tpu.parallel.spmd import (
    param_specs,
    shard_span_params,
    spmd_span_forward,
)
from bloombee_tpu.parallel.train import (
    Frozen,
    Trainable,
    make_train_step,
    place_frozen,
)
from bloombee_tpu.utils.tree import stack_params

SPEC = ModelSpec(
    family="llama",
    hidden_size=32,
    intermediate_size=64,
    num_attention_heads=4,
    num_key_value_heads=2,
    head_dim=8,
    num_hidden_layers=4,
    vocab_size=64,
    rms_norm_eps=1e-5,
)


def dense_reference(params_list, hidden):
    """Sequential single-device forward for comparison."""
    from bloombee_tpu.models.llama.block import block_forward, dense_attend
    from bloombee_tpu.ops.rotary import rotary_cos_sin

    b, s, _ = hidden.shape
    positions = jnp.broadcast_to(jnp.arange(s)[None], (b, s))
    cos, sin = rotary_cos_sin(positions, SPEC.head_dim, SPEC.rope_theta)
    h = hidden
    for p in params_list:
        h, _ = block_forward(p, SPEC, h, cos, sin, dense_attend())
    return h


def test_ring_attention_matches_dense():
    mesh = make_mesh(MeshConfig(sp=4))
    b, s, hq, hkv, hd = 2, 16, 4, 2, 8
    rng = jax.random.PRNGKey(0)
    q = jax.random.normal(rng, (b, s, hq, hd), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, hkv, hd), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, hkv, hd), jnp.float32)

    ref = masked_attention(q, k, v, causal_mask(s)[None])

    ring = jax.jit(
        jax.shard_map(
            functools.partial(ring_attention, axis_name="sp", causal=True),
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"),
            check_vma=False,
        )
    )
    out = ring(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_spmd_span_forward_matches_dense():
    mesh = make_mesh(MeshConfig(tp=2, sp=2))
    layers = [
        init_block_params(jax.random.PRNGKey(i), SPEC) for i in range(4)
    ]
    stacked = stack_params(layers)
    b, s = 2, 8
    hidden = jax.random.normal(jax.random.PRNGKey(9), (b, s, 32), jnp.float32)
    ref = dense_reference(layers, hidden)

    # pp=1: the whole span is one stage
    placed = shard_span_params(stacked, mesh)
    fwd = jax.jit(
        jax.shard_map(
            functools.partial(
                spmd_span_forward, spec=SPEC, sp_axis="sp", tp_axis="tp"
            ),
            mesh=mesh,
            in_specs=(param_specs(stacked), P(None, "sp", None)),
            out_specs=P(None, "sp", None),
            check_vma=False,
        )
    )
    out = fwd(placed, hidden)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-5)


def test_gpipe_matches_sequential():
    mesh = make_mesh(MeshConfig(pp=2, tp=2, sp=2))
    layers = [
        init_block_params(jax.random.PRNGKey(i), SPEC) for i in range(4)
    ]
    stacked = stack_params(layers)
    m, mb, s = 4, 1, 8
    hidden = jax.random.normal(
        jax.random.PRNGKey(3), (m, mb, s, 32), jnp.float32
    )
    ref = dense_reference(layers, hidden.reshape(m * mb, s, 32)).reshape(
        m, mb, s, 32
    )

    placed = shard_span_params(stacked, mesh)
    fwd = jax.jit(
        jax.shard_map(
            functools.partial(
                gpipe_forward, spec=SPEC, pp_axis="pp", sp_axis="sp",
                tp_axis="tp",
            ),
            mesh=mesh,
            in_specs=(param_specs(stacked), P(None, "dp", "sp", None)),
            out_specs=P(None, "dp", "sp", None),
            check_vma=False,
        )
    )
    out = fwd(placed, hidden)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=5e-5)


def test_spmd_moe_expert_parallel_consistent():
    """Mixtral-style MoE layer: expert-parallel (tp=2 shards the expert dim)
    must equal the unsharded run (tp=1). The MoE math itself is HF-verified
    in test_families.py; this checks the psum/slice sharding."""
    import jax.random as jr

    spec = ModelSpec(
        family="mixtral", hidden_size=32, intermediate_size=64,
        num_attention_heads=4, num_key_value_heads=2, head_dim=8,
        num_hidden_layers=2, vocab_size=64, num_experts=4,
        num_experts_per_tok=2,
    )
    layers = []
    for i in range(2):
        p = init_block_params(jr.PRNGKey(i), spec)
        for k in ("gate_proj", "up_proj", "down_proj"):
            del p[k]
        p["router"] = jr.normal(jr.PRNGKey(10 + i), (32, 4)) * 0.1
        p["experts_gate"] = jr.normal(jr.PRNGKey(20 + i), (4, 32, 64)) * 0.1
        p["experts_up"] = jr.normal(jr.PRNGKey(30 + i), (4, 32, 64)) * 0.1
        p["experts_down"] = jr.normal(jr.PRNGKey(40 + i), (4, 64, 32)) * 0.1
        layers.append(p)
    stacked = stack_params(layers)
    hidden = jr.normal(jr.PRNGKey(5), (2, 8, 32), jnp.float32)

    outs = {}
    for tp in (1, 2):
        mesh = make_mesh(MeshConfig(tp=tp, sp=2))
        placed = shard_span_params(stacked, mesh)
        fwd = jax.jit(
            jax.shard_map(
                functools.partial(
                    spmd_span_forward, spec=spec, sp_axis="sp", tp_axis="tp"
                ),
                mesh=mesh,
                in_specs=(param_specs(stacked), P(None, "sp", None)),
                out_specs=P(None, "sp", None),
                check_vma=False,
            )
        )
        outs[tp] = np.asarray(fwd(placed, hidden))
    np.testing.assert_allclose(outs[1], outs[2], atol=2e-5)


def test_full_mesh_train_step_learns():
    mesh = make_mesh(MeshConfig(dp=1, pp=2, tp=2, sp=2))
    layers = [
        init_block_params(jax.random.PRNGKey(i), SPEC) for i in range(4)
    ]
    frozen = place_frozen(
        Frozen(
            blocks=stack_params(layers),
            embed=jax.random.normal(
                jax.random.PRNGKey(7), (SPEC.vocab_size, 32), jnp.float32
            )
            * 0.1,
            norm=jnp.ones((32,), jnp.float32),
        ),
        mesh,
    )
    trainable = Trainable(
        prompts=jnp.zeros((4, 32), jnp.float32),
        lm_head=jax.random.normal(
            jax.random.PRNGKey(8), (32, SPEC.vocab_size), jnp.float32
        )
        * 0.1,
    )
    step = make_train_step(SPEC, mesh, num_micro=2, lr=0.5)

    rng = np.random.default_rng(0)
    # prompt(4) + input(8) = 12 positions, divisible by sp=2
    ids = rng.integers(0, SPEC.vocab_size, size=(4, 9))
    input_ids = jnp.asarray(ids[:, :-1])
    target_ids = jnp.asarray(ids[:, 1:])

    losses = []
    for _ in range(8):
        trainable, loss = step(trainable, frozen, input_ids, target_ids)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.9, losses  # it learns
    assert bool(jnp.any(trainable.prompts != 0))  # prompt grads flowed


def test_ulysses_matches_dense_and_ring():
    """Ulysses all-to-all sequence parallelism == dense causal attention ==
    ring attention, on a 4-device sp mesh."""
    import jax.random as jr
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from bloombee_tpu.ops.attention import causal_mask, masked_attention
    from bloombee_tpu.parallel.ring_attention import ring_attention
    from bloombee_tpu.parallel.ulysses import ulysses_attention

    b, s, h, hkv, hd = 2, 32, 8, 4, 16
    q = jr.normal(jr.PRNGKey(0), (b, s, h, hd), jnp.float32)
    k = jr.normal(jr.PRNGKey(1), (b, s, hkv, hd), jnp.float32)
    v = jr.normal(jr.PRNGKey(2), (b, s, hkv, hd), jnp.float32)
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("sp",))
    specs = (P(None, "sp"), P(None, "sp"), P(None, "sp"))

    uly = shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, axis_name="sp"),
        mesh=mesh, in_specs=specs, out_specs=P(None, "sp"),
        check_vma=False,
    )(q, k, v)
    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="sp"),
        mesh=mesh, in_specs=specs, out_specs=P(None, "sp"),
        check_vma=False,
    )(q, k, v)
    ref = masked_attention(q, k, v, causal_mask(s)[None])
    np.testing.assert_allclose(np.asarray(uly), np.asarray(ref),
                               atol=3e-5, rtol=3e-5)
    np.testing.assert_allclose(np.asarray(uly), np.asarray(ring),
                               atol=3e-5, rtol=3e-5)


def test_ulysses_kv_head_replication():
    """Hkv < sp: KV heads replicate across the mesh and results still match
    dense."""
    import jax.random as jr
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from bloombee_tpu.ops.attention import causal_mask, masked_attention
    from bloombee_tpu.parallel.ulysses import ulysses_attention

    b, s, h, hkv, hd = 1, 16, 4, 2, 8
    q = jr.normal(jr.PRNGKey(3), (b, s, h, hd), jnp.float32)
    k = jr.normal(jr.PRNGKey(4), (b, s, hkv, hd), jnp.float32)
    v = jr.normal(jr.PRNGKey(5), (b, s, hkv, hd), jnp.float32)
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("sp",))
    specs = (P(None, "sp"), P(None, "sp"), P(None, "sp"))
    uly = shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, axis_name="sp"),
        mesh=mesh, in_specs=specs, out_specs=P(None, "sp"),
        check_vma=False,
    )(q, k, v)
    ref = masked_attention(q, k, v, causal_mask(s)[None])
    np.testing.assert_allclose(np.asarray(uly), np.asarray(ref),
                               atol=3e-5, rtol=3e-5)


FALCON_SPEC = ModelSpec(
    family="falcon",
    hidden_size=32,
    intermediate_size=128,
    num_attention_heads=4,
    num_key_value_heads=2,
    head_dim=8,
    num_hidden_layers=2,
    vocab_size=64,
    norm_type="ln",
    parallel_attn=True,
    num_ln_in_parallel_attn=2,
    mlp_type="gelu",
)

QWEN2_SPEC = ModelSpec(
    family="qwen2",
    hidden_size=32,
    intermediate_size=64,
    num_attention_heads=4,
    num_key_value_heads=2,
    head_dim=8,
    num_hidden_layers=2,
    vocab_size=64,
)


def _rand_family_params(spec, seed, qkv_bias=False):
    """Random per-layer params for the family-generic body (no per-family
    init fn needed: the keys ARE the family definition)."""
    rng = np.random.default_rng(seed)
    d, inter = spec.hidden_size, spec.intermediate_size
    h, kv, hd = (
        spec.num_attention_heads, spec.num_key_value_heads, spec.head_dim
    )

    def w(*shape):
        return jnp.asarray(rng.normal(size=shape).astype(np.float32) * 0.05)

    p = {
        "q_proj": w(d, h * hd),
        "k_proj": w(d, kv * hd),
        "v_proj": w(d, kv * hd),
        "o_proj": w(h * hd, d),
        "up_proj": w(d, inter),
        "down_proj": w(inter, d),
        "input_layernorm": jnp.asarray(
            1.0 + rng.normal(size=(d,)).astype(np.float32) * 0.02
        ),
    }
    if spec.mlp_type in ("silu", "gelu_tanh_gated"):
        p["gate_proj"] = w(d, inter)
    if qkv_bias:
        p["q_bias"] = w(h * hd)
        p["k_bias"] = w(kv * hd)
        p["v_bias"] = w(kv * hd)
    if spec.norm_type == "ln":
        p["input_layernorm_bias"] = w(d)
    if spec.parallel_attn and spec.num_ln_in_parallel_attn == 2:
        p["mlp_layernorm"] = jnp.asarray(
            1.0 + rng.normal(size=(d,)).astype(np.float32) * 0.02
        )
        p["mlp_layernorm_bias"] = w(d)
    if not spec.parallel_attn:
        p["post_attention_layernorm"] = jnp.asarray(
            1.0 + rng.normal(size=(d,)).astype(np.float32) * 0.02
        )
        if spec.norm_type == "ln":
            p["post_attention_layernorm_bias"] = w(d)
    return p


@pytest.mark.parametrize(
    "spec,qkv_bias",
    [(FALCON_SPEC, False), (QWEN2_SPEC, True)],
    ids=["falcon_ln_parallel_gelu", "qwen2_biased_qkv"],
)
def test_spmd_span_forward_non_llama_families(spec, qkv_bias):
    """Family-generic SPMD body vs the serving-side dense forward (the
    same layer_body the servers run): falcon's LN + parallel-attn + plain
    GELU and qwen2's biased qkv must both agree under tp=2 x sp=2
    (round-4 verdict: the spmd path covered llama only)."""
    from bloombee_tpu.runtime.training import _train_plan, span_train_forward

    mesh = make_mesh(MeshConfig(tp=2, sp=2))
    layers = [
        _rand_family_params(spec, 100 + i, qkv_bias=qkv_bias)
        for i in range(spec.num_hidden_layers)
    ]
    stacked = stack_params(layers)
    b, s = 2, 8
    hidden = jax.random.normal(
        jax.random.PRNGKey(11), (b, s, spec.hidden_size), jnp.float32
    )
    plan = _train_plan(b, s, spec.num_hidden_layers)
    ref = span_train_forward(
        stacked, hidden, jnp.asarray(plan), spec=spec,
        windows=tuple(0 for _ in range(spec.num_hidden_layers)),
    )

    placed = shard_span_params(stacked, mesh)
    fwd = jax.jit(
        jax.shard_map(
            functools.partial(
                spmd_span_forward, spec=spec, sp_axis="sp", tp_axis="tp"
            ),
            mesh=mesh,
            in_specs=(param_specs(stacked), P(None, "sp", None)),
            out_specs=P(None, "sp", None),
            check_vma=False,
        )
    )
    out = fwd(placed, hidden)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-5)


def test_spmd_sliding_window_family_fails_loudly():
    spec = ModelSpec(
        family="mistral", hidden_size=32, intermediate_size=64,
        num_attention_heads=4, num_key_value_heads=2, head_dim=8,
        num_hidden_layers=2, vocab_size=64,
        layer_types=("sliding", "sliding"), sliding_window=8,
    )
    mesh = make_mesh(MeshConfig(tp=2, sp=2))
    layers = [_rand_family_params(QWEN2_SPEC, i) for i in range(2)]
    stacked = stack_params(layers)
    hidden = jnp.zeros((2, 8, 32), jnp.float32)
    fwd = jax.shard_map(
        functools.partial(
            spmd_span_forward, spec=spec, sp_axis="sp", tp_axis="tp"
        ),
        mesh=mesh,
        in_specs=(param_specs(stacked), P(None, "sp", None)),
        out_specs=P(None, "sp", None),
        check_vma=False,
    )
    with pytest.raises(NotImplementedError, match="sliding-window"):
        fwd(shard_span_params(stacked, mesh), hidden)


@pytest.mark.parametrize("s,block", [(32, 4), (36, 4)],
                         ids=["tiled", "tiled_padded"])
def test_ring_attention_tiled_matches_dense(s, block):
    """Small in-step tile size forces the (q block, k block) online-softmax
    tiling (incl. the pad-to-block path) — results must match dense
    exactly like the untiled case."""
    sp = 4
    if s % sp:
        s_use = s - (s % sp)
    else:
        s_use = s
    mesh = make_mesh(MeshConfig(sp=sp))
    b, hq, hkv, hd = 2, 4, 2, 8
    q = jax.random.normal(jax.random.PRNGKey(0), (b, s_use, hq, hd),
                          jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s_use, hkv, hd),
                          jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s_use, hkv, hd),
                          jnp.float32)
    ref = masked_attention(q, k, v, causal_mask(s_use)[None])
    ring = jax.jit(
        jax.shard_map(
            functools.partial(
                ring_attention, axis_name="sp", causal=True, block=block
            ),
            mesh=mesh,
            in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
            out_specs=P(None, "sp"),
            check_vma=False,
        )
    )
    out = ring(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
