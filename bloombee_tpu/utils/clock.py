"""Process-wide virtual clock: the single source of time for package code.

Every timing decision in the package (lease deadlines, ban/quarantine
backoff, keepalive idle detection, promotion sustain windows, admission
watermark ages) reads THIS module instead of ``time`` directly, so tests
can substitute a scaled or hand-stepped clock and run minutes of protocol
time in milliseconds of wall time — with bit-identical state transitions,
because the code under test never sees the substitution.

Three implementations:

- ``RealClock`` (the default): a 1:1 delegate to ``time`` /
  ``asyncio.sleep``. Byte-for-byte identical behavior to the raw calls it
  replaces — production never pays for the indirection with changed
  semantics.
- ``ScaledClock(scale)``: virtual time runs ``scale``× faster than wall
  time from the moment of installation; sleeps shrink by the same factor.
  Deadline math composed before and after installation stays coherent
  because the virtual timeline is anchored at the install instant. Used
  by e2e tests whose background loops (reapers, keepalives, announcers)
  must all speed up *together*.
- ``SteppableClock``: time is frozen until ``advance(dt)`` moves it.
  Sync sleepers block on a condition keyed to virtual time; async
  sleepers park on futures resolved by ``advance`` (thread-safely, via
  their own loop). Used by pure state-machine tests (bans, quarantine)
  that want zero real waiting and exact control of "when".

The module-level helpers (``now``/``monotonic``/``sleep``/``async_sleep``/
``deadline``/``remaining``/``cond_wait``) consult the installed clock on
every call, so installation mid-process retargets all package code at
once. ``perf_counter`` always reads the real clock: it feeds throughput
*measurements* (t_compute_ms stamps), never timing *decisions*, and a
scaled measurement would lie to operators.

bbtpu-lint BB008 enforces the contract: raw ``time.time`` /
``time.monotonic`` / ``time.sleep`` in package code outside this module
is a lint error.
"""

from __future__ import annotations

import asyncio
import heapq
import itertools
import threading
import time as _time

from bloombee_tpu.utils import env

env.declare(
    "BBTPU_CLOCK_SCALE", float, 1.0,
    "virtual-clock speedup: >1 installs a ScaledClock running this many "
    "times faster than wall time (sleeps shrink to match), so "
    "timing-dependent recovery paths (leases, bans, promotion windows) "
    "run in compressed wall time; 1.0 = real time, byte-for-byte",
)


class Clock:
    """Time source interface. ``time()`` is wall-clock (registry record
    stamps, NTP-style sync anchors); ``monotonic()`` is for intervals and
    deadlines; both advance on the same virtual timeline."""

    def time(self) -> float:
        raise NotImplementedError

    def monotonic(self) -> float:
        raise NotImplementedError

    def sleep(self, seconds: float) -> None:
        raise NotImplementedError

    async def async_sleep(self, seconds: float) -> None:
        raise NotImplementedError

    async def cond_wait(self, cond: asyncio.Condition,
                        timeout: float | None) -> None:
        """Wait on an already-acquired asyncio.Condition with a timeout
        measured on THIS clock. Raises asyncio.TimeoutError on expiry.
        May wake spuriously (callers re-check their predicate in a loop,
        per the Condition contract)."""
        raise NotImplementedError


class RealClock(Clock):
    """The default: a 1:1 delegate to the stdlib. No added semantics."""

    def time(self) -> float:
        return _time.time()

    def monotonic(self) -> float:
        return _time.monotonic()

    def sleep(self, seconds: float) -> None:
        _time.sleep(seconds)

    async def async_sleep(self, seconds: float) -> None:
        await asyncio.sleep(seconds)

    async def cond_wait(self, cond: asyncio.Condition,
                        timeout: float | None) -> None:
        await asyncio.wait_for(cond.wait(), timeout)


class ScaledClock(Clock):
    """Virtual time = anchor + (real - anchor) * scale, anchored at
    construction so pre-installation timestamps remain meaningful (they
    simply age faster from here on). Sleeps divide by the scale."""

    def __init__(self, scale: float):
        if scale <= 0:
            raise ValueError(f"clock scale must be > 0, got {scale}")
        self.scale = float(scale)
        self._anchor_mono = _time.monotonic()
        self._anchor_wall = _time.time()

    def time(self) -> float:
        return self._anchor_wall + (
            _time.time() - self._anchor_wall
        ) * self.scale

    def monotonic(self) -> float:
        return self._anchor_mono + (
            _time.monotonic() - self._anchor_mono
        ) * self.scale

    def sleep(self, seconds: float) -> None:
        _time.sleep(max(0.0, seconds) / self.scale)

    async def async_sleep(self, seconds: float) -> None:
        await asyncio.sleep(max(0.0, seconds) / self.scale)

    async def cond_wait(self, cond: asyncio.Condition,
                        timeout: float | None) -> None:
        real = None if timeout is None else max(0.0, timeout) / self.scale
        await asyncio.wait_for(cond.wait(), real)


class SteppableClock(Clock):
    """Hand-stepped time: frozen until ``advance(dt)``. Thread-safe —
    sync sleepers may block in worker threads while ``advance`` is called
    from the test thread; async sleepers are resolved on their own event
    loop via ``call_soon_threadsafe``."""

    def __init__(self, start: float = 1000.0):
        self._now = float(start)
        self._wall_anchor = _time.time() - float(start)
        self._cond = threading.Condition()
        self._seq = itertools.count()
        # (virtual deadline, seq, loop, future) min-heap of async sleepers
        self._async_waiters: list = []
        # virtual deadlines of threads currently blocked in sleep();
        # sim engines read these (via next_deadline/blocked_sleepers) to
        # decide how far to auto-advance without overshooting a waker
        self._sync_deadlines: dict = {}

    def time(self) -> float:
        with self._cond:
            return self._wall_anchor + self._now

    def monotonic(self) -> float:
        with self._cond:
            return self._now

    def advance(self, dt: float) -> None:
        """Move virtual time forward, waking every sleeper whose deadline
        has come due (sync sleepers via the condition, async sleepers on
        their own loop)."""
        if dt < 0:
            raise ValueError(f"cannot step time backwards ({dt})")
        due = []
        with self._cond:
            self._now += dt
            while self._async_waiters and (
                self._async_waiters[0][0] <= self._now
            ):
                due.append(heapq.heappop(self._async_waiters))
            self._cond.notify_all()
        for _, _, loop, fut in due:
            loop.call_soon_threadsafe(
                lambda f=fut: f.done() or f.set_result(None)
            )

    def sleep(self, seconds: float) -> None:
        key = (threading.get_ident(), next(self._seq))
        with self._cond:
            deadline = self._now + max(0.0, seconds)
            self._sync_deadlines[key] = deadline
            try:
                while self._now < deadline:
                    self._cond.wait()
            finally:
                del self._sync_deadlines[key]

    def next_deadline(self) -> float | None:
        """Earliest virtual deadline any sleeper (sync or async) is
        waiting for, or None when nobody is sleeping. A discrete-event
        driver advances exactly to this instant so no sleeper oversleeps
        virtual time."""
        with self._cond:
            cands = list(self._sync_deadlines.values())
            if self._async_waiters:
                cands.append(self._async_waiters[0][0])
            return min(cands) if cands else None

    def blocked_sleepers(self) -> int:
        """Number of threads currently blocked inside sleep() (async
        sleepers are visible via next_deadline, not counted here)."""
        with self._cond:
            return len(self._sync_deadlines)

    async def async_sleep(self, seconds: float) -> None:
        if seconds <= 0:
            await asyncio.sleep(0)
            return
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        with self._cond:
            heapq.heappush(
                self._async_waiters,
                (self._now + seconds, next(self._seq), loop, fut),
            )
        await fut

    async def cond_wait(self, cond: asyncio.Condition,
                        timeout: float | None) -> None:
        if timeout is None:
            await cond.wait()
            return
        deadline = self.monotonic() + timeout
        # poll in short real slices against the virtual deadline: the
        # notifier may live on another thread and advance() between slices
        while True:
            try:
                await asyncio.wait_for(cond.wait(), 0.005)
                return
            except asyncio.TimeoutError:
                if self.monotonic() >= deadline:
                    raise
                # wait_for re-acquired the condition lock for us; loop


_clock: Clock | None = None
_env_checked = False


def get() -> Clock:
    """The installed clock; lazily built from env once (RealClock unless
    BBTPU_CLOCK_SCALE says otherwise)."""
    global _clock, _env_checked
    if _clock is None:
        if not _env_checked:
            _env_checked = True
            scale = float(env.get("BBTPU_CLOCK_SCALE"))
            _clock = ScaledClock(scale) if scale != 1.0 else RealClock()
        else:
            _clock = RealClock()
    return _clock


def install(clock: Clock | None) -> Clock | None:
    """Install a process-wide clock (tests). None resets to RealClock.
    Returns the previously installed clock."""
    global _clock, _env_checked
    prev = _clock
    _clock = clock
    _env_checked = True  # an explicit clock overrides the env knob
    return prev


def reset() -> None:
    """Back to the pristine lazy state (test teardown): the next get()
    re-reads BBTPU_CLOCK_SCALE, so with no env override this is the
    default RealClock."""
    global _clock, _env_checked
    _clock = None
    _env_checked = False


def now() -> float:
    """Wall-clock seconds (virtual timeline)."""
    return get().time()


def monotonic() -> float:
    """Monotonic seconds (virtual timeline) — intervals and deadlines."""
    return get().monotonic()


def perf_counter() -> float:
    """ALWAYS the real high-resolution counter: measurement, not timing
    decisions. Compute-time stamps must reflect actual hardware speed
    even under a scaled test clock."""
    return _time.perf_counter()


def sleep(seconds: float) -> None:
    get().sleep(seconds)


async def async_sleep(seconds: float) -> None:
    await get().async_sleep(seconds)


def deadline(timeout: float | None) -> float | None:
    """monotonic() + timeout, passing None through."""
    return None if timeout is None else monotonic() + timeout


def remaining(dl: float | None) -> float | None:
    """Seconds until a deadline() value, None for no deadline."""
    return None if dl is None else dl - monotonic()


async def cond_wait(cond: asyncio.Condition,
                    timeout: float | None) -> None:
    """asyncio.Condition.wait with a virtual-clock timeout (raises
    asyncio.TimeoutError on expiry; condition must be held)."""
    await get().cond_wait(cond, timeout)
