"""HF checkpoint reading (safetensors, torch-free).

Replaces the reference's per-block HF-hub state-dict loading and .npy weight
conversion (/root/reference/src/bloombee/server/from_pretrained.py:58-548,
models/llama/block.py:329-384): server loads only its span's layers; client
loads only embeddings + final norm + lm head (reference
client/from_pretrained.py:17-70 skips `model.layers.*`).

Zero-egress note: model directories are local paths (config.json +
*.safetensors [+ index]); hub download plumbing can wrap this later.
"""

from __future__ import annotations

import json
import pathlib

import numpy as np
from safetensors import safe_open

from bloombee_tpu.models.spec import ModelSpec


class CheckpointReader:
    """Lazy tensor reader over a local HF model directory."""

    def __init__(self, model_dir: str | pathlib.Path):
        self.dir = pathlib.Path(model_dir)
        with open(self.dir / "config.json") as f:
            self.config = json.load(f)
        index_path = self.dir / "model.safetensors.index.json"
        if index_path.exists():
            with open(index_path) as f:
                index = json.load(f)
            self._weight_map = index["weight_map"]
        else:
            files = sorted(self.dir.glob("*.safetensors"))
            if not files:
                raise FileNotFoundError(f"no safetensors in {self.dir}")
            self._weight_map = {}
            for fp in files:
                with safe_open(fp, framework="numpy") as f:
                    for k in f.keys():
                        self._weight_map[k] = fp.name
        self._handles: dict[str, object] = {}

    def keys(self):
        return self._weight_map.keys()

    def has(self, name: str) -> bool:
        return name in self._weight_map

    def tensor(self, name: str) -> np.ndarray:
        fname = self._weight_map[name]
        h = self._handles.get(fname)
        if h is None:
            h = safe_open(self.dir / fname, framework="numpy")
            self._handles[fname] = h
        return h.get_tensor(name)

    def model_type(self) -> str:
        return self.config.get("model_type", "llama")


def read_tensor(reader: CheckpointReader, name: str, dtype=None):
    """Read one tensor as a jnp array with optional dtype cast (the shared
    helper for family weight converters)."""
    import jax.numpy as jnp

    w = jnp.asarray(reader.tensor(name))
    return w.astype(dtype) if dtype is not None else w


def load_spec(model_dir: str) -> ModelSpec:
    """ModelSpec from a local model dir via the family registry."""
    from bloombee_tpu.models.auto import spec_from_config_dict

    reader = CheckpointReader(model_dir)
    return spec_from_config_dict(reader.config)


def load_span_params(
    model_dir: str, start: int, end: int, dtype=None
):
    """Stacked per-layer params for blocks [start, end)."""
    from bloombee_tpu.models.auto import get_family
    from bloombee_tpu.utils.tree import stack_params

    reader = CheckpointReader(model_dir)
    family = get_family(reader.model_type())
    layers = [
        family.load_block_params(reader, i, dtype=dtype)
        for i in range(start, end)
    ]
    return stack_params(layers), family.spec_from_config_dict(reader.config)


def load_client_params(model_dir: str, dtype=None) -> dict:
    """Embeddings + final norm + LM head (the client-side trio), plus any
    family extras (embedding layernorm, norm bias, tied heads)."""
    import jax.numpy as jnp

    from bloombee_tpu.models.auto import get_family

    reader = CheckpointReader(model_dir)
    family = get_family(reader.model_type())
    if family.client_loader is not None:
        return family.client_loader(reader, dtype=dtype)
    names = family.client_param_names()
    embed = jnp.asarray(reader.tensor(names["embed"]))
    norm = jnp.asarray(reader.tensor(names["norm"]))
    if reader.has(names["lm_head"]):
        head = jnp.asarray(reader.tensor(names["lm_head"])).T
    else:  # tied embeddings
        head = embed.T
    if dtype is not None:
        embed, norm, head = (
            embed.astype(dtype), norm.astype(dtype), head.astype(dtype)
        )
    return {"embed": embed, "norm": norm, "lm_head": head}
