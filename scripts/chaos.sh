#!/usr/bin/env bash
# Chaos gate: replay the chaos-marked suite under a fixed seed matrix of
# ambient wire faults (the BBTPU_CHAOS_* env plan). Each entry is
# "SEED:DELAY_P:ADMIT:PARTITION_P:MIXED:SPEC:REBALANCE" — mild delay-only ambient
# chaos, so
# the per-test seeded FaultPlans stay the dominant fault source while
# connections opened before a test installs its plan still see injected
# jitter; the ADMIT flag additionally turns on server admission control
# (BBTPU_ADMIT, low high-watermark) so the overload scenario exercises
# shed-and-reroute recovery paths under the same ambient jitter; a
# nonzero PARTITION_P silently blackholes connections mid-flight (no
# FIN/RST), so keepalive half-open detection plus lease park/resume are
# what keep the suite green (keepalive is forced small for that entry);
# MIXED=1 turns on mixed-batch dispatch (BBTPU_MIXED_BATCH) so the fused
# decode+prefill path and its solo-replay failure recovery run under the
# same ambient jitter; SPEC=1 turns on batched tree-speculative
# verification (BBTPU_SPEC_BATCH) so grouped tree-verify dispatches and
# their rollback-then-solo-replay recovery run under ambient jitter too;
# REBALANCE=1 turns on the elastic self-healing control loop — measured-
# load rebalancing (BBTPU_MEASURED_REBALANCE) plus fast standby-promotion
# watermarks (BBTPU_PROMOTE_*) — so promotion/demotion decisions and the
# rebalance supervisor run against the same flaky-registry + wire jitter
# the chaos plans inject.
# Fixed seeds keep every run replayable bit-for-bit (wire/faults.py
# contract).
# Exits 0 when pytest is unavailable (mirrors scripts/lint.sh).
set -euo pipefail
cd "$(dirname "$0")/.."

if ! python -c "import pytest" >/dev/null 2>&1; then
    echo "chaos: pytest not installed; skipping" >&2
    exit 0
fi

MATRIX=("11:0.05:0:0:0:0:0" "23:0.1:0:0:0:0:0" "31:0.05:1:0:0:0:0"
        "43:0.02:0:0.02:0:0:0" "57:0.05:0:0:1:0:0" "71:0.05:0:0:0:1:0"
        "83:0.05:0:0:0:0:1")
for entry in "${MATRIX[@]}"; do
    IFS=: read -r seed delay_p admit partition_p mixed spec rebalance <<<"${entry}"
    partition_p="${partition_p:-0}"
    mixed="${mixed:-0}"
    spec="${spec:-0}"
    rebalance="${rebalance:-0}"
    # partitioned conns go silent instead of erroring: a small keepalive
    # turns the blackhole into a prompt local abort so lease park/resume
    # (not a step_timeout expiry) is the recovery path under test
    keepalive_s=0
    if [ "${partition_p}" != "0" ]; then
        keepalive_s=0.5
    fi
    # the rebalance entry runs with hair-trigger promotion watermarks so
    # the standby control loop actually fires inside short chaos tests
    promote_high_ms=1500
    promote_sustain_s=10
    if [ "${rebalance}" != "0" ]; then
        promote_high_ms=500
        promote_sustain_s=0.3
    fi
    echo "chaos: seed=${seed} delay_p=${delay_p} admit=${admit}" \
         "partition_p=${partition_p} mixed=${mixed} spec=${spec}" \
         "rebalance=${rebalance}" >&2
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    BBTPU_CHAOS=1 \
    BBTPU_CHAOS_SEED="${seed}" \
    BBTPU_CHAOS_DELAY_P="${delay_p}" \
    BBTPU_CHAOS_DELAY_S=0.02 \
    BBTPU_CHAOS_PARTITION_P="${partition_p}" \
    BBTPU_KEEPALIVE_S="${keepalive_s}" \
    BBTPU_ADMIT="${admit}" \
    BBTPU_ADMIT_HIGH_MS=400 \
    BBTPU_MIXED_BATCH="${mixed}" \
    BBTPU_SPEC_BATCH="${spec}" \
    BBTPU_MEASURED_REBALANCE="${rebalance}" \
    BBTPU_PROMOTE_HIGH_MS="${promote_high_ms}" \
    BBTPU_PROMOTE_SUSTAIN_S="${promote_sustain_s}" \
    python -m pytest tests/ -q -m chaos \
        -p no:cacheprovider -p no:xdist -p no:randomly "$@"
done
