"""Swarm-shared compile-artifact cache (server/artifacts.py + the
BlockServer artifact_get/artifact_put surface): zero-cold-start recovery.

Unit half: the bounded on-disk store (digest declines, path-escape
declines, LRU eviction under the cap), the compatibility fingerprint
(covering spans pass, anything else names the mismatching key), and the
strengthened CLI gates (ledger --require-recovery, jitwatch --require
--preinstalled).

Live half (chaos-marked, replayed by the scripts/chaos.sh ARTIFACT
entry): a standby that pre-installs the primary's artifacts over the
wire must warm up from persistent-cache LOADS alone — zero true warmup
compiles — then promote on primary death and serve tokens identical to
HF greedy; a corrupted artifact stream must decline every blob, fall
back to local compile (ledgered as server.artifact_fallback_compile),
and STILL serve token-identically; a dead covering peer must be retried
on the next peer, and exhausting every peer must degrade to local
compile — never a crash.
"""

import asyncio
import json
import os

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

from bloombee_tpu.server import artifacts
from bloombee_tpu.utils import clock, jitwatch, ledger
from bloombee_tpu.utils.clock import ScaledClock
from bloombee_tpu.wire import faults
from bloombee_tpu.wire.faults import FaultPlan, FaultRule

# jax's persistent-cache config is process-global; every test restores it
# so later suites (test_jitwatch.py's e2e in particular) never find the
# cache dir still pointing at this module's artifact stores
_CFG_KEYS = (
    "jax_compilation_cache_dir",
    "jax_persistent_cache_min_compile_time_secs",
    "jax_persistent_cache_min_entry_size_bytes",
    "jax_persistent_cache_enable_xla_caches",
)


@pytest.fixture(autouse=True)
def clean_slate():
    saved = {k: getattr(jax.config, k) for k in _CFG_KEYS}
    faults.set_plan(None)
    jitwatch.reset()
    yield
    faults.set_plan(None)
    jitwatch.reset()
    for k, v in saved.items():
        jax.config.update(k, v)
    # the persistent-cache OBJECT latches the dir it initialized with;
    # re-latch against the restored config so later suites don't keep
    # writing into this module's (temporary) artifact stores
    from jax.experimental.compilation_cache import compilation_cache as cc

    cc.reset_cache()


# ------------------------------------------------------------- store unit
def test_install_and_manifest_roundtrip(tmp_path):
    store = artifacts.ArtifactStore(str(tmp_path))
    blob = b"executable bytes" * 8
    assert store.install(
        "jit_f-0a-cache", blob, artifacts.blob_digest(blob)
    ) is None
    man = store.manifest()
    assert [e["name"] for e in man] == ["jit_f-0a-cache"]
    assert man[0]["digest"] == artifacts.blob_digest(blob)
    assert man[0]["size"] == len(blob)
    assert store.read_blob("jit_f-0a-cache") == blob


def test_corrupt_or_truncated_blob_declines(tmp_path):
    """A blob whose content does not match its manifest digest —
    truncated OR bit-flipped in flight — must never reach the store."""
    store = artifacts.ArtifactStore(str(tmp_path))
    blob = b"y" * 100
    digest = artifacts.blob_digest(blob)
    assert store.install("a-cache", blob[:-1], digest) == "digest_mismatch"
    flipped = bytes([blob[0] ^ 0x40]) + blob[1:]
    assert store.install("a-cache", flipped, digest) == "digest_mismatch"
    assert store.read_blob("a-cache") is None
    assert store.declined == 2
    assert store.manifest() == []


def test_path_escaping_names_decline(tmp_path):
    store = artifacts.ArtifactStore(str(tmp_path))
    blob = b"z"
    digest = artifacts.blob_digest(blob)
    for name in (
        "../evape-cache", "a/b-cache", "c\\d-cache", "e:f-cache",
        ".hidden-cache", "", "x" * 600 + "-cache",
    ):
        assert store.install(name, blob, digest) == "bad_name", name
        assert store.read_blob(name) is None
    # non-suffixed droppings in the directory are invisible, not errors
    (tmp_path / "notes.txt").write_bytes(b"hi")
    assert store.manifest() == []


def test_lru_eviction_under_cap(tmp_path):
    store = artifacts.ArtifactStore(str(tmp_path), max_mb=1)
    blob = bytes(300 * 1024)
    digest = artifacts.blob_digest(blob)
    for i, name in enumerate(("a-cache", "b-cache", "c-cache")):
        assert store.install(name, blob, digest) is None
        # pin strictly increasing mtimes so LRU order is deterministic
        os.utime(tmp_path / name, (i + 1.0, i + 1.0))
    assert store.evictions == 0  # 3 x 300KiB fits the 1MiB cap
    assert store.install("d-cache", blob, digest) is None  # 4th overflows
    assert store.total_bytes() <= store.max_bytes
    names = {e["name"] for e in store.manifest()}
    assert "a-cache" not in names, "oldest entry must be the one evicted"
    assert {"c-cache", "d-cache"} <= names
    assert store.evictions >= 1


# ------------------------------------------------------- fingerprint unit
def _spec():
    from bloombee_tpu.models.spec import ModelSpec

    return ModelSpec(
        family="llama", hidden_size=64, intermediate_size=128,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        num_hidden_layers=3, vocab_size=128,
    )


def test_fingerprint_compatibility():
    fp = artifacts.fingerprint(_spec(), 0, 3, "f32", 4)
    assert artifacts.fingerprint_compatible(fp, dict(fp)) is None
    other = dict(fp, spec_hash="0" * 32)
    assert artifacts.fingerprint_compatible(fp, other) == "spec_hash"
    assert artifacts.fingerprint_compatible(
        fp, dict(fp, dtype="bf16")
    ) == "dtype"
    assert artifacts.fingerprint_compatible(
        fp, dict(fp, jaxlib="0.0.0")
    ) == "jaxlib"
    # a covering peer's wider span is compatible; a narrower one is not
    mine = dict(fp, span=[1, 2])
    assert artifacts.fingerprint_compatible(mine, dict(fp, span=[0, 3])) \
        is None
    assert artifacts.fingerprint_compatible(fp, dict(fp, span=[1, 2])) \
        == "span"


def test_server_info_artifact_advert_wire_compat():
    from bloombee_tpu.swarm.data import ServerInfo

    si = ServerInfo(artifacts=True)
    assert ServerInfo.from_wire(si.to_wire()).artifacts is True
    # old peers omit the field entirely -> defaults False (the BB004
    # from_wire splat-filter contract for mixed swarms)
    d = si.to_wire()
    d.pop("artifacts")
    assert ServerInfo.from_wire(d).artifacts is False
    d["artifact_v2"] = {"future": 1}  # unknown fields drop, never raise
    assert ServerInfo.from_wire(d).artifacts is False


# ----------------------------------------------------------- gate CLI unit
def test_ledger_require_recovery_cli(tmp_path, capsys):
    path = tmp_path / "ledger.jsonl"
    line = {
        "faults": {"wire.corrupt": 2},
        "recoveries": {"server.promotion": 1},
    }
    req = ["--require", "--require-recovery",
           "server.artifact_fallback_compile"]
    path.write_text(json.dumps(line) + "\n")
    assert ledger._main([str(path)] + req) == 1
    assert "server.artifact_fallback_compile" in capsys.readouterr().err
    line["recoveries"]["server.artifact_fallback_compile"] = 3
    path.write_text(json.dumps(line) + "\n")
    assert ledger._main([str(path)] + req) == 0


def test_jitwatch_preinstalled_gate_cli(tmp_path, capsys):
    path = tmp_path / "w.jsonl"
    good = {"xla_compiles": 5, "compile_cache_hits": 5,
            "preinstalled": True, "fenced": True}
    path.write_text(json.dumps(good) + "\n")
    assert jitwatch._main([str(path), "--require", "--preinstalled"]) == 0
    # no process ever marked itself pre-installed: vacuous claim
    path.write_text(json.dumps(dict(good, preinstalled=False)) + "\n")
    assert jitwatch._main([str(path), "--require", "--preinstalled"]) == 1
    assert "NOT PREINSTALLED" in capsys.readouterr().err
    # zero cache hits: the installed artifacts were never exercised
    path.write_text(json.dumps(dict(good, compile_cache_hits=0)) + "\n")
    assert jitwatch._main([str(path), "--require", "--preinstalled"]) == 1
    assert "NO CACHE HITS" in capsys.readouterr().err
    # any true warmup compile for a pre-installed bucket is exactly the
    # cold start the artifact path exists to eliminate
    path.write_text(
        json.dumps(dict(good, preinstalled_warmup_misses=1)) + "\n"
    )
    assert jitwatch._main([str(path), "--require", "--preinstalled"]) == 1
    assert "miss" in capsys.readouterr().err
    # swallowed per-bucket warmup failures fail plain --require too
    path.write_text(json.dumps({
        "xla_compiles": 2, "warmup_compiles": 2, "fenced": True,
        "warmup_failures": 1,
    }) + "\n")
    assert jitwatch._main([str(path), "--require"]) == 1
    assert "DEGRADED WARMUP" in capsys.readouterr().err


# ----------------------------------------------------------------- live e2e
@pytest.fixture(scope="module")
def tiny_model_dir(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64,
        intermediate_size=128,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_hidden_layers=3,
        vocab_size=128,
        max_position_embeddings=256,
        tie_word_embeddings=False,
    )
    torch.manual_seed(5)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    d = tmp_path_factory.mktemp("tiny_llama_artifacts")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model, config


def _primary(model_dir, rc, art_dir, **kw):
    from bloombee_tpu.server.block_server import BlockServer

    return BlockServer(
        model_uid="tinyart", start=0, end=3, model_dir=model_dir,
        registry=rc, compute_dtype=jnp.float32, num_pages=64,
        page_size=4, announce_period=0.3, artifact_dir=art_dir, **kw,
    )


def _standby(model_dir, rc, art_dir, **kw):
    kw.setdefault("promote_high_ms", 500.0)
    kw.setdefault("promote_low_ms", 100.0)
    kw.setdefault("promote_sustain_s", 0.3)
    kw.setdefault("promote_jitter_s", 0.4)
    return _primary(
        model_dir, rc, art_dir, standby=True, drain_timeout=2.0, **kw
    )


async def _wait_for(cond, timeout, what):
    deadline = asyncio.get_event_loop().time() + timeout
    while not cond():
        if asyncio.get_event_loop().time() > deadline:
            raise AssertionError(f"timed out waiting for {what}")
        await asyncio.sleep(0.1)


async def _hf_identical(model_dir, rc, hf_model, config, seed):
    """Greedy-generate through the swarm and require exact HF parity."""
    from bloombee_tpu.client.model import DistributedModelForCausalLM

    model = DistributedModelForCausalLM.from_pretrained(
        model_dir, rc, model_uid="tinyart"
    )
    rng = np.random.default_rng(seed)
    input_ids = rng.integers(0, config.vocab_size, size=(1, 8))
    ids = await model.generate(
        input_ids, max_new_tokens=4, server_decode=False
    )
    with torch.no_grad():
        ref = hf_model.generate(
            torch.tensor(input_ids), max_new_tokens=4, do_sample=False,
            use_cache=True,
        ).numpy()
    np.testing.assert_array_equal(ids, ref)


@pytest.mark.chaos
def test_preinstalled_standby_zero_warmup_compiles(
    tiny_model_dir, monkeypatch, tmp_path
):
    """The acceptance run: the primary's warmup populates its artifact
    store; a standby pre-installs those artifacts over artifact_get, and
    — with the in-memory jit cache cleared to simulate a fresh process —
    warms up entirely from persistent-cache LOADS (>=1 cache hit, zero
    preinstalled warmup misses). The primary then dies, the standby
    promotes, and its tokens match HF greedy exactly. The flushed witness
    line must pass ``--require --preinstalled``."""
    monkeypatch.setenv("BBTPU_JITWATCH", "1")
    model_dir, hf_model, config = tiny_model_dir
    report = tmp_path / "jitwatch.jsonl"
    dir_a, dir_b = str(tmp_path / "store_a"), str(tmp_path / "store_b")

    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        # control-plane deadlines (announce lease, watcher, sustain,
        # jitter) run 4x compressed; restored to real before the compute-
        # heavy generate (test_promotion.py's clock discipline)
        prev = clock.install(ScaledClock(scale=4.0))
        try:
            primary = _primary(model_dir, rc(), dir_a)
            # the ctor just pointed jax's persistent cache at store A;
            # drop the in-memory executable cache so warmup actually
            # compiles — and therefore actually WRITES artifacts — even
            # when earlier tests already compiled these shapes
            jax.clear_caches()
            await primary.start()
            await primary.warmup(batch_sizes=(1,), prefill_tokens=8)
            assert primary.artifact_store is not None
            assert primary.artifact_store.manifest(), \
                "warmup persisted no artifacts"
            assert primary.server_info().artifacts is True

            standby = _standby(model_dir, rc(), dir_b)
            await standby.start()
            # a fresh process's worth of amnesia at the JOIN boundary:
            # nothing in memory, everything must ride fetched artifacts
            jax.clear_caches()
            jitwatch.reset()
            await standby.warmup(batch_sizes=(1,), prefill_tokens=8)
            assert standby._artifacts_preinstalled is True
            assert standby.artifact_blobs_fetched >= 1
            assert primary.artifact_gets_served >= 1
            snap = jitwatch.snapshot()
            assert snap["preinstalled"] is True
            assert snap["compile_cache_hits"] >= 1, snap
            assert snap["preinstalled_warmup_misses"] == 0, snap["compiles"]
            assert snap["fenced"] is True

            await primary.stop()  # tombstones the span: advert silence
            await _wait_for(
                lambda: standby._promoted, 20.0, "promotion after span loss"
            )
        finally:
            clock.install(prev)

        await _hf_identical(model_dir, rc(), hf_model, config, seed=3)

        # the artifact counters ride rpc_info (BB006 surfacing)
        from bloombee_tpu.wire.rpc import connect

        conn = await connect("127.0.0.1", standby.port)
        info, _ = await conn.call("rpc_info", {})
        assert info["artifact_preinstalled"] is True
        assert info["artifact_blobs_fetched"] >= 1
        assert info["artifact_store_bytes"] > 0
        await conn.close()

        await standby.stop()
        await reg.stop()

    asyncio.run(run())

    snap = jitwatch.snapshot()
    assert snap["steady_state_recompiles"] == 0, [
        c for c in snap["compiles"] if c["phase"] == "steady"
    ]
    jitwatch.flush(str(report))
    assert jitwatch._main(
        [str(report), "--require", "--preinstalled"]
    ) == 0
    # under scripts/chaos.sh the same line feeds the ARTIFACT entry's
    # strengthened gate (the autouse reset leaves nothing for the atexit
    # flush to double-write)
    jitwatch.flush()


@pytest.mark.chaos
def test_corrupt_artifact_stream_falls_back_token_identical(
    tiny_model_dir, tmp_path
):
    """Byzantine artifact transfer: every blob reply is bit-flipped in
    flight (well-formed frame, lying payload). The standby must decline
    every blob on the manifest-digest check, install NOTHING, fall back
    to local compile (ledgered as server.artifact_fallback_compile), and
    still promote + serve token-identically when the primary dies. Zero
    hard failures, zero crashes."""
    model_dir, hf_model, config = tiny_model_dir
    dir_a, dir_b = str(tmp_path / "store_a"), str(tmp_path / "store_b")
    base = ledger.snapshot()["recoveries"].get(
        "server.artifact_fallback_compile", 0
    )

    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        prev = clock.install(ScaledClock(scale=4.0))
        try:
            primary = _primary(model_dir, rc(), dir_a)
            jax.clear_caches()
            await primary.start()
            await primary.warmup(batch_sizes=(1,), prefill_tokens=8)
            assert primary.artifact_store.manifest()

            standby = _standby(model_dir, rc(), dir_b)
            await standby.start()
            # corrupt every artifact frame on the wire from here on; the
            # manifest reply carries no tensor (unaffected), each blob
            # reply gets one byte flipped
            plan = FaultPlan(seed=11)
            plan.add(FaultRule(
                site="send", action="corrupt", method="res",
                predicate=faults._is_artifact_transfer, nth=1, count=0,
            ))
            faults.set_plan(plan)
            await standby.warmup(batch_sizes=(1,), prefill_tokens=8)
            faults.set_plan(None)
            assert standby._artifacts_preinstalled is False
            assert standby.artifact_fallback_compiles >= 1
            assert standby.artifact_store.declined >= 1
            assert standby.artifact_blobs_fetched == 0, \
                "a corrupt blob survived the digest check"

            await primary.stop()
            await _wait_for(
                lambda: standby._promoted, 20.0, "promotion after span loss"
            )
        finally:
            clock.install(prev)
            faults.set_plan(None)

        await _hf_identical(model_dir, rc(), hf_model, config, seed=7)

        from bloombee_tpu.wire.rpc import connect

        conn = await connect("127.0.0.1", standby.port)
        info, _ = await conn.call("rpc_info", {})
        assert info["artifact_fallback_compiles"] >= 1
        assert info["artifact_store_declined"] >= 1
        await conn.close()

        await standby.stop()
        await reg.stop()

    asyncio.run(run())

    snap = ledger.snapshot()
    assert snap["recoveries"].get(
        "server.artifact_fallback_compile", 0
    ) > base, "the fallback path never ledgered"
    assert snap["faults"].get("wire.corrupt", 0) >= 1


class _DeadPeerFirst:
    """Registry wrapper pinning a known-dead peer to the front of every
    server listing, so the retry-on-next-peer path runs deterministically
    (live-registry dict order depends on declare order)."""

    def __init__(self, inner, dead_port: int):
        self._inner = inner
        self._dead_port = dead_port

    def __getattr__(self, name):
        return getattr(self._inner, name)

    async def get_module_infos(self, uid, blocks):
        infos = await self._inner.get_module_infos(uid, blocks)
        for info in infos or []:
            if info:
                info.servers = dict(sorted(
                    info.servers.items(),
                    key=lambda kv: kv[1].port != self._dead_port,
                ))
        return infos


@pytest.mark.chaos
def test_peer_death_mid_fetch_retries_then_falls_back(
    tiny_model_dir, tmp_path
):
    """Fetch fault tolerance, three acts: (1) the first covering peer is
    dead on the wire — the fetch retries the full blob set on the next
    peer and still pre-installs; (2) a stale fingerprint declines the
    whole peer and falls back; (3) with every peer dead or declined the
    fetch degrades to local compile — it never raises."""
    model_dir, _, _ = tiny_model_dir
    dir_a, dir_b = str(tmp_path / "store_a"), str(tmp_path / "store_b")

    from bloombee_tpu.swarm.data import ServerInfo, ServerState
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        primary = _primary(model_dir, rc(), dir_a)
        jax.clear_caches()
        await primary.start()
        await primary.warmup(batch_sizes=(1,), prefill_tokens=8)
        assert primary.artifact_store.manifest()

        # a covering "peer" that is ONLINE in the registry but already
        # dead on the wire (port 1 never listens)
        dead = ServerInfo(
            state=ServerState.ONLINE, host="127.0.0.1", port=1,
            throughput=1.0, start_block=0, end_block=3, artifacts=True,
        )
        await rc().declare_blocks(
            "tinyart", "srv-00dead", range(3), dead, expiration=60.0
        )

        standby = _standby(
            model_dir, _DeadPeerFirst(rc(), dead_port=1), dir_b
        )
        await standby.start()

        # act 1: dead peer first -> retried on the live primary
        assert await standby.prefetch_artifacts() is True
        assert standby._artifacts_preinstalled is True
        assert standby.artifact_fetch_retries >= 1
        assert standby.artifact_blobs_fetched >= 1

        # act 2: stale fingerprint -> the peer's whole artifact set is
        # for a different world; decline it all and fall back
        standby._artifacts_preinstalled = False
        real_fp = standby._artifact_fp
        standby._artifact_fp = lambda: dict(
            real_fp(), spec_hash="0" * 32
        )
        before = standby.artifact_fallback_compiles
        assert await standby.prefetch_artifacts() is False
        assert standby.artifact_fallback_compiles > before
        assert standby._artifacts_preinstalled is False
        standby._artifact_fp = real_fp

        # act 3: every peer dead -> graceful local-compile fallback
        await primary.stop()
        before = standby.artifact_fallback_compiles
        assert await standby.prefetch_artifacts() is False
        assert standby.artifact_fallback_compiles > before

        await standby.stop()
        await reg.stop()

    asyncio.run(run())


def test_warmup_failures_surface_in_rpc_info(
    tiny_model_dir, monkeypatch, tmp_path
):
    """Satellite of the same robustness story: per-bucket warmup failures
    were silently swallowed (logged, nothing else) — now they count into
    warmup_failures (rpc_info / health --probe) and flag the jitwatch
    report as warmup_degraded, so a zero-recompile green can't mask
    buckets that never warmed."""
    monkeypatch.setenv("BBTPU_JITWATCH", "1")
    model_dir, _, _ = tiny_model_dir

    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer
    from bloombee_tpu.wire.rpc import connect

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        server = _primary(
            model_dir, RegistryClient("127.0.0.1", reg.port), None
        )
        await server.start()
        jitwatch.reset()

        def boom(*a, **k):
            raise RuntimeError("no pages for warmup")

        monkeypatch.setattr(server.manager, "allocate", boom)
        await server.warmup(batch_sizes=(1, 2), prefill_tokens=8)
        assert server.warmup_failures >= 2
        snap = jitwatch.snapshot()
        assert snap["warmup_failures"] >= 2
        assert snap["warmup_degraded"] is True
        assert snap["fenced"] is True  # the fence still drops — degraded,
        # not deadlocked

        conn = await connect("127.0.0.1", server.port)
        info, _ = await conn.call("rpc_info", {})
        assert info["warmup_failures"] >= 2
        # no artifact store configured: the counters still surface, zeroed
        assert info["artifact_preinstalled"] is False
        assert info["artifact_store_bytes"] == 0
        await conn.close()

        await server.stop()
        await reg.stop()

    asyncio.run(run())

    # the degraded report fails plain --require (hollow-green protection)
    report = tmp_path / "degraded.jsonl"
    jitwatch.flush(str(report))
    assert jitwatch._main([str(report), "--require"]) == 1
