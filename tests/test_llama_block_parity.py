"""Llama block numerical parity vs HF transformers (torch CPU).

Port of the local half of /root/reference/tests/test_block_exact_match.py:
block forward atol 1e-4, step-by-step inference atol 1e-3.
"""

import numpy as np
import pytest
import torch

from bloombee_tpu.models.llama.block import (
    HF_BLOCK_KEYS,
    block_forward,
    convert_hf_block_params,
    dense_attend,
)
from bloombee_tpu.models.llama.config import llama_spec_from_hf
from bloombee_tpu.ops.rotary import rotary_cos_sin

import jax.numpy as jnp


@pytest.fixture(scope="module")
def tiny_hf_llama():
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64,
        intermediate_size=128,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_hidden_layers=2,
        vocab_size=256,
        max_position_embeddings=128,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    return model, config


def _layer_params(model, layer_idx):
    sd = model.model.layers[layer_idx].state_dict()
    tensors = {k: sd[k].numpy() for k in HF_BLOCK_KEYS}
    return convert_hf_block_params(tensors)


def test_block_forward_parity(tiny_hf_llama):
    model, config = tiny_hf_llama
    spec = llama_spec_from_hf(config)
    b, t = 2, 9

    torch.manual_seed(1)
    hidden = torch.randn(b, t, config.hidden_size, dtype=torch.float32)
    position_ids = torch.arange(t).unsqueeze(0).expand(b, -1)

    layer = model.model.layers[0]
    cos_t, sin_t = model.model.rotary_emb(hidden, position_ids)
    with torch.no_grad():
        ref_out = layer(
            hidden,
            position_embeddings=(cos_t, sin_t),
            attention_mask=None,
        )
    if isinstance(ref_out, tuple):
        ref_out = ref_out[0]

    params = _layer_params(model, 0)
    h = jnp.asarray(hidden.numpy())
    positions = jnp.asarray(position_ids.numpy())
    cos, sin = rotary_cos_sin(positions, spec.head_dim, spec.rope_theta)
    out, _ = block_forward(params, spec, h, cos, sin, dense_attend())

    np.testing.assert_allclose(
        np.asarray(out), ref_out.numpy(), atol=1e-4, rtol=1e-4
    )


def test_block_stepwise_inference_parity(tiny_hf_llama):
    """Prefill 5 tokens then decode 3 single tokens against dense past;
    compare with one full-sequence HF forward (atol 1e-3)."""
    model, config = tiny_hf_llama
    spec = llama_spec_from_hf(config)
    b, total = 1, 8

    torch.manual_seed(2)
    hidden = torch.randn(b, total, config.hidden_size, dtype=torch.float32)
    position_ids = torch.arange(total).unsqueeze(0)

    layer = model.model.layers[1]
    cos_t, sin_t = model.model.rotary_emb(hidden, position_ids)
    with torch.no_grad():
        ref_out = layer(
            hidden, position_embeddings=(cos_t, sin_t), attention_mask=None
        )
    if isinstance(ref_out, tuple):
        ref_out = ref_out[0]
    ref = ref_out.numpy()

    params = _layer_params(model, 1)
    h_all = jnp.asarray(hidden.numpy())

    prefill = 5
    positions = jnp.arange(total)[None, :]
    cos, sin = rotary_cos_sin(positions, spec.head_dim, spec.rope_theta)

    out_pre, (k_past, v_past) = block_forward(
        params, spec, h_all[:, :prefill], cos[:, :prefill], sin[:, :prefill],
        dense_attend(),
    )
    np.testing.assert_allclose(np.asarray(out_pre), ref[:, :prefill], atol=1e-3)

    outs = [out_pre]
    for i in range(prefill, total):
        out_i, (k_past, v_past) = block_forward(
            params, spec, h_all[:, i : i + 1], cos[:, i : i + 1],
            sin[:, i : i + 1], dense_attend(k_past, v_past),
        )
        outs.append(out_i)
    full = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(full), ref, atol=1e-3)
