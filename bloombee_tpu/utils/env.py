"""Structured env/flag layer.

The reference exposes ~60 ``BLOOMBEE_*`` switches through an ad-hoc
``os.environ`` scatter plus utils/debug_config.py:62-120 (group toggles and
named log channels). Here every switch is declared once in a registry with a
type, default, and help string, so ``describe()`` can print the authoritative
table (the role of the reference's README.environment-switches.md) and typos
in switch names are detectable instead of silently ignored.

Switches use the ``BBTPU_`` prefix. Reading is cheap (plain os.environ) and
uncached by default so tests can monkeypatch the environment.
"""

from __future__ import annotations

import dataclasses
import logging
import os

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class Flag:
    name: str  # full env var name, e.g. BBTPU_DEBUG
    kind: type  # bool | int | float | str
    default: object
    help: str


_REGISTRY: dict[str, Flag] = {}


def declare(name: str, kind: type, default, help_: str) -> Flag:
    """Register a switch. Called by the module that reads the switch, next to
    the code it controls, so the registry can never contain no-op entries."""
    flag = Flag(name, kind, default, help_)
    _REGISTRY[name] = flag
    return flag


def _parse(flag: Flag, raw: str):
    if flag.kind is bool:
        return raw.strip().lower() in ("1", "true", "yes", "on")
    try:
        return flag.kind(raw)
    except ValueError:
        logger.warning(
            "ignoring unparsable %s=%r (want %s)", flag.name, raw,
            flag.kind.__name__,
        )
        return flag.default


def get(name: str):
    """Read a declared flag from the environment (or its default)."""
    flag = _REGISTRY.get(name)
    if flag is None:
        # Some switches are read in more modules than the one declaring
        # them (e.g. a pure-client process reading BBTPU_PREFIX_CACHE,
        # declared next to the server-side pool it also controls). Pull
        # in the declaring modules once; only a genuinely unknown name —
        # a typo — still fails loudly after that.
        import_declaring_modules()
        flag = _REGISTRY[name]
    raw = os.environ.get(flag.name)
    if raw is None:
        return flag.default
    return _parse(flag, raw)


def import_declaring_modules() -> None:
    """Import every module that declares switches so describe() is complete
    (kept here, next to the registry, so new declare() sites only need to
    be added in one place)."""
    import bloombee_tpu.client.session  # noqa: F401
    import bloombee_tpu.kv.cache_manager  # noqa: F401
    import bloombee_tpu.models.hub  # noqa: F401
    import bloombee_tpu.runtime.executor  # noqa: F401
    import bloombee_tpu.server.admission  # noqa: F401
    import bloombee_tpu.server.artifacts  # noqa: F401
    import bloombee_tpu.server.block_selection  # noqa: F401
    import bloombee_tpu.server.block_server  # noqa: F401
    import bloombee_tpu.sim.cost  # noqa: F401
    import bloombee_tpu.sim.metrics  # noqa: F401
    import bloombee_tpu.sim.scenarios  # noqa: F401
    import bloombee_tpu.utils.clock  # noqa: F401
    import bloombee_tpu.utils.jitwatch  # noqa: F401
    import bloombee_tpu.utils.ledger  # noqa: F401
    import bloombee_tpu.utils.lockwatch  # noqa: F401
    import bloombee_tpu.wire.faults  # noqa: F401
    import bloombee_tpu.wire.pipeline  # noqa: F401
    import bloombee_tpu.wire.tensor_codec  # noqa: F401


def describe() -> str:
    """Authoritative flag table (reference README.environment-switches.md)."""
    lines = ["| switch | type | default | description |", "|---|---|---|---|"]
    for flag in sorted(_REGISTRY.values(), key=lambda f: f.name):
        lines.append(
            f"| {flag.name} | {flag.kind.__name__} | {flag.default!r} "
            f"| {flag.help} |"
        )
    return "\n".join(lines)


# Flags read by this module itself; feature modules declare their own
# switches next to the code that reads them.
declare("BBTPU_DEBUG", bool, False, "enable all debug log channels")
declare(
    "BBTPU_LOG_CHANNELS", str, "",
    "comma-separated debug channels (wire, kv, microbatch, spec, timing)",
)

# bench.py switches live here rather than next to their readers: the
# bench is a standalone script (not importable from
# import_declaring_modules without dragging its __main__ machinery in),
# but its switches still belong in the authoritative table.
declare(
    "BBTPU_BENCH_DEADLINE_S", float, 1500.0,
    "bench watchdog/backend-probe deadline in seconds; past it the "
    "bench emits partial results and exits 0",
)
declare(
    "BBTPU_BENCH_SMOKE", bool, False,
    "force the bench's reduced CPU smoke profile (tiny model, short "
    "phases) regardless of backend availability",
)


def log_channel_enabled(channel: str) -> bool:
    """Named debug channels (reference debug_config named log channels)."""
    if get("BBTPU_DEBUG"):
        return True
    raw = get("BBTPU_LOG_CHANNELS")
    return channel in tuple(c.strip() for c in raw.split(",") if c.strip())
