"""Swarm health check: which blocks are covered, by whom, with what state.

Port of the reference's `bloombee.cli.health`-style checks
(tests/test_aux_functions.py) reading registry records + rpc_info.

    python -m bloombee_tpu.cli.health MODEL_UID --num-blocks 32 \\
        --registry 127.0.0.1:7700
"""

from __future__ import annotations

import argparse
import asyncio


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("model_uid", nargs="?", default=None)
    parser.add_argument("--num-blocks", type=int)
    parser.add_argument("--registry", default="127.0.0.1:7700",
                        help="registry address or comma-separated replicas")
    parser.add_argument("--probe", action="store_true",
                        help="also call rpc_info on every server")
    parser.add_argument("--switches", action="store_true",
                        help="print the BBTPU_* env switch table and exit "
                        "(reference README.environment-switches.md)")
    args = parser.parse_args(argv)
    if args.switches:
        from bloombee_tpu.utils import env

        env.import_declaring_modules()
        print(env.describe())
        return
    if args.model_uid is None or args.num_blocks is None:
        parser.error("model_uid and --num-blocks are required")

    async def run():
        from bloombee_tpu.swarm.registry import make_registry
        from bloombee_tpu.swarm.spans import compute_spans
        from bloombee_tpu.wire.rpc import connect

        from bloombee_tpu.swarm.data import ServerState

        reg = make_registry(args.registry)
        if args.probe:
            # the discovery plane is a server too: surface its audited
            # error swallows (registry_swallowed_errors) the same way
            for part in args.registry.split(","):
                part = part.strip()
                if not part:
                    continue
                rhost, rport = part.rsplit(":", 1)
                rline = f"  registry {part}"
                conn = None
                try:
                    conn = await connect(rhost, int(rport))
                    probe, _ = await asyncio.wait_for(
                        conn.call("rpc_info", {}), 5
                    )
                    rline += "  [reachable]"
                    for k in ("keys", "registry_swallowed_errors"):
                        if probe.get(k):
                            rline += f"  {k}={probe[k]}"
                except Exception as e:
                    rline += f"  [UNREACHABLE: {type(e).__name__}]"
                finally:
                    if conn is not None:
                        await conn.close()
                print(rline)
        infos = await reg.get_module_infos(
            args.model_uid, range(args.num_blocks)
        )
        # JOINING included so warm standbys are operator-visible; coverage
        # counts only servers routing can actually use (ONLINE/DRAINING)
        spans = compute_spans(infos, min_state=ServerState.JOINING)
        covered = {
            b
            for s in spans.values()
            if s.server_info.state >= ServerState.ONLINE
            for b in range(s.start, s.end)
        }
        missing = [b for b in range(args.num_blocks) if b not in covered]

        print(f"model {args.model_uid}: {len(spans)} server(s)")
        for sid, span in sorted(spans.items(), key=lambda kv: kv[1].start):
            info = span.server_info
            line = (
                f"  {sid}  blocks [{span.start}:{span.end})  "
                f"{info.host}:{info.port}  throughput={info.throughput:.2f}"
            )
            if info.state == ServerState.JOINING:
                line += "  STANDBY"
            if getattr(info, "promoted_standby", False):
                line += "  PROMOTED"
            if info.cache_tokens_left is not None:
                line += f"  cache_tokens_left={info.cache_tokens_left}"
            if getattr(info, "kv_repl", False):
                line += "  kv_repl"
            if args.probe:
                conn = None
                try:
                    conn = await connect(info.host, info.port)
                    probe, _ = await asyncio.wait_for(
                        conn.call("rpc_info", {}), 5
                    )
                    line += "  [reachable]"
                    # failover/replication counters: lets an operator see
                    # replication running (or lagging) without log access
                    repl = {
                        k: probe[k]
                        for k in (
                            "repl_pages_sent",
                            "repl_pages_installed",
                            "repl_lag_pages",
                            "failover_replayed_tokens",
                        )
                        if probe.get(k)
                    }
                    if repl:
                        line += "  " + " ".join(
                            f"{k}={v}" for k, v in sorted(repl.items())
                        )
                    # nonzero arena_epoch = donated-arena self-heal
                    # events; sessions lost KV and had to replay
                    if probe.get("arena_epoch"):
                        line += f"  arena_epoch={probe['arena_epoch']}"
                    # stall-free scheduling counters: is chunked prefill
                    # firing, and are decode steps actually landing
                    # between chunks
                    sched = {
                        k: probe[k]
                        for k in (
                            "prefill_chunks",
                            "prefill_chunk_tokens",
                            "decode_steps_interleaved",
                        )
                        if probe.get(k)
                    }
                    if sched:
                        line += "  " + " ".join(
                            f"{k}={v}" for k, v in sorted(sched.items())
                        )
                    # mixed-batch dispatch counters: are decodes actually
                    # fusing into prefill-chunk device steps, and what the
                    # per-token dispatch amortization works out to
                    mixed = {
                        k: probe[k]
                        for k in (
                            "mixed_dispatches",
                            "mixed_tokens",
                            "step_dispatches",
                            "step_tokens",
                        )
                        if probe.get(k)
                    }
                    if mixed:
                        line += "  " + " ".join(
                            f"{k}={v}" for k, v in sorted(mixed.items())
                        )
                        dpt = probe.get("dispatches_per_token")
                        if dpt:
                            line += f"  dispatches_per_token={dpt:.3f}"
                    # speculative-decode counters: are tree-verify steps
                    # flowing, are they coalescing into group dispatches
                    # (--spec-batch), and what the swarm-measured draft
                    # acceptance works out to
                    spec = {
                        k: probe[k]
                        for k in (
                            "tree_steps",
                            "tree_rows",
                            "spec_tokens_drafted",
                            "spec_tokens_accepted",
                            "tree_group_dispatches",
                        )
                        if probe.get(k)
                    }
                    if spec:
                        line += "  " + " ".join(
                            f"{k}={v}" for k, v in sorted(spec.items())
                        )
                        rate = probe.get("spec_accept_rate")
                        if rate:
                            line += f"  spec_accept_rate={rate:.3f}"
                        width = probe.get("mean_tree_batch_width")
                        if width:
                            line += f"  mean_tree_batch_width={width:.2f}"
                    # universal ragged dispatch: fused dispatches, how
                    # many crossed row kinds, and any per-reason declines
                    # (an operator asked for fusing on a span that can't)
                    ragged = {
                        k: probe[k]
                        for k in (
                            "ragged_group_dispatches",
                            "ragged_cross_kind_dispatches",
                        )
                        if probe.get(k)
                    }
                    if ragged:
                        line += "  " + " ".join(
                            f"{k}={v}" for k, v in sorted(ragged.items())
                        )
                    declines = probe.get("ragged_declines") or {}
                    for reason, n in sorted(declines.items()):
                        line += f"  ragged_decline[{reason}]={n}"
                    # elastic self-healing counters: standby promotions /
                    # drain-backs and measured-load rebalance outcomes —
                    # the control loop's every decision, probeable without
                    # log access
                    elastic = {
                        k: probe[k]
                        for k in (
                            "promotions",
                            "demotions",
                            "promotions_yielded",
                            "demotions_aborted",
                            "rebalances_moved",
                            "rebalances_failed",
                            "rebalance_skipped_hysteresis",
                        )
                        if probe.get(k)
                    }
                    if elastic:
                        line += "  " + " ".join(
                            f"{k}={v}" for k, v in sorted(elastic.items())
                        )
                    # integrity counters: digest stamps emitted, audit
                    # re-executions served, liar-hook lies injected (test
                    # swarms only — nonzero here in production is an
                    # incident), and silent prefix hash-chain failures
                    integ = {
                        k: probe[k]
                        for k in (
                            "out_digests_sent",
                            "audit_forwards",
                            "liar_steps",
                            "seq_hash_extend_failures",
                        )
                        if probe.get(k)
                    }
                    if integ:
                        line += "  " + " ".join(
                            f"{k}={v}" for k, v in sorted(integ.items())
                        )
                    # lock-witness counters (BBTPU_LOCKWATCH=1 runs):
                    # observed acquisition-order edges and hierarchy
                    # violations — ANY nonzero lock_violations is a
                    # deadlock setup waiting for the right interleaving
                    watch = {
                        k: probe[k]
                        for k in (
                            "lock_order_edges",
                            "lock_violations",
                        )
                        if probe.get(k)
                    }
                    if watch:
                        line += "  " + " ".join(
                            f"{k}={v}" for k, v in sorted(watch.items())
                        )
                    # compile-witness counters (BBTPU_JITWATCH=1 runs):
                    # ANY nonzero steady_state_recompiles means a decode
                    # bucket escaped warmup — a first-token compile stall
                    # some session actually paid
                    jit = {
                        k: probe[k]
                        for k in (
                            "xla_compiles",
                            "compile_ms_total",
                            "warmup_compiles",
                            "warmup_failures",
                            "steady_state_recompiles",
                            "compile_cache_hits",
                            "preinstalled_warmup_misses",
                            "host_syncs_hot_path",
                        )
                        if probe.get(k)
                    }
                    if jit:
                        line += "  " + " ".join(
                            f"{k}={v}" for k, v in sorted(jit.items())
                        )
                    # compile-artifact counters (BBTPU_ARTIFACT_DIR runs):
                    # fallback_compiles > 0 means a server abandoned
                    # pre-installed artifacts and paid local compiles;
                    # declines/evictions show the store defending itself
                    art = {
                        k: probe[k]
                        for k in (
                            "artifact_preinstalled",
                            "artifact_fallback_compiles",
                            "artifact_gets_served",
                            "artifact_puts_installed",
                            "artifact_puts_declined",
                            "artifact_blobs_fetched",
                            "artifact_fetch_retries",
                            "artifact_store_bytes",
                            "artifact_evictions",
                            "artifact_store_declined",
                        )
                        if probe.get(k)
                    }
                    if art:
                        line += "  " + " ".join(
                            f"{k}={v}" for k, v in sorted(art.items())
                        )
                    # wire transport: bytes actually shipped vs raw tensor
                    # bytes (compression working or not), codec seconds,
                    # and the off-loop pipeline's depth/backpressure — the
                    # bytes/token floor under every multi-span latency
                    # number, probeable without log access (BB006)
                    tr = probe.get("transport") or {}
                    for dr in ("tx", "rx"):
                        d = tr.get(dr) or {}
                        if d.get("n"):
                            line += (
                                f"  {dr}_wire_bytes={d['wire_bytes']}"
                                f"  {dr}_ratio={d['ratio']:.3f}"
                                f"  {dr}_codec_s={d['s']:.3f}"
                            )
                    pipe = probe.get("wire_pipeline") or {}
                    if pipe.get("tx_jobs") or pipe.get("rx_jobs"):
                        line += (
                            "  pipeline="
                            + ("on" if pipe.get("enabled") else "off")
                        )
                        for k in (
                            "tx_jobs",
                            "rx_jobs",
                            "rx_depth_max",
                            "rx_backpressure_waits",
                            "tx_limit",
                        ):
                            if pipe.get(k):
                                line += f"  {k}={pipe[k]}"
                    # session lease counters: are leases reaping abandoned
                    # sessions, are clients resuming instead of replaying,
                    # and is keepalive traffic flowing on idle conns
                    lease = {
                        k: probe[k]
                        for k in (
                            "sessions_reaped",
                            "sessions_resumed",
                            "steps_deduped",
                            "keepalives_sent",
                            "pushes_dropped",
                        )
                        if probe.get(k)
                    }
                    if lease:
                        line += "  " + " ".join(
                            f"{k}={v}" for k, v in sorted(lease.items())
                        )
                    # live session ages: a large oldest-idle with leases
                    # off (session_lease_s=0) is exactly the wedged-session
                    # leak this server would never clean up
                    if probe.get("sessions_parked"):
                        line += f"  sessions_parked={probe['sessions_parked']}"
                    for k in ("session_oldest_s", "session_oldest_idle_s"):
                        v = probe.get(k)
                        if v:
                            line += f"  {k}={v:.1f}"
                    waits = probe.get("queue_wait_ms") or {}
                    for cls in ("prefill", "decode"):
                        w = waits.get(cls) or {}
                        if w.get("p95"):
                            line += (
                                f"  {cls}_wait_p95={w['p95']:.1f}ms"
                            )
                    # live load snapshot: the same numbers the server
                    # adverts for load-aware routing
                    load = probe.get("load") or {}
                    for k in (
                        "delay_ms",
                        "queue_depth",
                        "mean_batch_width",
                        "chunk_streams",
                        "pages_free",
                        "active_sessions",
                    ):
                        v = load.get(k)
                        if v:
                            line += f"  {k}={v}"
                    if load.get("shedding"):
                        line += "  SHEDDING"
                    # admission counters: what got shed, with what retry
                    # hints, and which clients are over their fair share
                    adm = probe.get("admission") or {}
                    for k in (
                        "shed_requests",
                        "shed_sessions",
                        "admitted_new",
                    ):
                        if adm.get(k):
                            line += f"  {k}={adm[k]}"
                    hist = adm.get("retry_after_ms_hist") or {}
                    if any(hist.values()):
                        # keys look like "<=250ms" / ">10000ms": sort by
                        # the numeric bound, overflow bucket last
                        def _bound(k):
                            digits = "".join(c for c in k if c.isdigit())
                            return (
                                k.startswith(">"),
                                int(digits) if digits else 0,
                            )

                        line += "  retry_after_ms_hist=" + ",".join(
                            f"{b}:{n}"
                            for b, n in sorted(
                                hist.items(), key=lambda kv: _bound(kv[0])
                            )
                            if n
                        )
                    debts = adm.get("client_debts") or {}
                    over = {
                        c: d for c, d in debts.items() if d > 0
                    }
                    if over:
                        line += "  over_share=" + ",".join(
                            f"{c}:{d:+.2f}"
                            for c, d in sorted(
                                over.items(), key=lambda kv: -kv[1]
                            )
                        )
                except Exception as e:
                    line += f"  [UNREACHABLE: {type(e).__name__}]"
                finally:
                    if conn is not None:
                        await conn.close()
            print(line)
        if missing:
            print(f"  MISSING blocks: {missing}")
            raise SystemExit(1)
        print("  swarm is COMPLETE")

    asyncio.run(run())


if __name__ == "__main__":
    main()
