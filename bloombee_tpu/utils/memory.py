"""Memory observability: device HBM stats + server-side accounting.

TPU-native role of the reference's utils/memory_usage.py (nvidia-smi /
torch.cuda.memory_allocated probes + the [MBPIPE_MEM] logging surface):
here the device side comes from PJRT's `memory_stats()` and the
framework-side accounting is exact — the server knows precisely which
arrays it holds (span params, KV arena, host-offloaded layers, parked KV).

Surfaces:
- `[memory]` log channel (BBTPU_LOG_CHANNELS=memory): one line per
  announce period from each server
- `rpc_info`/health: a `memory` dict the operator can poll remotely
"""

from __future__ import annotations

from typing import Any


def device_memory_stats() -> dict:
    """PJRT per-device memory counters (bytes_in_use / peak / limit);
    empty on backends that expose none (CPU)."""
    import jax

    try:
        stats = jax.devices()[0].memory_stats() or {}
    except Exception:
        return {}
    return {
        k: int(stats[k])
        for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")
        if k in stats
    }


def tree_nbytes(tree: Any) -> int:
    """Total bytes of every array leaf in a pytree (QuantWeight/QuantSlab
    NamedTuples flatten to their codes/scale leaves, so quantized storage
    is counted at its real size)."""
    import jax

    total = 0
    for leaf in jax.tree.leaves(tree):
        size = getattr(leaf, "size", None)
        itemsize = getattr(getattr(leaf, "dtype", None), "itemsize", None)
        if size is not None and itemsize is not None:
            total += int(size) * int(itemsize)
    return total


def server_memory_report(server) -> dict:
    """Exact framework-side accounting for one BlockServer + the device
    counters. All values in bytes (MiB is a presentation concern)."""
    report = {
        "span_params_bytes": tree_nbytes(server.executor.params),
        "host_layer_bytes": tree_nbytes(server.executor.host_layers),
        **server.manager.memory_stats(),
        "device": device_memory_stats(),
    }
    sp_params = getattr(server.executor, "_sp_params", None)
    if sp_params is not None:
        # the sp-prefill mesh holds a REPLICATED second copy of the span
        # params (one buffer per sp chip) — capacity planning must see it
        report["sp_params_bytes"] = tree_nbytes(sp_params) * int(
            server.executor.sp_mesh.devices.size
        )
    if server.adapter_factors:
        report["adapter_bytes"] = tree_nbytes(server.adapter_factors)
    return report


def format_report(report: dict) -> str:
    """One-line human rendering for the [memory] log channel."""
    mib = 1024 * 1024

    def m(key):
        return f"{report.get(key, 0) / mib:.1f}MiB"

    parts = [
        f"params={m('span_params_bytes')}",
        f"arena={m('kv_arena_bytes')}",
        f"host_layers={m('host_layer_bytes')}",
        f"parked={m('parked_kv_host_bytes')}({report.get('parked_seqs', 0)})",
        f"kv_tokens={report.get('kv_tokens_reserved', 0)}"
        f"/{report.get('kv_tokens_capacity', 0)}",
    ]
    dev = report.get("device") or {}
    if dev:
        used = dev.get("bytes_in_use", 0) / mib
        peak = dev.get("peak_bytes_in_use", 0) / mib
        limit = dev.get("bytes_limit", 0) / mib
        parts.append(f"hbm={used:.0f}/{limit:.0f}MiB(peak {peak:.0f})")
    return " ".join(parts)


__all__ = [
    "device_memory_stats",
    "tree_nbytes",
    "server_memory_report",
    "format_report",
]
