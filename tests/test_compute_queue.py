"""ComputeQueue unit suite: priority ordering, deadline expiry while
queued, caller cancellation, shutdown drain, and the continuous-batching
group pop (coalescing, compatibility keys, gather window, per-member
outcomes)."""

import asyncio
import threading
import time

import pytest

from bloombee_tpu.server.compute_queue import (
    PRIORITY_INFERENCE,
    PRIORITY_PREFILL_CHUNK,
    PRIORITY_TRAINING,
    ComputeQueue,
    DeadlineExpired,
    aged_chunk_priority,
)


def _jam(q):
    """Occupy the single compute worker until the returned event is set,
    so later submissions provably sit in the queue."""
    gate = threading.Event()
    task = asyncio.create_task(
        q.submit(PRIORITY_INFERENCE, gate.wait, 5.0)
    )
    return gate, task


# ------------------------------------------------------------ plain tasks
def test_priority_ordering():
    """Inference submitted AFTER training still runs first once the worker
    frees up — the queue orders by priority, not arrival."""

    async def run():
        q = ComputeQueue()
        q.start()
        gate, jam = _jam(q)
        await asyncio.sleep(0.05)  # the jam is now on the worker thread
        order = []
        t_train = asyncio.create_task(
            q.submit(PRIORITY_TRAINING, order.append, "train")
        )
        t_inf = asyncio.create_task(
            q.submit(PRIORITY_INFERENCE, order.append, "inference")
        )
        await asyncio.sleep(0.05)
        gate.set()
        await asyncio.gather(jam, t_train, t_inf)
        assert order == ["inference", "train"]
        await q.stop()

    asyncio.run(run())


def test_args_bound_at_submit_time():
    """Each submission's fn/args bind when submitted (functools.partial),
    so rapid-fire submissions can never see each other's arguments."""

    async def run():
        q = ComputeQueue()
        q.start()
        gate, jam = _jam(q)
        await asyncio.sleep(0.05)
        tasks = [
            asyncio.create_task(q.submit(PRIORITY_INFERENCE, lambda x: x, i))
            for i in range(8)
        ]
        gate.set()
        results = await asyncio.gather(jam, *tasks)
        assert results[1:] == list(range(8))
        await q.stop()

    asyncio.run(run())


def test_deadline_expires_while_queued():
    """A task whose monotonic deadline passes while it waits behind a slow
    step raises DeadlineExpired instead of running; in-budget work behind
    it is unaffected."""

    async def run():
        q = ComputeQueue()
        q.start()
        gate, jam = _jam(q)
        await asyncio.sleep(0.05)
        ran = []
        doomed = asyncio.create_task(
            q.submit(PRIORITY_INFERENCE, ran.append, "doomed",
                     deadline=time.monotonic() + 0.05)
        )
        healthy = asyncio.create_task(
            q.submit(PRIORITY_INFERENCE, ran.append, "healthy",
                     deadline=time.monotonic() + 60.0)
        )
        await asyncio.sleep(0.2)  # burn the doomed task's budget
        gate.set()
        await jam
        with pytest.raises(DeadlineExpired):
            await doomed
        await healthy
        assert ran == ["healthy"]
        await q.stop()

    asyncio.run(run())


def test_cancelled_caller_is_skipped():
    """Cancelling the awaiting task while its work is queued drops the
    work without poisoning the worker loop."""

    async def run():
        q = ComputeQueue()
        q.start()
        gate, jam = _jam(q)
        await asyncio.sleep(0.05)
        ran = []
        victim = asyncio.create_task(
            q.submit(PRIORITY_INFERENCE, ran.append, "victim")
        )
        await asyncio.sleep(0.05)
        victim.cancel()
        with pytest.raises(asyncio.CancelledError):
            await victim
        survivor = asyncio.create_task(
            q.submit(PRIORITY_INFERENCE, ran.append, "survivor")
        )
        gate.set()
        await asyncio.gather(jam, survivor)
        assert ran == ["survivor"]
        await q.stop()

    asyncio.run(run())


def test_stop_drains_pending_futures():
    """stop() must fail queued-but-unstarted work with CancelledError —
    a future that never resolves would hang its awaiter (a session
    handler) forever on server shutdown."""

    async def run():
        q = ComputeQueue()
        q.start()
        gate, jam = _jam(q)
        await asyncio.sleep(0.05)
        pending = [
            asyncio.create_task(q.submit(PRIORITY_INFERENCE, lambda: 1))
            for _ in range(3)
        ]
        await asyncio.sleep(0.05)
        await q.stop()
        gate.set()
        for t in pending:
            with pytest.raises(asyncio.CancelledError):
                await asyncio.wait_for(t, timeout=5.0)

    asyncio.run(run())


# ------------------------------------------------------------- group pop
def test_group_coalesces_queued_members():
    """Same-key batchable tasks queued while the worker is busy execute as
    ONE run_group call; each caller gets its own member's outcome."""

    async def run():
        q = ComputeQueue(max_group=8)
        q.start()
        calls = []

        def run_group(payloads):
            calls.append(list(payloads))
            return [p * 10 for p in payloads]

        gate, jam = _jam(q)
        await asyncio.sleep(0.05)
        ts = [
            asyncio.create_task(
                q.submit_group(PRIORITY_INFERENCE, "k", i, run_group)
            )
            for i in range(4)
        ]
        await asyncio.sleep(0.05)
        gate.set()
        results = await asyncio.gather(jam, *ts)
        assert results[1:] == [0, 10, 20, 30]
        assert calls == [[0, 1, 2, 3]]
        await q.stop()

    asyncio.run(run())


def test_group_respects_max_group():
    """More same-key members than max_group split into multiple dispatches
    — none are dropped."""

    async def run():
        q = ComputeQueue(max_group=2)
        q.start()
        calls = []

        def run_group(payloads):
            calls.append(list(payloads))
            return payloads

        gate, jam = _jam(q)
        await asyncio.sleep(0.05)
        ts = [
            asyncio.create_task(
                q.submit_group(PRIORITY_INFERENCE, "k", i, run_group)
            )
            for i in range(5)
        ]
        await asyncio.sleep(0.05)
        gate.set()
        results = await asyncio.gather(jam, *ts)
        assert results[1:] == [0, 1, 2, 3, 4]
        assert [len(c) for c in calls] == [2, 2, 1]
        await q.stop()

    asyncio.run(run())


def test_group_keys_do_not_mix():
    """Different compatibility keys (e.g. different adapters or dtypes)
    never share a dispatch."""

    async def run():
        q = ComputeQueue(max_group=8)
        q.start()
        calls = []

        def run_group(payloads):
            calls.append(sorted(payloads))
            return payloads

        gate, jam = _jam(q)
        await asyncio.sleep(0.05)
        ts = [
            asyncio.create_task(
                q.submit_group(PRIORITY_INFERENCE, key, f"{key}{i}",
                               run_group)
            )
            for i in range(2)
            for key in ("a", "b")
        ]
        await asyncio.sleep(0.05)
        gate.set()
        await asyncio.gather(jam, *ts)
        assert sorted(map(tuple, calls)) == [
            ("a0", "a1"), ("b0", "b1"),
        ]
        await q.stop()

    asyncio.run(run())


def test_compat_predicate_mixes_heterogeneous_keys():
    """A custom compat(members, candidate) predicate admits members with
    DIFFERENT keys into one dispatch (the mixed-batch hook): decode-keyed
    members absorb one chunk-keyed member, a second chunk stays out, and
    admission sees the members gathered so far (the predicate widens as
    the group grows)."""

    async def run():
        def compat(members, cand):
            # any number of "d" keys, at most one "c" key per group
            if cand.key == "c":
                return all(m.key != "c" for m in members)
            return cand.key == "d"

        q = ComputeQueue(max_group=8, compat=compat)
        q.start()
        calls = []

        def run_group(payloads):
            calls.append(sorted(payloads))
            return payloads

        gate, jam = _jam(q)
        await asyncio.sleep(0.05)
        ts = [
            asyncio.create_task(
                q.submit_group(PRIORITY_INFERENCE, key, payload, run_group)
            )
            for key, payload in (
                ("d", "d0"), ("d", "d1"), ("c", "c0"), ("c", "c1"),
            )
        ]
        await asyncio.sleep(0.05)
        gate.set()
        results = await asyncio.gather(jam, *ts)
        assert results[1:] == ["d0", "d1", "c0", "c1"]
        # first pop gathered both decodes AND one chunk; the second chunk
        # was requeued and dispatched on its own
        assert calls == [["c0", "d0", "d1"], ["c1"]]
        await q.stop()

    asyncio.run(run())


def test_group_member_exception_is_scattered():
    """run_group returning an Exception instance for one member fails only
    that member's future; the rest resolve normally."""

    async def run():
        q = ComputeQueue(max_group=8)
        q.start()

        def run_group(payloads):
            return [
                ValueError("bad row") if p == 1 else p for p in payloads
            ]

        gate, jam = _jam(q)
        await asyncio.sleep(0.05)
        ok = asyncio.create_task(
            q.submit_group(PRIORITY_INFERENCE, "k", 0, run_group)
        )
        bad = asyncio.create_task(
            q.submit_group(PRIORITY_INFERENCE, "k", 1, run_group)
        )
        await asyncio.sleep(0.05)
        gate.set()
        await jam
        assert await ok == 0
        with pytest.raises(ValueError, match="bad row"):
            await bad
        await q.stop()

    asyncio.run(run())


def test_group_member_deadline_drops_only_that_member():
    async def run():
        q = ComputeQueue(max_group=8)
        q.start()
        calls = []

        def run_group(payloads):
            calls.append(list(payloads))
            return payloads

        gate, jam = _jam(q)
        await asyncio.sleep(0.05)
        doomed = asyncio.create_task(
            q.submit_group(PRIORITY_INFERENCE, "k", "doomed", run_group,
                           deadline=time.monotonic() + 0.05)
        )
        healthy = asyncio.create_task(
            q.submit_group(PRIORITY_INFERENCE, "k", "healthy", run_group,
                           deadline=time.monotonic() + 60.0)
        )
        await asyncio.sleep(0.2)
        gate.set()
        await jam
        with pytest.raises(DeadlineExpired):
            await doomed
        assert await healthy == "healthy"
        assert calls == [["healthy"]]
        await q.stop()

    asyncio.run(run())


def test_gather_window_catches_late_arrivals(monkeypatch):
    """With BBTPU_BATCH_WINDOW_MS set, a member submitted shortly AFTER
    the worker popped the first one still joins the same dispatch."""
    monkeypatch.setenv("BBTPU_BATCH_WINDOW_MS", "250")

    async def run():
        q = ComputeQueue(max_group=8)
        q.start()
        calls = []

        def run_group(payloads):
            calls.append(list(payloads))
            return payloads

        first = asyncio.create_task(
            q.submit_group(PRIORITY_INFERENCE, "k", "early", run_group)
        )
        await asyncio.sleep(0.05)  # worker popped "early", window open
        second = asyncio.create_task(
            q.submit_group(PRIORITY_INFERENCE, "k", "late", run_group)
        )
        assert await first == "early"
        assert await second == "late"
        assert calls == [["early", "late"]]
        await q.stop()

    asyncio.run(run())


def test_gather_window_dispatches_early_on_full_house(monkeypatch):
    """With a group_hint (the server's open-session count), the gather
    window ends the moment the group holds every possible member instead
    of sleeping out the full window — here the window is far longer than
    the test timeout, so only early dispatch lets this pass."""
    monkeypatch.setenv("BBTPU_BATCH_WINDOW_MS", "30000")

    async def run():
        q = ComputeQueue(max_group=8, group_hint=lambda members: 2)
        q.start()
        calls = []

        def run_group(payloads):
            calls.append(list(payloads))
            return payloads

        first = asyncio.create_task(
            q.submit_group(PRIORITY_INFERENCE, "k", "a", run_group)
        )
        await asyncio.sleep(0.05)  # worker popped "a", window open
        second = asyncio.create_task(
            q.submit_group(PRIORITY_INFERENCE, "k", "b", run_group)
        )
        t0 = time.monotonic()
        assert await asyncio.wait_for(first, timeout=5.0) == "a"
        assert await asyncio.wait_for(second, timeout=5.0) == "b"
        assert time.monotonic() - t0 < 5.0
        assert calls == [["a", "b"]]
        await q.stop()

    asyncio.run(run())


def test_solo_session_skips_gather_window(monkeypatch):
    """group_hint == 1 (one open session): nobody else can ever join, so
    the window must not be slept at all."""
    monkeypatch.setenv("BBTPU_BATCH_WINDOW_MS", "30000")

    async def run():
        q = ComputeQueue(max_group=8, group_hint=lambda members: 1)
        q.start()

        def run_group(payloads):
            return payloads

        t0 = time.monotonic()
        out = await asyncio.wait_for(
            q.submit_group(PRIORITY_INFERENCE, "k", "solo", run_group),
            timeout=5.0,
        )
        assert out == "solo" and time.monotonic() - t0 < 5.0
        await q.stop()

    asyncio.run(run())


def test_wait_stats_report_queue_time():
    async def run():
        q = ComputeQueue()
        q.start()
        assert q.wait_stats_ms() == {
            "p50": 0.0, "p95": 0.0,
            "prefill": {"p50": 0.0, "p95": 0.0},
            "decode": {"p50": 0.0, "p95": 0.0},
        }
        gate, jam = _jam(q)
        await asyncio.sleep(0.05)
        waiter = asyncio.create_task(
            q.submit(PRIORITY_INFERENCE, lambda: None)
        )
        await asyncio.sleep(0.15)
        gate.set()
        await asyncio.gather(jam, waiter)
        stats = q.wait_stats_ms()
        # the second task waited >= ~150 ms behind the jam
        assert stats["p95"] >= 100.0
        assert stats["p50"] >= 0.0
        await q.stop()

    asyncio.run(run())


# ------------------------------------------- stall-free chunk scheduling
def test_per_class_wait_stats_split():
    """task_class buckets wait samples into per-class p50/p95 next to the
    blended numbers — the decode-class wait is the stall-free signal."""

    async def run():
        q = ComputeQueue()
        q.start()
        gate, jam = _jam(q)
        await asyncio.sleep(0.05)
        pre = asyncio.create_task(
            q.submit(PRIORITY_TRAINING, lambda: None, task_class="prefill")
        )
        dec = asyncio.create_task(
            q.submit(PRIORITY_INFERENCE, lambda: None, task_class="decode")
        )
        await asyncio.sleep(0.15)
        gate.set()
        await asyncio.gather(jam, pre, dec)
        stats = q.wait_stats_ms()
        assert stats["prefill"]["p95"] >= 100.0
        assert stats["decode"]["p95"] >= 100.0
        assert stats["p95"] >= 100.0
        await q.stop()

    asyncio.run(run())


def test_fresh_chunk_yields_to_later_decode():
    """A queued prefill chunk at PRIORITY_PREFILL_CHUNK loses to a decode
    step submitted AFTER it — decodes preempt the next chunk."""

    async def run():
        q = ComputeQueue()
        q.start()
        gate, jam = _jam(q)
        await asyncio.sleep(0.05)
        order = []
        t0 = time.monotonic()
        assert aged_chunk_priority(t0, now=t0) == PRIORITY_PREFILL_CHUNK
        chunk = asyncio.create_task(
            q.submit(aged_chunk_priority(t0), order.append, "chunk",
                     task_class="prefill")
        )
        await asyncio.sleep(0.02)
        dec = asyncio.create_task(
            q.submit(PRIORITY_INFERENCE, order.append, "decode",
                     task_class="decode")
        )
        await asyncio.sleep(0.05)
        gate.set()
        await asyncio.gather(jam, chunk, dec)
        assert order == ["decode", "chunk"]
        await q.stop()

    asyncio.run(run())


def test_aged_chunk_competes_at_decode_priority(monkeypatch):
    """Past the BBTPU_CHUNK_AGE_S horizon a chunk stream's priority decays
    to decode priority, so FIFO order protects it from starvation: an old
    stream's chunk submitted BEFORE a decode now runs first."""
    monkeypatch.setenv("BBTPU_CHUNK_AGE_S", "0.01")

    async def run():
        q = ComputeQueue()
        q.start()
        gate, jam = _jam(q)
        await asyncio.sleep(0.05)
        order = []
        started_long_ago = time.monotonic() - 1.0
        assert aged_chunk_priority(started_long_ago) == PRIORITY_INFERENCE
        chunk = asyncio.create_task(
            q.submit(aged_chunk_priority(started_long_ago),
                     order.append, "chunk", task_class="prefill")
        )
        await asyncio.sleep(0.02)
        dec = asyncio.create_task(
            q.submit(PRIORITY_INFERENCE, order.append, "decode",
                     task_class="decode")
        )
        await asyncio.sleep(0.05)
        gate.set()
        await asyncio.gather(jam, chunk, dec)
        assert order == ["chunk", "decode"]
        await q.stop()

    asyncio.run(run())


def test_chunk_priority_decay_is_monotonic():
    t0 = 1000.0
    prios = [
        aged_chunk_priority(t0, now=t0 + dt)
        for dt in (0.0, 0.5, 1.0, 1.9, 2.0, 50.0)
    ]
    assert prios[0] == PRIORITY_PREFILL_CHUNK
    assert all(a >= b for a, b in zip(prios, prios[1:]))
    assert prios[-2] == prios[-1] == PRIORITY_INFERENCE
    # chunks always outrank training work, even fresh
    assert all(PRIORITY_INFERENCE <= p < PRIORITY_TRAINING for p in prios)


def test_chunk_stream_interleaves_queued_decodes():
    """Fake resumable chunk driver (the server's _run_chunked_prefill
    shape, no model needed): each chunk is its own submission, so a decode
    queued while chunk N occupies the worker runs BEFORE chunk N+1 —
    decodes land between chunks instead of waiting out the whole prompt."""

    from bloombee_tpu.utils import clock as vclock
    from bloombee_tpu.utils.clock import ScaledClock

    async def run():
        q = ComputeQueue()
        q.start()
        order = []
        t0 = vclock.monotonic()

        def work(tag):
            # occupy the worker like a device dispatch — on the scaled
            # clock, so the interleaving stays but the waiting shrinks
            vclock.sleep(0.02)
            order.append(tag)

        async def chunk_stream():
            # re-enters the queue between chunks at the aging priority,
            # exactly like the server's chunked-prefill state machine
            for i in range(4):
                await q.submit(
                    aged_chunk_priority(t0), work, f"C{i}",
                    task_class="prefill",
                )

        done = asyncio.Event()

        async def decode_loop():
            i = 0
            while not done.is_set():
                await q.submit(
                    PRIORITY_INFERENCE, work, f"D{i}", task_class="decode"
                )
                i += 1

        dec = asyncio.create_task(decode_loop())
        await asyncio.sleep(0.01)
        await chunk_stream()
        done.set()
        await dec
        chunks = [i for i, t in enumerate(order) if t.startswith("C")]
        assert len(chunks) == 4
        # at least one decode ran strictly between two chunks of the
        # stream (with a monolithic prefill there is nothing "between")
        assert any(b - a > 1 for a, b in zip(chunks, chunks[1:])), order
        stats = q.wait_stats_ms()
        # per-class stats saw both sides of the interleave
        assert stats["decode"] != {"p50": 0.0, "p95": 0.0} or order
        await q.stop()

    prev = vclock.install(ScaledClock(scale=4.0))
    try:
        asyncio.run(run())
    finally:
        vclock.install(prev)
