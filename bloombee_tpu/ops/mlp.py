"""Gated MLP (SiLU / SwiGLU).

Replaces /root/reference/src/bloombee/flexgen_utils/pytorch_backend.py:1033
`mlp_llama`. XLA fuses the elementwise silu/mul into the surrounding matmuls.
"""

from __future__ import annotations

import jax


def silu_mlp(
    x: jax.Array,
    gate_w: jax.Array,  # [D, I]
    up_w: jax.Array,  # [D, I]
    down_w: jax.Array,  # [I, D]
) -> jax.Array:
    g = x @ gate_w
    u = x @ up_w
    return (jax.nn.silu(g) * u) @ down_w
