"""Swarm data structures.

Mirrors /root/reference/src/bloombee/data_structures.py:51-83 (ServerInfo is
the DHT-visible metrics surface) and RemoteSpanInfo (client routing unit).
"""

from __future__ import annotations

import dataclasses
import enum


class ServerState(enum.IntEnum):
    OFFLINE = 0
    JOINING = 1
    ONLINE = 2
    # still serving its in-flight sessions but about to exit: routing must
    # not start NEW sessions here (ordered above ONLINE so liveness filters
    # `state >= ONLINE` keep draining servers visible to their open clients)
    DRAINING = 3


@dataclasses.dataclass
class ServerInfo:
    state: ServerState = ServerState.ONLINE
    host: str = ""
    port: int = 0
    version: str = "0.1.0"
    throughput: float = 1.0  # overall rps used by routing / balancing
    network_rps: float | None = None
    inference_rps: float | None = None
    forward_rps: float | None = None
    cache_tokens_left: int | None = None
    next_pings: dict[str, float] | None = None  # server_id -> rtt seconds
    start_block: int | None = None
    end_block: int | None = None
    # dtype this server wants hidden states shipped in ("bf16" when it
    # computes in bf16; "f32" for exact-parity fp32 serving). Halves the
    # bytes of the latency-critical decode payload vs the round-1 fp32 wire.
    wire_dtype: str = "f32"
    # per-request LoRA adapters this server can apply (reference ServerInfo
    # adapters field, data_structures.py); routing filters on these when the
    # client sets ClientConfig.active_adapter
    adapters: list[str] | None = None
    # largest n accepted per decode_n RPC; the client clamps its chunk to
    # this BEFORE the first call (a larger chunk would be declined and
    # silently cost the whole fast path — advisor, round 4)
    decode_n_max: int | None = None
    # KV page size when this server runs the shared-prefix cache (clients
    # build page-aligned hash chains from it); 0 = no prefix cache, don't
    # probe. Unknown-field filtering in from_wire keeps old peers happy.
    page_size: int = 0
    # True when this server accepts kv_put page replication into its
    # prefix pool (prefix cache on, dense unquantized arena). Standby
    # selection requires it; old peers default to False via from_wire's
    # unknown-field filtering, so mixed swarms just never replicate.
    kv_repl: bool = False
    # live load snapshot for load-aware routing: sliding-window gauges the
    # server republishes every advert. Keys (all optional — adverts are
    # untrusted wire input, consumers must sanitize every field):
    #   ts (writer wall clock), delay_ms (server's own live queue-delay
    #   estimate), queue_depth, wait_ms/{p50,p95},
    #   prefill_wait_ms/decode_wait_ms (same shape, per class),
    #   mean_batch_width, chunk_streams, pages_free, active_sessions,
    #   shedding (admission controller past its high watermark).
    # Old peers drop the whole field via from_wire unknown-field
    # filtering; old adverts leave it None (routing then adds no load term).
    load: dict | None = None
    # True while this server is serving because it PROMOTED itself from a
    # standby (elastic control loop). Promoted replicas are the ones that
    # yield in promotion-storm resolution (lowest server_id keeps serving,
    # the rest demote) and the first to drain back when the span cools —
    # the span's primary server never demotes. Old peers drop the field on
    # the wire (from_wire filtering); default False = primary.
    promoted_standby: bool = False
    # True when this server stamps an out_digest (blake2b over the exact
    # span-output bytes it serialized) into every step reply — the
    # integrity layer's cheap in-flight-corruption fast path. Old peers
    # drop the field via from_wire filtering and default False, so clients
    # simply skip digest checks against them (audits still work).
    out_digest: bool = False
    # True when this server serves a compile-artifact store over
    # artifact_get (swarm-shared persistent compilation cache). JOINing
    # servers and standbys fetch their span's artifacts from covering
    # peers advertising this before falling back to local compile. Old
    # peers drop the field via from_wire filtering and default False, so
    # mixed swarms simply never trade artifacts.
    artifacts: bool = False

    def to_wire(self) -> dict:
        d = dataclasses.asdict(self)
        d["state"] = int(self.state)
        return d

    @classmethod
    def from_wire(cls, d: dict) -> "ServerInfo":
        d = dict(d)
        d["state"] = ServerState(d.get("state", 2))
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


@dataclasses.dataclass
class ModuleInfo:
    """One block uid's view: which servers serve it."""

    uid: str
    servers: dict[str, ServerInfo]


@dataclasses.dataclass
class RemoteSpanInfo:
    """A contiguous block range on one server (routing unit,
    reference data_structures.py RemoteSpanInfo)."""

    peer_id: str
    start: int
    end: int
    server_info: ServerInfo

    @property
    def length(self) -> int:
        return self.end - self.start
