"""Run the swarm registry (bootstrap node).

Reference: /root/reference/src/bloombee/cli/run_dht.py — the hivemind DHT
bootstrap role. Usage:

    python -m bloombee_tpu.cli.run_registry --host 0.0.0.0 --port 7700
"""

from __future__ import annotations

import argparse
import asyncio
import logging


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=7700)
    parser.add_argument("--persist", default=None,
                        help="snapshot records to this file so a restarted "
                        "registry knows the swarm immediately")
    parser.add_argument("--log-level", default="INFO")
    args = parser.parse_args(argv)
    logging.basicConfig(level=args.log_level)

    from bloombee_tpu.swarm.registry import RegistryServer

    async def run():
        reg = RegistryServer(host=args.host, port=args.port,
                             persist_path=args.persist)
        await reg.start()
        logging.info("registry listening on %s:%d", args.host, reg.port)
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
