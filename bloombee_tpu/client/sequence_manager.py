"""RemoteSequenceManager: the client routing brain.

Port of /root/reference/src/bloombee/client/routing/sequence_manager.py:66-599:
keeps a fresh view of which server spans cover which blocks, builds a chain of
spans covering [0, num_blocks) by shortest-path search ("min_latency": Dijkstra
over block boundaries with per-span compute cost + per-hop network cost,
reference `_build_inference_graph` :235-296), or length-weighted random choice
("max_throughput", :320-342), and bans failing peers with backoff (:412-429).
"""

from __future__ import annotations

import dataclasses
import heapq
import logging
import random

from bloombee_tpu.swarm.data import RemoteSpanInfo, ServerState

# load-advert interpretation lives in swarm/load.py now (servers use it
# too: measured-load rebalancing, standby promotion); re-exported here
# because this was its historical home and tests/callers import it from
# the client package.
from bloombee_tpu.swarm.load import (  # noqa: F401  (re-exports)
    LOAD_DELAY_CAP_S,
    LOAD_SHED_PENALTY_S,
    LOAD_STALE_S,
    _QUEUE_DEPTH_COST_S,
    _finite_pos,
    predicted_queue_delay_s,
)
from bloombee_tpu.swarm.ping import DEFAULT_RTT_S, PingAggregator
from bloombee_tpu.utils import clock, ledger
from bloombee_tpu.swarm.spans import compute_spans

logger = logging.getLogger(__name__)

DEFAULT_HOP_COST_S = DEFAULT_RTT_S  # until a peer has been measured
CACHE_MISSING_PENALTY_S = 10.0  # reference: +10s if cache won't fit


class MissingBlocksError(RuntimeError):
    def __init__(self, blocks):
        super().__init__(
            f"no online server covers block(s) {blocks}; swarm incomplete"
        )
        self.blocks = blocks


@dataclasses.dataclass
class _BanState:
    """Per-peer failure bookkeeping: exponential backoff with jitter plus a
    half-open probe. Each strike doubles the ban (bounded by ban_max);
    once the ban expires the FIRST route that would use the peer becomes
    the trial (probing=True) and other routes keep avoiding it until the
    trial either succeeds (note_peer_ok resets everything) or fails
    (re-banned with the next doubling)."""

    strikes: int = 0
    banned_until: float = 0.0
    probing: bool = False
    probe_until: float = 0.0  # trial lease; expires so an unused or
    # wedged probe route cannot exclude the peer forever


class RemoteSequenceManager:
    def __init__(
        self,
        registry,
        model_uid: str,
        num_blocks: int,
        update_period: float = 5.0,
        ban_timeout: float = 15.0,
        ban_max: float = 120.0,
        rng: random.Random | None = None,
        allowed_servers: list[str] | None = None,
        blocked_servers: list[str] | None = None,
        active_adapter: str | None = None,
        load_aware: bool = True,  # add the predicted-queue-delay term
        # from live load adverts to Dijkstra edge costs
        overload_timeout: float = 2.0,  # base avoid-backoff after an
        # overloaded shed — a distinct, much shorter penalty class than
        # fault bans (the server is healthy, just busy right now)
        overload_max: float = 15.0,  # overload-avoid cap (faults: ban_max)
        quarantine_timeout: float = 600.0,  # base exile after an
        # integrity conviction — a peer that LIED (vs crashed) gets the
        # longest penalty class: minutes, not seconds
        quarantine_max: float = 3600.0,
        integrity_strike_limit: int = 2,  # sanity-gate rejects before a
        # peer tips from "suspicious" into quarantine
    ):
        self.registry = registry
        self.model_uid = model_uid
        self.num_blocks = num_blocks
        self.update_period = update_period
        self.ban_timeout = ban_timeout  # base (first-strike) backoff
        self.ban_max = ban_max
        self.probe_timeout = 30.0  # half-open trial lease
        self.load_aware = load_aware
        self.overload_timeout = overload_timeout
        self.overload_max = overload_max
        self.overload_probe_timeout = 10.0  # half-open lease, hot peers
        self.allowed_servers = (
            set(allowed_servers) if allowed_servers else None
        )
        self.blocked_servers = set(blocked_servers or ())
        self.active_adapter = active_adapter
        self.spans: dict[str, RemoteSpanInfo] = {}
        # dedicated warm standbys (JOINING adverts): invisible to routing,
        # visible to pick_standby as replication/failover targets
        self.standby_spans: dict[str, RemoteSpanInfo] = {}
        self._bans: dict[str, _BanState] = {}
        # overload penalty class: same half-open state machine as fault
        # bans, but a separate map with shorter base/cap so "busy" never
        # escalates into the minutes-long exile reserved for failures
        self._hot: dict[str, _BanState] = {}
        # integrity penalty class (Byzantine, not crash, faults): same
        # half-open machine, much longer base/cap, and — unlike bans —
        # escalation survives a successful probe (a liar that behaves for
        # one probe step re-enters at the doubled backoff next conviction)
        self.quarantine_timeout = quarantine_timeout
        self.quarantine_max = quarantine_max
        self.quarantine_probe_timeout = 60.0
        self.integrity_strike_limit = integrity_strike_limit
        self._quarantine: dict[str, _BanState] = {}
        self._quarantine_history: dict[str, int] = {}  # strikes survive readmit
        self._integrity_strikes: dict[str, int] = {}
        self.peers_quarantined = 0  # counter: quarantine events (bench/health)
        self._last_update = 0.0
        self._rng = rng or random.Random()
        # measured client->server RTTs (reference ping.py PingAggregator);
        # server->server edges come from announced next_pings
        self.pinger = PingAggregator()

    # ---------------------------------------------------------------- updates
    async def update(self, force: bool = False) -> None:
        now = clock.monotonic()
        if not force and now - self._last_update < self.update_period:
            return
        infos = await self.registry.get_module_infos(
            self.model_uid, range(self.num_blocks)
        )
        self.spans = compute_spans(infos)
        # JOINING servers are warm standbys (elastic self-healing): kept
        # OUT of self.spans so no route ever lands on one, but tracked so
        # pick_standby can ship them replicated KV — when one promotes,
        # its next advert is ONLINE and it enters self.spans normally
        self.standby_spans = {
            pid: s
            for pid, s in compute_spans(
                infos, min_state=ServerState.JOINING
            ).items()
            if s.server_info.state == ServerState.JOINING
        }
        self._last_update = now
        self._prune_bans()
        banned_now = {
            p for p, st in self._bans.items()
            if st.banned_until > clock.monotonic()
        }
        to_ping = [
            (s.peer_id, s.server_info.host, s.server_info.port)
            for s in self.spans.values()
            if s.peer_id not in banned_now
            and self.pinger.needs_measure(s.peer_id)
        ]
        if to_ping:
            # timeboxed: recovery and session-open must not stall on a dead
            # peer (its failed ping would only record FAILED_RTT_S anyway)
            await self.pinger.measure_many(to_ping, overall_timeout=2.0)

    # ---------------------------------------------------------------- banning
    def ban_peer(self, peer_id: str) -> None:
        """Failure strike: exponential backoff with jitter (reference
        on_request_failure's flat ban_timeout, hardened). Each strike
        doubles the ban up to ban_max; jitter (0.75-1.25x, seeded rng)
        de-synchronizes many clients re-probing a recovered server at
        once. The peer's measured RTT is dropped so a later re-admission
        re-measures instead of routing on pre-failure latency."""
        state = self._bans.setdefault(peer_id, _BanState())
        state.probing = False
        state.strikes += 1
        backoff = min(
            self.ban_timeout * (2.0 ** (state.strikes - 1)), self.ban_max
        )
        backoff *= 0.75 + 0.5 * self._rng.random()
        state.banned_until = clock.monotonic() + backoff
        self.pinger.forget(peer_id)
        ledger.recovery("client.ban")
        logger.info(
            "banned peer %s for %.1fs (strike %d)", peer_id, backoff,
            state.strikes,
        )

    def note_peer_overloaded(
        self, peer_id: str, retry_after_s: float | None = None
    ) -> None:
        """Overload strike: the peer shed our work with a retriable
        `overloaded` — it is healthy, just busy, so it gets the SHORT
        penalty class (overload_timeout base / overload_max cap), never a
        fault ban. The server's retry_after hint floors the backoff; the
        measured RTT is kept (the peer is alive and its latency is
        current)."""
        state = self._hot.setdefault(peer_id, _BanState())
        state.probing = False
        state.strikes += 1
        backoff = min(
            self.overload_timeout * (2.0 ** (state.strikes - 1)),
            self.overload_max,
        )
        if retry_after_s is not None and retry_after_s > 0:
            backoff = max(backoff, min(retry_after_s, self.overload_max))
        backoff *= 0.75 + 0.5 * self._rng.random()
        state.banned_until = clock.monotonic() + backoff
        ledger.recovery("client.overload_backoff")
        logger.info(
            "avoiding overloaded peer %s for %.1fs (strike %d)", peer_id,
            backoff, state.strikes,
        )

    def note_integrity_strike(self, peer_id: str) -> bool:
        """An integrity check (sanity gate, digest, audit suspicion)
        rejected this peer's output. Strikes accumulate for the life of
        the session — ordinary successes do NOT clear them, because a lie
        is evidence of Byzantine behavior, not a transient fault. Returns
        True when the strike tipped the peer into quarantine."""
        n = self._integrity_strikes.get(peer_id, 0) + 1
        self._integrity_strikes[peer_id] = n
        logger.warning(
            "integrity strike %d/%d against peer %s", n,
            self.integrity_strike_limit, peer_id,
        )
        if n >= self.integrity_strike_limit:
            self.quarantine_peer(peer_id)
            return True
        return False

    def quarantine_peer(self, peer_id: str) -> None:
        """Integrity conviction: exile the peer with the longest penalty
        class. Same exponential backoff + half-open probe machinery as
        fault bans, but escalation is restored from `_quarantine_history`
        so a readmitted liar that re-offends starts from the doubled
        backoff, not from scratch. The accumulated sanity strikes reset:
        after readmission, fresh evidence is required to re-convict."""
        state = self._quarantine.setdefault(peer_id, _BanState())
        state.strikes = max(
            state.strikes, self._quarantine_history.get(peer_id, 0)
        )
        state.probing = False
        state.strikes += 1
        self._quarantine_history[peer_id] = state.strikes
        backoff = min(
            self.quarantine_timeout * (2.0 ** (state.strikes - 1)),
            self.quarantine_max,
        )
        backoff *= 0.75 + 0.5 * self._rng.random()
        state.banned_until = clock.monotonic() + backoff
        self._integrity_strikes.pop(peer_id, None)
        self.peers_quarantined += 1
        ledger.recovery("client.quarantine")
        self.pinger.forget(peer_id)
        logger.warning(
            "QUARANTINED peer %s for %.0fs (conviction %d): excluded from "
            "routing and standby selection", peer_id, backoff, state.strikes,
        )

    def note_peer_ok(self, peer_id: str) -> None:
        """A request through this peer succeeded: the half-open trial (or
        any lingering strike/overload history) is cleared so the next
        failure starts from the base backoff again. A quarantined peer
        that passes its probe is readmitted, but its escalation history
        survives in `_quarantine_history` (and its sanity strikes were
        already reset at conviction) — liars don't earn a clean slate."""
        if self._bans.pop(peer_id, None) is not None:
            logger.info("peer %s recovered; ban history reset", peer_id)
        self._hot.pop(peer_id, None)
        if self._quarantine.pop(peer_id, None) is not None:
            logger.info(
                "quarantined peer %s passed its half-open probe; readmitted "
                "(escalation history retained)", peer_id,
            )

    def _ban_excludes(self, peer_id: str, now: float) -> bool:
        """True when bans, overload-avoidance OR quarantine keep this peer
        out of routing right now. An expired entry admits exactly ONE
        route as the half-open probe; other routes keep avoiding the peer
        until the probe resolves."""
        return self._state_excludes(
            self._bans, peer_id, now, self.probe_timeout, "banned"
        ) or self._state_excludes(
            self._hot, peer_id, now, self.overload_probe_timeout,
            "overloaded",
        ) or self._integrity_excludes(peer_id, now)

    def _integrity_excludes(self, peer_id: str, now: float) -> bool:
        """Quarantine exclusion (half-open like the other classes, with
        the long probe lease). Checked in EVERY pool construction — normal
        routing, the degraded standby pool, and the warm-standby list —
        because a lying peer must never be handed work or replicated KV,
        however desperate the swarm is."""
        return self._state_excludes(
            self._quarantine, peer_id, now, self.quarantine_probe_timeout,
            "quarantined",
        )

    @staticmethod
    def _state_excludes(
        states: dict[str, _BanState], peer_id: str, now: float,
        probe_timeout: float, kind: str,
    ) -> bool:
        state = states.get(peer_id)
        if state is None:
            return False
        if now < state.banned_until:
            return True
        if state.probing and now < state.probe_until:
            return True  # a trial is already in flight elsewhere
        state.probing = True  # this route becomes (or renews) the trial
        state.probe_until = now + probe_timeout
        logger.info("half-open probe: trying %s peer %s", kind, peer_id)
        return False

    def _overload_active(self, peer_id: str, now: float | None = None) -> bool:
        """True while the peer is inside its overload-avoid backoff (no
        probe side effects — a read-only check for standby selection)."""
        state = self._hot.get(peer_id)
        if state is None:
            return False
        if now is None:
            now = clock.monotonic()
        return now < state.banned_until or (
            state.probing and now < state.probe_until
        )

    def _prune_bans(self) -> None:
        """Drop entries that can no longer matter: peers that left the
        swarm view, and long-expired bans whose peer was never re-routed
        (without this the maps grow monotonically with churn)."""
        now = clock.monotonic()
        if self.spans:
            for d in (self._quarantine_history, self._integrity_strikes):
                for pid in list(d):
                    if pid not in self.spans:
                        del d[pid]
        for states, cap in ((self._bans, self.ban_max),
                            (self._hot, self.overload_max),
                            (self._quarantine, self.quarantine_max)):
            for pid in list(states):
                state = states[pid]
                gone = self.spans and pid not in self.spans
                long_expired = (
                    not state.probing
                    and now > state.banned_until + 4 * cap
                )
                if gone or long_expired:
                    del states[pid]

    def _active_spans(
        self, overload_excludes: bool = True
    ) -> list[RemoteSpanInfo]:
        # overload_excludes=False keeps hot (but not fault-banned) peers in
        # the pool: pick_standby prefers cool standbys itself but must be
        # able to degrade to a hot one when nothing else qualifies.
        now = clock.monotonic()
        return [
            s
            for s in self.spans.values()
            if s.server_info.state != ServerState.DRAINING
            and not (
                self._ban_excludes(s.peer_id, now)
                if overload_excludes
                else (
                    self._state_excludes(
                        self._bans, s.peer_id, now, self.probe_timeout,
                        "banned",
                    )
                    or self._integrity_excludes(s.peer_id, now)
                )
            )
            and s.peer_id not in self.blocked_servers
            and (
                self.allowed_servers is None
                or s.peer_id in self.allowed_servers
            )
            and (
                self.active_adapter is None
                or self.active_adapter in (s.server_info.adapters or ())
            )
        ]

    # ---------------------------------------------------------------- routing
    def make_sequence(
        self,
        start: int = 0,
        end: int | None = None,
        mode: str = "min_latency",
        cache_tokens_needed: int | None = None,
        relay: bool = False,  # True: hops go server->client->server
        prefer: set[str] | None = None,  # peers to bias toward (recovery
        # hint: standbys already holding this session's replicated pages)
    ) -> list[RemoteSpanInfo]:
        end = self.num_blocks if end is None else end
        spans = self._active_spans()
        if mode == "max_throughput":
            return self._random_route(spans, start, end)
        return self._dijkstra_route(
            spans, start, end, cache_tokens_needed, relay, prefer=prefer
        )

    def pick_standby(
        self, span: RemoteSpanInfo, exclude: set[str] | None = None
    ) -> RemoteSpanInfo | None:
        """A replication standby for `span`: an active peer serving EXACTLY
        the same block range (replicated pages carry the full span's layers
        at the server's page geometry, so only an identical span + page
        size can install them), advertising kv_repl support, and not on
        the session's current route. Highest-throughput candidate wins;
        None when the swarm has no eligible alternative (the caller
        degrades to plain full-replay recovery)."""
        info = span.server_info
        now = clock.monotonic()
        pool = list(self._active_spans(overload_excludes=False))
        # dedicated warm standbys (JOINING adverts) qualify too — they are
        # what the elastic control loop promotes on failover, so they are
        # exactly where this session's pages should be waiting
        pool += [
            s for s in self.standby_spans.values()
            if not self._state_excludes(
                self._bans, s.peer_id, now, self.probe_timeout, "banned"
            )
            and not self._integrity_excludes(s.peer_id, now)
            and s.peer_id not in self.blocked_servers
            and (
                self.allowed_servers is None
                or s.peer_id in self.allowed_servers
            )
        ]
        cands = [
            s for s in pool
            if s.peer_id != span.peer_id
            and s.peer_id not in (exclude or ())
            and s.server_info.kv_repl
            and s.server_info.start_block == info.start_block
            and s.server_info.end_block == info.end_block
            and s.server_info.page_size == info.page_size
        ]
        # avoid HOT standbys: replicating to (or failing over onto) a
        # server already past its watermark just moves the overload.
        # Recently-shed peers are filtered outright (unless nothing else
        # qualifies); among the rest, advertised load discounts throughput.
        cool = [s for s in cands if not self._overload_active(s.peer_id)]
        if cool:
            cands = cool
        if not cands:
            return None
        return max(
            cands,
            key=lambda s: (
                s.server_info.inference_rps
                or s.server_info.throughput or 0.0
            ) / (1.0 + predicted_queue_delay_s(s.server_info)),
        )

    def _compute_cost(
        self, span: RemoteSpanInfo, blocks: int, cache_tokens_needed
    ) -> float:
        rps = span.server_info.inference_rps or span.server_info.throughput or 1.0
        cost = blocks / max(rps, 1e-6)
        left = span.server_info.cache_tokens_left
        if (
            cache_tokens_needed is not None
            and left is not None
            and left < cache_tokens_needed
        ):
            cost += CACHE_MISSING_PENALTY_S
        if self.load_aware:
            # live-advert term: predicted queue delay ADDS to the cost
            # (bounded, sanitized, staleness-discounted — see
            # predicted_queue_delay_s), so Dijkstra's positivity invariant
            # holds for arbitrary advert garbage
            cost += predicted_queue_delay_s(span.server_info)
        return cost

    def _hop_cost(
        self, prev_peer: str | None, span: RemoteSpanInfo, relay: bool
    ) -> float:
        """Network edge cost: client->server from measured RTTs; server->
        server from the previous server's announced next_pings (reference
        _build_inference_graph, sequence_manager.py:235-296), falling back
        to the client's measurement of the target. Relay sessions
        (use_push=False) route every hop through the client, so announced
        server->server RTTs don't apply — the client's own RTT does."""
        if prev_peer is not None and not relay:
            prev = self.spans.get(prev_peer)
            next_pings = (
                prev.server_info.next_pings if prev is not None else None
            ) or {}
            if span.peer_id in next_pings:
                return float(next_pings[span.peer_id])
        return self.pinger.get(span.peer_id, DEFAULT_HOP_COST_S)

    def _dijkstra_route(
        self, spans, start: int, end: int, cache_tokens_needed,
        relay: bool = False, prefer: set[str] | None = None,
    ) -> list[RemoteSpanInfo]:
        # states = (block boundary, arriving peer); a span [s, e) contributes
        # edges (b, p) -> (e, span.peer) for every b in [s, e) (a server can
        # serve a suffix of its span), costed with the real measured RTT for
        # the p -> span hop plus the span's compute time
        spans_by_block: dict[int, list[RemoteSpanInfo]] = {}
        for span in spans:
            s, e = max(span.start, start), min(span.end, end)
            for b in range(s, e):
                spans_by_block.setdefault(b, []).append(span)
        import itertools

        tie = itertools.count()  # heap tiebreaker (peer ids aren't ordered)
        src = (start, None)
        dist: dict[tuple, float] = {src: 0.0}
        prev: dict[tuple, tuple[tuple, RemoteSpanInfo]] = {}
        heap: list = [(0.0, next(tie), start, None)]
        goal: tuple | None = None
        while heap:
            d, _, node_b, node_p = heapq.heappop(heap)
            state = (node_b, node_p)
            if node_b == end:
                goal = state
                break
            if d > dist.get(state, float("inf")):
                continue
            for span in spans_by_block.get(node_b, []):
                e = min(span.end, end)
                cost = self._hop_cost(node_p, span, relay) + self._compute_cost(
                    span, e - node_b, cache_tokens_needed
                )
                if prefer and span.peer_id in prefer:
                    # recovery hint: a standby holding this session's
                    # replicated KV saves an O(history) replay — worth far
                    # more than a latency edge. Scaling (not zeroing)
                    # keeps edge costs positive, so Dijkstra stays valid.
                    cost *= 0.05
                nxt = (e, span.peer_id)
                nd = d + cost
                if nd < dist.get(nxt, float("inf")):
                    dist[nxt] = nd
                    prev[nxt] = (state, span)
                    heapq.heappush(heap, (nd, next(tie), e, span.peer_id))
        if goal is None:
            if start == end:
                return []
            covered = {b for s in spans for b in range(s.start, s.end)}
            missing = [b for b in range(start, end) if b not in covered]
            raise MissingBlocksError(missing or list(range(start, end)))
        # walk back
        route: list[RemoteSpanInfo] = []
        state = goal
        while state != src:
            pstate, span = prev[state]
            route.append(
                RemoteSpanInfo(
                    span.peer_id, pstate[0], state[0], span.server_info
                )
            )
            state = pstate
        return list(reversed(route))

    def _random_route(self, spans, start: int, end: int):
        """Length-weighted random chaining (reference :320-342)."""
        route = []
        cur = start
        while cur < end:
            options = [s for s in spans if s.start <= cur < s.end]
            if not options:
                raise MissingBlocksError([cur])
            weights = [s.end - cur for s in options]
            chosen = self._rng.choices(options, weights=weights)[0]
            stop = min(chosen.end, end)
            route.append(
                RemoteSpanInfo(chosen.peer_id, cur, stop, chosen.server_info)
            )
            cur = stop
        return route
