"""Ulysses-style sequence parallelism: all-to-all head/sequence exchange.

The second canonical long-context scheme next to ring attention
(parallel/ring_attention.py): instead of rotating KV blocks around the ring,
ONE all-to-all over the "sp" axis re-shards [B, C_local, H, hd] into
[B, C_full, H_local, hd] — every device then runs plain dense attention on
the FULL sequence for its head slice, and a final all-to-all restores the
sequence sharding. Four all-to-alls per attention (q, k, v, out — constant
in mesh size, vs the ring's 2*sp ppermutes), at the cost of requiring
heads % sp == 0; communication rides ICI either way. This fills the
reference's explicit long-context gap (SURVEY.md §5: no ring/Ulysses/
context parallelism at all).
"""

from __future__ import annotations

import jax
from jax import lax

from bloombee_tpu.ops.attention import causal_mask, masked_attention, repeat_kv


def ulysses_attention(
    q: jax.Array,  # [B, C, H, hd] local sequence chunk, all heads
    k: jax.Array,  # [B, C, Hkv, hd]
    v: jax.Array,  # [B, C, Hkv, hd]
    axis_name: str = "sp",
    causal: bool = True,
    scale: float | None = None,
) -> jax.Array:
    """Must be called inside shard_map with `axis_name` mapped; returns the
    local output chunk [B, C, H, hd]."""
    n = lax.axis_size(axis_name)
    b, c, h, hd = q.shape
    hkv = k.shape[2]
    if h % n:
        raise ValueError(f"heads={h} must divide over sp={n}")
    if hkv % n:
        # replicate KV heads up to the mesh size so each device owns at
        # least one; attention math is unchanged (repeat_kv semantics)
        if n % hkv:
            raise ValueError(
                f"kv heads={hkv} must divide or be divisible by sp={n}"
            )
        rep = n // hkv
        k = repeat_kv(k, rep)
        v = repeat_kv(v, rep)

    # head-shard + sequence-gather: [B, C, H, hd] -> [B, C*n, H/n, hd]
    qg = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    kg = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    vg = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)

    s = qg.shape[1]
    mask = (
        causal_mask(s)[None]
        if causal
        else jax.numpy.ones((1, s, s), bool)
    )
    out = masked_attention(qg, kg, vg, mask, scale=scale)  # GQA inside

    # restore sequence sharding: [B, C*n, H/n, hd] -> [B, C, H, hd]
    return lax.all_to_all(
        out, axis_name, split_axis=1, concat_axis=2, tiled=True
    )
