"""Admission control: per-client token-rate fair shares + overload shedding.

Without this layer, overload protection degenerates to queue-time deadline
aborts (PR 1): every request is accepted, rots in the compute queue, and
dies at its deadline — established decode streams and brand-new prompts
alike. The controller inverts that: once the measured queue delay crosses a
high watermark, NEW work (session opens, a fresh session's prefill) is
refused up front with a retriable ``overloaded(retry_after_ms)`` so the
client can reroute immediately, while the next decode step of an
established session is ALWAYS admitted — streams degrade (slower TBT)
instead of dying.

Fairness comes from per-client token-rate accounting over a sliding
window (weighted fair shares, cf. the reference's per-client quota hooks
and Sarathi-Serve's interference analysis): at the high watermark only
clients consuming more than their equal share are shed; clients at or
under their share keep being admitted until a harder watermark
(``hard_factor`` x high). One heavy client therefore backs off long before
it can starve light ones, and an uncontended client is never shed below
the hard watermark at all.
"""

from __future__ import annotations

import collections
import math

from bloombee_tpu.utils import clock, env

env.declare(
    "BBTPU_ADMIT", bool, False,
    "enable the BlockServer admission controller: past BBTPU_ADMIT_HIGH_MS "
    "of measured queue delay, NEW sessions/prefills are shed with a "
    "retriable overloaded(retry_after_ms) wire error instead of queueing "
    "into deadline aborts; established sessions' next decode step is "
    "always admitted",
)
env.declare(
    "BBTPU_ADMIT_HIGH_MS", float, 750.0,
    "admission high watermark: queue delay (ms) past which new work from "
    "over-fair-share clients is shed; under-share clients are shed only "
    "past 4x this value",
)
env.declare(
    "BBTPU_ADMIT_WINDOW_S", float, 5.0,
    "sliding window (s) for per-client token-rate fair-share accounting "
    "and for the recent-queue-wait estimate behind admission decisions",
)
env.declare(
    "BBTPU_ADMIT_RETRY_MS", float, 250.0,
    "base retry_after_ms hint on overloaded sheds; scaled up with overload "
    "severity and with the shed client's fair-share debt",
)

# retry_after histogram buckets (upper bounds, ms) — coarse on purpose:
# this is an operator signal in health --probe, not a benchmark
_HIST_BUCKETS = (50, 100, 250, 500, 1000, 2500, 5000, 10000)
_RETRY_CAP_MS = 30_000.0


class AdmissionController:
    """Decides whether NEW work is admitted given the live queue delay.

    The caller (BlockServer) is responsible for only consulting
    ``admit_new`` for new work — established sessions' decode steps must
    never be routed through it (that asymmetry IS the failure-model
    contract, see ARCHITECTURE.md "Failure model").
    """

    def __init__(
        self,
        *,
        high_ms: float | None = None,
        window_s: float | None = None,
        retry_ms: float | None = None,
        hard_factor: float = 4.0,
    ) -> None:
        self.high_ms = float(
            env.get("BBTPU_ADMIT_HIGH_MS") if high_ms is None else high_ms
        )
        self.window_s = max(0.1, float(
            env.get("BBTPU_ADMIT_WINDOW_S") if window_s is None else window_s
        ))
        self.retry_ms = float(
            env.get("BBTPU_ADMIT_RETRY_MS") if retry_ms is None else retry_ms
        )
        self.hard_factor = float(hard_factor)
        # client id -> deque of (monotonic_ts, tokens) admitted in-window
        self._tokens: dict[str, collections.deque] = {}
        # observability (surfaced via _rpc_info -> health --probe)
        self.shed_requests = 0
        self.shed_sessions = 0
        self.admitted_new = 0
        self.retry_after_hist: dict[str, int] = {}
        self.shedding = False  # live gauge, re-published in load adverts

    # ------------------------------------------------------------ accounting
    def note_tokens(self, client: str, tokens: int, now: float | None = None):
        """Charge `tokens` processed tokens (batch x seq) to `client`."""
        now = clock.monotonic() if now is None else now
        dq = self._tokens.setdefault(client, collections.deque())
        dq.append((now, max(0, int(tokens))))
        self._prune(dq, now)

    def _prune(self, dq: collections.deque, now: float) -> None:
        while dq and now - dq[0][0] > self.window_s:
            dq.popleft()

    def token_rate(self, client: str, now: float | None = None) -> float:
        """Tokens/s charged to `client` over the sliding window."""
        now = clock.monotonic() if now is None else now
        dq = self._tokens.get(client)
        if not dq:
            return 0.0
        self._prune(dq, now)
        return sum(n for _, n in dq) / self.window_s

    def fair_share_debt(self, client: str, now: float | None = None) -> float:
        """How far past its equal-weight share of the window's tokens this
        client is: (its fraction of all in-window tokens) - 1/n_active.
        > 0 means over-share (shed first), <= 0 at-or-under share. A client
        alone in the window is by construction at 0 debt — uncontended
        traffic can never look greedy."""
        now = clock.monotonic() if now is None else now
        rates = {}
        for c in list(self._tokens):
            r = self.token_rate(c, now)
            if r > 0.0:
                rates[c] = r
        total = sum(rates.values())
        if total <= 0.0:
            return 0.0
        # an unseen client counts as an extra active party: its share is
        # what it WOULD be entitled to if admitted
        n = len(rates) if client in rates else len(rates) + 1
        return rates.get(client, 0.0) / total - 1.0 / n

    def debts(self, now: float | None = None) -> dict[str, float]:
        now = clock.monotonic() if now is None else now
        return {
            c: round(self.fair_share_debt(c, now), 3)
            for c in list(self._tokens)
        }

    # ------------------------------------------------------------- decisions
    def admit_new(
        self, client: str, queue_delay_ms: float, now: float | None = None
    ) -> int | None:
        """Admission decision for NEW work from `client` given the current
        queue delay. Returns None to admit, or a retry_after_ms hint when
        the work is shed."""
        now = clock.monotonic() if now is None else now
        delay = float(queue_delay_ms)
        if not math.isfinite(delay):
            delay = 0.0
        if delay < self.high_ms:
            self.shedding = False
            self.admitted_new += 1
            return None
        self.shedding = True
        debt = self.fair_share_debt(client, now)
        if debt <= 0.0 and delay < self.high_ms * self.hard_factor:
            # at/under fair share: keep admitting until the hard watermark,
            # so a heavy neighbor cannot push light clients out
            self.admitted_new += 1
            return None
        # retry grows with overload severity and with how far over its
        # share the client is — heavy clients wait longer (weighted fair)
        retry = (
            self.retry_ms
            * (delay / max(self.high_ms, 1e-9))
            * (1.0 + 4.0 * max(0.0, debt))
        )
        retry_ms = int(min(retry, _RETRY_CAP_MS))
        self.shed_requests += 1
        self._note_hist(retry_ms)
        return retry_ms

    def _note_hist(self, retry_ms: int) -> None:
        for b in _HIST_BUCKETS:
            if retry_ms <= b:
                key = f"<={b}ms"
                break
        else:
            key = f">{_HIST_BUCKETS[-1]}ms"
        self.retry_after_hist[key] = self.retry_after_hist.get(key, 0) + 1

    def stats(self) -> dict:
        """Counters for _rpc_info / health --probe."""
        return {
            "shed_requests": self.shed_requests,
            "shed_sessions": self.shed_sessions,
            "admitted_new": self.admitted_new,
            "retry_after_ms_hist": dict(self.retry_after_hist),
            "client_debts": self.debts(),
            "shedding": self.shedding,
        }
