"""Stall-free scheduling e2e: chunked prefill + prefill/decode interleave.

Correctness bar (ISSUE 5): greedy decode must be TOKEN-IDENTICAL with
chunking on and off (both pinned to HF) — including through a prefix-cache
adoption (the suffix prefill chunks too) and under seeded chaos delays
mid-prefill; concurrent sessions' decode steps must actually land BETWEEN
the chunks of a long prefill (decode_steps_interleaved > 0, surfaced via
rpc_info next to per-class queue waits); and a deadline abort mid-stream
must roll back and free every speculative page the partial prefill wrote.
"""

import asyncio
import time

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from bloombee_tpu.client.config import ClientConfig
from bloombee_tpu.client.model import DistributedModelForCausalLM
from bloombee_tpu.server.block_server import BlockServer, _Session
from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer
from bloombee_tpu.wire import faults
from bloombee_tpu.wire.faults import FaultPlan, FaultRule
from bloombee_tpu.wire.rpc import connect


@pytest.fixture(scope="module")
def tiny_model_dir(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64,
        intermediate_size=128,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_hidden_layers=3,
        vocab_size=128,
        max_position_embeddings=256,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    d = tmp_path_factory.mktemp("tiny_llama_chunked")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model, config


@pytest.fixture(autouse=True)
def no_leaked_plan():
    yield
    faults.set_plan(None)


def _server(model_dir, registry, start, end, **kw):
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("num_pages", 64)
    kw.setdefault("page_size", 4)
    return BlockServer(
        model_uid="tiny", start=start, end=end, model_dir=model_dir,
        registry=registry, **kw,
    )


def _hf_greedy(model, input_ids, max_new_tokens):
    with torch.no_grad():
        out = model.generate(
            torch.tensor(input_ids), max_new_tokens=max_new_tokens,
            do_sample=False, use_cache=True,
        )
    return out.numpy()


def _assert_no_leaks(server):
    table = server.manager.table
    if hasattr(table, "counts"):  # prefix-cache table: full accounting
        c = table.counts()
        assert c["free"] + c["referenced"] + c["cached"] == table.num_pages, c
        assert c["referenced"] == 0, c
    else:
        assert table.free_pages == table.num_pages


# ------------------------------------------------------- chunked == monolithic
def test_chunked_prefill_token_identical(tiny_model_dir, monkeypatch):
    """A 13-token prompt prefilled in 4-token chunks across a two-span
    chain (one server configured via the ctor flag, the other via
    BBTPU_PREFILL_CHUNK) generates exactly the HF greedy tokens, and the
    counters prove the chunking actually happened. The same prompt on a
    prefill_chunk=0 server is also HF-exact with zero chunks — unset means
    byte-for-byte the monolithic path."""
    model_dir, hf_model, config = tiny_model_dir
    input_ids = (np.arange(13)[None, :] * 5 + 3) % config.vocab_size
    ref = _hf_greedy(hf_model, input_ids, 6)

    async def run_chunked():
        monkeypatch.setenv("BBTPU_PREFILL_CHUNK", "4")
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s_a = _server(model_dir, rc(), 0, 2, prefill_chunk=4)
        s_b = _server(model_dir, rc(), 2, 3)  # env-configured
        for s in (s_a, s_b):
            await s.start()
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny",
            # no relay push: each span server sees the prefill itself, so
            # the per-server chunk counters below are exact
            config=ClientConfig(use_push=False),
        )
        try:
            ids = await model.generate(input_ids, max_new_tokens=6)
            np.testing.assert_array_equal(ids, ref)
            for s in (s_a, s_b):
                # 13 tokens at budget 4 -> spans 4+4+4+1 on each span server
                assert s.prefill_chunks == 4, s.prefill_chunks
                assert s.prefill_chunk_tokens == 13
            conn = await connect("127.0.0.1", s_a.port)
            info, _ = await conn.call("rpc_info", {})
            assert info["prefill_chunks"] == 4
            assert info["prefill_chunk_tokens"] == 13
            assert info["decode_steps_interleaved"] == 0  # nothing concurrent
            assert info["queue_wait_ms"]["prefill"]["p95"] >= 0.0
            await conn.close()
            await asyncio.sleep(0.2)  # server-side session teardown is async
            for s in (s_a, s_b):
                _assert_no_leaks(s)
        finally:
            for s in (s_a, s_b):
                await s.stop()
            await reg.stop()

    async def run_monolithic():
        monkeypatch.delenv("BBTPU_PREFILL_CHUNK", raising=False)
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s = _server(
            model_dir, RegistryClient("127.0.0.1", reg.port), 0, 3,
            prefill_chunk=0,
        )
        await s.start()
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, RegistryClient("127.0.0.1", reg.port),
            model_uid="tiny",
        )
        try:
            ids = await model.generate(input_ids, max_new_tokens=6)
            np.testing.assert_array_equal(ids, ref)
            assert s.prefill_chunks == 0
            assert s.prefill_chunk_tokens == 0
        finally:
            await s.stop()
            await reg.stop()

    asyncio.run(run_chunked())
    asyncio.run(run_monolithic())


# --------------------------------------------- prefix adoption + chunked tail
def test_chunked_suffix_prefill_after_prefix_adoption(tiny_model_dir):
    """Prefix cache on a chunking server: a cold session publishes an
    8-token (2-page) prefix; a warm session with a 16-token prompt sharing
    that prefix adopts it and prefills only the suffix — which chunks too
    (first chunk settles the adoption). Both generations are HF-exact, the
    hit is recorded, and no page leaks."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s = _server(
            model_dir, rc(), 0, 3, prefix_cache=True, prefill_chunk=4
        )
        await s.start()

        shared = (np.arange(8)[None, :] * 7 + 1) % config.vocab_size
        long_ids = np.concatenate(
            [shared, (np.arange(8)[None, :] * 3 + 2) % config.vocab_size],
            axis=1,
        )
        ref_cold = _hf_greedy(hf_model, shared, 5)
        ref_warm = _hf_greedy(hf_model, long_ids, 5)

        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny",
            config=ClientConfig(use_push=False, prefix_cache=True),
        )
        try:
            ids_cold = await model.generate(shared, max_new_tokens=5)
            np.testing.assert_array_equal(ids_cold, ref_cold)
            chunks_cold = s.prefill_chunks
            assert chunks_cold >= 2  # the 8-token cold prefill chunked

            ids_warm = await model.generate(long_ids, max_new_tokens=5)
            np.testing.assert_array_equal(ids_warm, ref_warm)
            stats = s.manager.prefix_stats()
            assert stats["prefix_hits"] >= 1
            assert stats["prefix_hit_tokens"] >= 7
            # the adopted-suffix prefill itself ran as multiple chunks
            assert s.prefill_chunks > chunks_cold + 1

            await asyncio.sleep(0.2)  # server-side session teardown is async
            _assert_no_leaks(s)
        finally:
            await s.stop()
            await reg.stop()

    asyncio.run(run())


# ------------------------------------------------------------------ chaos e2e
@pytest.mark.chaos
def test_chunked_prefill_token_identical_under_chaos(tiny_model_dir):
    """Seeded frame delays land mid-prefill while the server is chunking:
    tokens stay exactly HF greedy and the chunk counters still add up."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s = _server(
            model_dir, RegistryClient("127.0.0.1", reg.port), 0, 3,
            prefill_chunk=4,
        )
        await s.start()

        plan = FaultPlan(seed=42)
        plan.add(FaultRule(site="send", action="delay", method="sitem",
                           prob=0.3, delay_s=0.02))
        faults.set_plan(plan)

        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, RegistryClient("127.0.0.1", reg.port),
            model_uid="tiny",
        )
        rng = np.random.default_rng(11)
        prompts = [
            rng.integers(0, config.vocab_size, size=(1, 9 + i))
            for i in range(3)
        ]
        try:
            outs = await asyncio.gather(*(
                model.generate(p, max_new_tokens=6) for p in prompts
            ))
            for p, got in zip(prompts, outs):
                ref = _hf_greedy(hf_model, p, 6)
                # HF generate stops at EOS; ours runs all 6 tokens —
                # compare the common prefix (the numerics statement)
                np.testing.assert_array_equal(
                    np.asarray(got)[:, :ref.shape[1]], ref
                )
            assert s.prefill_chunks >= sum(
                -(-p.shape[1] // 4) for p in prompts
            ) - 3  # every prompt chunked (>=2 chunks each)
            assert any(act == "delay" for _, act, _ in plan.log)
        finally:
            faults.set_plan(None)
            await s.stop()
            await reg.stop()

    asyncio.run(run())


# ------------------------------------------------- decode lands between chunks
def test_decode_interleaves_between_chunks(tiny_model_dir):
    """Two sessions decode continuously while a third prefills a 40-token
    prompt in 4-token chunks: decode steps must land BETWEEN chunks
    (decode_steps_interleaved > 0 — the stall-free claim), every session
    stays HF-exact, and rpc_info surfaces the scheduling counters plus the
    per-class queue waits."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s = _server(model_dir, rc(), 0, 3, prefill_chunk=4, max_batch=8)
        await s.start()
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny"
        )
        rng = np.random.default_rng(5)
        dec_prompts = [
            rng.integers(0, config.vocab_size, size=(1, 5 + i))
            for i in range(2)
        ]
        long_ids = (np.arange(40)[None, :] * 5 + 3) % config.vocab_size
        ref_long = _hf_greedy(hf_model, long_ids, 4)

        dec_sessions = [model.inference_session(40, 1) for _ in range(2)]
        for sess in dec_sessions:
            await sess.__aenter__()
        long_sess = model.inference_session(48, 1)
        await long_sess.__aenter__()
        try:
            # decoders: prefill + one warm decode step each, then loop
            toks = []
            for sess, p in zip(dec_sessions, dec_prompts):
                out = await sess.step(model.embed(p))
                toks.append(np.argmax(model.logits(out)[:, -1], axis=-1))
            generated = [[t] for t in toks]
            prefill_done = asyncio.Event()

            async def decode_loop(i):
                sess = dec_sessions[i]
                while not prefill_done.is_set() and len(generated[i]) < 28:
                    out = await sess.step(
                        model.embed(generated[i][-1][:, None])
                    )
                    generated[i].append(
                        np.argmax(model.logits(out)[:, -1], axis=-1)
                    )

            async def long_prefill():
                try:
                    return await long_sess.step(model.embed(long_ids))
                finally:
                    prefill_done.set()

            out_long, _, _ = await asyncio.gather(
                long_prefill(), decode_loop(0), decode_loop(1)
            )

            # the stall-free claim: decode steps ran between chunks
            assert s.prefill_chunks >= 10  # the 40-token prompt alone
            assert s.decode_steps_interleaved > 0

            # numerics: the chunked long prefill continues HF-exact ...
            t = np.argmax(model.logits(out_long)[:, -1], axis=-1)
            got_long = [t]
            for _ in range(3):
                out = await long_sess.step(model.embed(t[:, None]))
                t = np.argmax(model.logits(out)[:, -1], axis=-1)
                got_long.append(t)
            np.testing.assert_array_equal(
                np.concatenate(got_long), ref_long[0, long_ids.shape[1]:]
            )
            # ... and so does every interleaved decoder
            for p, g in zip(dec_prompts, generated):
                ref = _hf_greedy(hf_model, p, len(g))
                got = np.concatenate(g)[: ref.shape[1] - p.shape[1]]
                np.testing.assert_array_equal(
                    got, ref[0, p.shape[1]:p.shape[1] + got.shape[0]]
                )

            conn = await connect("127.0.0.1", s.port)
            info, _ = await conn.call("rpc_info", {})
            assert info["prefill_chunks"] == s.prefill_chunks
            assert info["decode_steps_interleaved"] == \
                s.decode_steps_interleaved
            waits = info["queue_wait_ms"]
            assert waits["prefill"]["p95"] >= 0.0  # per-class split exists
            assert waits["decode"]["p95"] >= 0.0
            await conn.close()
        finally:
            for sess in (*dec_sessions, long_sess):
                await sess.__aexit__(None, None, None)
            await s.stop()
            await reg.stop()

    asyncio.run(run())


# ------------------------------------------------ deadline abort frees pages
def test_deadline_abort_mid_stream_frees_partial_pages(
    tiny_model_dir, monkeypatch
):
    """A client deadline expiring between chunks aborts the stream: the
    step is dropped (deadlines_expired counts it, no reply is sent) and
    the rollback frees every speculative page the completed chunks wrote —
    the handle is back at zero context with zero referenced pages."""
    from bloombee_tpu.utils import clock as vclock
    from bloombee_tpu.utils.clock import ScaledClock

    model_dir, _, config = tiny_model_dir

    class FakeStream:
        def __init__(self):
            self.sent = []

        async def send(self, msg, tensors=None):
            self.sent.append(msg)

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        s = _server(
            model_dir, RegistryClient("127.0.0.1", reg.port), 0, 3,
            prefill_chunk=4,
        )
        await s.start()
        try:
            orig = s.executor.prefill_chunk

            def slow_chunk(handle, hidden, **kw):
                # 4 chunks x 60 virtual ms >> the 100 ms budget; the
                # sleep runs on the installed (scaled) clock so the wall
                # cost halves while the deadline math stays identical
                vclock.sleep(0.06)
                return orig(handle, hidden, **kw)

            monkeypatch.setattr(s.executor, "prefill_chunk", slow_chunk)
            async with s.manager.allocate(1, 17, timeout=5.0) as handle:
                session = _Session("dl-test", handle, 1)
                stream = FakeStream()
                hidden = np.zeros((1, 16, config.hidden_size), np.float32)
                await s._run_step(
                    session, stream,
                    {"step": 0, "deadline_s": 0.1, "commit": True},
                    [hidden],
                )
                assert s.deadlines_expired == 1
                assert stream.sent == []  # dropped, not answered
                assert s.prefill_chunks >= 1  # some chunks DID run ...
                # ... and the rollback erased their speculative writes
                lens = np.asarray(s.manager.context_lens(handle))
                assert int(lens[0]) == 0, lens
                table = s.manager.table
                assert table.free_pages == table.num_pages
        finally:
            await s.stop()
            await reg.stop()

    # deadline_s, the chunk sleeps, and the server's expiry check all
    # read the same installed clock, so a 2x scale preserves every
    # comparison while halving the real sleeping
    prev = vclock.install(ScaledClock(scale=2.0))
    try:
        asyncio.run(run())
    finally:
        vclock.install(prev)
