"""Draft-tree structures: linearization, ancestor masks, depths.

Port of the invariants of /root/reference/src/bloombee/models/llama/
spe_dec_tree.py: linearized node order, the O(n*depth) parent-walk ancestor
matrix (:139-179 — the arch-reform replacement for the O(n^3) matmul), and
incremental tree attention masks (:180-363). Nodes are NEW draft tokens only;
parent == -1 means "child of the last committed token".
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class DraftTree:
    tokens: np.ndarray  # [T] int64 draft token ids, linearized
    parents: np.ndarray  # [T] int32, index into tokens; -1 = root level

    def __post_init__(self):
        self.tokens = np.asarray(self.tokens, dtype=np.int64)
        self.parents = np.asarray(self.parents, dtype=np.int32)
        if np.any(self.parents >= np.arange(len(self.parents))):
            raise ValueError("parents must precede children in linear order")

    @property
    def size(self) -> int:
        return len(self.tokens)

    def depths(self) -> np.ndarray:
        """[T] depth of each node (root level = 0); O(n*depth) parent walk."""
        d = np.zeros(self.size, dtype=np.int32)
        for i in range(self.size):
            p = self.parents[i]
            d[i] = 0 if p < 0 else d[p] + 1
        return d

    def ancestors_or_self(self) -> np.ndarray:
        """[T, T] bool: A[i, j] = node j is an ancestor of i (or i itself)."""
        t = self.size
        a = np.zeros((t, t), dtype=bool)
        for i in range(t):
            j = i
            while j >= 0:
                a[i, j] = True
                j = self.parents[j]
        return a

    def path_to(self, node: int) -> list[int]:
        """Linear indices from root level down to `node` inclusive."""
        path = []
        j = node
        while j >= 0:
            path.append(j)
            j = self.parents[j]
        return path[::-1]

    def children_of(self, node: int) -> np.ndarray:
        """Linear indices of `node`'s children (-1 for the root level)."""
        return np.nonzero(self.parents == node)[0]


def tree_attention_mask(tree: DraftTree) -> np.ndarray:
    """[T, T] visibility among the tree's tokens (ancestor-or-self).

    The committed-prefix part of the mask is handled inside the span step
    (runtime/step.py _attend_paged: prefix keys always visible)."""
    return tree.ancestors_or_self()


def pruned_step_arrays(
    mask: np.ndarray,  # [B, T, T] full tree mask
    depths: np.ndarray,  # [B, T]
    keep: np.ndarray,  # [B, K] kept linear indices, -1 padded
) -> tuple[np.ndarray, np.ndarray]:
    """Tree mask + depths restricted to kept nodes per row (what a pruning
    span forwards downstream — reference block_functions.py:423-531 works in
    the inverse direction, restoring pruned rows). Padded entries get an
    all-False mask row (they still see the committed prefix in the step) and
    depth 0."""
    b, k = keep.shape
    mask_k = np.zeros((b, k, k), dtype=bool)
    depths_k = np.zeros((b, k), dtype=np.int32)
    for i in range(b):
        valid = np.nonzero(keep[i] >= 0)[0]
        idx = keep[i][valid]
        mask_k[i][np.ix_(valid, valid)] = mask[i][np.ix_(idx, idx)]
        depths_k[i][valid] = depths[i][idx]
    return mask_k, depths_k


def chain_tree(tokens: np.ndarray) -> DraftTree:
    """Degenerate tree: a single chain (classic draft-K speculative decode)."""
    t = len(tokens)
    return DraftTree(tokens=tokens, parents=np.arange(-1, t - 1, dtype=np.int32))
