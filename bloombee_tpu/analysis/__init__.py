"""bbtpu-lint: project-specific AST static analysis (rules BB001–BB006).

Run via `python -m bloombee_tpu.analysis` or `scripts/analyze.sh`; the
invariants each rule guards are documented in ARCHITECTURE.md
("Invariants") and in bloombee_tpu/analysis/rules.py.
"""

from bloombee_tpu.analysis.core import Finding, analyze_source
from bloombee_tpu.analysis.rules import ALL_CODES, make_rules

__all__ = ["Finding", "analyze_source", "make_rules", "ALL_CODES"]
