"""Pallas flash attention vs dense reference (interpreter mode on CPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bloombee_tpu.ops.attention import causal_mask, masked_attention
from bloombee_tpu.ops.pallas.flash_attention import flash_attention


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("hkv", [4, 2, 1])
def test_flash_matches_dense(causal, hkv):
    b, t, h, hd = 2, 256, 4, 64
    rng = jax.random.PRNGKey(0)
    q = jax.random.normal(rng, (b, t, h, hd), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, t, hkv, hd), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, t, hkv, hd), jnp.float32)

    if causal:
        mask = causal_mask(t)[None]
    else:
        mask = jnp.ones((1, t, t), bool)
    ref = masked_attention(q, k, v, mask)

    out = flash_attention(
        q, k, v, causal=causal, block_q=64, block_k=64, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_flash_prefix_offset_matches_dense():
    """S > T: queries attend to a committed prefix plus themselves, with
    absolute positions offset by s - t (chunked-prefill shape)."""
    b, t, s, h, hkv, hd = 1, 64, 192, 4, 2, 32
    q = jax.random.normal(jax.random.PRNGKey(0), (b, t, h, hd), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, hkv, hd), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, hkv, hd), jnp.float32)
    ref = masked_attention(q, k, v, causal_mask(t, offset=s - t, s=s)[None])
    out = flash_attention(
        q, k, v, causal=True, block_q=32, block_k=32, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_flash_explicit_offset_masks_padded_tail():
    """offset=0 with S > T (fresh prefill over a page-padded context): keys
    beyond the causal horizon — including the garbage tail — are masked."""
    b, t, s, h, hkv, hd = 1, 64, 128, 4, 2, 32
    q = jax.random.normal(jax.random.PRNGKey(0), (b, t, h, hd), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, hkv, hd), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, hkv, hd), jnp.float32)
    # dense reference sees only the first t keys (the real ones)
    ref = masked_attention(
        q, k[:, :t], v[:, :t], causal_mask(t)[None]
    )
    # poison the tail: if the kernel ever attends there, outputs explode
    k = k.at[:, t:].set(100.0)
    v = v.at[:, t:].set(100.0)
    out = flash_attention(
        q, k, v, causal=True, block_q=32, block_k=32, interpret=True,
        offset=0,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_span_prefill_flash_matches_dense():
    """The serving span step with the flash path on vs off (executor
    heuristic end-to-end): identical prefill outputs."""
    import ml_dtypes

    from bloombee_tpu.kv.cache_manager import CacheManager
    from bloombee_tpu.models.llama.block import init_block_params
    from bloombee_tpu.models.spec import ModelSpec
    from bloombee_tpu.runtime.executor import SpanExecutor
    from bloombee_tpu.utils.tree import stack_params

    spec = ModelSpec(
        family="llama", hidden_size=64, intermediate_size=128,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        num_hidden_layers=2, vocab_size=64,
    )
    params = stack_params(
        [init_block_params(jax.random.PRNGKey(i), spec, dtype=jnp.float32)
         for i in range(2)]
    )
    hidden = np.asarray(
        jax.random.normal(jax.random.PRNGKey(7), (2, 128, 64), jnp.float32)
    )

    import asyncio
    import os

    async def run_one(flag):
        os.environ["BBTPU_FLASH_ATTENTION"] = flag
        os.environ["BBTPU_FLASH_INTERPRET"] = "1"  # non-TPU backend gate
        try:
            manager = CacheManager(
                num_layers=2, num_pages=64, page_size=16,
                n_kv_heads=2, head_dim=16, dtype=jnp.float32,
            )
            ex = SpanExecutor(params, spec, manager,
                              compute_dtype=jnp.float32)
            async with manager.allocate(2, 256) as handle:
                return ex.prefill(handle, hidden)
        finally:
            del os.environ["BBTPU_FLASH_ATTENTION"]
            del os.environ["BBTPU_FLASH_INTERPRET"]

    out_flash = asyncio.run(run_one("1"))
    out_dense = asyncio.run(run_one("0"))
    np.testing.assert_allclose(out_flash, out_dense, atol=2e-5, rtol=2e-5)


def test_flash_rejects_bad_shapes():
    q = jnp.zeros((1, 100, 2, 16))
    k = v = jnp.zeros((1, 100, 2, 16))
    with pytest.raises(ValueError):
        flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)
    q = jnp.zeros((1, 64, 4, 16))
    k = v = jnp.zeros((1, 64, 3, 16))
    with pytest.raises(ValueError):  # H not a multiple of Hkv
        flash_attention(q, k, v, interpret=True)
    q = jnp.zeros((1, 128, 4, 16))
    k = v = jnp.zeros((1, 64, 2, 16))
    with pytest.raises(ValueError):  # S < T
        flash_attention(q, k, v, interpret=True)


def test_flash_ragged_starts_lens_matches_dense():
    """Per-row starts/lens (mixed-length batch): each row's queries sit at
    its own offset and see only its own keys — the case that previously
    fell back to the dense gather (round-4 verdict #10)."""
    b, t, s, h, hkv, hd = 3, 64, 192, 4, 2, 32
    q = jax.random.normal(jax.random.PRNGKey(0), (b, t, h, hd), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, hkv, hd), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, hkv, hd), jnp.float32)
    starts = np.array([0, 37, 100], np.int32)
    lens = starts + t

    # dense per-row reference: row r attends keys [0, lens[r]) causally
    # from its own offset
    refs = []
    for r in range(b):
        mask = causal_mask(t, offset=int(starts[r]), s=s)[None]
        mask = mask & (jnp.arange(s)[None, None, :] < int(lens[r]))
        refs.append(masked_attention(q[r:r+1], k[r:r+1], v[r:r+1], mask))
    ref = jnp.concatenate(refs, axis=0)

    # poison keys beyond each row's lens: attending there must explode
    k_p, v_p = np.array(k), np.array(v)
    for r in range(b):
        k_p[r, int(lens[r]):] = 100.0
        v_p[r, int(lens[r]):] = 100.0
    out = flash_attention(
        jnp.asarray(q), jnp.asarray(k_p), jnp.asarray(v_p), causal=True,
        block_q=32, block_k=32, interpret=True,
        starts=jnp.asarray(starts), lens=jnp.asarray(lens),
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_span_prefill_flash_mixed_length_batch(monkeypatch):
    """Executor-level: a second-turn prefill over rows with DIFFERENT
    committed context lengths must engage flash and match the dense path
    (previously the uniform-starts gate forced dense)."""
    import asyncio

    from bloombee_tpu.kv.cache_manager import CacheManager
    from bloombee_tpu.models.llama.block import init_block_params
    from bloombee_tpu.models.spec import ModelSpec
    from bloombee_tpu.runtime.executor import SpanExecutor
    from bloombee_tpu.utils.tree import stack_params

    spec = ModelSpec(
        family="llama", hidden_size=32, intermediate_size=64,
        num_attention_heads=4, num_key_value_heads=2, head_dim=8,
        num_hidden_layers=2, vocab_size=64,
    )
    params = stack_params(
        [init_block_params(jax.random.PRNGKey(i), spec, dtype=jnp.float32)
         for i in range(2)]
    )
    rng = np.random.default_rng(0)
    turn1 = rng.standard_normal((2, 40, 32)).astype(np.float32) * 0.1
    lens1 = [17, 40]  # ragged first-turn lengths
    turn2 = rng.standard_normal((2, 128, 32)).astype(np.float32) * 0.1

    def run(flash: bool):
        monkeypatch.setenv("BBTPU_FLASH_ATTENTION", "1" if flash else "0")
        monkeypatch.setenv("BBTPU_FLASH_INTERPRET", "1" if flash else "")
        monkeypatch.setenv("BBTPU_PAGED_ATTENTION", "0")

        async def go():
            manager = CacheManager(
                num_layers=2, num_pages=64, page_size=16,
                n_kv_heads=2, head_dim=8, dtype=jnp.float32,
            )
            ex = SpanExecutor(
                params, spec, manager, compute_dtype=jnp.float32,
                max_chunk_tokens=512,
            )
            async with manager.allocate(2, 256) as handle:
                # ragged turn 1: padded rectangle, per-row commit
                ex.prefill(handle, turn1, commit=False)
                manager.commit(handle, lengths=lens1)
                assert sorted(manager.context_lens(handle)) == sorted(lens1)
                # turn 2: T=128 over rows with different starts
                return ex.prefill(handle, turn2)

        return asyncio.run(go())

    dense = run(False)
    flash = run(True)
    np.testing.assert_allclose(
        np.asarray(flash, np.float32), np.asarray(dense, np.float32),
        rtol=2e-4, atol=2e-4,
    )
