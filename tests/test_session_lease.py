"""Session leases, keepalive half-open detection, reconnect-resume.

The correctness bar (ISSUE 7): a partitioned/stalled client mid-decode
must not wedge the server — keepalives detect the half-open connection,
the session lease parks its KV pages as evictable refcount-0 cached pool
entries (counted reclaimable within one lease period), the reaper frees
them for good, and graceful drain never waits on a wedged session. A
client that DOES come back re-attaches the parked session on a fresh
stream and retransmits the interrupted step under its ORIGINAL id:
servers that already applied it answer from the recorded reply
(at-most-once — counter-asserted via steps_deduped), so the generation
continues token-identical with zero prompt-replay tokens. A resume
arriving after the lease expired degrades to the PR 4 full-replay path.
"""

import asyncio
import time

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from bloombee_tpu.client.config import ClientConfig
from bloombee_tpu.client.model import DistributedModelForCausalLM
from bloombee_tpu.client.session import InferenceSession
from bloombee_tpu.client.sequence_manager import RemoteSequenceManager
from bloombee_tpu.server.block_server import BlockServer
from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer
from bloombee_tpu.utils import clock
from bloombee_tpu.utils.clock import ScaledClock
from bloombee_tpu.wire import faults
from bloombee_tpu.wire.faults import FaultPlan, FaultRule
from bloombee_tpu.wire.rpc import RpcError, RpcServer, connect


@pytest.fixture(scope="module")
def tiny_model_dir(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64,
        intermediate_size=128,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_hidden_layers=3,
        vocab_size=128,
        max_position_embeddings=256,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    d = tmp_path_factory.mktemp("tiny_llama_lease")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model, config


@pytest.fixture(autouse=True)
def no_leaked_plan():
    yield
    faults.set_plan(None)


def _server(model_dir, registry, start, end, **kw):
    kw.setdefault("compute_dtype", jnp.float32)
    kw.setdefault("num_pages", 64)
    kw.setdefault("page_size", 4)
    # the Python table backs the prefix pool the cached-park path needs;
    # without it parking degrades to host-tier copies (still covered by
    # the manager, but these tests pin the zero-copy contract)
    kw.setdefault("prefix_cache", True)
    return BlockServer(
        model_uid="tiny", start=start, end=end, model_dir=model_dir,
        registry=registry, **kw,
    )


def _hf_greedy(model, input_ids, max_new_tokens):
    with torch.no_grad():
        out = model.generate(
            torch.tensor(input_ids), max_new_tokens=max_new_tokens,
            do_sample=False, use_cache=True,
        )
    return out.numpy()


def _counts(server):
    table = server.manager.table
    c = table.counts()
    assert c["free"] + c["referenced"] + c["cached"] == table.num_pages, c
    return c


def _partition_spans(session):
    """Blackhole every span connection: the client's sends stop reaching
    the wire and arriving frames are swallowed, with no FIN/RST — the
    half-open case only keepalives can detect. A conn captures its fault
    plan at creation, so arm these (already-open) conns directly."""
    for sp in session._spans:
        sp.conn.fault_plan = FaultPlan()
        sp.conn._bbtpu_partitioned = True


async def _greedy_decode(model, session, out, n, dtype=np.int64):
    new = np.zeros((out.shape[0], 0), dtype=dtype)
    for _ in range(n):
        logits = model.logits(out[:, -1:])[:, 0]
        nxt = np.argmax(logits, axis=-1).astype(dtype)[:, None]
        new = np.concatenate([new, nxt], axis=1)
        out = await session.step(model.embed(nxt), ids=nxt)
    return new, out


async def _wait_for(pred, timeout_s, what):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        await asyncio.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


# ------------------------------------------------------------------ wire
@pytest.mark.chaos
def test_keepalive_detects_half_open_both_ends():
    """A partitioned connection (no FIN/RST, both directions blackholed)
    is detected by BOTH endpoints' keepalives: the client's pending call
    fails fast instead of hanging in recv(), and the server reaps its
    half of the connection."""

    async def echo(meta, tensors):
        return meta, []

    async def run():
        server = RpcServer(
            unary_handlers={"echo": echo}, host="127.0.0.1",
            keepalive_s=0.2,
        )
        await server.start()
        conn = await connect("127.0.0.1", server.port, keepalive_s=0.2)
        meta, _ = await conn.call("echo", {"x": 1})
        assert meta["x"] == 1
        assert len(server._conns) == 1

        conn.fault_plan = FaultPlan()
        conn._bbtpu_partitioned = True
        t0 = time.monotonic()
        with pytest.raises(RpcError):
            # without keepalives this recv would hang until the 10s
            # wait_for: the abort must beat it by a wide margin
            await asyncio.wait_for(conn.call("echo", {}), 10)
        assert time.monotonic() - t0 < 3.0
        assert conn.keepalives_sent >= 1

        # the server pings too, never hears a pong, and aborts its side
        await _wait_for(
            lambda: not server._conns, 5.0, "server-side conn reap"
        )
        assert server.keepalives_sent >= 1
        await server.stop()

    asyncio.run(run())


# ----------------------------------------------------------- lease reaper
@pytest.mark.chaos
def test_abandoned_session_reaped_within_lease(tiny_model_dir):
    """Acceptance (a): a client partitioned mid-decode never reconnects.
    The keepalive fences the half-open stream, the session parks — its
    pages counted reclaimable (refcount 0) immediately — and the reaper
    frees every page within the lease period. No page leaks, no page is
    freed twice (the invariant would break either way)."""
    model_dir, _, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        server = _server(
            model_dir, rc(), 0, 3, session_lease_s=1.0, keepalive_s=0.2,
        )
        await server.start()
        manager = RemoteSequenceManager(rc(), "tiny", 3)

        rng = np.random.default_rng(2)
        s = InferenceSession(manager, max_length=24, batch_size=1)
        async with s:
            await s.step(
                rng.standard_normal((1, 8, config.hidden_size))
                .astype(np.float32) * 0.02
            )
            for _ in range(2):
                await s.step(
                    rng.standard_normal((1, 1, config.hidden_size))
                    .astype(np.float32) * 0.02
                )
            assert _counts(server)["referenced"] > 0

            _partition_spans(s)
            # sit out the keepalive fence + lease on a compressed process
            # clock: every timing loop involved (keepalive idle check,
            # park deadline, reaper tick) reads clock.*, so the whole
            # detection->park->reap sequence runs 20x faster with
            # identical state transitions. No compute is in flight during
            # the window, so nothing real-time can be mis-fenced.
            prev = clock.install(ScaledClock(scale=20.0))
            try:
                # park (keepalive fences the silent stream) makes every
                # page refcount-0 — reclaimable under pressure from that
                # instant
                await _wait_for(
                    lambda: _counts(server)["referenced"] == 0,
                    5.0, "pages to become reclaimable at park",
                )
                # the reaper then frees them for good within the lease
                await _wait_for(
                    lambda: server.sessions_reaped >= 1, 5.0, "lease reap"
                )
            finally:
                clock.install(prev)
            assert not server._sessions
            c = _counts(server)
            # nothing pinned; synthetic park entries purged back to the
            # free list (real-hash pages may legitimately stay pooled).
            # _counts' free+referenced+cached == num_pages invariant is
            # the double-free/leak detector here
            assert c["referenced"] == 0

        await server.stop()
        await reg.stop()

    asyncio.run(run())


@pytest.mark.chaos
def test_drain_does_not_wait_on_wedged_session(tiny_model_dir):
    """Graceful drain with a parked (wedged-client) session and a LONG
    lease must not wait out the drain timeout: parked sessions are
    force-expired up front, their pages reclaimed, and drain returns as
    soon as the live set is empty."""
    model_dir, _, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        server = _server(
            model_dir, rc(), 0, 3, session_lease_s=30.0, keepalive_s=0.2,
        )
        await server.start()
        manager = RemoteSequenceManager(rc(), "tiny", 3)

        rng = np.random.default_rng(3)
        s = InferenceSession(manager, max_length=24, batch_size=1)
        async with s:
            await s.step(
                rng.standard_normal((1, 8, config.hidden_size))
                .astype(np.float32) * 0.02
            )
            _partition_spans(s)
            await _wait_for(
                lambda: any(
                    sess.parked for sess in server._sessions.values()
                ),
                5.0, "session to park",
            )
            t0 = time.monotonic()
            await server.drain(timeout=20.0)
            assert time.monotonic() - t0 < 5.0  # never waited the lease out
            assert not server._sessions
            assert _counts(server)["referenced"] == 0

        await server.stop()
        await reg.stop()

    asyncio.run(run())


# ------------------------------------------------------- reconnect-resume
@pytest.mark.chaos
def test_reconnect_resume_token_identical_zero_replay(tiny_model_dir):
    """Acceptance (b): the connection dies mid-decode, the client resumes
    the lease-parked session on a fresh stream, and the generation
    finishes token-identical to HF greedy with ZERO prompt tokens
    replayed — the parked KV was adopted as-is."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        server = _server(model_dir, rc(), 0, 3, session_lease_s=30.0)
        await server.start()

        input_ids = (np.arange(10)[None, :] * 3 + 2) % config.vocab_size
        ref = _hf_greedy(hf_model, input_ids, 8)

        cfg = ClientConfig(use_push=False, resume=True)
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny", config=cfg
        )
        session = model.inference_session(24, 1)
        await session.__aenter__()
        out = await session.step(model.embed(input_ids), ids=input_ids)
        first, out = await _greedy_decode(
            model, session, out, 4, dtype=input_ids.dtype
        )
        # sever the wire under the session (RST; the client notices on
        # its next send and takes the cheap resume path)
        for sp in session._spans:
            sp.conn.abort("test: injected failure")
        rest, _ = await _greedy_decode(
            model, session, out, 4, dtype=input_ids.dtype
        )
        np.testing.assert_array_equal(
            np.concatenate([input_ids, first, rest], axis=1), ref
        )
        assert session.resumed_streams == 1
        assert session.resume_declines == 0
        # zero replay: the resume adopted the parked KV, nothing was
        # re-prefilled or re-routed
        assert session.failover_replayed_tokens == 0
        assert server.sessions_resumed == 1
        await session.__aexit__(None, None, None)

        await asyncio.sleep(0.2)  # server-side teardown is async
        assert _counts(server)["referenced"] == 0
        await server.stop()
        await reg.stop()

    asyncio.run(run())


@pytest.mark.chaos
def test_lost_reply_dedup_at_most_once(tiny_model_dir):
    """The hard at-most-once case: the server APPLIES a decode step but
    the reply vanishes in a partition. The resumed client retransmits the
    step under its original id; the server must answer from the recorded
    reply without re-applying KV (steps_deduped == 1) and the generation
    stays token-identical — the acceptance gate's exact-token + counter
    assertion."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        server = _server(model_dir, rc(), 0, 3, session_lease_s=30.0)
        await server.start()

        input_ids = (np.arange(10)[None, :] * 7 + 5) % config.vocab_size
        ref = _hf_greedy(hf_model, input_ids, 8)

        # partition on the 3rd stream reply from the server: the prefill
        # reply is #1, decode step 1's is #2, decode step 2's is #3 — so
        # step 2 is applied server-side but its reply never lands
        faults.set_plan(FaultPlan(seed=1).add(FaultRule(
            site="read", action="partition", method="sitem",
            port=server.port, nth=3,
        )))

        cfg = ClientConfig(use_push=False, resume=True, step_timeout=2.0)
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny", config=cfg
        )
        session = model.inference_session(24, 1)
        await session.__aenter__()
        out = await session.step(model.embed(input_ids), ids=input_ids)
        toks, _ = await _greedy_decode(
            model, session, out, 8, dtype=input_ids.dtype
        )
        np.testing.assert_array_equal(
            np.concatenate([input_ids, toks], axis=1), ref
        )
        assert session.resumed_streams == 1
        assert session.failover_replayed_tokens == 0
        # the retransmitted step was answered from the recorded reply —
        # applied exactly once (a double-apply would have shifted every
        # subsequent token off the HF reference above)
        assert server.steps_deduped == 1
        assert server.sessions_resumed == 1

        # operator-facing counters ride rpc_info
        conn = await connect("127.0.0.1", server.port)
        info, _ = await conn.call("rpc_info", {})
        assert info["steps_deduped"] == 1
        assert info["sessions_resumed"] == 1
        assert info["session_lease_s"] == 30.0
        assert "keepalives_sent" in info and "sessions_reaped" in info
        await conn.close()

        await session.__aexit__(None, None, None)
        await asyncio.sleep(0.2)
        assert _counts(server)["referenced"] == 0
        await server.stop()
        await reg.stop()

    asyncio.run(run())


@pytest.mark.chaos
def test_resume_declined_after_lease_expiry_full_replay(tiny_model_dir):
    """A client that comes back AFTER its lease expired gets a decline
    (the pages are gone) and falls back to the PR 4 full-replay recovery
    — still token-identical, with the whole committed history replayed."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        server = _server(model_dir, rc(), 0, 3, session_lease_s=0.5)
        await server.start()

        input_ids = (np.arange(10)[None, :] * 11 + 4) % config.vocab_size
        ref = _hf_greedy(hf_model, input_ids, 8)

        cfg = ClientConfig(
            use_push=False, resume=True, ban_timeout=0.2, ban_max=0.5,
        )
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny", config=cfg
        )
        session = model.inference_session(24, 1)
        await session.__aenter__()
        out = await session.step(model.embed(input_ids), ids=input_ids)
        first, out = await _greedy_decode(
            model, session, out, 4, dtype=input_ids.dtype
        )
        for sp in session._spans:
            sp.conn.abort("test: injected failure")
        # sit out the lease on a 20x compressed process clock: the park
        # deadline and reaper tick both read clock.*, so the 0.5s lease
        # expires in ~30ms wall with identical transitions (no compute is
        # in flight during the window)
        prev = clock.install(ScaledClock(scale=20.0))
        try:
            await _wait_for(
                lambda: server.sessions_reaped >= 1, 5.0, "lease reap"
            )
        finally:
            clock.install(prev)
        rest, _ = await _greedy_decode(
            model, session, out, 4, dtype=input_ids.dtype
        )
        np.testing.assert_array_equal(
            np.concatenate([input_ids, first, rest], axis=1), ref
        )
        assert session.resume_declines >= 1
        assert session.resumed_streams == 0
        # full replay: the 14 committed tokens (10 prompt + 4 decoded)
        # re-prefilled on the fresh session
        assert session.failover_replayed_tokens == 14
        await session.__aexit__(None, None, None)

        await asyncio.sleep(0.2)
        assert _counts(server)["referenced"] == 0
        await server.stop()
        await reg.stop()

    asyncio.run(run())
