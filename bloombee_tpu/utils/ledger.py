"""Recovery-coverage ledger: proof that a chaos run tested something.

A green chaos entry is only meaningful if faults actually fired AND
recovery machinery actually ran — a run whose probabilistic plan happened
to inject nothing (or whose injections never reached a recovery path)
passes vacuously. Every injection site calls ``fault(name)`` and every
recovery path calls ``recovery(name)``; the counters are process-wide,
thread-safe, and dumped as one JSON line to ``BBTPU_CHAOS_LEDGER`` at
interpreter exit (append mode — one line per process, merged by the
reader). ``scripts/chaos.sh`` fails any matrix entry whose merged ledger
shows zero faults or zero recoveries.

Registered point names (the coverage vocabulary — grep for callers):

faults
  ``wire.delay|reset|close|stall|drop|corrupt|partition`` — FaultPlan
  injections per action; ``wire.scheduled.<action>`` — FaultSchedule
  firings; ``server.crash`` — hard process-crash via BlockServer.crash().

recoveries
  ``client.reroute_replay`` — session failover onto a new chain with
  history replay; ``client.ban`` / ``client.overload_backoff`` /
  ``client.quarantine`` — peer penalty classes; ``server.resume_dedup``
  — duplicate step suppressed on session resume; ``server.rollback_solo_replay``
  — batched dispatch failure isolated by solo replay;
  ``server.lease_park`` / ``server.lease_reap`` — disconnected session
  parked / force-expired; ``server.promotion`` — standby promoted to
  serving; ``server.rebalance_reannounce`` — measured-load rebalance
  re-announced a new span; ``server.artifact_fallback_compile`` — the
  compile-artifact path (corrupt/declined/unfetchable blobs, fingerprint
  mismatch, peer death mid-fetch, no covering peer) fell back to local
  compile instead of pre-installing.

With no ledger path configured the counters still accumulate in memory
(tests read ``snapshot()`` directly) and nothing is written.
"""

from __future__ import annotations

import atexit
import collections
import json

from bloombee_tpu.utils import env, lockwatch

env.declare(
    "BBTPU_CHAOS_LEDGER", str, "",
    "path to append this process's fault/recovery coverage ledger to at "
    "exit (one JSON line per process); empty = in-memory only. Set by "
    "scripts/chaos.sh so the gate can fail entries that tested nothing",
)

_lock = lockwatch.thread_lock("utils.ledger")
_faults: collections.Counter = collections.Counter()
_recoveries: collections.Counter = collections.Counter()
_atexit_registered = False


def _ensure_atexit() -> None:
    global _atexit_registered
    if not _atexit_registered:
        _atexit_registered = True
        if env.get("BBTPU_CHAOS_LEDGER"):
            atexit.register(flush)


def fault(name: str, n: int = 1) -> None:
    """Record an injected fault at a named point."""
    with _lock:
        _faults[name] += n
    _ensure_atexit()


def recovery(name: str, n: int = 1) -> None:
    """Record an exercised recovery path at a named point."""
    with _lock:
        _recoveries[name] += n
    _ensure_atexit()


def snapshot() -> dict:
    with _lock:
        return {
            "faults": dict(_faults),
            "recoveries": dict(_recoveries),
        }


def reset() -> None:
    with _lock:
        _faults.clear()
        _recoveries.clear()


def flush(path: str | None = None) -> None:
    """Append this process's ledger as one JSON line (atexit hook; also
    callable directly by harnesses that outlive their chaos phase)."""
    path = path or env.get("BBTPU_CHAOS_LEDGER")
    if not path:
        return
    snap = snapshot()
    if not snap["faults"] and not snap["recoveries"]:
        return
    try:
        with open(path, "a") as f:
            f.write(json.dumps(snap, sort_keys=True) + "\n")
    except OSError:  # ledger must never take down the process it audits
        pass


def merge_lines(text: str) -> dict:
    """Merge a multi-process ledger file (one JSON line each) into one
    {"faults": {...}, "recoveries": {...}} dict — the reader half of the
    chaos.sh gate."""
    faults: collections.Counter = collections.Counter()
    recoveries: collections.Counter = collections.Counter()
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            snap = json.loads(line)
        except ValueError:
            continue
        faults.update(snap.get("faults") or {})
        recoveries.update(snap.get("recoveries") or {})
    return {"faults": dict(faults), "recoveries": dict(recoveries)}


def _main(argv=None) -> int:
    """``python -m bloombee_tpu.utils.ledger PATH [--require]``: merge and
    print a ledger file; with --require, exit 1 unless it shows at least
    one fault AND one recovery (the chaos.sh vacuous-green gate)."""
    import argparse
    import sys

    ap = argparse.ArgumentParser(description=_main.__doc__)
    ap.add_argument("path")
    ap.add_argument("--require", action="store_true",
                    help="fail (exit 1) on an empty half of the ledger")
    ap.add_argument("--require-recovery", action="append", default=[],
                    metavar="NAME",
                    help="with --require: additionally fail unless this "
                         "named recovery point fired at least once "
                         "(repeatable) — pins a chaos entry to the exact "
                         "degraded path it exists to exercise")
    args = ap.parse_args(argv)
    try:
        with open(args.path) as f:
            text = f.read()
    except OSError:
        text = ""
    merged = merge_lines(text)
    n_f = sum(merged["faults"].values())
    n_r = sum(merged["recoveries"].values())
    print(f"ledger: {n_f} fault(s), {n_r} recovery(ies)")
    for kind in ("faults", "recoveries"):
        for name, n in sorted(merged[kind].items()):
            print(f"  {kind[:-1] if kind == 'faults' else 'recovery'} "
                  f"{name}={n}")
    if args.require and (n_f == 0 or n_r == 0):
        print(
            "ledger: EMPTY — a chaos entry must observe >=1 injected fault "
            "and >=1 exercised recovery path; a run that injected nothing "
            "(or whose injections never reached recovery machinery) is a "
            "vacuous green", file=sys.stderr,
        )
        return 1
    if args.require:
        missing = [
            name for name in args.require_recovery
            if not merged["recoveries"].get(name)
        ]
        if missing:
            print(
                f"ledger: required recovery point(s) never fired: "
                f"{', '.join(missing)} — the degraded path this entry "
                "exists to exercise did not run", file=sys.stderr,
            )
            return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
