#!/usr/bin/env bash
# bbtpu-lint gate: project-specific AST rules (BB001-BB013) plus the
# README env-switch-table and ARCHITECTURE lock-hierarchy-table drift
# checks, against the committed baseline.
#
#   scripts/analyze.sh                     # the CI gate
#   scripts/analyze.sh --update-baseline   # accept current findings
#   scripts/analyze.sh --fix-env-docs      # regenerate README table
#   scripts/analyze.sh --fix-lock-docs     # regenerate ARCHITECTURE table
#   scripts/analyze.sh --json              # machine-readable findings
#   scripts/analyze.sh --list-rules
set -euo pipefail
cd "$(dirname "$0")/.."

# --check-env-docs imports the package to populate the env registry;
# keep that import off any TPU tunnel.
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

case "${1:-}" in
    --update-baseline|--fix-env-docs|--fix-lock-docs|--list-rules|--dump-env-table)
        exec python -m bloombee_tpu.analysis "$@"
        ;;
esac

exec python -m bloombee_tpu.analysis --check-env-docs --check-lock-docs "$@"
