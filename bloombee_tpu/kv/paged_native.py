"""Native (C++) paged table behind the PagedKVTable API.

The table is the hot host-side control plane of every serving step; the C++
implementation (native/paged_table.cc) replicates kv/paged.py exactly —
including LIFO free-list order, so slot assignment is bit-identical (pinned
by a randomized equivalence test). `make_table` picks the implementation:
BBTPU_NATIVE_TABLE=1 (default) uses C++ when the toolchain builds it, with
a silent fall back to the pure-Python table otherwise.
"""

from __future__ import annotations

import ctypes

import numpy as np

from bloombee_tpu.kv.paged import DEFAULT_PAGE_SIZE, OutOfPages, PagedKVTable
from bloombee_tpu.utils import env

env.declare(
    "BBTPU_NATIVE_TABLE", bool, True,
    "use the C++ paged table when the toolchain can build it",
)


def _check(rc: int, what: str) -> int:
    if rc == -1:
        raise KeyError(f"{what}: unknown sequence")
    if rc == -2:
        raise OutOfPages(what)
    if rc < 0:
        raise ValueError(f"{what}: rc={rc}")
    return rc


class _NativeSeqView:
    """Duck-typed stand-in for paged.SeqState (read-only fields)."""

    __slots__ = ("_t", "_sid")

    def __init__(self, table: "NativePagedKVTable", sid: int):
        self._t = table
        self._sid = sid

    @property
    def l_acc(self) -> int:
        return _check(
            self._t._lib.pt_l_acc(self._t._h, self._sid), "l_acc"
        )

    @property
    def l_seq(self) -> int:
        return _check(
            self._t._lib.pt_l_seq(self._t._h, self._sid), "l_seq"
        )

    @property
    def num_pages(self) -> int:
        return _check(
            self._t._lib.pt_num_seq_pages(self._t._h, self._sid),
            "num_pages",
        )

    @property
    def pages(self) -> list[int]:
        n = _check(
            self._t._lib.pt_num_seq_pages(self._t._h, self._sid), "pages"
        )
        out = np.empty(max(n, 1), dtype=np.int32)
        self._t._lib.pt_page_row(
            self._t._h, self._sid,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), n,
        )
        return [int(x) for x in out[:n]]


class NativePagedKVTable:
    """C++-backed table with kv/paged.PagedKVTable's exact API."""

    def __init__(self, num_pages: int, page_size: int = DEFAULT_PAGE_SIZE):
        from bloombee_tpu.native import paged_table_lib

        lib = paged_table_lib()
        if lib is None:
            raise RuntimeError("native paged table unavailable")
        if num_pages <= 0 or page_size <= 0:
            raise ValueError("num_pages and page_size must be positive")
        self._lib = lib
        self.num_pages = num_pages
        self.page_size = page_size
        self._h = lib.pt_create(num_pages, page_size)

    def __del__(self):  # pragma: no cover - interpreter teardown
        try:
            self._lib.pt_destroy(self._h)
        except Exception:
            pass

    # ------------------------------------------------------------- lifecycle
    @property
    def free_pages(self) -> int:
        return _check(self._lib.pt_free_pages(self._h), "free_pages")

    @property
    def free_tokens(self) -> int:
        return self.free_pages * self.page_size

    def counts(self) -> dict:
        """Page census, kv/paged.PagedKVTable.counts() shape. The native
        table has no prefix pool, so cached is always 0."""
        free = self.free_pages
        return {
            "free": free,
            "referenced": self.num_pages - free,
            "cached": 0,
        }

    def has_seq(self, seq_id: int) -> bool:
        return bool(_check(self._lib.pt_has_seq(self._h, seq_id), "has_seq"))

    def seq(self, seq_id: int) -> _NativeSeqView:
        if not self.has_seq(seq_id):
            raise KeyError(seq_id)
        return _NativeSeqView(self, seq_id)

    def add_seq(self, seq_id: int) -> None:
        rc = self._lib.pt_add_seq(self._h, seq_id)
        if rc == -3:
            raise ValueError(f"sequence {seq_id} already exists")
        _check(rc, "add_seq")

    def drop_seq(self, seq_id: int) -> None:
        _check(self._lib.pt_drop_seq(self._h, seq_id), "drop_seq")

    # --------------------------------------------------------------- writing
    def assign_write_slots(
        self, seq_id: int, num_tokens: int, commit: bool = True
    ) -> np.ndarray:
        out = np.empty(max(num_tokens, 1), dtype=np.int32)
        rc = self._lib.pt_assign_write_slots(
            self._h, seq_id, num_tokens, 1 if commit else 0,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        if rc == -2:
            raise OutOfPages(f"write of {num_tokens} tokens")
        if rc == -3:
            raise ValueError(
                "committed write must follow the committed prefix"
            )
        _check(rc, "assign_write_slots")
        return out[:num_tokens].copy()

    # ------------------------------------------------------ commit / rollback
    def commit(self, seq_id: int, length: int | None = None) -> None:
        rc = self._lib.pt_commit(
            self._h, seq_id, -1 if length is None else length
        )
        if rc == -3:
            raise ValueError(f"commit length {length} out of range")
        _check(rc, "commit")

    def accept(self, seq_id: int, num_accepted: int) -> None:
        rc = self._lib.pt_accept(self._h, seq_id, num_accepted)
        if rc == -3:
            raise ValueError(
                f"accept {num_accepted} outside speculative window"
            )
        _check(rc, "accept")

    def rollback(self, seq_id: int) -> None:
        _check(self._lib.pt_rollback(self._h, seq_id), "rollback")

    def truncate_speculative(self, seq_id: int, length: int) -> None:
        rc = self._lib.pt_truncate_speculative(self._h, seq_id, length)
        if rc == -3:
            raise ValueError(f"truncate length {length} out of range")
        _check(rc, "truncate_speculative")

    def reset_seq(self, seq_id: int) -> None:
        _check(self._lib.pt_reset_seq(self._h, seq_id), "reset_seq")

    def restore_committed(self, seq_id: int, l_acc: int) -> None:
        rc = self._lib.pt_restore_committed(self._h, seq_id, l_acc)
        if rc == -3:
            raise ValueError(f"l_acc {l_acc} out of range")
        _check(rc, "restore_committed")

    def range_slots(self, seq_id: int, start: int, end: int) -> np.ndarray:
        out = np.empty(max(end - start, 1), dtype=np.int32)
        rc = self._lib.pt_range_slots(
            self._h, seq_id, start, end,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        if rc == -3:
            raise ValueError("range beyond allocated pages")
        _check(rc, "range_slots")
        return out[: end - start].copy()

    # ---------------------------------------------------------- device plans
    def page_table(self, seq_ids: list[int], max_pages: int) -> np.ndarray:
        out = np.zeros((len(seq_ids), max_pages), dtype=np.int32)
        for i, sid in enumerate(seq_ids):
            rc = self._lib.pt_page_row(
                self._h, sid,
                out[i].ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                max_pages,
            )
            if rc == -3:
                raise ValueError(
                    f"sequence {sid} has more pages than bucket {max_pages}"
                )
            _check(rc, "page_table")
        return out

    def context_lens(
        self, seq_ids: list[int], committed_only: bool = False
    ) -> np.ndarray:
        fn = self._lib.pt_l_acc if committed_only else self._lib.pt_l_seq
        return np.asarray(
            [_check(fn(self._h, s), "context_lens") for s in seq_ids],
            dtype=np.int32,
        )

    def prefix_slots(
        self, seq_id: int, committed_only: bool = True
    ) -> np.ndarray:
        n = _check(
            (self._lib.pt_l_acc if committed_only else self._lib.pt_l_seq)(
                self._h, seq_id
            ),
            "prefix_slots",
        )
        return self.range_slots(seq_id, 0, n)


def make_table(
    num_pages: int,
    page_size: int = DEFAULT_PAGE_SIZE,
    prefix_cache: bool = False,
):
    """The serving table: native when available and enabled, else Python.

    The prefix cache (refcounts, hash pool, copy-on-write) lives only in
    the Python table — enabling it forces the Python implementation even
    when the native one would build.
    """
    if not prefix_cache and env.get("BBTPU_NATIVE_TABLE"):
        try:
            return NativePagedKVTable(num_pages, page_size)
        except Exception as e:
            import logging

            logging.getLogger(__name__).info(
                "native table unavailable (%s); using python table", e
            )
    return PagedKVTable(num_pages, page_size)
