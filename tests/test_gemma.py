"""Gemma3 (HF parity) and Gemma-4 (heterogeneous head_dim) families.

Gemma3 is parity-tested against transformers (per-layer rope theta, qk
norms, sliding layers). Gemma-4 has no transformers implementation in this
environment, so the heterogeneous machinery (per-layer head_dim / kv heads /
k_eq_v over per-layer KV slabs, reference backend.py:243-306) is pinned by
the paged-cache invariant: stepwise decode must equal the full-sequence
forward, and serving must be deterministic end-to-end.
"""

import asyncio
import json

import numpy as np
import pytest

import jax.numpy as jnp

from bloombee_tpu.client.model import DistributedModelForCausalLM
from bloombee_tpu.server.block_server import BlockServer
from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer


def test_gemma3_block_parity_vs_hf(tmp_path):
    import torch
    from transformers import Gemma3TextConfig, Gemma3ForCausalLM

    config = Gemma3TextConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, head_dim=16, num_hidden_layers=4,
        vocab_size=128, max_position_embeddings=128, sliding_window=8,
        rope_theta=1_000_000.0, rope_local_base_freq=10_000.0,
        query_pre_attn_scalar=16, tie_word_embeddings=True,
    )
    torch.manual_seed(0)
    hf = Gemma3ForCausalLM(config).eval().to(torch.float32)
    hf.save_pretrained(tmp_path, safe_serialization=True)

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s = BlockServer(
            model_uid="g3", start=0, end=4, model_dir=str(tmp_path),
            registry=rc(), compute_dtype=jnp.float32, num_pages=64,
            page_size=4,
        )
        await s.start()
        model = DistributedModelForCausalLM.from_pretrained(
            str(tmp_path), rc(), model_uid="g3"
        )
        input_ids = np.arange(12)[None, :] % config.vocab_size
        async with model.inference_session(32, 1) as sess:
            out = await sess.step(model.embed(input_ids))
        logits = model.logits(out)
        with torch.no_grad():
            ref = hf(torch.tensor(input_ids)).logits.numpy()
        np.testing.assert_allclose(logits, ref, atol=2e-3, rtol=2e-3)

        ids = await model.generate(input_ids[:, :6], max_new_tokens=6)
        with torch.no_grad():
            prompt = torch.tensor(input_ids[:, :6])
            # explicit mask: generate otherwise treats token 0 as padding
            # (gemma pad_token_id == 0) and silently masks it
            ref_ids = hf.generate(
                prompt, attention_mask=torch.ones_like(prompt),
                max_new_tokens=6, do_sample=False, use_cache=True,
            ).numpy()
        np.testing.assert_array_equal(ids, ref_ids)

        await s.stop()
        await reg.stop()

    asyncio.run(run())


@pytest.fixture()
def gemma4_dir(tmp_path):
    """Synthetic gemma-4 checkpoint: sliding layers head_dim 16 / 2 kv
    heads; full layers head_dim 32 / 1 kv head with K=V aliasing."""
    from safetensors.numpy import save_file

    rng = np.random.default_rng(0)
    d_model, inter, heads, vocab = 48, 96, 4, 96
    hd_s, hd_f, kv_s, kv_f = 16, 32, 2, 1
    layer_types = [
        "sliding_attention", "full_attention",
        "sliding_attention", "full_attention",
    ]
    config = {
        "model_type": "gemma4",
        "hidden_size": d_model, "intermediate_size": inter,
        "num_attention_heads": heads, "num_key_value_heads": kv_s,
        "head_dim": hd_s, "num_hidden_layers": len(layer_types),
        "vocab_size": vocab, "rms_norm_eps": 1e-6,
        "rope_theta": 1_000_000.0, "rope_local_base_freq": 10_000.0,
        "sliding_window": 8, "layer_types": layer_types,
        "global_head_dim": hd_f, "num_global_key_value_heads": kv_f,
        "attention_k_eq_v": True, "use_qk_norm": True,
        "query_pre_attn_scalar": 16,
    }
    (tmp_path / "config.json").write_text(json.dumps(config))

    def w(*shape, scale=0.05):
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    tensors = {
        "model.language_model.embed_tokens.weight": w(vocab, d_model),
        "model.language_model.norm.weight": w(d_model, scale=0.01),
    }
    for i, lt in enumerate(layer_types):
        full = lt == "full_attention"
        hd = hd_f if full else hd_s
        kv = kv_f if full else kv_s
        p = f"model.language_model.layers.{i}"
        for ln in ("input_layernorm", "post_attention_layernorm",
                   "pre_feedforward_layernorm", "post_feedforward_layernorm"):
            tensors[f"{p}.{ln}.weight"] = w(d_model, scale=0.01)
        tensors[f"{p}.self_attn.q_proj.weight"] = w(heads * hd, d_model)
        tensors[f"{p}.self_attn.k_proj.weight"] = w(kv * hd, d_model)
        if not full:  # full layers alias V to K: no v weight
            tensors[f"{p}.self_attn.v_proj.weight"] = w(kv * hd, d_model)
        tensors[f"{p}.self_attn.o_proj.weight"] = w(d_model, heads * hd)
        tensors[f"{p}.self_attn.q_norm.weight"] = w(hd, scale=0.01)
        tensors[f"{p}.self_attn.k_norm.weight"] = w(hd, scale=0.01)
        tensors[f"{p}.mlp.gate_proj.weight"] = w(inter, d_model)
        tensors[f"{p}.mlp.up_proj.weight"] = w(inter, d_model)
        tensors[f"{p}.mlp.down_proj.weight"] = w(d_model, inter)
    save_file(tensors, str(tmp_path / "model.safetensors"))
    return str(tmp_path)


def test_gemma4_spec_is_heterogeneous(gemma4_dir):
    from bloombee_tpu.models.checkpoint import load_spec

    spec = load_spec(gemma4_dir)
    assert spec.heterogeneous
    assert spec.head_dim_for_layer(0) == 16 and spec.kv_heads_for_layer(0) == 2
    assert spec.head_dim_for_layer(1) == 32 and spec.kv_heads_for_layer(1) == 1
    assert spec.spec_for_layer(1).k_eq_v and not spec.spec_for_layer(0).k_eq_v
    assert spec.theta_for_layer(0) == 10_000.0
    assert spec.theta_for_layer(1) == 1_000_000.0


def test_gemma4_stepwise_equals_full_forward(gemma4_dir):
    """The paged-cache invariant on per-layer slabs: prefill + token-by-token
    decode must equal one full-sequence forward."""
    from bloombee_tpu.kv.cache_manager import CacheManager
    from bloombee_tpu.models.checkpoint import load_span_params
    from bloombee_tpu.runtime.executor import SpanExecutor

    params, spec = load_span_params(gemma4_dir, 0, 4, dtype=jnp.float32)
    assert isinstance(params, tuple) and len(params) == 4
    rng = np.random.default_rng(1)
    hidden = rng.standard_normal((2, 10, spec.hidden_size)).astype(np.float32)

    async def run(split):
        manager = CacheManager(
            num_layers=4, num_pages=32, page_size=4,
            n_kv_heads=spec.num_key_value_heads, head_dim=spec.head_dim,
            dtype=jnp.float32, hetero_spec=spec,
        )
        ex = SpanExecutor(params, spec, manager, compute_dtype=jnp.float32)
        outs = []
        async with manager.allocate(2, 16) as handle:
            if split == 0:
                outs.append(ex.prefill(handle, hidden))
            else:
                outs.append(ex.prefill(handle, hidden[:, :split]))
                for i in range(split, hidden.shape[1]):
                    outs.append(ex.decode(handle, hidden[:, i : i + 1]))
        return np.concatenate(outs, axis=1)

    full = asyncio.run(run(0))
    stepped = asyncio.run(run(6))
    np.testing.assert_allclose(stepped, full, atol=1e-4, rtol=1e-4)


def test_gemma4_e2e_serving(gemma4_dir):
    """Full swarm path over the heterogeneous family: deterministic greedy
    generate, twice the same."""

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s = BlockServer(
            model_uid="g4", start=0, end=4, model_dir=gemma4_dir,
            registry=rc(), compute_dtype=jnp.float32, num_pages=64,
            page_size=4,
        )
        await s.start()
        model = DistributedModelForCausalLM.from_pretrained(
            gemma4_dir, rc(), model_uid="g4"
        )
        input_ids = np.arange(6)[None, :] % model.spec.vocab_size
        a = await model.generate(input_ids, max_new_tokens=6)
        b = await model.generate(input_ids, max_new_tokens=6)
        np.testing.assert_array_equal(a, b)
        assert a.shape == (1, 12)
        await s.stop()
        await reg.stop()

    asyncio.run(run())


def test_gemma4_hetero_sparsity_and_adapters(gemma4_dir):
    """Previously-excluded hetero compositions: attn_sparsity (top-k sparse
    decode) runs on the unrolled span, and an MLP-targeting per-request
    LoRA adapter is exactly a merged-weights run (attention-geometry
    projections vary per layer, so MLP adapters are the uniform-shape
    case; attention adapters fail loudly at stack time)."""
    from bloombee_tpu.kv.cache_manager import CacheManager
    from bloombee_tpu.models.checkpoint import load_span_params
    from bloombee_tpu.runtime.executor import SpanExecutor

    params, spec = load_span_params(gemma4_dir, 0, 4, dtype=jnp.float32)
    rng = np.random.default_rng(5)
    d, inter, r = spec.hidden_size, spec.intermediate_size, 2
    a = rng.standard_normal((4, d, r)).astype(np.float32) * 0.1
    b_f = rng.standard_normal((4, r, inter)).astype(np.float32) * 0.1
    factors = {"gate_proj": {"a": jnp.asarray(a), "b": jnp.asarray(b_f)}}

    def make_ex(p, adapters=None, sparsity=1.0):
        manager = CacheManager(
            num_layers=4, num_pages=32, page_size=4,
            n_kv_heads=spec.num_key_value_heads, head_dim=spec.head_dim,
            dtype=jnp.float32, hetero_spec=spec,
        )
        return manager, SpanExecutor(
            p, spec, manager, compute_dtype=jnp.float32,
            adapters=adapters, attn_sparsity=sparsity,
        )

    hidden = rng.standard_normal((1, 6, d)).astype(np.float32) * 0.1
    step = rng.standard_normal((1, 1, d)).astype(np.float32) * 0.1

    async def drive(manager, ex, adapter=None):
        async with manager.allocate(1, 16) as handle:
            pre = ex.prefill(handle, hidden, adapter=adapter)
            out = ex.decode(handle, step, adapter=adapter)
        return np.asarray(pre, np.float32), np.asarray(out, np.float32)

    # adapters: unmerged factors == manually merged weights, token-exact
    m1, ex1 = make_ex(params, adapters={"tuned": factors})
    got_pre, got_out = asyncio.run(drive(m1, ex1, adapter="tuned"))
    merged = tuple(
        {
            **layer,
            "gate_proj": layer["gate_proj"] + a[i] @ b_f[i],
        }
        for i, layer in enumerate(params)
    )
    m2, ex2 = make_ex(merged)
    want_pre, want_out = asyncio.run(drive(m2, ex2))
    np.testing.assert_allclose(got_pre, want_pre, atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(got_out, want_out, atol=2e-5, rtol=2e-5)

    # sparsity: runs, stays finite, and actually changes decode outputs
    m3, ex3 = make_ex(params, sparsity=0.3)
    _, sparse_out = asyncio.run(drive(m3, ex3))
    m4, ex4 = make_ex(params)
    _, dense_out = asyncio.run(drive(m4, ex4))
    assert np.isfinite(sparse_out).all()
    assert not np.allclose(sparse_out, dense_out), (
        "top-k sparsity had no effect"
    )


def test_gemma4_hetero_int4_kv(gemma4_dir):
    """int4 KV x heterogeneous spans (previously excluded): per-layer
    QuantSlabs quantize each geometry's head_dim independently. Stepwise
    decode must equal the full forward under the SAME quantized arena
    (per-row group quantization is order-independent), stay close to the
    dense arena, and survive a park/unpark round trip."""
    from bloombee_tpu.kv.cache_manager import CacheManager
    from bloombee_tpu.kv.quant import QuantSlab
    from bloombee_tpu.models.checkpoint import load_span_params
    from bloombee_tpu.runtime.executor import SpanExecutor

    params, spec = load_span_params(gemma4_dir, 0, 4, dtype=jnp.float32)
    rng = np.random.default_rng(2)
    hidden = rng.standard_normal((2, 10, spec.hidden_size)).astype(np.float32)

    def run(split, quant):
        async def go():
            manager = CacheManager(
                num_layers=4, num_pages=32, page_size=4,
                n_kv_heads=spec.num_key_value_heads, head_dim=spec.head_dim,
                dtype=jnp.float32, hetero_spec=spec, quant=quant,
            )
            if quant:
                assert isinstance(manager.arena["k"][0], QuantSlab)
            ex = SpanExecutor(params, spec, manager, compute_dtype=jnp.float32)
            outs = []
            async with manager.allocate(2, 16) as handle:
                if split == 0:
                    outs.append(ex.prefill(handle, hidden))
                else:
                    outs.append(ex.prefill(handle, hidden[:, :split]))
                    if quant:  # park/unpark round trip mid-generation
                        manager.park_sequence(handle.seq_ids[0])
                    for i in range(split, hidden.shape[1]):
                        outs.append(ex.decode(handle, hidden[:, i:i + 1]))
            return np.concatenate(outs, axis=1)

        return asyncio.run(go())

    full_q = run(0, "int4")
    stepped_q = run(6, "int4")
    np.testing.assert_allclose(stepped_q, full_q, atol=1e-4, rtol=1e-4)
    dense = run(0, None)
    # quantization error is bounded (relative Frobenius), not zero
    assert not np.allclose(full_q, dense, atol=1e-6)
    rel = np.linalg.norm(full_q - dense) / np.linalg.norm(dense)
    assert rel < 0.2, rel


def test_gemma4_e2e_quantized_weights_and_kv(gemma4_dir):
    """Hetero span with BOTH int8 weights and an int4 KV arena (both
    previously excluded): serves deterministic finite generations, with
    the per-layer weight dicts actually quantized."""
    from bloombee_tpu.models.wquant import QuantWeight

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s = BlockServer(
            model_uid="g4q", start=0, end=4, model_dir=gemma4_dir,
            registry=rc(), compute_dtype=jnp.float32, num_pages=64,
            page_size=4, weight_quant="int8", kv_quant="int4",
        )
        await s.start()
        assert any(
            isinstance(leaf, QuantWeight)
            for leaf in s.executor.params[0].values()
        ), "per-layer weights were not quantized"
        model = DistributedModelForCausalLM.from_pretrained(
            gemma4_dir, rc(), model_uid="g4q"
        )
        input_ids = np.arange(6)[None, :] % model.spec.vocab_size
        a = await model.generate(input_ids, max_new_tokens=6)
        b = await model.generate(input_ids, max_new_tokens=6)
        np.testing.assert_array_equal(a, b)
        assert a.shape == (1, 12) and np.all(a < model.spec.vocab_size)
        await s.stop()
        await reg.stop()

    asyncio.run(run())


def test_gemma4_tp2_matches_tp1(gemma4_dir):
    """Heterogeneous span under TP serving (previously excluded): layers
    whose dims divide tp shard (q/o/MLP everywhere, KV on sliding layers
    with 2 kv heads); the full layers' single KV head replicates. tp=2
    output must match tp=1 through the real executor."""
    from bloombee_tpu.kv.cache_manager import CacheManager
    from bloombee_tpu.models.checkpoint import load_span_params
    from bloombee_tpu.parallel.serving import make_serving_mesh
    from bloombee_tpu.runtime.executor import SpanExecutor

    params, spec = load_span_params(gemma4_dir, 0, 4, dtype=jnp.float32)
    rng = np.random.default_rng(3)
    prefill = rng.standard_normal((2, 6, spec.hidden_size)).astype(
        np.float32
    )
    steps = [
        rng.standard_normal((2, 1, spec.hidden_size)).astype(np.float32)
        for _ in range(3)
    ]

    def run(mesh):
        async def go():
            manager = CacheManager(
                num_layers=4, num_pages=32, page_size=4,
                n_kv_heads=spec.num_key_value_heads, head_dim=spec.head_dim,
                dtype=jnp.float32, hetero_spec=spec,
            )
            ex = SpanExecutor(
                params, spec, manager, compute_dtype=jnp.float32, mesh=mesh
            )
            outs = []
            async with manager.allocate(2, 16) as handle:
                outs.append(np.asarray(ex.prefill(handle, prefill)))
                for s in steps:
                    outs.append(np.asarray(ex.decode(handle, s)))
            return outs

        return asyncio.run(go())

    ref = run(None)
    tp2 = run(make_serving_mesh(2))
    for a, b in zip(tp2, ref):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            atol=1e-5, rtol=1e-5,
        )


def test_gemma4_tp2_block_server_e2e(gemma4_dir):
    """Full swarm path with a tp=2 heterogeneous server: greedy generation
    must match the tp=1 server token-for-token."""

    async def run_swarm(tp):
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s = BlockServer(
            model_uid="g4tp", start=0, end=4, model_dir=gemma4_dir,
            registry=rc(), compute_dtype=jnp.float32, num_pages=64,
            page_size=4, tp=tp,
        )
        await s.start()
        model = DistributedModelForCausalLM.from_pretrained(
            gemma4_dir, rc(), model_uid="g4tp"
        )
        input_ids = np.arange(6)[None, :] % model.spec.vocab_size
        ids = await model.generate(
            input_ids, max_new_tokens=6, server_decode=False
        )
        await s.stop()
        await reg.stop()
        return ids

    async def run():
        tp1 = await run_swarm(1)
        tp2 = await run_swarm(2)
        np.testing.assert_array_equal(tp1, tp2)

    asyncio.run(run())
