"""Aux subsystems: block selection, native codec parity, CLI smoke,
timing tables (ports of reference test coverage for block_selection,
lossless_transport internals, and cli/health-style checks)."""

import subprocess
import sys

import numpy as np

from bloombee_tpu.server.block_selection import (
    block_throughputs,
    choose_best_blocks,
    should_choose_other_blocks,
)
from bloombee_tpu.swarm.data import ModuleInfo, RemoteSpanInfo, ServerInfo
from bloombee_tpu.swarm.spans import compute_spans


def _infos(num_blocks, spans):
    """spans: list of (server_id, start, end, throughput)."""
    infos = [ModuleInfo(uid=f"m.{i}", servers={}) for i in range(num_blocks)]
    for sid, start, end, tput in spans:
        info = ServerInfo(throughput=tput, start_block=start, end_block=end)
        for i in range(start, end):
            infos[i].servers[sid] = info
    return infos


def test_choose_best_blocks_picks_least_served():
    infos = _infos(8, [("A", 0, 4, 2.0), ("B", 2, 6, 1.0)])
    assert block_throughputs(infos).tolist() == [2, 2, 3, 3, 1, 1, 0, 0]
    start, end = choose_best_blocks(infos, compute_spans(infos), 3)
    assert (start, end) == (5, 8)


def test_should_choose_other_blocks_hysteresis():
    # A sits on a well-served region while blocks 4..8 are empty -> move
    infos = _infos(8, [("A", 0, 4, 1.0), ("B", 0, 4, 5.0)])
    spans = compute_spans(infos)
    assert should_choose_other_blocks("A", infos, spans)
    # balanced swarm -> stay (hysteresis)
    infos = _infos(4, [("A", 0, 2, 1.0), ("B", 2, 4, 1.0)])
    spans = compute_spans(infos)
    assert not should_choose_other_blocks("A", infos, spans)


def test_native_byte_split_parity():
    from bloombee_tpu.native import byte_split_lib
    from bloombee_tpu.wire.tensor_codec import _merge_planes, _split_planes

    rng = np.random.default_rng(0)
    raw = rng.integers(0, 255, size=(1 << 16,), dtype=np.uint8).tobytes()
    split = _split_planes(raw)
    # plane layout: low bytes then high bytes
    ref = np.frombuffer(raw, np.uint8).reshape(-1, 2).T.tobytes()
    assert split == ref
    assert _merge_planes(split) == raw
    # record which path ran so CI logs show it (both are correct)
    print("native lib:", "yes" if byte_split_lib() else "numpy fallback")


def test_cli_help_smoke():
    for mod in ("bloombee_tpu.cli.run_server", "bloombee_tpu.cli.run_registry"):
        out = subprocess.run(
            [sys.executable, "-m", mod, "--help"],
            capture_output=True, text=True, timeout=120,
        )
        assert out.returncode == 0, out.stderr
        assert "usage" in out.stdout.lower()


def test_chunked_head_matches_full():
    """Vocab-chunked LM head (low-RAM client path) is numerically identical
    to the one-shot head, including ragged last chunks and soft-capping."""
    import jax.numpy as jnp
    import numpy as np

    from bloombee_tpu.client.model import _norm_head, _norm_head_chunked

    rng = np.random.default_rng(0)
    d, v = 32, 1000  # v deliberately not a multiple of step
    params = {
        "norm": jnp.asarray(rng.normal(size=(d,)).astype(np.float32)),
        "lm_head": jnp.asarray(rng.normal(size=(d, v)).astype(np.float32)),
    }
    hidden = jnp.asarray(rng.normal(size=(2, 3, d)).astype(np.float32))
    for soft_cap in (0.0, 30.0):
        full = _norm_head(params, hidden, eps=1e-5, soft_cap=soft_cap)
        chunked = _norm_head_chunked(
            params, hidden, eps=1e-5, soft_cap=soft_cap, step=256
        )
        np.testing.assert_allclose(
            np.asarray(chunked), np.asarray(full), rtol=1e-6, atol=1e-6
        )
