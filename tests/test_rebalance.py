"""Automatic swarm rebalancing + background-task supervision.

Reference: /root/reference/src/bloombee/server/server.py:479-542 (the
module-container restart loop driven by should_choose_other_blocks) and
block_selection.py:40-95 (move simulation with hysteresis). Here the move
happens in-process: drain, reload the new span, swap the serving stack,
re-announce — no container restart.
"""

import asyncio
import time

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from bloombee_tpu.client.model import DistributedModelForCausalLM
from bloombee_tpu.server.block_selection import (
    _best_landing,
    rebalance_target,
)
from bloombee_tpu.server.block_server import BlockServer
from bloombee_tpu.swarm.data import ModuleInfo, ServerInfo
from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer
from bloombee_tpu.swarm.spans import compute_spans
from bloombee_tpu.utils import clock
from bloombee_tpu.utils.clock import ScaledClock


def _infos(spans, n_blocks):  # spans: {sid: (start, end, throughput)}
    infos = [ModuleInfo(uid=f"b{i}", servers={}) for i in range(n_blocks)]
    for sid, (s, e, tput) in spans.items():
        si = ServerInfo(throughput=tput, start_block=s, end_block=e)
        for i in range(s, e):
            infos[i].servers[sid] = si
    return infos


def test_rebalance_target_moves_off_overlap():
    """Two servers stacked on [0,2) of a 3-block model leave block 2
    unserved; one of them must move to [1,3)."""
    infos = _infos({"a": (0, 2, 1.0), "b": (0, 2, 1.0)}, 3)
    target = rebalance_target("b", infos, compute_spans(infos))
    assert target == (1, 3)


def test_rebalance_target_hysteresis_keeps_balanced_swarm():
    """A balanced split must NOT move (the hysteresis margin prevents
    thrash)."""
    infos = _infos({"a": (0, 2, 1.0), "b": (2, 4, 1.0)}, 4)
    assert rebalance_target("a", infos, compute_spans(infos)) is None
    assert rebalance_target("b", infos, compute_spans(infos)) is None


def _best_landing_naive(without, n, t):
    """The O(blocks^2) per-candidate array-copy scan _best_landing
    replaced; kept here as the property-test oracle."""
    best, best_start = None, None
    for start in range(len(without) - n + 1):
        cand = without.copy()
        cand[start : start + n] += t
        m = float(cand.min())
        if best is None or m > best:
            best, best_start = m, start
    return best, best_start


def test_best_landing_matches_naive_property():
    """Sliding-window landing scan must be EXACTLY equivalent (value and
    tie-broken start) to the naive per-window copy over random arrays —
    the min of (prefix, window+t, suffix) decomposition is lossless."""
    rng = np.random.default_rng(1234)
    for _ in range(300):
        b = int(rng.integers(1, 40))
        n = int(rng.integers(1, b + 1))
        t = float(rng.uniform(0, 5))
        without = rng.uniform(0, 10, size=b)
        if rng.random() < 0.3:
            # ties are the tiebreak-sensitive case: quantize so equal
            # candidate minima actually occur
            without = np.round(without)
            t = round(t)
        got = _best_landing(without, n, t)
        want = _best_landing_naive(without, n, t)
        assert got == want, (b, n, t, without)
    # degenerate shapes
    assert _best_landing(np.zeros(3), 4, 1.0) == (None, None)
    assert _best_landing(np.zeros(3), 0, 1.0) == (None, None)


def _hot(delay_ms=1e9):
    """A fresh load advert pinning predicted queue delay at the cap."""
    return {"ts": time.time(), "delay_ms": delay_ms}


def test_measured_rebalance_attracts_mover_to_hot_span():
    """a+c stacked on [0,2), b alone and CHRONICALLY HOT on [2,4): the
    static objective sees a balanced-enough swarm (no move beats the
    margin), but measured-load weighting discounts b's effective
    throughput ~11x, so c must move to absorb the hot span."""
    infos = _infos({"a": (0, 2, 1.0), "b": (2, 4, 1.0), "c": (0, 2, 1.0)}, 4)
    for i in range(2, 4):
        infos[i].servers["b"].load = _hot()
    spans = compute_spans(infos)
    assert rebalance_target("c", infos, spans, measured=False) is None
    assert rebalance_target("c", infos, spans, measured=True) == (2, 4)


def test_measured_rebalance_cold_start_falls_back_to_static():
    """With no load adverts anywhere, the measured objective must be
    byte-identical to the static one (automatic cold-start fallback)."""
    rng = np.random.default_rng(7)
    for _ in range(50):
        n_blocks = int(rng.integers(2, 10))
        spans_cfg = {}
        for sid in "abcde"[: int(rng.integers(2, 5))]:
            n = int(rng.integers(1, n_blocks + 1))
            s = int(rng.integers(0, n_blocks - n + 1))
            spans_cfg[sid] = (s, s + n, float(rng.uniform(0.5, 3.0)))
        infos = _infos(spans_cfg, n_blocks)
        spans = compute_spans(infos)
        for sid in spans_cfg:
            assert rebalance_target(
                sid, infos, spans, measured=True
            ) == rebalance_target(sid, infos, spans, measured=False)


def test_measured_rebalance_bounds_hostile_adverts():
    """A garbage advert (NaN/inf/negative delay) must leave the decision
    identical to no advert at all — the shared sanitized load term is the
    only reading of the wire data."""
    base = _infos({"a": (0, 2, 1.0), "b": (2, 4, 1.0)}, 4)
    for garbage in (
        {"ts": time.time(), "delay_ms": float("nan")},
        {"ts": time.time(), "delay_ms": float("inf")},
        {"ts": time.time(), "delay_ms": -5.0},
        {"ts": time.time(), "queue_depth": "wat"},
    ):
        infos = _infos({"a": (0, 2, 1.0), "b": (2, 4, 1.0)}, 4)
        for i in range(2, 4):
            infos[i].servers["b"].load = garbage
        assert rebalance_target(
            "a", infos, compute_spans(infos), measured=True
        ) == rebalance_target(
            "a", base, compute_spans(base), measured=True
        )


@pytest.fixture(scope="module")
def tiny_model_dir(tmp_path_factory):
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64,
        intermediate_size=128,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_hidden_layers=3,
        vocab_size=128,
        max_position_embeddings=256,
        tie_word_embeddings=False,
    )
    torch.manual_seed(7)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    d = tmp_path_factory.mktemp("tiny_llama_rebal")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model, config


def test_e2e_pathological_split_converges(tiny_model_dir):
    """Two servers both serving [0,2) of a 3-layer model (block 2 dark):
    the rebalancing supervisor must move one to [1,3) WITHOUT operator
    action, after which a client can run the full model and match HF."""
    model_dir, hf_model, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        def server(start, end, **kw):
            return BlockServer(
                model_uid="tiny", start=start, end=end, model_dir=model_dir,
                registry=rc(), compute_dtype=jnp.float32, num_pages=64,
                page_size=4, announce_period=0.5, **kw,
            )

        # both servers are BORN on a 4x compressed clock: every deadline
        # in the move sequence (supervisor tick, rebalance period, drain
        # budget, re-announce lease) reads clock.*, so convergence AND
        # the hysteresis window run 4x compressed on one timeline.
        # Installing mid-run instead leaves in-flight announce sleeps
        # holding real deadlines while virtual time jumps ahead: the
        # peer's lease flaps expired and the supervisor chases phantom
        # uncovered blocks. The poll deadline stays real as a hard cap;
        # weight loading is real compute, but nothing virtual-clocked
        # fences it tighter than the 2.0s drain budget. Restored to real
        # before the generate.
        prev = clock.install(ScaledClock(scale=4.0))
        try:
            s_a = server(0, 2)  # static
            s_b = server(0, 2, rebalance_period=1.0, drain_timeout=2.0)
            await s_a.start()
            await s_b.start()
            # supervisor tick = announce_period (0.5s); rebalance after 1s
            deadline = asyncio.get_event_loop().time() + 30.0
            while (s_b.start_block, s_b.end_block) == (0, 2):
                if asyncio.get_event_loop().time() > deadline:
                    raise AssertionError("rebalance never happened")
                await asyncio.sleep(0.25)
            assert (s_b.start_block, s_b.end_block) == (1, 3)

            # stability: no further move (hysteresis), observed over 2.5
            # virtual seconds (several supervisor ticks)
            await clock.async_sleep(2.5)
            assert (s_b.start_block, s_b.end_block) == (1, 3)
            assert (s_a.start_block, s_a.end_block) == (0, 2)
        finally:
            clock.install(prev)

        # swarm must now serve the whole model, correct vs HF
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny"
        )
        rng = np.random.default_rng(4)
        input_ids = rng.integers(0, config.vocab_size, size=(1, 4))
        ids = await model.generate(
            input_ids, max_new_tokens=5, server_decode=False
        )
        with torch.no_grad():
            ref = hf_model.generate(
                torch.tensor(input_ids), max_new_tokens=5, do_sample=False,
                use_cache=True,
            ).numpy()
        np.testing.assert_array_equal(ids, ref)

        await s_a.stop()
        await s_b.stop()
        await reg.stop()

    asyncio.run(run())


def test_supervisor_survives_registry_flaps(tiny_model_dir):
    """Satellite regression: transient registry errors during the periodic
    rebalance check must log-and-retry, not kill the supervisor — the
    pathological split still converges through a registry that fails every
    other get_module_infos, and the supervisor task stays alive after."""
    model_dir, _, config = tiny_model_dir

    class FlakyRegistry:
        def __init__(self, inner, fail_every=2):
            self._inner = inner
            self._calls = 0
            self._fail_every = fail_every

        async def get_module_infos(self, *a, **kw):
            self._calls += 1
            if self._calls % self._fail_every == 0:
                raise RuntimeError("injected registry flap")
            return await self._inner.get_module_infos(*a, **kw)

        def __getattr__(self, name):
            return getattr(self._inner, name)

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s_a = BlockServer(
            model_uid="tiny", start=0, end=2, model_dir=model_dir,
            registry=rc(), compute_dtype=jnp.float32, num_pages=64,
            page_size=4, announce_period=0.5,
        )
        s_b = BlockServer(
            model_uid="tiny", start=0, end=2, model_dir=model_dir,
            registry=rc(), compute_dtype=jnp.float32, num_pages=64,
            page_size=4, announce_period=0.5, rebalance_period=1.0,
            drain_timeout=2.0,
        )
        flaky = FlakyRegistry(rc())
        s_b.registry = flaky
        # same born-on-a-4x-compressed-clock setup as the
        # pathological-split test: the log-and-retry cadence and every
        # move deadline are clock-driven
        prev = clock.install(ScaledClock(scale=4.0))
        try:
            await s_a.start()
            await s_b.start()
            deadline = asyncio.get_event_loop().time() + 30.0
            while (s_b.start_block, s_b.end_block) == (0, 2):
                if asyncio.get_event_loop().time() > deadline:
                    raise AssertionError(
                        "rebalance never happened through registry flaps"
                    )
                await asyncio.sleep(0.25)
        finally:
            clock.install(prev)
        assert (s_b.start_block, s_b.end_block) == (1, 3)
        # the supervisor saw real injected failures and is still alive
        assert flaky._calls >= flaky._fail_every
        assert not s_b._supervisor_task.done()
        assert s_b.rebalances_moved == 1

        await s_a.stop()
        await s_b.stop()
        await reg.stop()

    asyncio.run(run())


def test_supervisor_restarts_dead_announce_loop(tiny_model_dir):
    """Kill the announce task; the supervisor must restart it and the
    server must stay visible in the registry past the expiry window."""
    model_dir, _, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        s = BlockServer(
            model_uid="tiny", start=0, end=3, model_dir=model_dir,
            registry=rc(), compute_dtype=jnp.float32, num_pages=64,
            page_size=4, announce_period=0.5,
        )
        await s.start()
        s._announce_task.cancel()
        # expiry = announce_period * 2.5 = 1.25s; wait well past it and
        # confirm the record is still alive (supervisor restarted the
        # loop). Supervisor tick, announce lease, and registry expiry all
        # read clock.*, so the wait runs 4x compressed.
        prev = clock.install(ScaledClock(scale=4.0))
        try:
            await clock.async_sleep(3.0)
        finally:
            clock.install(prev)
        infos = await rc().get_module_infos("tiny", range(3))
        assert any(s.server_id in i.servers for i in infos), (
            "server expired from the registry after its announce loop died"
        )
        await s.stop()
        await reg.stop()

    asyncio.run(run())
