"""Model resolution: local dirs, or HF-hub download with a disk LRU cache.

Capability port of /root/reference/src/bloombee/server/from_pretrained.py
:168-308 (per-block hub state-dict loading) + utils/disk_cache.py:41 (cache
locking + LRU disk eviction), restructured for this framework's local-dir
loaders: `resolve_model_dir` turns a model NAME into a local snapshot
directory (downloading into the cache on first use), after which every
existing checkpoint reader works unchanged.

Offline note: this environment has zero egress, so the download path is
exercised in tests through a local `fetch_fn` injection; the default uses
huggingface_hub when importable.
"""

from __future__ import annotations

import fcntl
import os
import pathlib
import shutil

from bloombee_tpu.utils import clock, env

env.declare(
    "BBTPU_CACHE_DIR", str, os.path.expanduser("~/.cache/bloombee_tpu"),
    "disk cache for downloaded model snapshots (reference BLOOMBEE_CACHE)",
)
env.declare(
    "BBTPU_CACHE_MAX_BYTES", int, 0,
    "LRU-evict cached model snapshots beyond this total size (0 = no limit)",
)


def _dir_size(path: pathlib.Path) -> int:
    return sum(
        f.stat().st_size for f in path.rglob("*") if f.is_file()
    )


def _touch_access(path: pathlib.Path) -> None:
    (path / ".last_access").write_text(str(clock.now()))


def _last_access(path: pathlib.Path) -> float:
    marker = path / ".last_access"
    try:
        return float(marker.read_text())
    except Exception:
        try:
            return path.stat().st_mtime
        except OSError:
            return 0.0


def evict_lru(cache_dir: str, max_bytes: int, keep: str | None = None) -> int:
    """Delete least-recently-used snapshot dirs until under budget
    (reference disk_cache.py `_remove_old_models`). Returns bytes freed."""
    root = pathlib.Path(cache_dir)
    if max_bytes <= 0 or not root.exists():
        return 0
    # global eviction lock: per-model locks don't serialize evictors, and
    # another process's in-flight .partial must never be collected (dotted
    # names are locks/partials, not snapshots)
    with open(root / ".evict.lock", "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        entries = [
            p for p in root.iterdir()
            if p.is_dir()
            and not p.name.startswith(".")
            and (keep is None or p.name != keep)
        ]
        sizes = {p: _dir_size(p) for p in entries}
        total = sum(sizes.values())
        if keep is not None and (root / keep).exists():
            total += _dir_size(root / keep)
        freed = 0
        for p in sorted(entries, key=_last_access):
            if total <= max_bytes:
                break
            sz = sizes[p]
            shutil.rmtree(p, ignore_errors=True)
            total -= sz
            freed += sz
        fcntl.flock(lock, fcntl.LOCK_UN)
    return freed


def _default_fetch(name: str, dest: str) -> None:
    """Download a hub snapshot into dest (weights + config only)."""
    from huggingface_hub import snapshot_download

    snapshot_download(
        repo_id=name,
        local_dir=dest,
        allow_patterns=[
            "config.json", "*.safetensors", "model.safetensors.index.json",
            "tokenizer*", "generation_config.json",
        ],
    )


def resolve_model_dir(
    name_or_path: str,
    cache_dir: str | None = None,
    max_cache_bytes: int | None = None,
    fetch_fn=None,
) -> str:
    """Local directory for a model: existing paths pass through; hub names
    download once into the LRU cache (file-locked against concurrent
    servers on one host — reference disk_cache lock)."""
    if os.path.isdir(name_or_path):
        return name_or_path
    cache_dir = cache_dir or env.get("BBTPU_CACHE_DIR")
    max_bytes = (
        max_cache_bytes
        if max_cache_bytes is not None
        else env.get("BBTPU_CACHE_MAX_BYTES")
    )
    safe = name_or_path.replace("/", "--")
    root = pathlib.Path(cache_dir)
    root.mkdir(parents=True, exist_ok=True)
    dest = root / safe
    lock_path = root / f".{safe}.lock"
    with open(lock_path, "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if (dest / "config.json").exists():
                _touch_access(dest)
                return str(dest)
            evict_lru(cache_dir, max_bytes, keep=safe)
            tmp = root / f".{safe}.partial"
            shutil.rmtree(tmp, ignore_errors=True)
            (fetch_fn or _default_fetch)(name_or_path, str(tmp))
            # a killed previous attempt can leave a config-less dest dir;
            # os.replace cannot overwrite a non-empty directory
            shutil.rmtree(dest, ignore_errors=True)
            os.replace(tmp, dest)
            _touch_access(dest)
            # enforce the budget again now that the new snapshot's size is
            # known (the pre-download pass can't account for it)
            evict_lru(cache_dir, max_bytes, keep=safe)
            return str(dest)
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)
