"""SpanExecutor: host-side orchestration around the jitted span step.

Covers the roles of the reference's TransformerBackend.inference_step plumbing
(/root/reference/src/bloombee/server/backend.py:487-789): cache select/update,
mask choice, chunked prefill (`_estimate_max_chunk_length`, backend.py:839-845)
— but with bucketed static shapes instead of dynamic ones. Each distinct
(batch, tokens, pages) bucket compiles once; subsequent steps reuse the cached
executable (the CUDA-graph role of the reference's cuda_graphs.py).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

import ml_dtypes

from bloombee_tpu.kv.cache_manager import CacheHandle, CacheManager
from bloombee_tpu.models.spec import ModelSpec
from bloombee_tpu.runtime.step import (
    pack_plan,
    pack_ragged_plan,
    pack_step_payload,
    span_step_packed,
    span_step_ragged,
)
from bloombee_tpu.utils import env, jitwatch

env.declare(
    "BBTPU_FLASH_ATTENTION", bool, True,
    "use the Pallas flash kernel for eligible long prefill steps (T>=128, "
    "causal, uniform context lengths, no tree/window/alibi/softcap)",
)
env.declare(
    "BBTPU_PAGED_ATTENTION", bool, True,
    "use the Pallas paged-attention kernel for eligible single-token decode "
    "steps (T=1, dense arena, no tree/window/alibi/softcap); TPU backend "
    "only unless BBTPU_PAGED_INTERPRET forces the interpreter (tests)",
)
env.declare(
    "BBTPU_PAGED_MIN_CONTEXT", int, 512,
    "use the paged decode kernel only when the bucketed context is at least "
    "this many tokens (measured crossover vs the dense gather path on v5e: "
    "dense wins at 256, paged wins 1k+ and is 1.5x at 4k)",
)
env.declare(
    "BBTPU_PAGED_INTERPRET", bool, False,
    "run the paged decode kernel in interpreter mode on non-TPU backends "
    "(CPU parity tests; far too slow for production)",
)
env.declare(
    "BBTPU_FLASH_INTERPRET", bool, False,
    "run the flash prefill kernel in interpreter mode on non-TPU backends "
    "(CPU parity tests; far too slow for production)",
)
env.declare(
    "BBTPU_SP_MIN_TOKENS", int, 1024,
    "spread a session's prefill over the --sp mesh (ring attention) only "
    "when the prompt has at least this many tokens; short prefills stay "
    "single-chip (chunk overhead + collectives would dominate)",
)
env.declare(
    "BBTPU_PREFILL_CHUNK", int, 0,
    "stall-free scheduling (Sarathi-Serve): split prefills into chunks of "
    "at most this many tokens, each a separate compute-queue task so "
    "queued decode steps run between chunks (0 = monolithic prefill, one "
    "queue task for the whole prompt). Rounded to a power of two so every "
    "chunk hits the same compiled bucket",
)


def next_pow2(n: int, floor: int = 1) -> int:
    v = floor
    while v < n:
        v *= 2
    return v


def plan_prefill_chunks(
    t: int, budget: int, cap: int | None = None
) -> list[tuple[int, int]]:
    """Split a t-token prefill into [start, end) chunk spans of at most
    `budget` tokens each (pow2-rounded so every full chunk compiles into
    the SAME (batch, tokens) bucket; `cap` bounds the rounded budget, e.g.
    at max_chunk_tokens). budget<=0 or t<=budget -> one whole-prompt span,
    i.e. chunking disabled."""
    if budget <= 0 or t <= budget:
        return [(0, t)]
    b = next_pow2(int(budget))
    if b > budget:
        b //= 2  # round DOWN: never exceed the operator's token budget
    if cap is not None:
        while b > cap:
            b //= 2
    b = max(1, b)
    if t <= b:
        return [(0, t)]
    return [(s, min(s + b, t)) for s in range(0, t, b)]


@functools.partial(jax.jit, donate_argnames=("arena_k", "arena_v"))
def _arena_write_all(arena_k, arena_v, slots, k_new, v_new):
    """Scatter every layer's new KV rows into the donated arena (the
    sp-prefill landing step; quantized slabs quantize inside arena_write)."""
    from jax import lax

    from bloombee_tpu.kv.arena import arena_write

    def body(_, xs):
        k_l, v_l, kn, vn = xs
        return None, arena_write(k_l, v_l, slots, kn, vn)

    _, (new_k, new_v) = lax.scan(
        body, None, (arena_k, arena_v, k_new, v_new)
    )
    return new_k, new_v


class SpanExecutor:
    def __init__(
        self,
        stacked_params: dict,
        spec: ModelSpec,
        manager: CacheManager,
        max_chunk_tokens: int = 512,
        compute_dtype=jnp.bfloat16,
        start_block: int = 0,
        mesh=None,  # jax.sharding.Mesh with a "tp" axis: TP-sharded serving
        adapters: dict[str, dict] | None = None,  # name -> stacked factors
        host_layers: list | None = None,  # weight-offload: per-layer host
        # param pytrees for the span's LAST len(host_layers) layers; they
        # stream to the device per step with one-ahead prefetch (reference
        # FlexGen Policy weight percentages / convert_block.py
        # PipelineParallelWrapper pre-forward H2D)
        attn_sparsity: float = 1.0,  # <1: keep only the top
        # attn_sparsity*(S-1) past keys per query plus the newest token
        # (reference FlexGen Policy.attn_sparsity,
        # pytorch_backend.py:564-638); approximate — dense path only
        sp_mesh=None,  # (tp=1, sp) mesh: long prefills (>= SP_MIN_TOKENS)
        # spread over the sp chips via ring attention, K/V landing in the
        # paged arena; decode stays single-chip (parallel/sp_serving.py)
    ):
        if not 0.0 < attn_sparsity <= 1.0:
            raise ValueError(f"attn_sparsity in (0, 1], got {attn_sparsity}")
        self.attn_sparsity = float(attn_sparsity)
        self.mesh = mesh
        self.sp_mesh = sp_mesh
        self._sp_params = None
        if sp_mesh is not None:
            if mesh is not None:
                raise ValueError(
                    "sp prefill + TP serving not supported together yet"
                )
            if host_layers:
                raise ValueError(
                    "sp prefill + weight offload not supported together"
                )
            if spec.heterogeneous:
                raise ValueError(
                    "sp prefill + heterogeneous head_dim spans not "
                    "supported together"
                )
            if manager.quant is not None:
                # _sp_eligible would silently never fire (quantized arenas
                # attend quantized KV during single-chip prefill; ring
                # attends full precision) while the replicated param copy
                # still costs every sp chip — fail at startup instead
                raise ValueError(
                    "sp prefill + quantized KV arena not supported "
                    "together (single-chip prefill attends quantized KV; "
                    "ring attention would change the numerics)"
                )
            from bloombee_tpu.parallel.sp_serving import (
                place_sp_params,
                sp_unsupported,
            )

            reason = sp_unsupported(spec, stacked_params)
            if reason is not None:
                raise ValueError(f"sp prefill unavailable: {reason}")
            # a replicated copy over the sp chips (the single-chip decode
            # path keeps its own placement; span params are a small price
            # next to the long-context KV this feature exists to serve)
            self._sp_params = place_sp_params(stacked_params, sp_mesh)
        self.host_layers = list(host_layers or [])
        self.resident = manager.num_layers - len(self.host_layers)
        if self.host_layers:
            if spec.heterogeneous:
                raise ValueError(
                    "weight offload + heterogeneous head_dim spans not "
                    "supported together"
                )
            if manager.quant is not None:
                raise ValueError(
                    "weight offload + quantized KV arena not supported "
                    "together"
                )
            if self.resident < 0:
                raise ValueError(
                    f"{len(self.host_layers)} host layers > "
                    f"{manager.num_layers} span layers"
                )
            lead = jax.tree.leaves(stacked_params)[0].shape[0] if (
                self.resident > 0
            ) else 0
            if self.resident and lead != self.resident:
                raise ValueError(
                    f"resident params stack has {lead} layers, expected "
                    f"{self.resident}"
                )
        if mesh is not None:
            from bloombee_tpu.parallel import serving as tp_serving

            if spec.heterogeneous:
                # per-layer geometry: q heads/experts must divide; layers
                # whose KV heads don't divide replicate their K/V
                tp_serving.check_tp_divides(
                    spec, mesh.devices.size, hetero=True
                )
                stacked_params = tp_serving.place_hetero_span_params(
                    stacked_params, mesh, spec, start_block
                )
            else:
                tp_serving.check_tp_divides(spec, mesh.devices.size)
                if stacked_params is not None:  # fully-offloaded: no prefix
                    stacked_params = tp_serving.place_span_params(
                        stacked_params, mesh
                    )
            manager.arena = tp_serving.place_arena_for(
                spec, manager.arena, mesh
            )
            if adapters:
                # low-rank factors are small: replicate over the mesh and let
                # GSPMD partition the delta einsums as it sees fit
                adapters = {
                    name: tp_serving.replicated(f, mesh)
                    for name, f in adapters.items()
                }
        self.adapters = adapters or {}
        self.params = stacked_params
        self.spec = spec
        self.manager = manager
        # per-layer sliding windows (gemma-style alternating layers); layer
        # types are indexed by ABSOLUTE block id, so the span offset matters
        self.windows = tuple(
            spec.window_for_layer(start_block + i)
            for i in range(manager.num_layers)
        )
        self.max_chunk_tokens = max_chunk_tokens
        self.compute_dtype = compute_dtype
        self.start_block = start_block
        # ship hidden states over the host link at half width when computing
        # in bf16 (transfer latency/bandwidth is the bottleneck; SURVEY.md
        # section 3.3 timing decomposition)
        self.transfer_dtype = np.dtype(
            ml_dtypes.bfloat16 if compute_dtype == jnp.bfloat16 else np.float32
        )
        self.page_size = manager.page_size

    # ------------------------------------------------------------------ steps
    def prefill(
        self,
        handle: CacheHandle,
        hidden: np.ndarray,
        commit: bool = True,
        layers: tuple[int, int] | None = None,
        fetch: bool = True,
        adapter: str | None = None,
    ):
        """Run full-sequence prefill, chunked to bound attention logits memory
        (reference: backend.py:525-531 chunked inference).

        With fetch=False the (lazy) device array is returned instead of a
        host copy — callers fetch it OUTSIDE the serialized compute queue so
        concurrent sessions' d2h round trips overlap (the round trip, not
        compute, dominates per-step latency on DCN/tunnel-attached hosts).
        """
        outs = []
        t = hidden.shape[1]
        if self._sp_eligible(handle, t, commit, layers, adapter):
            return self._sp_prefill(handle, hidden, fetch)
        for start in range(0, t, self.max_chunk_tokens):
            chunk = hidden[:, start : start + self.max_chunk_tokens]
            outs.append(
                self._step(
                    handle, chunk, commit=commit, layers=layers, fetch=fetch,
                    adapter=adapter,
                )
            )
        if len(outs) == 1:
            return outs[0]
        cat = np.concatenate if fetch else jnp.concatenate
        return cat(outs, axis=1)

    def prefill_chunk(
        self,
        handle: CacheHandle,
        hidden: np.ndarray,
        commit: bool = False,
        layers: tuple[int, int] | None = None,
        fetch: bool = False,
        adapter: str | None = None,
    ):
        """Run ONE chunk of a resumable chunked prefill (Sarathi-Serve
        stall-free batching): the caller slices the prompt with
        `plan_prefill_chunks` and submits each chunk as its OWN compute
        task, letting decode steps interleave between chunks.

        The position offset carries automatically: `_step` reads the
        handle's current context length (which includes earlier chunks'
        speculative tokens) as the rotary/write start. Chunks should run
        with commit=False — speculative writes let a mid-prefill abort
        free every partial page via `manager.rollback`; the caller commits
        the handle once after the final chunk, exactly like the batched
        decode path."""
        if hidden.shape[1] > self.max_chunk_tokens:
            # one queue task must stay one device dispatch — feeding a
            # chunk bigger than the attention-memory bound would silently
            # re-monolith the schedule
            raise ValueError(
                f"prefill chunk of {hidden.shape[1]} tokens exceeds "
                f"max_chunk_tokens={self.max_chunk_tokens}"
            )
        return self._step(
            handle, hidden, commit=commit, layers=layers, fetch=fetch,
            adapter=adapter,
        )

    def prefill_chunked(
        self,
        handle: CacheHandle,
        hidden: np.ndarray,
        chunk_tokens: int,
        commit: bool = True,
        layers: tuple[int, int] | None = None,
        fetch: bool = True,
        adapter: str | None = None,
    ):
        """Whole-prompt prefill via the chunked path, all chunks in ONE
        call (no queue re-entry — warmup and tests; the server drives
        chunks through the compute queue itself). Token-identical to
        `prefill`: same program, same buckets, positions carried across
        chunks; speculative writes committed after the last chunk."""
        spans = plan_prefill_chunks(
            hidden.shape[1], chunk_tokens, cap=self.max_chunk_tokens
        )
        outs = []
        try:
            for s, e in spans:
                outs.append(
                    self.prefill_chunk(
                        handle, hidden[:, s:e], commit=False, layers=layers,
                        fetch=fetch, adapter=adapter,
                    )
                )
        except Exception:
            if self.manager.epoch_valid(handle):
                self.manager.rollback(handle)
            raise
        if commit:
            self.manager.commit(handle)
        if len(outs) == 1:
            return outs[0]
        cat = np.concatenate if fetch else jnp.concatenate
        return cat(outs, axis=1)

    def _sp_eligible(self, handle, t, commit, layers, adapter) -> bool:
        """Sequence-parallel prefill fires for a FRESH full-span committed
        prefill of a long prompt (starts all zero); everything else takes
        the single-chip chunked path."""
        return bool(
            self.sp_mesh is not None
            and commit
            and layers is None
            and adapter is None
            # (quantized arenas are rejected at __init__ — sp_mesh and
            # manager.quant can never coexist here)
            and t >= env.get("BBTPU_SP_MIN_TOKENS")
            # is_fresh, NOT a bare length check: a host-parked session's
            # table length reads 0 while its real KV sits in the park —
            # sp-prefilling it from position 0 would orphan that KV and
            # blow the unpark invariant on the next decode
            and self.manager.is_fresh(handle)
        )

    def _sp_prefill(self, handle, hidden: np.ndarray, fetch: bool):
        """Whole-prompt prefill over the sp mesh (ring attention), K/V
        scattered into the paged arena so decode continues single-chip
        (parallel/sp_serving.py)."""
        from bloombee_tpu.parallel.sp_serving import sp_prefill

        b, t, d = hidden.shape
        sp = self.sp_mesh.devices.shape[1]
        # pow2 bucket FIRST (compile count stays O(log T), same contract
        # as the single-chip path), then round up to a multiple of sp for
        # the ring chunks
        t_pad = next_pow2(t)
        t_pad = -(-t_pad // sp) * sp
        h_pad = np.zeros((b, t_pad, d), dtype=self.transfer_dtype)
        h_pad[:, :t] = hidden.astype(self.transfer_dtype)
        slots = self.manager.write_slots(handle, t, commit=True)  # [b*t]
        with jitwatch.region("sp_prefill", f"b{b},t{t_pad}"):
            out, ks, vs = sp_prefill(
                self._sp_params, h_pad, self.sp_mesh, spec=self.spec
            )
        # pad tokens write to the drop slot; real tokens land in their
        # assigned pages
        oob = self.manager.capacity_tokens
        slots_pad = np.full((b, t_pad), oob, np.int32)
        slots_pad[:, :t] = slots.reshape(b, t)
        dev0 = jax.devices()[0]
        l = self.manager.num_layers
        hkv = ks.shape[3]
        hd = ks.shape[4]
        k_new = jax.device_put(
            ks.reshape(l, b * t_pad, hkv, hd), dev0
        )
        v_new = jax.device_put(
            vs.reshape(l, b * t_pad, hkv, hd), dev0
        )
        arena = self.manager.arena
        try:
            with jitwatch.region("arena_write_all", f"b{b},t{t_pad}"):
                new_k, new_v = _arena_write_all(
                    arena["k"], arena["v"],
                    jnp.asarray(slots_pad.reshape(-1)), k_new, v_new,
                )
        except Exception:
            # same contract as every other donated-arena step: a runtime
            # failure after donation leaves deleted buffers — rebuild so
            # the server survives (sessions replay), then re-raise
            if self._arena_consumed(arena):
                self._rebuild_after_failure("sp prefill")
            raise
        self.manager.arena = {"k": new_k, "v": new_v}
        out = out[:, :t]
        if not fetch:
            return out
        return self.fetch(out)

    def decode(
        self,
        handle: CacheHandle,
        hidden: np.ndarray,
        commit: bool = True,
        tree_mask: np.ndarray | None = None,
        layers: tuple[int, int] | None = None,
        depths: np.ndarray | None = None,
        fetch: bool = True,
        adapter: str | None = None,
    ):
        return self._step(
            handle, hidden, commit=commit, tree_mask=tree_mask, layers=layers,
            depths=depths, fetch=fetch, adapter=adapter,
        )

    def decode_group(
        self,
        handles: list[CacheHandle],
        hiddens: list[np.ndarray],  # per-member [b_i, 1, D], same dtype
        layers: tuple[int, int] | None = None,
        adapter: str | None = None,
    ):
        """Row-stack several sessions' single-token decode steps into ONE
        span dispatch (Orca-style continuous batching over the paged
        arena: each row's attention reads only its own pages, so the
        merged step is numerically identical to the members run alone).
        The total row count shares `_step`'s pow2 batch bucketing, so the
        merged widths hit the same compile cache as big single-session
        batches.

        KV writes are SPECULATIVE (commit=False): the caller commits the
        combined handle only after the dispatch succeeds, so a failed
        batch rolls back cleanly and can replay row-by-row without ghost
        tokens in any member's page table.

        Returns (out, combined_handle): `out` is the lazy [sum(b_i), 1, D]
        device result (slice rows per member, fetch off-queue), and the
        combined handle is what the caller commits or rolls back.

        Thin delegation onto `ragged_group`, whose pure-decode fast path
        runs exactly this packed dispatch; the [R, D] -> [R, 1, D] reshape
        back to the historical contract is a lazy view."""
        out, combined = self.ragged_group(
            handles, hiddens, layers=layers, adapter=adapter
        )
        return out[:, None, :], combined

    def ragged_unsupported(self, has_tree: bool = False) -> str | None:
        """Why this executor can't run the universal ragged dispatch; None
        when it can. These configs have their own step machinery (offload
        layer chain, hetero span, decode-only top-k) that the ragged path
        doesn't replicate — the server falls back to separate dispatches,
        byte-for-byte the flags-off behavior. TP-mesh spans are SUPPORTED:
        the payload replicates over the mesh and GSPMD shards the dense
        attend_ragged over heads, exactly like the packed step (the Pallas
        ragged kernel stays single-chip-only via the use_kernel gate).
        Tree rows additionally exclude sliding-window layers: the ragged
        tree mask replaces causality outright, and window clipping against
        depth-positioned tree tokens only exists on the solo dense path."""
        if self.host_layers:
            return "weight offload"
        if self.spec.heterogeneous:
            return "heterogeneous span"
        if self.attn_sparsity < 1.0:
            return "sparse (top-k) attention"
        if has_tree and any(w > 0 for w in self.windows):
            return "sliding-window layers"
        return None

    def mixed_unsupported(self) -> str | None:
        """PR-8 surface: why causal (decode + chunk) ragged dispatch is
        unavailable. Thin delegation onto the unified gate."""
        return self.ragged_unsupported(has_tree=False)

    def tree_group_unsupported(self) -> str | None:
        """PR-10 surface: why tree-verify rows can't join a ragged
        dispatch. Thin delegation onto the unified gate."""
        return self.ragged_unsupported(has_tree=True)

    def mixed_group(
        self,
        handles: list[CacheHandle],
        hiddens: list[np.ndarray],  # per-member [b_i, t_i, D], same dtype
        layers: tuple[int, int] | None = None,
        adapter: str | None = None,
    ):
        """Causal ragged dispatch (N single-token decodes plus one
        multi-token prefill chunk — the Sarathi-Serve fused iteration).
        Thin delegation onto `ragged_group`; kept as the PR-8 call
        surface."""
        reason = self.mixed_unsupported()
        if reason is not None:
            raise ValueError(f"mixed_group unsupported: {reason}")
        return self.ragged_group(
            handles, hiddens, layers=layers, adapter=adapter
        )

    def tree_group(
        self,
        handles: list[CacheHandle],
        hiddens: list[np.ndarray],  # per-member [b_i, t_i, D], same dtype
        tree_masks: list[np.ndarray],  # per-member [b_i, t_i, t_i] bool
        depths_list: list[np.ndarray],  # per-member [b_i, t_i] i32
        layers: tuple[int, int] | None = None,
        adapter: str | None = None,
    ):
        """Tree-verify ragged dispatch (N sessions' linearized speculative
        trees verified as ONE span step). Thin delegation onto
        `ragged_group`; kept as the PR-10 call surface."""
        reason = self.tree_group_unsupported()
        if reason is not None:
            raise ValueError(f"tree_group unsupported: {reason}")
        return self.ragged_group(
            handles, hiddens, tree_masks=tree_masks,
            depths_list=depths_list, layers=layers, adapter=adapter,
        )

    def ragged_group(
        self,
        handles: list[CacheHandle],
        hiddens: list[np.ndarray],  # per-member [b_i, t_i, D], same dtype
        tree_masks: list | None = None,  # per-member [b_i, t_i, t_i] bool
        # or None for causal members (decode rows / the prefill chunk)
        depths_list: list | None = None,  # per-member [b_i, t_i] i32, None
        # for causal members (positions run sequentially from the start)
        layers: tuple[int, int] | None = None,
        adapter: str | None = None,
    ):
        """THE universal ragged dispatch: N sessions' rows — single-token
        decodes, linearized tree-verify rows, at most one multi-token
        prefill chunk — pack row-major into ONE pow2 bucket [1, R, D] and
        run as ONE jitted span dispatch over an ephemeral combined handle.
        Per-token (q_seq, q_pos) carry the member structure into the
        ragged kernel (dense attend_ragged for kernel-ineligible configs
        and TP-mesh spans, where GSPMD shards the rows' heads over the
        mesh). Members are CAUSAL by default; a member whose entry in
        `tree_masks`/`depths_list` is non-None contributes TREE rows.
        When any tree member is present the whole dispatch takes the
        tree-mask variant, and causal members ride along as
        lower-triangular rows at sequential depths — exactly causality, so
        the fused step stays token-identical to the members run alone.

        KV writes are SPECULATIVE for every member; commit/rollback stays
        per-member with the CALLER as recovery owner (decodes
        commit/rollback, the chunk commits on its last chunk /
        truncate_speculative's on failure, tree members truncate and
        replay solo — block_server._dispatch_ragged).

        Returns (out, combined_handle): `out` is the lazy [R, D] device
        result in member-major token order (slice b_i * t_i row blocks
        per member, fetch off-queue)."""
        n_members = len(handles)
        if tree_masks is None:
            tree_masks = [None] * n_members
        if depths_list is None:
            depths_list = [None] * n_members
        has_tree = any(tm is not None for tm in tree_masks)
        if (
            not has_tree
            and all(int(hid.shape[1]) == 1 for hid in hiddens)
        ):
            # pure single-token decodes: the legacy packed path IS this
            # dispatch (same [B, 1, D] bucket family as big single-session
            # batches, byte-for-byte PR-2 continuous batching — including
            # on offloaded/hetero/sparse spans the ragged packing gates
            # off). [B, 1, D] -> [R, D] is a lazy view, not a copy.
            combined = self.manager.combine_handles(handles)
            hidden = np.concatenate(hiddens, axis=0)
            # recovery owner: the caller commits/rolls back the combined
            # handle around this dispatch
            out = self._step(  # bbtpu: noqa[BB001]
                combined, hidden, commit=False, layers=layers, fetch=False,
                adapter=adapter,
            )
            return out.reshape(out.shape[0], out.shape[2]), combined
        reason = self.ragged_unsupported(has_tree=has_tree)
        if reason is not None:
            raise ValueError(f"ragged_group unsupported: {reason}")
        spec = self.spec
        from bloombee_tpu.models.checkpoint import resolve_adapter

        lora = resolve_adapter(self.adapters, adapter)
        combined = self.manager.combine_handles(handles)
        self.manager.ensure_resident(combined)

        d = spec.hidden_size
        counts: list[int] = []
        row_blocks = []
        for hid in hiddens:
            b_i, t_i, d_i = hid.shape
            assert d_i == d
            counts.extend([t_i] * b_i)
            row_blocks.append(hid.reshape(b_i * t_i, d))
        n_seqs = len(counts)
        r = sum(counts)
        # the tree-mask variant keeps every row's in-step width static:
        # causal members' rows become lower-triangular tree rows, so one
        # t_max bucket covers the whole mix
        t_max = next_pow2(max(counts)) if has_tree else 0

        starts = self.manager.context_lens(combined)  # [B] before write
        # recovery owner: block_server._dispatch_ragged rolls decodes
        # back, truncates the chunk and every tree member to their
        # pre-dispatch lengths if this dispatch fails
        slots = self.manager.write_slots_ragged(  # bbtpu: noqa[BB001]
            combined, counts, commit=False
        )  # [R]
        total_lens = self.manager.context_lens(combined)  # [B] after write

        rb = next_pow2(r)
        sb = next_pow2(n_seqs)
        arena_tokens = self.manager.capacity_tokens
        pages_needed = int(
            max(-(-int(l) // self.page_size) for l in total_lens)
        )
        pb = min(
            next_pow2(max(pages_needed, 1), floor=4),
            arena_tokens // self.page_size,
        )
        oob = arena_tokens  # out-of-bounds slot => dropped write

        h_pad = np.zeros((1, rb, d), dtype=self.transfer_dtype)
        h_pad[0, :r] = np.concatenate(row_blocks, axis=0).astype(
            self.transfer_dtype
        )
        slots_pad = np.full((rb,), oob, dtype=np.int32)
        slots_pad[:r] = slots
        positions = np.zeros((1, rb), dtype=np.int32)
        # padding rows own no sequence (q_seq >= B): fully masked in the
        # kernel, sliced away with the pad rows
        q_seq = np.full((rb,), sb, dtype=np.int32)
        if has_tree:
            nt = np.zeros((sb,), dtype=np.int32)
            tree_rows = np.zeros((rb, t_max), dtype=np.int32)
        off = 0
        s_i = 0
        for m_i, hid in enumerate(hiddens):
            b_i, t_i, _ = hid.shape
            tm = tree_masks[m_i]
            dep = depths_list[m_i]
            if tm is not None:
                tm = np.asarray(tm, dtype=bool)
                dep = np.asarray(dep, dtype=np.int32)
            for row in range(b_i):
                if tm is not None:
                    positions[0, off : off + t_i] = starts[s_i] + dep[row]
                else:
                    positions[0, off : off + t_i] = starts[s_i] + np.arange(
                        t_i, dtype=np.int32
                    )
                q_seq[off : off + t_i] = s_i
                if has_tree:
                    nt[s_i] = t_i
                    if tm is not None:
                        tree_rows[off : off + t_i, :t_i] = tm[row]
                    else:
                        # causal rows under the tree mask: token j sees
                        # in-step tokens 0..j at sequential depths — the
                        # lower triangle is exactly causal attention
                        tree_rows[off : off + t_i, :t_i] = np.tril(
                            np.ones((t_i, t_i), dtype=np.int32)
                        )
                off += t_i
                s_i += 1
        pt_pad = np.zeros((sb, pb), dtype=np.int32)
        pt_pad[:n_seqs] = self.manager.page_table(combined, pb)
        lens_pad = np.zeros((sb,), dtype=np.int32)
        lens_pad[:n_seqs] = total_lens
        num_layers = self.manager.num_layers
        layer_active = np.ones((num_layers,), dtype=np.int32)
        if layers is not None:
            layer_active[:] = 0
            layer_active[layers[0] : layers[1]] = 1
        if has_tree:
            plan = pack_ragged_plan(
                slots_pad, pt_pad, positions, lens_pad, q_seq, layer_active,
                nt=nt, tree_rows=tree_rows,
            )
            tag = f"r{rb},s{sb},p{pb},t{t_max}"
        else:
            plan = pack_ragged_plan(
                slots_pad, pt_pad, positions, lens_pad, q_seq, layer_active
            )
            tag = f"r{rb},s{sb},p{pb}"

        # ragged-kernel eligibility mirrors _step's chunk gate: dense
        # arena, [R*H, hd] VMEM budget, contexts past the paged crossover,
        # single-chip (Pallas kernels don't GSPMD-partition — TP-mesh
        # spans run the dense attend_ragged path). Ineligible configs run
        # attend_ragged — still ONE dispatch.
        use_kernel = bool(
            not getattr(self, "_paged_broken", False)
            and self.mesh is None
            and self.manager.quant is None
            and rb * spec.num_attention_heads <= 2048
            and pb * self.page_size >= env.get("BBTPU_PAGED_MIN_CONTEXT")
            and not spec.alibi
            and not spec.attn_logit_softcap
            and env.get("BBTPU_PAGED_ATTENTION")
            and (
                jax.default_backend() == "tpu"
                or env.get("BBTPU_PAGED_INTERPRET")
            )
        )

        payload = pack_step_payload(h_pad, plan)
        if self.mesh is not None:
            # commit the h2d payload replicated over the tp mesh; the
            # sharded params/arena make GSPMD split the per-head work
            from bloombee_tpu.parallel import serving as tp_serving

            payload_dev = tp_serving.replicated(payload, self.mesh)
        else:
            payload_dev = jnp.asarray(payload)
        arena = self.manager.arena
        step_kwargs = {"t_max": t_max} if has_tree else {}

        def _run(use_kernel_now: bool):
            with jitwatch.region("span_step_ragged", tag):
                return span_step_ragged(
                    self.params,
                    arena["k"],
                    arena["v"],
                    payload_dev,
                    lora,
                    spec=spec,
                    r=rb,
                    n_seqs=sb,
                    page_size=self.page_size,
                    max_pages=pb,
                    windows=self.windows,
                    use_kernel=use_kernel_now,
                    **step_kwargs,
                )

        try:
            out, new_k, new_v = _run(use_kernel)
        except Exception:
            # same self-heal contract as _step: retry on the dense ragged
            # path only if the donated arena buffers are still alive
            if self._arena_consumed(arena):
                self._rebuild_after_failure("ragged group step")
                raise
            if not use_kernel:
                raise
            import logging

            logging.getLogger(__name__).exception(
                "paged ragged kernel failed; retrying on the dense "
                "ragged path"
            )
            out, new_k, new_v = _run(False)
            self._paged_broken = True
        self.manager.arena = {"k": new_k, "v": new_v}
        return out[0, :r], combined

    def fetch(self, out) -> np.ndarray:
        """Materialize a fetch=False result on host in the wire dtype
        (blocks on the device round trip — call off the compute queue).
        A list of per-chunk results concatenates along the token axis.

        This is the package's ONE deliberate d2h chokepoint: results go
        straight onto the wire, so the sync is the contract, not a leak.
        Dispatchers pass fetch=False and call this off-queue (jitwatch
        counts any call that lands on the compute thread as a hot-path
        sync — the convoy BB011 flags statically)."""
        jitwatch.host_sync("executor.fetch")
        if isinstance(out, (list, tuple)):
            return np.concatenate(  # bbtpu: noqa[BB011] wire-bound d2h by contract; hot dispatchers use fetch=False and fetch off-queue
                [np.asarray(o) for o in out], axis=1
            ).astype(self.transfer_dtype)
        return np.asarray(out).astype(self.transfer_dtype)  # bbtpu: noqa[BB011] wire-bound d2h by contract; hot dispatchers use fetch=False and fetch off-queue

    def decode_n(
        self,
        handle: CacheHandle,
        ids: np.ndarray,  # [B] int: the input token of the first step
        n: int,
        client_params: dict,  # embed + norm + lm_head (checkpoint's trio)
        eos_token_id: int | None = None,
        finished: np.ndarray | None = None,  # [B] bool rows already at EOS
        adapter: str | None = None,
    ):
        """Run N greedy decode steps entirely on device and return the [B, n]
        selected token ids as a lazy device array (caller fetches off-queue).

        One jitted lax.scan does embed -> span -> norm+head -> argmax per
        step (runtime/decode_loop.py), so an RPC pays ONE host<->device round
        trip for n tokens instead of n round trips. Valid only when this
        span is the whole model (the server checks), dense, fully
        device-resident, and un-sharded. N is bucketed to the next power of
        two; padding steps write to out-of-bounds slots (dropped) and their
        tokens are sliced away, so no garbage reaches the KV arena.
        """
        spec = self.spec
        if self.host_layers or spec.heterogeneous or self.mesh is not None:
            raise ValueError(
                "decode_n needs a dense, fully device-resident, un-sharded "
                "span"
            )
        if self.manager.quant is not None:
            raise ValueError("decode_n + quantized KV arena not supported")
        if self.attn_sparsity < 1.0:
            # the per-step path recomputes top-k from the CURRENT context
            # length every step; a k frozen at trace time would diverge
            raise ValueError("decode_n + attn_sparsity not supported")
        from bloombee_tpu.models.checkpoint import resolve_adapter

        lora = resolve_adapter(self.adapters, adapter)
        self.manager.ensure_resident(handle)
        b = int(ids.shape[0])
        bb = next_pow2(b)
        nb = next_pow2(n)
        arena_tokens = self.manager.capacity_tokens
        lens_now = self.manager.context_lens(handle)
        final_max = int(lens_now.max()) + n
        pb = min(
            next_pow2(max(-(-final_max // self.page_size), 1), floor=4),
            arena_tokens // self.page_size,
        )
        oob = arena_tokens
        layer_active = np.ones((self.manager.num_layers,), np.int32)
        pt_pad = np.zeros((bb, pb), np.int32)
        lens_pad = np.zeros((bb,), np.int32)
        pos_pad = np.zeros((bb, 1), np.int32)
        plans = []
        for i in range(nb):
            slots_pad = np.full((bb, 1), oob, np.int32)
            if i < n:
                slots_pad[:b, 0] = self.manager.write_slots(
                    handle, 1, commit=True
                )
                total_lens = self.manager.context_lens(handle)
                pt_pad[:b] = self.manager.page_table(handle, pb)
                lens_pad[:b] = total_lens
                pos_pad[:b, 0] = total_lens - 1
            plans.append(
                pack_plan(slots_pad, pt_pad, pos_pad, lens_pad, layer_active)
            )
        plans = np.stack(plans)

        # paged gating uses the STARTING length's page bucket (the same
        # bucket the per-step path sees on the chunk's first step), so a
        # chunk beginning below the paged crossover stays dense like its
        # per-step equivalent. A chunk that CROSSES the crossover keeps one
        # kernel throughout (the flag is static over the scan) while the
        # per-step path would switch mid-way — the kernels agree to ~1e-5,
        # so an exact argmax tie at the boundary could in principle flip;
        # everywhere else greedy outputs are bitwise identical.
        pb_start = min(
            next_pow2(
                max(-(-(int(lens_now.max()) + 1) // self.page_size), 1),
                floor=4,
            ),
            arena_tokens // self.page_size,
        )
        use_paged = bool(
            not getattr(self, "_paged_broken", False)
            and pb_start * self.page_size
            >= env.get("BBTPU_PAGED_MIN_CONTEXT")
            and not spec.alibi
            and not spec.attn_logit_softcap
            and env.get("BBTPU_PAGED_ATTENTION")
            and (
                jax.default_backend() == "tpu"
                or env.get("BBTPU_PAGED_INTERPRET")
            )
        )
        ids_pad = np.zeros((bb,), np.int32)
        ids_pad[:b] = np.asarray(ids).reshape(-1)
        fin_pad = np.ones((bb,), bool)  # padding rows never select real ids
        fin_pad[:b] = (
            np.asarray(finished, dtype=bool) if finished is not None else False
        )
        arena = self.manager.arena

        from bloombee_tpu.runtime.decode_loop import decode_loop

        def _run(use_paged_now: bool):
            with jitwatch.region("decode_loop", f"b{bb},n{nb},p{pb}"):
                return decode_loop(  # bbtpu: noqa[BB012] eos_id is a per-model token constant (cardinality 1 per checkpoint), not a request shape
                    client_params, self.params, arena["k"], arena["v"],
                    jnp.asarray(ids_pad), jnp.asarray(fin_pad),
                    jnp.asarray(plans), lora,
                    spec=spec, page_size=self.page_size, max_pages=pb,
                    eos_id=(
                        -1 if eos_token_id is None else int(eos_token_id)
                    ),
                    compute_dtype=self.compute_dtype,
                    windows=self.windows,
                    use_paged=use_paged_now,
                )

        try:
            toks, new_k, new_v = _run(use_paged)
        except Exception:
            # same self-heal contract as _step: retry on the gather path
            # only if the donated arena buffers are still alive
            if self._arena_consumed(arena):
                self._rebuild_after_failure("decode_n")
                raise
            if not use_paged:
                raise
            import logging

            logging.getLogger(__name__).exception(
                "paged decode kernel failed in decode_n; retrying on the "
                "dense gather path"
            )
            toks, new_k, new_v = _run(False)
            self._paged_broken = True
        self.manager.arena = {"k": new_k, "v": new_v}
        return toks[:b, :n]

    def _place_step_inputs(self, h_pad, plan, tm_pad):
        """Pack and commit one step's (payload, tree mask) to the device —
        replicated over the tp mesh when serving sharded."""
        payload = pack_step_payload(h_pad, plan)
        if self.mesh is not None:
            from bloombee_tpu.parallel import serving as tp_serving

            return (
                tp_serving.replicated(payload, self.mesh),
                tp_serving.replicated(tm_pad, self.mesh)
                if tm_pad is not None else None,
            )
        return (
            jnp.asarray(payload),
            jnp.asarray(tm_pad) if tm_pad is not None else None,
        )

    @staticmethod
    def _arena_consumed(arena) -> bool:
        return any(
            getattr(a, "is_deleted", lambda: False)()
            for a in jax.tree.leaves((arena["k"], arena["v"]))
        )

    def _rebuild_after_failure(self, where: str) -> None:
        """A failure consumed the donated arena mid-chain: without a fresh
        arena every later step would compute on deleted buffers, bricking
        the server. Rebuild (zeroed) and bump the epoch so pre-rebuild
        sessions fail loudly and their clients replay (advisor, round 2)."""
        import logging

        logging.getLogger(__name__).error(
            "%s failed after the donated arena was consumed; rebuilding a "
            "fresh arena — live sessions' KV is lost and their clients "
            "must replay", where,
        )
        self.manager.rebuild_arena()
        if self.mesh is not None:
            # the fresh slabs land on the default device; a TP server must
            # re-place them or every later step runs with an unsharded
            # arena against sharded params (x tp HBM + a recompile)
            from bloombee_tpu.parallel import serving as tp_serving

            self.manager.arena = tp_serving.place_arena_for(
                self.spec, self.manager.arena, self.mesh
            )

    def _run_offloaded(
        self, h_pad, slots_pad, pt_pad, positions, lens_pad, layer_active,
        tm_pad, lora, bb, tb, pb, use_flash, use_paged, attn_topk=0,
        t_real=None,
    ):
        """Weight-offload step: scan the device-resident prefix, then stream
        each offloaded layer's params host->device with ONE-AHEAD prefetch
        (jax transfers are async, so layer l+1's H2D copy overlaps layer l's
        compute — the copy-engine overlap of the reference's
        PipelineParallelWrapper pre-forward H2D, convert_block.py:138-263).
        The arena never leaves the device; each layer_step updates its slab
        in place via donation."""
        from bloombee_tpu.runtime.step import layer_step

        ak, av = self.manager.arena["k"], self.manager.arena["v"]
        resident = self.resident
        # under TP, every per-step input commits replicated to the mesh
        # and each streamed host layer places SHARDED (its H2D bytes split
        # across the tp chips); single-chip keeps plain transfers
        if self.mesh is not None:
            from bloombee_tpu.parallel import serving as tp_serving

            place_rep = functools.partial(
                tp_serving.replicated, mesh=self.mesh
            )
            place_layer = functools.partial(
                tp_serving.place_layer_params, mesh=self.mesh
            )
        else:
            place_rep = jnp.asarray
            place_layer = jax.device_put
        tm_dev = place_rep(tm_pad) if tm_pad is not None else None
        use_tm = tm_pad is not None

        la_res = layer_active[:resident].copy()
        if resident and la_res.any():
            plan_res = pack_plan(
                slots_pad, pt_pad, positions, lens_pad, la_res
            )
            lora_res = (
                jax.tree.map(lambda x: x[:resident], lora)
                if lora is not None else None
            )
            hidden, ak, av = span_step_packed(
                self.params, ak, av,
                place_rep(pack_step_payload(h_pad, plan_res)), tm_dev,
                lora_res,
                spec=self.spec, b=bb, t=tb, page_size=self.page_size,
                max_pages=pb, use_tree_mask=use_tm,
                windows=self.windows[:resident], use_flash=use_flash,
                use_paged=use_paged, resident=resident, attn_topk=attn_topk,
                t_real=t_real,
            )
        else:
            hidden = place_rep(h_pad)

        idxs = [
            l for l in range(resident, self.manager.num_layers)
            if layer_active[l]
        ]
        if not idxs:
            return hidden, ak, av
        plan1 = place_rep(
            pack_plan(
                slots_pad, pt_pad, positions, lens_pad,
                np.ones((1,), np.int32),
            )
        )
        nxt = place_layer(self.host_layers[idxs[0] - resident])
        for i, l in enumerate(idxs):
            cur, nxt = nxt, (
                place_layer(self.host_layers[idxs[i + 1] - resident])
                if i + 1 < len(idxs) else None
            )
            lora_l = (
                jax.tree.map(lambda x: x[l], lora)
                if lora is not None else None
            )
            hidden, ak, av = layer_step(  # bbtpu: noqa[BB012] window is per-layer checkpoint config (few distinct values per model), not a request shape
                cur, ak, av, hidden, plan1, jnp.int32(l), tm_dev, lora_l,
                spec=self.spec, page_size=self.page_size, max_pages=pb,
                use_tree_mask=use_tm, window=int(self.windows[l]),
                use_flash=use_flash, use_paged=use_paged,
                attn_topk=attn_topk, t_real=t_real,
            )
        return hidden, ak, av

    # --------------------------------------------------------------- internals
    def _step(
        self,
        handle: CacheHandle,
        hidden: np.ndarray,
        commit: bool,
        tree_mask: np.ndarray | None = None,
        layers: tuple[int, int] | None = None,
        depths: np.ndarray | None = None,
        fetch: bool = True,
        adapter: str | None = None,
    ):
        spec = self.spec
        from bloombee_tpu.models.checkpoint import resolve_adapter

        lora = resolve_adapter(self.adapters, adapter)
        b, t, d = hidden.shape
        assert d == spec.hidden_size

        # over-subscribed servers may have parked this session's KV to
        # host while it was idle; bring it back before writing
        self.manager.ensure_resident(handle)
        starts = self.manager.context_lens(handle)  # [B] before write
        slots = self.manager.write_slots(handle, t, commit=commit)  # [B*T]
        total_lens = self.manager.context_lens(handle)  # [B] after write

        # buckets; tree steps keep T exact — the tree mask's key-position
        # arithmetic in step._attend_paged assumes the written token count
        # equals T (tree shapes are already bucketed by the drafter)
        bb = next_pow2(b)
        tb = t if (t == 1 or tree_mask is not None) else next_pow2(t)
        arena_tokens = self.manager.capacity_tokens
        pages_needed = int(
            max(-(-int(l) // self.page_size) for l in total_lens)
        )
        pb = min(
            next_pow2(max(pages_needed, 1), floor=4),
            arena_tokens // self.page_size,
        )

        oob = arena_tokens  # out-of-bounds slot => dropped write
        h_pad = np.zeros((bb, tb, d), dtype=self.transfer_dtype)
        h_pad[:b, :t] = hidden.astype(self.transfer_dtype)
        slots_pad = np.full((bb, tb), oob, dtype=np.int32)
        slots_pad[:b, :t] = slots.reshape(b, t)
        # rotary positions: sequential for plain steps; start + per-node tree
        # depth for tree steps (reference: tree rotary ids, backend.py:944)
        positions = np.zeros((bb, tb), dtype=np.int32)
        if depths is not None:
            positions[:b, :t] = starts[:, None] + np.asarray(depths)[:, :t]
        else:
            positions[:b, :t] = (
                starts[:, None] + np.arange(t, dtype=np.int32)[None, :]
            )
        pt_pad = np.zeros((bb, pb), dtype=np.int32)
        pt_pad[:b] = self.manager.page_table(handle, pb)
        lens_pad = np.zeros((bb,), dtype=np.int32)
        lens_pad[:b] = total_lens
        num_layers = self.manager.num_layers
        layer_active = np.ones((num_layers,), dtype=np.int32)
        if layers is not None:
            layer_active[:] = 0
            layer_active[layers[0] : layers[1]] = 1
        plan = pack_plan(slots_pad, pt_pad, positions, lens_pad, layer_active)
        tm_pad = None
        if tree_mask is not None:
            tm_pad = np.zeros((bb, tb, tb), dtype=bool)
            tm_pad[:b, :t, :t] = tree_mask

        # paged-kernel eligibility (per-seq lens may differ — masked
        # in-kernel; sliding windows ride as traced scalars, skipping
        # out-of-window pages outright). Short contexts stay on the dense
        # path — the gather is cheap there and the kernel's page-granular
        # grid costs more than it saves (measured crossover ~512 tokens).
        # T==1: plain decode (int4 arenas dequantize in-kernel).
        # T>1 (round-4 verdict #5): tree-verify steps (tree mask applied
        # in-kernel; tree+window stays dense — depth-positioned windows
        # don't fit the kernel's arithmetic) and short multi-token chunks
        # below flash's T>=128 domain, bounded by the [T*H, hd] VMEM
        # budget; dense arenas only.
        t1_ok = tb == 1 and self.manager.quant in (None, "int4")
        chunk_ok = (
            1 < tb < 128
            and self.manager.quant is None
            and tb * self.spec.num_attention_heads <= 2048
            and (tree_mask is None or all(w == 0 for w in self.windows))
        )
        use_paged = bool(
            not getattr(self, "_paged_broken", False)
            and self.attn_sparsity >= 1.0  # kernel has no top-k path
            and pb * self.page_size >= env.get("BBTPU_PAGED_MIN_CONTEXT")
            and self.mesh is None  # Pallas kernels don't GSPMD-partition
            and not self.spec.heterogeneous
            and (t1_ok or chunk_ok)
            and not self.spec.alibi
            and not self.spec.attn_logit_softcap
            and env.get("BBTPU_PAGED_ATTENTION")
            and (
                jax.default_backend() == "tpu"
                or env.get("BBTPU_PAGED_INTERPRET")
            )
        )

        # flash eligibility: per-row starts/lens ride into the kernel as
        # traced vectors, so MIXED-length batches engage flash too; the
        # only row-shape requirement left is that every row wrote exactly
        # this step's t tokens (ragged commit_lens replay writes a padded
        # rectangle first, satisfying this during the step)
        s_ctx = pb * self.page_size
        use_flash = bool(
            self.mesh is None  # Pallas kernels don't GSPMD-partition
            # (attn_sparsity is decode-only, so flash PREFILL is unaffected)
            and not self.spec.heterogeneous
            and tree_mask is None
            and tb >= 128
            and tb % 128 == 0
            and s_ctx % 128 == 0
            and s_ctx >= tb
            and not self.spec.alibi
            and not self.spec.attn_logit_softcap
            and all(w == 0 for w in self.windows)
            and np.all(total_lens == starts + t)
            and env.get("BBTPU_FLASH_ATTENTION")
            and (
                jax.default_backend() == "tpu"
                or env.get("BBTPU_FLASH_INTERPRET")
            )
        )

        attn_topk = 0
        if self.attn_sparsity < 1.0 and tb == 1 and tree_mask is None:
            # decode-only approximation (FlexGen applies sparsity at
            # generation only): sparsifying prefill would corrupt the
            # cached context every layer feeds the next. k derives from the
            # pow2 bucket of the largest TRUE row length — attn_topk is a
            # static jit arg, so an exact per-step k would retrace the span
            # every few tokens; pow2 bucketing caps compiles at O(log S) at
            # the cost of k being up to 2x looser right after a boundary.
            attn_topk = max(
                1,
                int(
                    self.attn_sparsity
                    * (next_pow2(int(total_lens.max())) - 1)
                ),
            )

        arena = self.manager.arena
        if self.host_layers:
            def _run_off(use_paged_now: bool):
                with jitwatch.region("layer_step", f"b{bb},t{tb},p{pb}"):
                    return self._run_offloaded(
                        h_pad, slots_pad, pt_pad, positions, lens_pad,
                        layer_active, tm_pad, lora, bb, tb, pb, use_flash,
                        use_paged_now, attn_topk, t_real=t,
                    )

            try:
                out, new_k, new_v = _run_off(use_paged)
            except Exception:
                # same self-heal contract as the dense branch below: retry
                # on the gather path only if the donated arena buffers are
                # still alive (a compile failure surfaces before donation
                # consumes them; a mid-chain runtime failure does not)
                if self._arena_consumed(arena):
                    self._rebuild_after_failure("offloaded step")
                    raise
                if not use_paged:
                    raise
                import logging

                logging.getLogger(__name__).exception(
                    "paged decode kernel failed in the offload path; "
                    "retrying on the dense gather path"
                )
                out, new_k, new_v = _run_off(False)
                self._paged_broken = True
        elif self.spec.heterogeneous:
            from bloombee_tpu.runtime.hetero import span_step_hetero

            payload_dev, tm_dev = self._place_step_inputs(h_pad, plan, tm_pad)
            try:
                with jitwatch.region(
                    "span_step_hetero", f"b{bb},t{tb},p{pb}"
                ):
                    out, new_k, new_v = span_step_hetero(  # bbtpu: noqa[BB012] layer_active is the hetero residency mask — one value per (span, offload split), not per request
                        self.params,
                        arena["k"],
                        arena["v"],
                        payload_dev,
                        tm_dev,
                        lora,
                        spec=spec,
                        b=bb,
                        t=tb,
                        page_size=self.page_size,
                        max_pages=pb,
                        use_tree_mask=tree_mask is not None,
                        start_block=self.start_block,
                        layer_active=tuple(int(x) for x in layer_active),
                        attn_topk=attn_topk,
                    )
            except Exception:
                # same donated-arena contract as the dense branch: a
                # runtime failure after donation must rebuild so the
                # server survives (sessions replay), then re-raise
                if self._arena_consumed(arena):
                    self._rebuild_after_failure("hetero span step")
                raise
        else:
            payload_dev, tm_dev = self._place_step_inputs(h_pad, plan, tm_pad)

            def _run(use_paged_now: bool):
                with jitwatch.region("span_step", f"b{bb},t{tb},p{pb}"):
                    return span_step_packed(
                        self.params,
                        arena["k"],
                        arena["v"],
                        payload_dev,
                        tm_dev,
                        lora,
                        attn_topk=attn_topk,
                        spec=spec,
                        b=bb,
                        t=tb,
                        page_size=self.page_size,
                        max_pages=pb,
                        use_tree_mask=tree_mask is not None,
                        windows=self.windows,
                        use_flash=use_flash,
                        use_paged=use_paged_now,
                        t_real=t,
                    )

            try:
                out, new_k, new_v = _run(use_paged)
            except Exception:
                # Only the paged-kernel path self-heals, and only when the
                # donated arena buffers are still alive (a compile failure
                # surfaces at call time BEFORE donation consumes them; if a
                # runtime failure already ate the arena, retrying would
                # compute on deleted buffers — rebuild so the server
                # survives, then re-raise the real error).
                if self._arena_consumed(arena):
                    self._rebuild_after_failure("span step")
                    raise
                if not use_paged:
                    raise
                import logging

                logging.getLogger(__name__).exception(
                    "paged decode kernel failed; retrying on the dense "
                    "gather path"
                )
                out, new_k, new_v = _run(False)
                # the dense path works while paged does not -> the kernel
                # itself is broken on this backend; stop trying it
                self._paged_broken = True
        self.manager.arena = {"k": new_k, "v": new_v}
        out = out[:b, :t]
        if not fetch:
            return out  # lazy device array; caller fetches off-queue
        # keep the transfer dtype (bf16 when computing in bf16): this array
        # goes straight onto the wire (reply or server-to-server push)
        return self.fetch(out)
