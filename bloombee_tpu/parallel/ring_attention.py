"""Ring attention: sequence/context parallelism over the "sp" mesh axis.

Each device holds one sequence chunk of Q, K, V. KV chunks rotate around the
ring (lax.ppermute over ICI) while each device accumulates its Q block's
attention with a numerically-stable online softmax (flash-attention style
streaming stats). After sp steps every Q block has seen every KV block and
no device ever materializes full-sequence attention logits.

Within one ring step the local chunk is processed in (q block, k block)
tiles with the SAME online update, so peak logits memory is
[B, H, block, block] regardless of chunk length — without tiling, a 64k
prompt over sp=4 would need ~34 GB of fp32 logits per step and the long
prompts the sp path exists for would OOM instead of speeding up. Chunks
that don't divide the block size are padded; padded keys get a sentinel
position no causal mask admits, padded query rows are sliced off.

This fills the reference's explicit long-context gap (SURVEY.md section 5:
"no ring attention / Ulysses / context parallelism" — it only chunks prefill
and offloads the KV slab to host). Compute stays in the input dtype for the
MXU; softmax stats are fp32.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from bloombee_tpu.ops.attention import NEG_INF as NEG, repeat_kv

_PAD_POS = 1 << 30  # sentinel: padded keys are in everyone's causal future


def ring_attention(
    q: jax.Array,  # [B, C, H, hd] local query chunk
    k: jax.Array,  # [B, C, Hkv, hd] local key chunk
    v: jax.Array,  # [B, C, Hkv, hd]
    axis_name: str = "sp",
    causal: bool = True,
    scale: float | None = None,
    block: int = 512,  # in-step tile size: peak logits = [B, H, blk, blk]
) -> jax.Array:
    """Must be called inside shard_map with `axis_name` mapped; returns the
    local output chunk [B, C, H, hd]."""
    n = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    b, c, h, hd = q.shape
    n_rep = h // k.shape[2]
    if scale is None:
        scale = hd**-0.5

    blk = min(block, c)
    c_pad = -(-c // blk) * blk
    if c_pad != c:
        pad = ((0, 0), (0, c_pad - c), (0, 0), (0, 0))
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        v = jnp.pad(v, pad)
    n_blk = c_pad // blk
    valid = jnp.arange(c_pad) < c
    q_pos = rank * c + jnp.arange(c_pad)  # padded q rows: garbage, sliced
    qf = q  # [B, Cp, H, hd]
    qp_bs = q_pos.reshape(n_blk, blk)
    q_bs = qf.transpose(1, 0, 2, 3).reshape(n_blk, blk, b, h, hd)

    def step(carry, i):
        m, l, acc, k_cur, v_cur = carry
        src = (rank - i) % n  # who produced the block currently held
        # padded keys sit past every real position: no causal mask admits
        # the sentinel, so they contribute nothing on any rank
        kv_pos = jnp.where(valid, src * c + jnp.arange(c_pad), _PAD_POS)

        def attend(m, l, acc):
            k_r = repeat_kv(k_cur, n_rep)  # [B, Cp, H, hd]
            v_r = repeat_kv(v_cur, n_rep)
            k_bs = k_r.transpose(1, 0, 2, 3).reshape(n_blk, blk, b, h, hd)
            v_bs = v_r.transpose(1, 0, 2, 3).reshape(n_blk, blk, b, h, hd)
            kvp_bs = kv_pos.reshape(n_blk, blk)
            m_bs = m.reshape(b, h, n_blk, blk).transpose(2, 0, 1, 3)
            l_bs = l.reshape(b, h, n_blk, blk).transpose(2, 0, 1, 3)
            acc_bs = acc.reshape(b, h, n_blk, blk, hd).transpose(
                2, 0, 1, 3, 4
            )

            def one_q(xs):
                q_blk, qp, m_b, l_b, acc_b = xs

                def k_step(carry, ks):
                    m_b, l_b, acc_b = carry
                    k_blk, v_blk, kvp = ks
                    logits = (
                        jnp.einsum(
                            "qbhd,kbhd->bhqk", q_blk, k_blk
                        ).astype(jnp.float32)
                        * scale
                    )  # [b, h, blk, blk]
                    if causal:
                        mask = kvp[None, :] <= qp[:, None]
                    else:
                        mask = (kvp < _PAD_POS)[None, :] & jnp.ones(
                            (blk, 1), bool
                        )
                    logits = jnp.where(mask[None, None], logits, NEG)
                    pmask = mask[None, None].astype(jnp.float32)
                    m_new = jnp.maximum(m_b, logits.max(axis=-1))
                    p = jnp.exp(logits - m_new[..., None]) * pmask
                    corr = jnp.exp(m_b - m_new)
                    l_new = l_b * corr + p.sum(axis=-1)
                    acc_new = acc_b * corr[..., None] + jnp.einsum(
                        "bhqk,kbhd->bhqd", p.astype(q.dtype), v_blk
                    ).astype(jnp.float32)
                    return (m_new, l_new, acc_new), None

                (m_b, l_b, acc_b), _ = lax.scan(
                    k_step, (m_b, l_b, acc_b), (k_bs, v_bs, kvp_bs)
                )
                return m_b, l_b, acc_b

            # lax.map serializes q tiles, so peak logits stay one tile
            m2, l2, acc2 = lax.map(
                one_q, (q_bs, qp_bs, m_bs, l_bs, acc_bs)
            )
            m2 = m2.transpose(1, 2, 0, 3).reshape(b, h, c_pad)
            l2 = l2.transpose(1, 2, 0, 3).reshape(b, h, c_pad)
            acc2 = acc2.transpose(1, 2, 0, 3, 4).reshape(b, h, c_pad, hd)
            return m2, l2, acc2

        if causal:
            # skip blocks entirely in this rank's causal future (half of all
            # (rank, src) pairs): the ppermute still runs every step —
            # collectives must stay uniform across the ring — but the
            # logits/softmax FLOPs are branched away. (Callers wrap with
            # check_vma=False: the identity skip branch is replicated-typed
            # while attend's outputs vary over the ring axis, which strict
            # vma checking would reject despite being correct here.)
            m, l, acc = lax.cond(
                src <= rank, attend, lambda m, l, acc: (m, l, acc), m, l, acc
            )
        else:
            m, l, acc = attend(m, l, acc)

        # rotate KV to the next rank on the ring
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (m, l, acc, k_nxt, v_nxt), None

    m0 = jnp.full((b, h, c_pad), NEG, jnp.float32)
    l0 = jnp.zeros((b, h, c_pad), jnp.float32)
    acc0 = jnp.zeros((b, h, c_pad, hd), jnp.float32)
    # scan (not fori_loop) so the ring is reverse-differentiable for training
    (m, l, acc, _, _), _ = lax.scan(
        step, (m0, l0, acc0, k, v), jnp.arange(n)
    )

    out = acc / jnp.maximum(l, 1e-20)[..., None]  # fully-masked rows -> 0
    out = out.transpose(0, 2, 1, 3).astype(q.dtype)  # [B, Cp, H, hd]
    return out[:, :c]
