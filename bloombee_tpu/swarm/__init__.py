"""Swarm discovery: registry service, server records, span computation.

Replaces the reference's hivemind Kademlia DHT layer
(/root/reference/src/bloombee/utils/dht.py:28-153, data_structures.py) with a
registry service speaking the same record semantics: per-block uid keys,
per-server subkeys, record expiration as the liveness signal, and
`compute_spans` turning block records into contiguous server spans.
"""

from bloombee_tpu.swarm.data import ServerInfo, ServerState, RemoteSpanInfo, ModuleInfo
from bloombee_tpu.swarm.registry import RegistryServer, RegistryClient, InProcessRegistry
from bloombee_tpu.swarm.spans import compute_spans

__all__ = [
    "ServerInfo",
    "ServerState",
    "RemoteSpanInfo",
    "ModuleInfo",
    "RegistryServer",
    "RegistryClient",
    "InProcessRegistry",
    "compute_spans",
]
