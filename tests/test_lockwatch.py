"""Runtime lock-order witness (utils/lockwatch.py): edge recording,
hierarchy-violation + cycle detection, the zero-overhead-when-off
contract, the multi-process report/--require gate, and one live e2e
swarm run with the witness on (replication guarantees a cross-lock
edge: repl_lock is held across peer-pool and send-lock acquisitions).
"""

import asyncio
import json
import threading

import numpy as np
import pytest

from bloombee_tpu.utils import lockwatch


@pytest.fixture(autouse=True)
def fresh_witness():
    lockwatch.reset()
    yield
    lockwatch.reset()


@pytest.fixture
def watch_on(monkeypatch):
    monkeypatch.setenv("BBTPU_LOCKWATCH", "1")
    monkeypatch.delenv("BBTPU_LOCKWATCH_REPORT", raising=False)


# ------------------------------------------------------- off = plain locks
def test_off_returns_plain_stdlib_locks(monkeypatch):
    """The zero-overhead contract: with the switch off the factories
    return the stdlib objects themselves — no wrapper in the acquire
    path, nothing recorded, nothing to misbehave in production."""
    monkeypatch.delenv("BBTPU_LOCKWATCH", raising=False)
    assert type(lockwatch.thread_lock("utils.ledger")) is type(
        threading.Lock()
    )
    assert isinstance(
        lockwatch.thread_lock("kv.cache_manager", reentrant=True),
        type(threading.RLock()),
    )

    async def check_async():
        assert isinstance(lockwatch.async_lock("rpc.send"), asyncio.Lock)

    asyncio.run(check_async())
    assert lockwatch.counters() == {
        "lock_order_edges": 0, "lock_violations": 0,
    }


# ----------------------------------------------------------- edge recording
def test_records_cross_lock_edges_in_order(watch_on):
    a = lockwatch.thread_lock("kv.cache_manager", reentrant=True)
    b = lockwatch.thread_lock("utils.ledger")
    with a:
        with b:
            pass
    snap = lockwatch.snapshot()
    assert snap["edges"] == [["kv.cache_manager", "utils.ledger", 1]]
    assert snap["violations"] == []
    assert lockwatch.counters() == {
        "lock_order_edges": 1, "lock_violations": 0,
    }


def test_reentrant_self_acquire_is_quiet(watch_on):
    a = lockwatch.thread_lock("kv.cache_manager", reentrant=True)
    with a:
        with a:
            pass
    snap = lockwatch.snapshot()
    assert snap["edges"] == []
    assert snap["violations"] == []


def test_nonreentrant_self_acquire_is_a_violation(watch_on):
    # a plain Lock would deadlock here; exercise the witness's check
    # through its recording API (the wrapper records after the inner
    # acquire, which would never return)
    lockwatch._witness.acquire("utils.ledger", False, "thread")
    lockwatch._witness.acquire("utils.ledger", False, "thread")
    snap = lockwatch.snapshot()
    assert snap["violations"]
    assert "re-acquired" in snap["violations"][0]["why"]
    lockwatch._witness.release("utils.ledger", "thread")
    lockwatch._witness.release("utils.ledger", "thread")


def test_descending_order_is_a_violation(watch_on):
    lo = lockwatch.thread_lock("kv.cache_manager", reentrant=True)
    hi = lockwatch.thread_lock("utils.ledger")
    with hi:
        with lo:
            pass
    snap = lockwatch.snapshot()
    assert snap["violations"], snap
    v = snap["violations"][0]
    assert (v["held"], v["acquired"]) == ("utils.ledger", "kv.cache_manager")
    assert lockwatch.counters()["lock_violations"] >= 1


def test_release_removes_innermost_hold(watch_on):
    a = lockwatch.thread_lock("server.repl")
    b = lockwatch.thread_lock("rpc.send")
    with a:
        with b:
            pass
        # b released: a new acquisition must see only `a` held
        with b:
            pass
    snap = lockwatch.snapshot()
    assert snap["edges"] == [["server.repl", "rpc.send", 2]]
    assert snap["violations"] == []


# ------------------------------------------------------------ async domain
def test_async_locks_and_to_thread_propagation(watch_on):
    """Task-held locks ride a ContextVar: sync code on the loop and
    asyncio.to_thread workers (which copy the context) both see them,
    so a thread-lock acquisition inside to_thread records the edge
    from the task's asyncio hold."""

    async def run():
        r = lockwatch.async_lock("server.repl")
        s = lockwatch.async_lock("rpc.send")
        assert not r.locked()
        async with r:
            assert r.locked()  # block_server drain-trigger probe contract
            async with s:
                pass

            def work():
                with lockwatch.thread_lock("utils.ledger"):
                    pass

            await asyncio.to_thread(work)
        assert not r.locked()

    asyncio.run(run())
    snap = lockwatch.snapshot()
    assert ["server.repl", "rpc.send", 1] in snap["edges"]
    assert ["server.repl", "utils.ledger", 1] in snap["edges"]
    assert snap["violations"] == []


# --------------------------------------------------------- cycle detection
def test_find_cycles():
    assert lockwatch.find_cycles([("a", "b"), ("b", "c")]) == []
    cycles = lockwatch.find_cycles([("a", "b"), ("b", "c"), ("c", "a")])
    assert cycles and set(cycles[0]) == {"a", "b", "c"}
    # a cycle between undeclared keys still counts against counters()
    lockwatch._witness.edges[("x", "y")] = 1
    lockwatch._witness.edges[("y", "x")] = 1
    assert lockwatch.counters()["lock_violations"] >= 1


# ------------------------------------------------------- report + gate CLI
def test_flush_merge_and_require_gate(tmp_path, watch_on, capsys):
    report = tmp_path / "lockwatch.jsonl"

    a = lockwatch.thread_lock("kv.cache_manager", reentrant=True)
    b = lockwatch.thread_lock("utils.ledger")
    with a:
        with b:
            pass
    lockwatch.flush(str(report))
    # second "process": same edge again, appended as its own line
    lockwatch.flush(str(report))
    assert len(report.read_text().splitlines()) == 2

    merged = lockwatch.merge_lines(report.read_text())
    assert merged["edges"] == [["kv.cache_manager", "utils.ledger", 2]]

    assert lockwatch._main([str(report), "--require"]) == 0
    out = capsys.readouterr().out
    assert "1 edge(s)" in out and "0 violation(s)" in out


def test_require_gate_fails_on_empty_report(tmp_path, capsys):
    report = tmp_path / "empty.jsonl"
    report.write_text("")
    assert lockwatch._main([str(report), "--require"]) == 1
    assert "EMPTY" in capsys.readouterr().err
    # without --require an empty report only informs
    assert lockwatch._main([str(report)]) == 0


def test_require_gate_fails_on_violation_and_cycle(tmp_path, capsys):
    report = tmp_path / "bad.jsonl"
    report.write_text(json.dumps({
        "edges": [["kv.cache_manager", "utils.ledger", 1],
                  ["utils.ledger", "kv.cache_manager", 1]],
        "violations": [{"held": "utils.ledger",
                        "acquired": "kv.cache_manager",
                        "why": "descending"}],
    }) + "\n")
    assert lockwatch._main([str(report), "--require"]) == 1
    out = capsys.readouterr()
    assert "VIOLATION" in out.out and "CYCLE" in out.out


def test_flush_skips_empty_witness(tmp_path, watch_on):
    report = tmp_path / "noop.jsonl"
    lockwatch.flush(str(report))
    assert not report.exists() or report.read_text() == ""


# ------------------------------------------------------------- live e2e run
@pytest.fixture(scope="module")
def tiny_model_dir(tmp_path_factory):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        hidden_size=64,
        intermediate_size=128,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_hidden_layers=3,
        vocab_size=128,
        max_position_embeddings=256,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config).eval().to(torch.float32)
    d = tmp_path_factory.mktemp("tiny_llama_lockwatch")
    model.save_pretrained(d, safe_serialization=True)
    return str(d), config


def test_e2e_swarm_witness_observes_edges(tiny_model_dir, monkeypatch):
    """The acceptance run: a live two-server swarm with KV replication
    under BBTPU_LOCKWATCH=1 must observe at least one cross-lock
    acquisition edge (replication holds repl_lock across the peer-pool
    and send-lock acquisitions) with ZERO hierarchy violations and ZERO
    cycles — the runtime cross-validation of the static lock model."""
    import jax.numpy as jnp

    from bloombee_tpu.client.config import ClientConfig
    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    monkeypatch.setenv("BBTPU_LOCKWATCH", "1")
    monkeypatch.delenv("BBTPU_LOCKWATCH_REPORT", raising=False)
    model_dir, config = tiny_model_dir

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()

        def rc():
            return RegistryClient("127.0.0.1", reg.port)

        def server(throughput):
            return BlockServer(
                model_uid="tiny", start=0, end=3, model_dir=model_dir,
                registry=rc(), compute_dtype=jnp.float32, num_pages=64,
                page_size=4, prefix_cache=True, throughput=throughput,
            )

        s_a, s_b = server(10.0), server(1.0)
        await s_a.start()
        await s_b.start()

        cfg = ClientConfig(use_push=False, prefix_cache=True,
                           kv_repl_every=1)
        model = DistributedModelForCausalLM.from_pretrained(
            model_dir, rc(), model_uid="tiny", config=cfg
        )
        input_ids = (np.arange(12)[None, :] * 5 + 3) % config.vocab_size
        async with model.inference_session(28, 1) as sess:
            assert sess._standby_peers()
            out = await sess.step(model.embed(input_ids), ids=input_ids)
            for _ in range(4):
                logits = model.logits(out[:, -1:])[:, 0]
                nxt = np.argmax(logits, axis=-1).astype(
                    input_ids.dtype
                )[:, None]
                out = await sess.step(model.embed(nxt), ids=nxt)
            # wait until a replication pass actually shipped pages —
            # that pass is the guaranteed cross-lock nesting
            primary_port = sess._spans[0].span.server_info.port
            primary = s_a if s_a.port == primary_port else s_b
            for _ in range(100):
                if primary.repl_pages_sent >= 1:
                    break
                await asyncio.sleep(0.05)
            assert primary.repl_pages_sent >= 1

            # the counters also ride rpc_info (BB006 surfacing)
            from bloombee_tpu.wire.rpc import connect

            conn = await connect("127.0.0.1", primary.port)
            info, _ = await conn.call("rpc_info", {})
            assert info["lock_order_edges"] >= 1
            assert info["lock_violations"] == 0
            await conn.close()

        await s_a.stop()
        await s_b.stop()
        await reg.stop()

    asyncio.run(run())

    snap = lockwatch.snapshot()
    edges = [(a, b) for a, b, _ in snap["edges"]]
    assert ("server.repl", "rpc.send") in edges or (
        "server.repl", "server.peer_pool"
    ) in edges, snap["edges"]
    assert snap["violations"] == [], snap["violations"]
    assert lockwatch.find_cycles(edges) == []
