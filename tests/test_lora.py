"""LoRA adapter merging: served logits must match an HF model whose weights
were merged in torch (port of /root/reference/tests/test_peft.py intent)."""

import asyncio
import json

import numpy as np
import torch

import jax.numpy as jnp


def test_lora_merge_matches_torch(tmp_path):
    from safetensors.torch import save_file
    from transformers import LlamaConfig, LlamaForCausalLM

    from bloombee_tpu.client.model import DistributedModelForCausalLM
    from bloombee_tpu.server.block_server import BlockServer
    from bloombee_tpu.swarm.registry import RegistryClient, RegistryServer

    config = LlamaConfig(
        hidden_size=64, intermediate_size=128, num_attention_heads=4,
        num_key_value_heads=2, num_hidden_layers=2, vocab_size=128,
        rms_norm_eps=1e-5, tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    hf = LlamaForCausalLM(config).eval().to(torch.float32)
    base = str(tmp_path / "base")
    hf.save_pretrained(base, safe_serialization=True)

    # random LoRA on q_proj/v_proj of both layers (PEFT layout)
    r, alpha = 4, 8.0
    adapter = tmp_path / "adapter"
    adapter.mkdir()
    tensors = {}
    torch.manual_seed(1)
    for i in range(2):
        for proj in ("q_proj", "v_proj"):
            mod_w = getattr(hf.model.layers[i].self_attn, proj).weight
            a = torch.randn(r, mod_w.shape[1]) * 0.1
            b = torch.randn(mod_w.shape[0], r) * 0.1
            key = f"base_model.model.model.layers.{i}.self_attn.{proj}"
            tensors[f"{key}.lora_A.weight"] = a
            tensors[f"{key}.lora_B.weight"] = b
            # merge into the torch reference: W += alpha/r * B @ A
            mod = getattr(hf.model.layers[i].self_attn, proj)
            with torch.no_grad():
                mod.weight += (alpha / r) * (b @ a)
    save_file(tensors, str(adapter / "adapter_model.safetensors"))
    (adapter / "adapter_config.json").write_text(
        json.dumps({"r": r, "lora_alpha": alpha, "peft_type": "LORA"})
    )

    async def run():
        reg = RegistryServer(host="127.0.0.1")
        await reg.start()
        server = BlockServer(
            model_uid="m", start=0, end=2, model_dir=base,
            registry=RegistryClient("127.0.0.1", reg.port),
            compute_dtype=jnp.float32, num_pages=32, page_size=4,
            adapter_dirs=[str(adapter)],
        )
        await server.start()
        model = DistributedModelForCausalLM.from_pretrained(
            base, RegistryClient("127.0.0.1", reg.port), model_uid="m"
        )
        input_ids = np.arange(8)[None, :]
        async with model.inference_session(16, 1) as sess:
            out = await sess.step(model.embed(input_ids))
        logits = model.logits(out)
        with torch.no_grad():
            ref = hf(torch.tensor(input_ids)).logits.numpy()
        np.testing.assert_allclose(logits, ref, atol=2e-3, rtol=2e-3)
        await server.stop()
        await reg.stop()

    asyncio.run(run())
