"""RTT measurement for routing (reference utils/ping.py:59-100 PingAggregator).

EMA round-trip times per peer, measured by timing an `rpc_info` unary call.
Used on the client (client->server edges of the routing graph) and on
servers (next-hop pings announced in ServerInfo.next_pings, reference
server.py:1000-1007, so the client's Dijkstra can cost server->server hops
with real measurements instead of a constant).
"""

from __future__ import annotations

import asyncio

from bloombee_tpu.utils import clock

DEFAULT_RTT_S = 0.01  # used until a peer has been measured
FAILED_RTT_S = 5.0  # unreachable peers look very expensive, not infinite


class PingAggregator:
    def __init__(self, alpha: float = 0.3, stale_after: float = 30.0):
        self.alpha = alpha
        self.stale_after = stale_after
        self._rtt: dict[str, float] = {}
        self._measured_at: dict[str, float] = {}
        # NTP-style peer clock offsets (reference handler.py:498-575): lets
        # timing tables attribute ONE-WAY wire time across machines
        self._clock_offset: dict[str, float] = {}

    def clock_offset(self, peer_id: str) -> float | None:
        """Estimated peer_clock - local_clock in seconds (None until the
        peer has replied with a timestamp)."""
        return self._clock_offset.get(peer_id)

    def record(self, peer_id: str, rtt: float) -> None:
        old = self._rtt.get(peer_id)
        self._rtt[peer_id] = (
            rtt if old is None else old * (1 - self.alpha) + rtt * self.alpha
        )
        self._measured_at[peer_id] = clock.monotonic()

    def get(self, peer_id: str, default: float = DEFAULT_RTT_S) -> float:
        return self._rtt.get(peer_id, default)

    def forget(self, peer_id: str) -> None:
        """Drop a peer's RTT (and clock offset) so its next admission to
        routing re-measures. Called when a peer is banned: the pre-failure
        EMA describes a server that no longer exists in that form — a
        recovered peer routing on stale low latency would soak up traffic
        it can't serve (and a stale FAILED_RTT_S would shun a healthy one)."""
        self._rtt.pop(peer_id, None)
        self._measured_at.pop(peer_id, None)
        self._clock_offset.pop(peer_id, None)

    def needs_measure(self, peer_id: str) -> bool:
        at = self._measured_at.get(peer_id)
        return at is None or clock.monotonic() - at > self.stale_after

    def to_wire(self) -> dict[str, float]:
        """Fresh entries only; departed peers (never re-measured) are evicted
        so long-lived servers' announce payloads don't grow with churn."""
        cutoff = clock.monotonic() - 4 * self.stale_after
        for pid in [
            p for p, at in self._measured_at.items() if at < cutoff
        ]:
            self._rtt.pop(pid, None)
            self._measured_at.pop(pid, None)
            self._clock_offset.pop(pid, None)
        return dict(self._rtt)

    async def measure(
        self, peer_id: str, host: str, port: int, timeout: float = 2.0
    ) -> float:
        """One rpc_info round trip on a fresh connection; EMA-recorded.
        Unreachable peers record FAILED_RTT_S (routing avoids, bans expire)."""
        from bloombee_tpu.wire.rpc import connect

        t0 = clock.perf_counter()
        try:
            conn = await asyncio.wait_for(connect(host, port), timeout)
            try:
                # stamp AFTER connect: the NTP midpoint must halve only the
                # rpc round trip, not the TCP handshake
                t_call = clock.perf_counter()
                t_call_wall = clock.now()
                meta, _ = await asyncio.wait_for(
                    conn.call("rpc_info", {}, []), timeout
                )
                call_rtt = clock.perf_counter() - t_call
            finally:
                await conn.close()
            rtt = clock.perf_counter() - t0
            server_time = meta.get("server_time")
            if server_time is not None:
                self._clock_offset[peer_id] = float(server_time) - (
                    t_call_wall + call_rtt / 2.0
                )
        except Exception:
            rtt = FAILED_RTT_S
        self.record(peer_id, rtt)
        return rtt

    async def measure_many(
        self,
        peers: list[tuple[str, str, int]],
        timeout: float = 1.0,
        overall_timeout: float | None = 2.0,
    ) -> None:
        """Ping peers concurrently: [(peer_id, host, port)]. The whole batch
        is timeboxed — each completed measure records its own result, so a
        timeout keeps partial data and never blocks the caller long."""
        task = asyncio.gather(
            *(self.measure(pid, h, p, timeout) for pid, h, p in peers)
        )
        try:
            await asyncio.wait_for(task, overall_timeout)
        except asyncio.TimeoutError:
            pass
