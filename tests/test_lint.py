"""Lint gate: scripts/lint.sh must pass as part of the tier-1 suite.

The script itself exits 0 when ruff is not installed (CI images without
the tool must not fail the suite for a missing linter), so this test is a
no-op there and a real ruff gate everywhere else.
"""

import pathlib
import subprocess

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_lint_clean():
    proc = subprocess.run(
        ["bash", str(REPO / "scripts" / "lint.sh")],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, (
        f"ruff regressions:\n{proc.stdout}\n{proc.stderr}"
    )


def test_analyze_clean():
    """bbtpu-lint (BB001-BB006 + env-docs drift) against the committed
    baseline: a new finding, or a BBTPU_* switch missing from README's
    generated table, fails tier-1 — not just a dev-machine lint run."""
    proc = subprocess.run(
        ["bash", str(REPO / "scripts" / "analyze.sh")],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, (
        f"bbtpu-lint findings:\n{proc.stdout}\n{proc.stderr}"
    )
