"""Acceptance rules for tree speculative decoding.

Port of the semantics of /root/reference/src/bloombee/models/llama/
spec_decoding_verify.py:44-154 (SpecInfer-style): greedy path-matching, and
multi-round rejection sampling against the draft distribution with residual
fallback. Greedy speculative decode is exactly equivalent to plain greedy
decode — the e2e test asserts token equality.

Inputs are per-sequence: `logits` [T, V] target logits for every tree node
(logits[i] predicts the token AFTER node i), `root_logits` [V] target logits
at the last committed token (predicting the first tree level).
"""

from __future__ import annotations

import numpy as np

from bloombee_tpu.spec.tree import DraftTree


def accept_greedy(
    tree: DraftTree,
    root_logits: np.ndarray,  # [V]
    logits: np.ndarray,  # [T, V]
    verifiable: np.ndarray | None = None,  # [T] bool: node has real logits
) -> tuple[list[int], int]:
    """Returns (accepted_node_indices in path order, bonus_token).

    Walk from the root level: at each step the target's argmax picks the
    required token; descend into the child carrying it, else stop. The bonus
    token is the target's argmax after the last accepted node (or at the
    root if nothing was accepted).

    `verifiable` marks nodes whose logits are real (mid-chain pruning drops
    the rest — reference backend.py:395-410). Descent stops at an
    unverifiable child, but no token is lost: the bonus IS that child's
    token (the argmax that selected it).
    """
    accepted: list[int] = []
    cur = -1  # -1 = root level (children of the last committed token)
    cur_logits = root_logits
    while True:
        want = int(np.argmax(cur_logits))
        children = tree.children_of(cur)
        nxt = -1
        for c in children:
            if int(tree.tokens[c]) == want and (
                verifiable is None or verifiable[c]
            ):
                nxt = int(c)
                break
        if nxt < 0:
            return accepted, want
        accepted.append(nxt)
        cur = nxt
        cur_logits = logits[nxt]


def _softmax(x: np.ndarray) -> np.ndarray:
    x = x - x.max(axis=-1, keepdims=True)
    e = np.exp(x)
    return e / e.sum(axis=-1, keepdims=True)


def accept_sampling(
    tree: DraftTree,
    root_logits: np.ndarray,
    logits: np.ndarray,
    draft_probs: np.ndarray,  # [T, V]; kept for API compat (sibling order)
    rng: np.random.Generator,
    temperature: float = 1.0,
) -> tuple[list[int], int]:
    """Exact sampling accept for DETERMINISTICALLY proposed candidates.

    Our drafter proposes each level's children by greedy top-k — with
    probability 1, not drawn from its softmax — so the SpecInfer
    min(1, p/q) rule (which assumes candidates sampled from q) would bias
    the output. For deterministic proposals the exact scheme is sequential
    enumeration: try the children in order, accepting child c with the
    tempered target's residual-normalized probability
    P(c | not any earlier sibling); if all fail, sample the bonus from the
    remaining residual. By the chain rule the emitted token at every level
    is distributed EXACTLY as softmax(target / temperature), regardless of
    which or how many candidates the drafter proposed (verified by a
    total-variation harness in tests).
    """
    accepted: list[int] = []
    cur = -1
    cur_logits = root_logits
    while True:
        p = _softmax(cur_logits / max(temperature, 1e-6))
        children = list(tree.children_of(cur))
        nxt = -1
        residual = p.copy()
        for c in children:
            tok = int(tree.tokens[c])
            mass = float(residual.sum())
            if mass <= 0.0:
                break
            if rng.random() < residual[tok] / mass:
                nxt = int(c)
                break
            residual[tok] = 0.0  # rejected => condition on "not tok"
        if nxt < 0:
            mass = float(residual.sum())
            if mass <= 0.0:  # numerically all mass was on rejected tokens
                bonus = int(np.argmax(p))
            else:
                bonus = int(rng.choice(len(residual), p=residual / mass))
            return accepted, bonus
        accepted.append(nxt)
        cur = nxt
        cur_logits = logits[nxt]
