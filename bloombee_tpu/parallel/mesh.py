"""Device mesh construction.

Axes: dp (data/batch), pp (pipeline stages), tp (tensor/heads), sp
(sequence/context). Collectives along tp/sp are the hot ones and should map
to ICI on real hardware; dp/pp gradients and activations tolerate DCN.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("dp", "pp", "tp", "sp")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    dp: int = 1
    pp: int = 1
    tp: int = 1
    sp: int = 1

    @property
    def size(self) -> int:
        return self.dp * self.pp * self.tp * self.sp


def make_mesh(config: MeshConfig, devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    if config.size > len(devices):
        raise ValueError(
            f"mesh needs {config.size} devices, have {len(devices)}"
        )
    arr = np.asarray(devices[: config.size]).reshape(
        config.dp, config.pp, config.tp, config.sp
    )
    return Mesh(arr, AXES)
